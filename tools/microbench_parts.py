"""Component-level costs of the window update step on the real TPU.

Measures, in isolation:
  * [B,P] arbitrary-index gather of probe chains (current hashtable._probe)
  * [B] gather with contiguous slice_sizes=(P,2) (candidate redesign)
  * scatter-add of B lanes into a C*R accumulator
  * scatter-set of B bool lanes
  * single lookup vs full 5-round upsert
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def timeit(f, *args, iters=10):
    out = f(*args)
    import jax

    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = f(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=262_144)
    ap.add_argument("--capacity", type=int, default=1 << 22)
    ap.add_argument("--probe", type=int, default=16)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import hashtable

    B, C, P, R = args.batch, args.capacity, args.probe, 8
    rng = np.random.default_rng(0)

    keys64 = rng.integers(0, 2**63, size=B, dtype=np.int64)
    h = keys64.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
    hi = jnp.asarray((h >> np.uint64(32)).astype(np.uint32))
    lo = jnp.asarray((h & np.uint64(0xFFFFFFFF)).astype(np.uint32))
    valid = jnp.ones(B, bool)

    table = hashtable.create(C, P)
    table, slot, ok = hashtable.upsert(table, hi, lo, valid)
    jax.block_until_ready(table.keys)
    tk = table.keys

    base = np.asarray(
        jax.jit(lambda h_, l_: hashtable._chain(h_, l_, C, 1))(hi, lo)
    )[:, 0]
    cand = jnp.asarray(
        (base[:, None] + np.arange(P)[None, :]) % C, np.int32
    )
    base_j = jnp.asarray(base, np.int32)

    @jax.jit
    def gather_arbitrary(tk_, cand_):
        return tk_[cand_]                     # [B, P, 2]

    @jax.jit
    def gather_slices(tk_, base_):
        # one gather of B contiguous (P, 2) slices
        import jax.lax as lax

        return lax.gather(
            tk_, base_[:, None],
            lax.GatherDimensionNumbers(
                offset_dims=(1, 2), collapsed_slice_dims=(),
                start_index_map=(0,),
            ),
            slice_sizes=(P, 2), mode="clip",
        )

    print(f"gather [B,P] arbitrary: {timeit(gather_arbitrary, tk, cand):8.2f} ms")
    print(f"gather B slices (P,2):  {timeit(gather_slices, tk, base_j):8.2f} ms")

    acc = jnp.zeros(C * R, jnp.float32)
    flat = jnp.asarray(rng.integers(0, C * R, B), np.int32)
    upd = jnp.ones(B, jnp.float32)

    @jax.jit
    def scatter_add(acc_, flat_, upd_):
        return acc_.at[flat_].add(upd_)

    touched = jnp.zeros(C * R, bool)

    @jax.jit
    def scatter_set(t_, flat_):
        return t_.at[flat_].set(True)

    print(f"scatter-add B->C*R:     {timeit(scatter_add, acc, flat, upd):8.2f} ms")
    print(f"scatter-set B->C*R:     {timeit(scatter_set, touched, flat):8.2f} ms")

    @jax.jit
    def one_lookup(tk_, hi_, lo_):
        return hashtable._lookup_or_empty(tk_, C, P, hi_, lo_)

    print(f"single lookup:          {timeit(one_lookup, tk, hi, lo):8.2f} ms")

    def full_upsert(tk_, hi_, lo_):
        return hashtable._upsert_impl(tk_, hi_, lo_, (C, P, 4), valid)

    print(f"upsert (1+4 rounds):    {timeit(full_upsert, tk, hi, lo):8.2f} ms")

    # h2d: one fused transfer vs 5 separate
    cols = [np.asarray(rng.random(B), np.float32) for _ in range(5)]

    def h2d_sep():
        return [jnp.asarray(c) for c in cols]

    packed = np.stack(cols)

    def h2d_packed():
        return jnp.asarray(packed)

    print(f"h2d 5 separate arrays:  {timeit(h2d_sep, iters=5):8.2f} ms")
    print(f"h2d 1 packed array:     {timeit(h2d_packed, iters=5):8.2f} ms")


if __name__ == "__main__":
    main()
