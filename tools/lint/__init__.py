"""Unified hot-path invariant linter (ISSUE 9; trace tier ISSUE 11).

``python -m tools.lint`` runs all 12 rules — 7 AST-tier (source-level
invariants, one shared parsed-module cache) and 5 trace-tier (compiled-
graph invariants over the canonical kernel families, one shared trace
cache; see tools/lint/kernel_audit.py). See tools/lint/core.py for the
framework and docs/static-analysis.md for the rule catalog.
"""

from __future__ import annotations

import os
import sys

# the shims under tools/ are imported as TOP-LEVEL modules by the
# legacy tests (sys.path points at tools/); make the package importable
# from there too
_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.core import (  # noqa: E402,F401
    Finding, LintInternalError, ParsedModule, RepoTree, Rule, run_rules,
)
from tools.lint.rules import all_rules, rule_by_name  # noqa: E402,F401

DEFAULT_ROOT = _ROOT


def run_lint(root: str = None, rule: str = None, tier: str = None):
    """All (or one) rule(s) over the repo; returns the finding list.
    ``tier`` ("ast"/"trace") filters like the CLI's --tier."""
    tree = RepoTree(root or DEFAULT_ROOT)
    rules = [rule_by_name(rule)] if rule else all_rules(tier)
    return run_rules(tree, rules)
