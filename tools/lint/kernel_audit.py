"""Trace-time evidence cache for the compiled-graph auditor (ISSUE 11).

The AST tier reads source; every property it checks is a property of
what the author WROTE. But the contracts the ROADMAP's perf story
actually rests on — donated state buffers really aliasing, PR 7's "one
sort feeds four" staying one sort, no host callbacks inside the
megastep scan, no silent f64 widening — are properties of the COMPILED
program, visible only after tracing. This module builds that evidence
exactly once per process and shares it across every trace-tier rule,
the same parse-once economics RepoTree gives the AST tier:

  * The CANONICAL grid: runtime/step.py ``kernel_family_grid()`` (the
    real step builders over routes x layouts x planes x fused depths)
    plus ops/window_kernels.py ``kernel_family_grid()`` (the raw kernel
    bodies). Each family is traced (``jax.make_jaxpr``) for primitive
    evidence; donated step families are additionally LOWERED for the
    StableHLO input/output alias table; the ``deep`` representatives are
    fully COMPILED for the executable's alias table + memory stats.
    Everything runs on the CPU backend under abstract-or-tiny inputs —
    no accelerator needed, tier-1 friendly.
  * FIXTURE kernels: a virtual tree (the red-team fixture path) yields
    families only from files carrying the ``# lint-kernel-fixture``
    marker, each defining ``lint_kernel_families()``. The canonical grid
    is NEVER built for virtual trees, so AST fixtures impersonating
    runtime/step.py stay cheap and trace fixtures are explicit.

Evidence per family (:class:`FamilyTrace`): grouped primitive counts
(sort/scatter/gather/while_scan/cond — the op-budget ledger currency),
host-crossing primitives with their scan/cond nesting path, wide-dtype
(64-bit) values, and the abstract input signature (the compile-signature
ledger currency: two call sites disagreeing on this string means two
compiles of the "same" step — a recompile storm). Donation evidence
(:meth:`KernelAudit.donation_report`) is computed lazily because only
the donation-effective rule pays for lowering/compiling.
"""

from __future__ import annotations

import contextlib
import hashlib
import os
import re
import time
import warnings
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from tools.lint.core import LintInternalError, RepoTree

# the module that owns the step-builder grid; a disk tree without it is
# not this repo (e.g. a CLI test tmp dir) and gets an empty audit
STEP_HOME = "flink_tpu/runtime/step.py"
WK_HOME = "flink_tpu/ops/window_kernels.py"

# virtual-tree files carrying this marker are exec'd for fixture
# families; everything else in a virtual tree is AST-tier material
FIXTURE_MARKER = "# lint-kernel-fixture"

# ledger currency: jaxpr primitive name -> budget group
OP_GROUPS = ("sort", "scatter", "gather", "while_scan", "cond")

# primitives that cross the device/host boundary from inside a traced
# program — any of these inside a kernel serializes the step pipeline
HOST_CROSSING_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "infeed", "outfeed", "host_local_array_to_global_array",
    "device_put",
})

# 64-bit dtypes a kernel jaxpr must never materialize (dtype-discipline)
WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")

_ALIAS_ARG_SPLIT = re.compile(r"%arg\d+\s*:")


def _op_group(prim_name: str) -> Optional[str]:
    if prim_name == "sort":
        return "sort"
    if prim_name.startswith("scatter"):
        return "scatter"
    if prim_name.startswith("gather"):
        return "gather"
    if prim_name in ("scan", "while"):
        return "while_scan"
    if prim_name == "cond":
        return "cond"
    return None


def _subjaxprs(value):
    """Every ClosedJaxpr/Jaxpr reachable from one eqn.params value."""
    import jax

    vals = value if isinstance(value, (list, tuple)) else (value,)
    for v in vals:
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v


def iter_eqns(jaxpr, path: Tuple[str, ...] = ()):
    """Depth-first (path, eqn) over a jaxpr and everything it closes
    over — scan/cond/while/pjit bodies included. ``path`` is the chain
    of enclosing control primitives, so a rule can say "debug_callback
    inside scan" instead of just "somewhere"."""
    for eqn in jaxpr.eqns:
        yield path, eqn
        name = eqn.primitive.name
        sub_path = path if name == "pjit" else path + (name,)
        for v in eqn.params.values():
            for sub in _subjaxprs(v):
                yield from iter_eqns(sub, sub_path)


def _aval_str(x) -> str:
    import jax

    if isinstance(x, jax.ShapeDtypeStruct):
        return jax.core.ShapedArray(x.shape, x.dtype).str_short()
    aval = getattr(x, "aval", None)
    if aval is None:
        aval = jax.core.get_aval(x)
    return aval.str_short()


def abstract_signature(args) -> str:
    """The family's abstract input signature: one comma-joined
    ``aval.str_short()`` per flattened leaf, in tree order. Two calls
    that disagree on this string compile separately — the signature
    ledger pins it so an accidental split (a recompile storm) fails lint
    before it fails in production."""
    import jax

    leaves = jax.tree_util.tree_leaves(args)
    return ",".join(_aval_str(x) for x in leaves)


def signature_digest(signature: str) -> str:
    return hashlib.sha256(signature.encode()).hexdigest()[:12]


@dataclass
class FamilyTrace:
    """Jaxpr-level evidence for one kernel family (compile evidence is
    lazy; see KernelAudit.donation_report)."""

    name: str
    path: str                  # repo-relative anchor for findings
    line: int
    donated: bool
    deep: bool
    builder: str               # source builder/function name ("" = n/a)
    op_counts: Dict[str, int]  # group -> count (OP_GROUPS keys, always)
    signature: str
    digest: str
    host_crossings: List[Tuple[str, str]]   # (primitive, nesting path)
    wide_dtypes: List[Tuple[str, str]]      # (primitive, aval string)
    n_eqns: int = 0


@dataclass
class _Entry:
    name: str
    fn: Any
    args: Tuple
    donate: Tuple[int, ...]
    path: str
    line: int
    builder: str = ""
    deep: bool = False
    x64: bool = False


def _trace_entry(e: _Entry) -> FamilyTrace:
    import jax

    ctx = (jax.experimental.enable_x64() if e.x64
           else contextlib.nullcontext())
    with ctx, warnings.catch_warnings():
        warnings.simplefilter("ignore")
        closed = jax.make_jaxpr(getattr(e.fn, "jit", e.fn))(*e.args)
    counts = {g: 0 for g in OP_GROUPS}
    crossings: List[Tuple[str, str]] = []
    wide: Dict[Tuple[str, str], None] = {}
    n_eqns = 0
    for path, eqn in iter_eqns(closed.jaxpr):
        n_eqns += 1
        prim = eqn.primitive.name
        g = _op_group(prim)
        if g is not None:
            counts[g] += 1
        if prim in HOST_CROSSING_PRIMS:
            crossings.append((prim, "/".join(path) or "<top>"))
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in WIDE_DTYPES:
                wide[(prim, aval.str_short())] = None
    sig = abstract_signature(e.args)
    return FamilyTrace(
        name=e.name, path=e.path, line=e.line, donated=bool(e.donate),
        deep=e.deep, builder=e.builder, op_counts=counts,
        signature=sig, digest=signature_digest(sig),
        host_crossings=crossings, wide_dtypes=sorted(wide),
        n_eqns=n_eqns,
    )


def _lowered_alias_params(mlir_text: str) -> Tuple[set, int]:
    """Parameter indices of ``@main`` carrying ``tf.aliasing_output``
    in the lowered StableHLO, plus the total parameter count. A donated
    leaf the lowering could not alias (shape/dtype mismatch, runtime
    refusal) simply drops out of this table."""
    m = re.search(r"func\.func public @main\((.*?)\)(?:\s*->|\s*\{)",
                  mlir_text, re.S)
    if m is None:
        return set(), 0
    chunks = _ALIAS_ARG_SPLIT.split(m.group(1))[1:]
    return (
        {i for i, c in enumerate(chunks) if "tf.aliasing_output" in c},
        len(chunks),
    )


def _executable_alias_params(hlo_text: str) -> set:
    """Parameter indices in the compiled executable's
    ``input_output_alias={...}`` table (what XLA actually kept)."""
    m = re.search(r"input_output_alias=\{(.*?)\}\s*\n", hlo_text)
    if m is None:
        m = re.search(r"input_output_alias=\{(.*)", hlo_text)
    if m is None:
        return set()
    return {int(x) for x in re.findall(r"\(\s*(\d+)\s*,", m.group(1))}


def _donated_leaves(args, donate: Tuple[int, ...]):
    """[(flat_index, leaf_path_str, leaf_size)] for every leaf of every
    donated argument, in flattened-argument order (closure consts lower
    to module constants, not params, so flat indices are the module's
    pre-DCE parameter space)."""
    import jax
    import numpy as np

    out = []
    offset = 0
    for i, a in enumerate(args):
        flat, _ = jax.tree_util.tree_flatten_with_path(a)
        if i in donate:
            for j, (kp, leaf) in enumerate(flat):
                out.append((
                    offset + j,
                    f"arg{i}{jax.tree_util.keystr(kp)}",
                    int(np.prod(getattr(leaf, "shape", ()) or (1,))),
                ))
        offset += len(flat)
    return out


def _kept_param_map(lowered, n_flat: int) -> Dict[int, int]:
    """{flat invar index: lowered param position}. jit lowers with
    keep_unused=False, so unused invars are DROPPED from the module's
    parameter list (``kept_var_idx``) and every later param shifts —
    the packed families' zero-size touched plane taught us this the
    hard way. Falls back to the identity map when the private lowering
    attribute moves."""
    try:
        kept = sorted(lowered._lowering.compile_args["kept_var_idx"])
        return {flat: pos for pos, flat in enumerate(kept)}
    except (AttributeError, KeyError, TypeError):
        return {i: i for i in range(n_flat)}


class KernelAudit:
    """Shared trace-time evidence for one set of kernel families.

    ``traces`` (eager, built at construction) carries the jaxpr
    evidence every rule reads; :meth:`donation_report` lowers — and for
    ``deep`` families compiles — on first use and caches, so a CLI run
    filtered to a jaxpr-only rule never pays for XLA."""

    def __init__(self, entries: List[_Entry]):
        t0 = time.monotonic()
        self._entries = {e.name: e for e in entries}
        self.traces: Dict[str, FamilyTrace] = {}
        for e in entries:
            try:
                self.traces[e.name] = _trace_entry(e)
            except Exception as ex:   # an untraceable family is a broken
                raise LintInternalError(      # build, not a finding
                    f"kernel family {e.name!r} failed to trace: "
                    f"{type(ex).__name__}: {ex}"
                ) from ex
        self.build_seconds = time.monotonic() - t0
        self.donation_seconds = 0.0
        self._donation: Dict[str, dict] = {}

    def donation_report(self, name: str) -> dict:
        """Alias evidence for one donated family:

        ``leaves``: donated (param, leaf-path) pairs;
        ``missing_lowered``: leaf paths absent from the lowered alias
        table (the donation is ineffective — XLA will copy);
        ``dropped_by_executable``: lowered-aliased leaves the compiled
        executable's table dropped (deep families only);
        ``executable_checked``: whether the compile-level check ran.
        """
        if name in self._donation:
            return self._donation[name]
        e = self._entries[name]
        if not e.donate:
            rep = {"leaves": [], "missing_lowered": [],
                   "dropped_by_executable": [], "executable_checked": False}
            self._donation[name] = rep
            return rep
        t0 = time.monotonic()
        jitfn = getattr(e.fn, "jit", e.fn)
        try:
            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                lowered = jitfn.lower(*e.args)
                aliased, _nparams = _lowered_alias_params(
                    lowered.as_text())
                exec_aliased = None
                if e.deep:
                    compiled = lowered.compile()
                    exec_aliased = _executable_alias_params(
                        compiled.as_text())
        except Exception as ex:
            raise LintInternalError(
                f"kernel family {name!r} failed to lower/compile: "
                f"{type(ex).__name__}: {ex}"
            ) from ex
        import jax

        leaves = _donated_leaves(e.args, e.donate)
        param_of = _kept_param_map(
            lowered, len(jax.tree_util.tree_leaves(e.args)))
        missing = []
        for flat, lp, size in leaves:
            p = param_of.get(flat)
            if p is None:
                # dropped as unused: a zero-size leaf costs nothing; a
                # real leaf the kernel never reads means its output is
                # written fresh — the donation buys nothing
                if size > 0:
                    missing.append(f"{lp} (unused by the kernel body)")
            elif p not in aliased:
                missing.append(lp)
        dropped = []
        if exec_aliased is not None:
            dropped = [lp for flat, lp, _size in leaves
                       if param_of.get(flat) is not None
                       and param_of[flat] in aliased
                       and param_of[flat] not in exec_aliased]
        rep = {
            "leaves": leaves,
            "missing_lowered": missing,
            "dropped_by_executable": dropped,
            "executable_checked": exec_aliased is not None,
        }
        self.donation_seconds += time.monotonic() - t0
        self._donation[name] = rep
        return rep


# ---------------------------------------------------------- entry points

_canonical_audit: Optional[KernelAudit] = None
_fixture_audits: Dict[tuple, KernelAudit] = {}


def _canonical_entries() -> List[_Entry]:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime import step as rstep

    ctx = MeshContext.create(n_shards=1, max_parallelism=8)
    entries: List[_Entry] = []
    for fam in rstep.kernel_family_grid():
        fn, args, donate = rstep.build_family(fam, ctx)
        entries.append(_Entry(
            name=fam.name, fn=fn, args=args, donate=donate,
            path=STEP_HOME,
            line=fam.builder.__code__.co_firstlineno,
            builder=fam.builder.__name__, deep=fam.deep,
        ))
    for name, fn, args in wk.kernel_family_grid():
        entries.append(_Entry(
            name=name, fn=fn, args=tuple(args), donate=(),
            path=WK_HOME, line=fn.__code__.co_firstlineno,
            builder=fn.__name__,
        ))
    return entries


def _fixture_entries(tree: RepoTree) -> List[_Entry]:
    import jax

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    entries: List[_Entry] = []
    for relpath in sorted(tree._virtual):
        src = tree._virtual[relpath]
        if not relpath.endswith(".py") or FIXTURE_MARKER not in src:
            continue
        ns: dict = {}
        try:
            exec(compile(src, relpath, "exec"), ns)
            fams = ns["lint_kernel_families"]()
        except Exception as ex:
            raise LintInternalError(
                f"kernel fixture {relpath} failed to load: "
                f"{type(ex).__name__}: {ex}"
            ) from ex
        for d in fams:
            fn = d["fn"]
            donate = tuple(d.get("donate", ()))
            if donate:
                fn = jax.jit(fn, donate_argnums=donate)
            entries.append(_Entry(
                name=d["name"], fn=fn, args=tuple(d["args"]),
                donate=donate, path=relpath, line=int(d.get("line", 1)),
                builder=d.get("builder", ""), deep=True,
                x64=bool(d.get("x64", False)),
            ))
    return entries


def get_audit(tree: RepoTree) -> Optional[KernelAudit]:
    """The KernelAudit for ``tree``, or None when the tree has no kernel
    families to audit (a disk tree that isn't this repo, or a virtual
    tree without fixture-marked files).

    Disk trees share ONE process-wide audit: the canonical grid is built
    from the installed flink_tpu modules, independent of the tree root,
    so every rule — and every parametrized test — pays the trace cost
    once. Virtual (fixture) audits are cached by file content."""
    global _canonical_audit
    if tree._virtual is not None:
        key = tuple(sorted(
            (rp, hashlib.sha256(src.encode()).hexdigest())
            for rp, src in tree._virtual.items()
            if rp.endswith(".py") and FIXTURE_MARKER in src
        ))
        if not key:
            return None
        if key not in _fixture_audits:
            _fixture_audits[key] = KernelAudit(_fixture_entries(tree))
        return _fixture_audits[key]
    if not tree.exists(STEP_HOME):
        return None
    if _canonical_audit is None:
        _canonical_audit = KernelAudit(_canonical_entries())
    return _canonical_audit


# ---------------------------------------------------------------- ledgers

def load_ledger(tree: RepoTree, relpath: str) -> Optional[dict]:
    """Parse one checked-in ledger; None when absent, LintInternalError
    when present but not valid JSON (a corrupt ledger is a broken
    build, not a finding)."""
    import json

    text = tree.read_text(relpath)
    if text is None:
        return None
    try:
        return json.loads(text)
    except ValueError as ex:
        raise LintInternalError(
            f"ledger {relpath} is not valid JSON: {ex}"
        ) from ex


def write_ledger(root: str, relpath: str, data: dict) -> None:
    """Rewrite one ledger deterministically (sorted keys, 2-space
    indent, trailing newline) so --update-ledger diffs are minimal."""
    import json

    path = os.path.join(root, relpath)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(data, f, indent=2, sort_keys=True)
        f.write("\n")


# ------------------------------------------------------------- bench hook

def kernel_structural_stamp(fn, args) -> dict:
    """Structural evidence for ONE kernel, for the bench detail JSON
    (ISSUE 11 satellite): grouped op counts, abstract-signature digest,
    and the compiled executable's memory_analysis byte totals — so
    BENCH_*.json carries a structural trajectory (did the sort count or
    the temp footprint move?) next to events/s."""
    import jax

    jitfn = getattr(fn, "jit", fn)
    closed = jax.make_jaxpr(jitfn)(*args)
    counts = {g: 0 for g in OP_GROUPS}
    for _path, eqn in iter_eqns(closed.jaxpr):
        g = _op_group(eqn.primitive.name)
        if g is not None:
            counts[g] += 1
    sig = abstract_signature(args)
    out = {"ops": counts, "signature_digest": signature_digest(sig)}
    try:
        mem = jitfn.lower(*args).compile().memory_analysis()
        if mem is not None:
            out["memory_bytes"] = {
                "argument": int(getattr(mem, "argument_size_in_bytes", 0)),
                "output": int(getattr(mem, "output_size_in_bytes", 0)),
                "temp": int(getattr(mem, "temp_size_in_bytes", 0)),
                "generated_code": int(
                    getattr(mem, "generated_code_size_in_bytes", 0)),
                "alias": int(getattr(mem, "alias_size_in_bytes", 0)),
            }
    except Exception as ex:    # memory stats are best-effort telemetry
        out["memory_bytes"] = {"error": f"{type(ex).__name__}: {ex}"}
    return out
