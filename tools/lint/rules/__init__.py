"""Rule plugins for the hot-path invariant linter (tools/lint).

One module per rule; ALL_RULES is the registry the CLI and the tier-1
test parametrize over. Two tiers: "ast" rules read source through the
shared RepoTree parse cache; "trace" rules (ISSUE 11) build the real
kernel families through the shared KernelAudit trace cache and read the
jaxpr / lowered / compiled program. Catalog with the invariant each
rule protects: docs/static-analysis.md.
"""

from __future__ import annotations

from typing import List, Optional

from tools.lint.core import Rule

from tools.lint.rules.hot_path_sync import HotPathSyncRule
from tools.lint.rules.sort_seam import SortSeamRule
from tools.lint.rules.retrace import RetraceRule
from tools.lint.rules.donation import DonationRule
from tools.lint.rules.config_hygiene import ConfigHygieneRule
from tools.lint.rules.thread_state import ThreadStateRule
from tools.lint.rules.fault_seams import FaultSeamRule
from tools.lint.rules.donation_effective import DonationEffectiveRule
from tools.lint.rules.host_crossing import HostCrossingRule
from tools.lint.rules.dtype_discipline import DtypeDisciplineRule
from tools.lint.rules.op_budget import OpBudgetRule
from tools.lint.rules.compile_signature import CompileSignatureRule


def all_rules(tier: Optional[str] = None) -> List[Rule]:
    """Fresh instances: migration order, then ISSUE 9's five AST rules,
    then ISSUE 11's five trace rules. ``tier`` filters ("ast"/"trace");
    None returns both tiers — the CLI default."""
    rules: List[Rule] = [
        HotPathSyncRule(),
        SortSeamRule(),
        RetraceRule(),
        DonationRule(),
        ConfigHygieneRule(),
        ThreadStateRule(),
        FaultSeamRule(),
        DonationEffectiveRule(),
        HostCrossingRule(),
        DtypeDisciplineRule(),
        OpBudgetRule(),
        CompileSignatureRule(),
    ]
    if tier is not None:
        rules = [r for r in rules if r.tier == tier]
    return rules


def rule_by_name(name: str) -> Rule:
    for r in all_rules():
        if r.name == name:
            return r
    from tools.lint.core import LintInternalError

    raise LintInternalError(
        f"unknown rule {name!r}; known: "
        f"{', '.join(r.name for r in all_rules())}"
    )
