"""Rule plugins for the hot-path invariant linter (tools/lint).

One module per rule; ALL_RULES is the registry the CLI and the tier-1
test parametrize over. Catalog with the invariant each rule protects:
docs/static-analysis.md.
"""

from __future__ import annotations

from typing import List

from tools.lint.core import Rule

from tools.lint.rules.hot_path_sync import HotPathSyncRule
from tools.lint.rules.sort_seam import SortSeamRule
from tools.lint.rules.retrace import RetraceRule
from tools.lint.rules.donation import DonationRule
from tools.lint.rules.config_hygiene import ConfigHygieneRule
from tools.lint.rules.thread_state import ThreadStateRule
from tools.lint.rules.fault_seams import FaultSeamRule


def all_rules() -> List[Rule]:
    """Fresh instances, migration order first then ISSUE 9's five."""
    return [
        HotPathSyncRule(),
        SortSeamRule(),
        RetraceRule(),
        DonationRule(),
        ConfigHygieneRule(),
        ThreadStateRule(),
        FaultSeamRule(),
    ]


def rule_by_name(name: str) -> Rule:
    for r in all_rules():
        if r.name == name:
            return r
    from tools.lint.core import LintInternalError

    raise LintInternalError(
        f"unknown rule {name!r}; known: "
        f"{', '.join(r.name for r in all_rules())}"
    )
