"""Rule ``dtype-discipline``: no 64-bit values anywhere in a kernel
jaxpr.

The state planes are f32/int32/uint32 by design — the TPU has no f64
ALU (it emulates at >10x cost) and every widened plane doubles HBM
traffic on the bandwidth-bound sweep. The classic leak: a Python float
literal or an ``np.float64`` scalar folding into a traced op under
``jax.experimental.enable_x64``, silently promoting a whole accumulator
plane. With x64 DISABLED the leak self-heals (JAX demotes), so unit
tests never see it; this rule traces the canonical families and flags
any equation whose output materializes float64/int64/uint64/complex128
— the evidence tier where the leak is visible regardless of the test
environment's x64 setting.
"""

from __future__ import annotations

from typing import List

from tools.lint.core import Finding, RepoTree, Rule
from tools.lint.kernel_audit import get_audit


class DtypeDisciplineRule(Rule):
    name = "dtype-discipline"
    title = ("no f64/i64 widening in any traced kernel family (the TPU "
             "emulates 64-bit at >10x cost)")
    established = "PR 10"
    tier = "trace"

    def check(self, tree: RepoTree) -> List[Finding]:
        audit = get_audit(tree)
        if audit is None:
            return []
        out: List[Finding] = []
        for name in sorted(audit.traces):
            tr = audit.traces[name]
            for prim, aval in tr.wide_dtypes:
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r}: primitive {prim!r} "
                    f"materializes a 64-bit value ({aval}) — a Python "
                    f"scalar or np.float64 leaked into the trace; cast "
                    f"at the boundary (jnp.float32/int32) so the plane "
                    f"never widens",
                    tr.builder or "<family>",
                ))
        return out
