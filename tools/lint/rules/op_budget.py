"""Rule ``op-budget``: per-family sort/scatter/gather/scan counts match
the checked-in golden ledger.

PR 7's headline wins are STRUCTURAL: the precombine path pays ONE
shared sort that feeds four scatter consumers, the packed planes
collapse the touched-bit scatter into the accumulator scatter, the
resident megastep keeps fire evaluation inside one scan. None of that
is visible to a unit test (the numbers stay right) and a benchmark only
catches it as noise two PRs later. This rule counts the budget-relevant
primitive groups (sort, scatter, gather, while/scan, cond) in every
canonical kernel family's jaxpr and diffs them against
``tools/lint/ledgers/op_budget.json``:

  * a drifted count is a finding — "your change added a second sort to
    the update kernel" fails the build at lint time;
  * a DELIBERATE change (you redesigned the kernel) is recorded with
    ``python -m tools.lint --rule op-budget --update-ledger``, which
    rewrites the ledger from a fresh trace — the diff then shows up in
    review next to the code that caused it;
  * on top of the ledger, one hard invariant that must never drift even
    WITH an update: a ``.precombine`` family pays at most one sort (the
    whole point of the shared-sort seam).

Not suppressible: like sort-seam, an op-budget change is a design
decision; the ledger (reviewed in the PR diff) is the escape hatch.
"""

from __future__ import annotations

from typing import Dict, List

from tools.lint.core import Finding, LintInternalError, RepoTree, Rule
from tools.lint.kernel_audit import (
    OP_GROUPS, get_audit, load_ledger, write_ledger,
)

LEDGER_PATH = "tools/lint/ledgers/op_budget.json"


class OpBudgetRule(Rule):
    name = "op-budget"
    title = ("per-kernel-family sort/scatter/gather/scan counts match "
             "the checked-in golden ledger")
    established = "PR 10"
    tier = "trace"
    suppressible = False
    update_ledger = False     # set by the CLI's --update-ledger flag

    def check(self, tree: RepoTree) -> List[Finding]:
        audit = get_audit(tree)
        if audit is None:
            return []
        actual: Dict[str, Dict[str, int]] = {
            name: dict(tr.op_counts)
            for name, tr in audit.traces.items()
        }
        out: List[Finding] = []
        # the hard seam invariant survives even a ledger update
        for name in sorted(actual):
            tr = audit.traces[name]
            if ".precombine" in name and actual[name]["sort"] > 1:
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r} pays {actual[name]['sort']} "
                    f"sorts — the precombine contract is ONE shared sort "
                    f"feeding every scatter consumer (PR 7); this cannot "
                    f"be ledgered away",
                    tr.builder or "<family>",
                ))
        if self.update_ledger:
            if tree.root is None:
                raise LintInternalError(
                    "--update-ledger needs a disk tree to write to")
            write_ledger(tree.root, LEDGER_PATH, {"families": actual})
            return out
        data = load_ledger(tree, LEDGER_PATH)
        if data is None:
            out.append(Finding(
                self.name, LEDGER_PATH, 1,
                f"op-budget ledger missing — generate it with "
                f"'python -m tools.lint --rule {self.name} "
                f"--update-ledger' and commit it",
            ))
            return out
        ledger: Dict[str, Dict[str, int]] = data.get("families", {})
        for name in sorted(set(actual) | set(ledger)):
            if name not in ledger:
                tr = audit.traces[name]
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r} is not in the op-budget "
                    f"ledger — a new family needs its budget recorded "
                    f"(--update-ledger) so future drift is caught",
                    tr.builder or "<family>",
                ))
                continue
            if name not in actual:
                out.append(Finding(
                    self.name, LEDGER_PATH, 1,
                    f"op-budget ledger lists unknown kernel family "
                    f"{name!r} — stale entry (or a hand edit without "
                    f"--update-ledger); regenerate the ledger",
                ))
                continue
            diffs = [
                f"{g}: {ledger[name].get(g, 0)} -> {actual[name][g]}"
                for g in OP_GROUPS
                if actual[name][g] != ledger[name].get(g, 0)
            ]
            if diffs:
                tr = audit.traces[name]
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r} op budget drifted from the "
                    f"ledger: {'; '.join(diffs)} — if this structural "
                    f"change is deliberate, rerun with --update-ledger "
                    f"and commit the ledger diff",
                    tr.builder or "<family>",
                ))
        return out
