"""Rule ``no-host-crossing``: no host-callback / transfer primitives
inside a traced kernel.

The AST hot-path-sync rule catches the constructs an author WRITES
(``block_until_ready``, ``.item()``, ``np.asarray``); this is its
compiled-program complement: ``jax.debug.print`` left over from a
debugging session lowers to a ``debug_callback`` primitive INSIDE the
megastep scan body, ``pure_callback``/``io_callback`` smuggle arbitrary
host round trips into the graph, and a traced ``device_put`` is an
implicit transfer — all invisible to source-level scanning once they
hide behind a helper, all serializing the dispatch pipeline at every
scan iteration. The finding names the nesting path (e.g. ``scan/cond``)
so "a print inside the K-fused scan body fires K times per dispatch" is
legible from the lint output.
"""

from __future__ import annotations

from typing import List

from tools.lint.core import Finding, RepoTree, Rule
from tools.lint.kernel_audit import get_audit


class HostCrossingRule(Rule):
    name = "no-host-crossing"
    title = ("no callback/transfer primitives in any traced kernel "
             "family (the compiled complement of hot-path-sync)")
    established = "PR 10"
    tier = "trace"

    def check(self, tree: RepoTree) -> List[Finding]:
        audit = get_audit(tree)
        if audit is None:
            return []
        out: List[Finding] = []
        for name in sorted(audit.traces):
            tr = audit.traces[name]
            for prim, path in tr.host_crossings:
                where = ("at the kernel top level" if path == "<top>"
                         else f"inside the {path} body")
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r}: host-crossing primitive "
                    f"{prim!r} {where} — every execution pays a device->"
                    f"host round trip (a leftover jax.debug.print lowers "
                    f"to debug_callback; remove it or move the readback "
                    f"to the lagged monitoring channel)",
                    tr.builder or "<family>",
                ))
        return out
