"""Rule ``config``: every config key is declared, documented, shipped,
and type-consistent.

PR 4 made conf-file strings coerce STRICTLY through the declared
``ConfigOption`` type (a misspelled boolean is an error, never a
silently-disabled watchdog). That guarantee only holds for keys that
HAVE a declaration — a typed-getter read of an undeclared literal key
(``config.get_int("stat.probe-len", 16)``) bypasses the whole scheme:
no declared type, no default registry entry, no docs anchor, and a typo
silently reads the fallback forever. Through rounds 6-8 the option
space grew to ~40 keys (``recovery.elastic``, ``pipeline.fused-fire``,
``state.packed-planes``, ...) and the drift is exactly what this rule
pins down:

  * read-undeclared — every literal key passed to
    ``get_str/get_int/get_bool/get_float`` under ``flink_tpu/`` must
    resolve to a declared ``ConfigOption``.
  * conf-missing — every declared key must appear in
    ``conf/flink-tpu-conf.yaml`` (a commented default line counts: the
    file is the operator-facing key catalog).
  * docs-missing — every declared key must be mentioned somewhere in
    ``docs/*.md``.
  * default-type-mismatch — a declared literal default must match the
    option's declared/inferred type (bool-before-int, as
    core/config.py coerces).
  * default-drift — a literal fallback at a read site that contradicts
    the declared default (two sources of truth disagreeing is how the
    web handlers and the executor drift apart).
  * perf-doc — performance knobs (``pipeline.*``, ``exchange.*``,
    ``state.packed-planes``, ``execution.micro-batch-size``) must be
    mentioned in docs/performance.md, and the keys served by the web
    monitor's ``/checkpoints/config``-style routes (any literal read
    in runtime/web.py) must be mentioned in docs/ — the route exists
    so operators can see the knobs; the docs must name them.

Established by PR 4 (strict coercion); unified + extended here
(ISSUE 9).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from tools.lint.core import (
    Finding, QualnameVisitor, RepoTree, Rule, const_str,
)

SCAN_ROOT = "flink_tpu"
CONF_FILE = "conf/flink-tpu-conf.yaml"
DOCS_DIR = "docs"
PERF_DOC = "docs/performance.md"
WEB_MODULE = "flink_tpu/runtime/web.py"

TYPED_GETTERS = {
    "get_str": str, "get_int": int, "get_bool": bool, "get_float": float,
}

PERF_PREFIXES = ("pipeline.", "exchange.")
PERF_KEYS = ("state.packed-planes", "execution.micro-batch-size")


def _mentions(text: str, key: str) -> bool:
    """Token-bounded mention of ``key`` in ``text``: plain substring
    would let a key that PREFIXES another declared key ride its
    sibling's mention (delete the 'security.auth.token' conf line and
    'security.auth.token-file' still contains it; same for
    'restart-strategy' inside 'restart-strategy.fixed-delay.*'). A
    trailing sentence period (dot NOT followed by a word char) still
    counts as a boundary."""
    return re.search(
        r"(?<![\w.-])" + re.escape(key) + r"(?![\w-])(?!\.[\w-])", text
    ) is not None


def _py_type_of_literal(node: ast.AST) -> Optional[type]:
    if isinstance(node, ast.Constant):
        v = node.value
        if v is None:
            return None
        return bool if isinstance(v, bool) else type(v)
    if isinstance(node, ast.BinOp):   # 1 << 16 style defaults
        try:
            return type(ast.literal_eval(node))
        except (ValueError, TypeError, SyntaxError):
            return None
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _py_type_of_literal(node.operand)
    return None


def _literal_value(node: ast.AST):
    try:
        return ast.literal_eval(node)
    except (ValueError, TypeError, SyntaxError):
        return _NOT_LITERAL


_NOT_LITERAL = object()


class Declaration:
    def __init__(self, key: str, path: str, line: int,
                 default_node: Optional[ast.AST],
                 type_name: Optional[str]):
        self.key = key
        self.path = path
        self.line = line
        self.default_node = default_node
        self.type_name = type_name


def collect_declarations(tree: RepoTree) -> Dict[str, Declaration]:
    decls: Dict[str, Declaration] = {}
    for pm in tree.walk(SCAN_ROOT):
        if "ConfigOption" not in pm.source:
            continue
        for node in ast.walk(pm.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func
            name = (
                fname.id if isinstance(fname, ast.Name)
                else fname.attr if isinstance(fname, ast.Attribute)
                else None
            )
            if name != "ConfigOption" or not node.args:
                continue
            key = const_str(node.args[0])
            if key is None:
                continue
            default_node = node.args[1] if len(node.args) > 1 else None
            type_name = None
            for kw in node.keywords:
                if kw.arg == "default" and default_node is None:
                    default_node = kw.value
                if kw.arg == "type" and isinstance(kw.value, ast.Name):
                    type_name = kw.value.id
            decls.setdefault(key, Declaration(
                key, pm.relpath, node.lineno, default_node, type_name,
            ))
    return decls


class _ReadScanner(QualnameVisitor):
    """Literal-key typed-getter reads: (key, getter, default_node)."""

    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.reads: List[Tuple[str, str, Optional[ast.AST], int, str]] = []

    def visit_Call(self, node: ast.Call):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in TYPED_GETTERS
            and node.args
        ):
            key = const_str(node.args[0])
            if key is not None and "." in key:
                default = node.args[1] if len(node.args) > 1 else None
                self.reads.append(
                    (key, f.attr, default, node.lineno, self.qualname())
                )
        self.generic_visit(node)


class ConfigHygieneRule(Rule):
    name = "config"
    title = ("literal config reads resolve to declared ConfigOptions; "
             "declared keys are shipped in conf/, documented in docs/, "
             "and type/default-consistent")
    established = "PR 4"

    def check(self, tree: RepoTree) -> List[Finding]:
        out: List[Finding] = []
        decls = collect_declarations(tree)
        conf_text = tree.read_text(CONF_FILE) or ""
        docs_text = self._docs_text(tree)
        perf_text = tree.read_text(PERF_DOC) or ""

        # -- read sites --------------------------------------------------
        for pm in tree.walk(SCAN_ROOT):
            sc = _ReadScanner(pm.relpath)
            sc.visit(pm.tree)
            for key, getter, default, line, qn in sc.reads:
                decl = decls.get(key)
                if decl is None:
                    out.append(Finding(
                        self.name, pm.relpath, line,
                        f"config key {key!r} read via .{getter}() has no "
                        f"declared ConfigOption — declare it (strict "
                        f"coercion, defaults registry, docs anchor all "
                        f"hang off the declaration)",
                        qn,
                    ))
                    continue
                if default is not None and decl.default_node is not None:
                    rv = _literal_value(default)
                    dv = _literal_value(decl.default_node)
                    if (
                        rv is not _NOT_LITERAL
                        and dv is not _NOT_LITERAL
                        and rv != dv
                    ):
                        out.append(Finding(
                            self.name, pm.relpath, line,
                            f"fallback {rv!r} for {key!r} contradicts "
                            f"the declared default {dv!r} "
                            f"({decl.path}:{decl.line}) — two sources "
                            f"of truth; align them",
                            qn,
                        ))

        # -- declarations ------------------------------------------------
        for key, decl in sorted(decls.items()):
            if not _mentions(conf_text, key):
                out.append(Finding(
                    self.name, decl.path, decl.line,
                    f"declared option {key!r} is missing from "
                    f"{CONF_FILE} — ship every key in the operator-"
                    f"facing catalog (a commented default line counts)",
                ))
            if not _mentions(docs_text, key):
                out.append(Finding(
                    self.name, decl.path, decl.line,
                    f"declared option {key!r} is not mentioned anywhere "
                    f"in docs/ — document the knob",
                ))
            self._check_default_type(decl, out)
            if (
                key.startswith(PERF_PREFIXES) or key in PERF_KEYS
            ) and not _mentions(perf_text, key):
                out.append(Finding(
                    self.name, decl.path, decl.line,
                    f"performance knob {key!r} is not mentioned in "
                    f"{PERF_DOC} — the perf doc's knob tables must "
                    f"cover it",
                ))

        # -- web-route-served keys must be documented --------------------
        web = tree.module(WEB_MODULE)
        if web is not None:
            sc = _ReadScanner(web.relpath)
            sc.visit(web.tree)
            for key, _getter, _default, line, qn in sc.reads:
                if key in decls and not _mentions(docs_text, key):
                    # already reported at the declaration; route-serving
                    # makes it worth anchoring at the handler too
                    out.append(Finding(
                        self.name, web.relpath, line,
                        f"web route serves config key {key!r} that docs/ "
                        f"never mentions — operators can see the knob "
                        f"but cannot look it up",
                        qn,
                    ))
        return out

    def _docs_text(self, tree: RepoTree) -> str:
        chunks = []
        if tree._virtual is not None:
            for rp in tree._virtual:
                if rp.startswith(DOCS_DIR + "/"):
                    chunks.append(tree.read_text(rp) or "")
        else:
            import os
            d = os.path.join(tree.root, DOCS_DIR)
            if os.path.isdir(d):
                for fn in sorted(os.listdir(d)):
                    if fn.endswith(".md"):
                        chunks.append(
                            tree.read_text(f"{DOCS_DIR}/{fn}") or ""
                        )
        return "\n".join(chunks)

    def _check_default_type(self, decl: Declaration, out: List[Finding]):
        if decl.default_node is None:
            return
        lit_t = _py_type_of_literal(decl.default_node)
        if lit_t is None:
            return
        declared_t = {
            "str": str, "int": int, "bool": bool, "float": float,
        }.get(decl.type_name or "")
        if declared_t is None:
            return
        ok = lit_t is declared_t or (declared_t is float and lit_t is int)
        if not ok:
            out.append(Finding(
                self.name, decl.path, decl.line,
                f"option {decl.key!r} declares type="
                f"{decl.type_name} but its default is a "
                f"{lit_t.__name__} — strict coercion will fight the "
                f"default; align them",
            ))
