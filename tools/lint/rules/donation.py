"""Rule ``donation``: no read of a variable after it was passed in a
donated position to a ``donate_argnums``-compiled function.

Every windowed step in runtime/step.py donates its state argument
(``@partial(jax.jit, donate_argnums=(0,))``): XLA updates the 100MB+
shard arrays in place instead of copy-on-write. The contract is that
the caller must NOT touch the old reference afterwards — a read
dereferences a deleted buffer and raises (or worse, on some backends,
silently reads garbage). The executor's recovery and snapshot paths
each tripped over this by hand before the rule existed (PR 5's
megastep-boundary work documents the discipline at length).

Detection is two-pass over the shared module cache:

  * Pass 1 collects "donated callables" across the scoped modules:
    functions compiled with ``donate_argnums`` (decorator or
    ``jax.jit(f, donate_argnums=...)`` call) and — the cross-module
    half — ``build_*`` factories in runtime/step.py whose returned
    inner function is donated (including the thin-wrapper case, e.g.
    build_window_update_step_exchange returning a plain wrapper around
    its donated ``_jit_step``).
  * Pass 2 walks each function in the scoped modules: a call through a
    resolvable donated callable (a local name or ``self.attr`` bound
    from a donated builder, or a directly-donated def) marks the plain
    ``Name`` passed at each donated position as DEAD; any later load of
    that name in the same function — by line order, with no intervening
    rebind — is a finding. ``state, aux = step(state, ...)`` is the
    sanctioned idiom: the assignment rebinds the name at the call line.

The analysis is deliberately straight-line (line-ordered within one
function body); it resolves the idioms this codebase actually uses and
is documented not to chase attribute aliasing. Established by PR 5;
unified here (ISSUE 9).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.lint.core import (
    Finding, RepoTree, Rule, dotted_name, functions_in,
)

SCOPE = (
    "flink_tpu/runtime/step.py",
    "flink_tpu/runtime/executor.py",
    "flink_tpu/runtime/dcn.py",
    "flink_tpu/cep/accel.py",
)

# module that owns the donated step factories (pass 1 cross-module map)
BUILDER_HOME = "flink_tpu/runtime/step.py"


def _donate_argnums_of(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """(argnums,) when ``call`` is jax.jit/partial(jax.jit, ...) with a
    donate_argnums constant, else None."""
    dn = dotted_name(call.func)
    is_jit = dn in ("jax.jit", "jit")
    if dn == "partial" and call.args and dotted_name(
            call.args[0]) in ("jax.jit", "jit"):
        is_jit = True
    if not is_jit:
        return None
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                nums = []
                for el in v.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                            el.value, int):
                        nums.append(el.value)
                return tuple(nums)
            return ()   # non-constant: donation exists, positions unknown
    return None


def _donated_defs(scope_tree: ast.AST) -> Dict[str, Tuple[int, ...]]:
    """{function name: donated argnums} for defs under ``scope_tree``
    whose decorators carry donate_argnums."""
    out: Dict[str, Tuple[int, ...]] = {}
    for node in ast.walk(scope_tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            if isinstance(dec, ast.Call):
                nums = _donate_argnums_of(dec)
                if nums:
                    out[node.name] = nums
    return out


def donated_builders(tree: RepoTree) -> Dict[str, Tuple[int, ...]]:
    """{builder name: donated argnums} for ``build_*`` factories in
    runtime/step.py that return a donated inner function (directly, or
    through a one-hop wrapper that forwards its first argument)."""
    pm = tree.module(BUILDER_HOME)
    if pm is None:
        return {}
    out: Dict[str, Tuple[int, ...]] = {}
    for node in pm.tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        inner = _donated_defs(node)
        if not inner:
            continue
        # inner defs by name, for the wrapper hop
        defs = {
            n.name: n for n in ast.walk(node)
            if isinstance(n, ast.FunctionDef) and n is not node
        }
        returned: Optional[str] = None
        for stmt in node.body:
            if isinstance(stmt, ast.Return) and isinstance(
                    stmt.value, ast.Name):
                returned = stmt.value.id
        if returned is None:
            continue
        if returned in inner:
            out[node.name] = inner[returned]
            continue
        wrapper = defs.get(returned)
        if wrapper is None or not wrapper.args.args:
            continue
        first_param = wrapper.args.args[0].arg
        for call in ast.walk(wrapper):
            if (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id in inner
                and call.args
                and isinstance(call.args[0], ast.Name)
                and call.args[0].id == first_param
            ):
                out[node.name] = inner[call.func.id]
                break
    return out


def donate_sites(tree: RepoTree) -> Dict[str, Tuple[str, int]]:
    """{builder name: (path, line)} — the ``donate_argnums`` source
    line (the decorated inner def's jit decorator) this rule attributes
    each donated builder's donation to. The trace tier's
    donation-effective rule stitches this into its findings' note field
    so one finding carries both tiers' evidence: the compiled alias
    table that is missing the leaf AND the source line that requested
    the donation."""
    pm = tree.module(BUILDER_HOME)
    if pm is None:
        return {}
    out: Dict[str, Tuple[str, int]] = {}
    for node in pm.tree.body:
        if not isinstance(node, ast.FunctionDef):
            continue
        for inner in ast.walk(node):
            if not isinstance(inner, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            for dec in inner.decorator_list:
                if isinstance(dec, ast.Call) and _donate_argnums_of(dec):
                    out.setdefault(node.name, (pm.relpath, dec.lineno))
    return out


def _local_donated_callables(mod_tree: ast.AST,
                             builders: Dict[str, Tuple[int, ...]],
                             ) -> Dict[str, Tuple[int, ...]]:
    """Names (and 'self.attr' paths) bound to donated callables in this
    module: donated defs, jax.jit(f, donate_argnums=...) assignments,
    and assignments from donated builders."""
    out: Dict[str, Tuple[int, ...]] = dict(_donated_defs(mod_tree))
    for node in ast.walk(mod_tree):
        if not (isinstance(node, ast.Assign)
                and isinstance(node.value, ast.Call)):
            continue
        call = node.value
        nums = _donate_argnums_of(call)
        if nums is None:
            callee = dotted_name(call.func)
            if callee is not None:
                nums = builders.get(callee.rsplit(".", 1)[-1])
        if not nums:
            continue
        for t in node.targets:
            dn = dotted_name(t)
            if dn:
                out[dn] = nums
    return out


def _walk_shallow(fn: ast.AST):
    """ast.walk limited to ONE function scope: does not descend into
    nested defs/lambdas (their reads/kills are analysed separately —
    a nonlocal donated name crossing scopes is beyond the straight-line
    contract and stays the author's responsibility)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class _NameEvents(ast.NodeVisitor):
    """All Name loads/stores in one function body (not nested defs)."""

    def __init__(self):
        self.loads: List[Tuple[str, int]] = []
        self.stores: List[Tuple[str, int]] = []

    def visit_Name(self, node: ast.Name):
        if isinstance(node.ctx, ast.Load):
            self.loads.append((node.id, node.lineno))
        else:
            self.stores.append((node.id, node.lineno))

    def visit_FunctionDef(self, node):
        pass          # nested defs are separate scopes

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


class DonationRule(Rule):
    name = "donation"
    title = ("no read of a variable after it was passed in a donated "
             "position to a donate_argnums-compiled function")
    established = "PR 5"

    def check(self, tree: RepoTree) -> List[Finding]:
        builders = donated_builders(tree)
        out: List[Finding] = []
        for pm in tree.walk(*SCOPE):
            donated = _local_donated_callables(pm.tree, builders)
            if not donated:
                continue
            for qn, fn in functions_in(pm.tree):
                out.extend(self._check_function(pm, qn, fn, donated))
        return out

    def _check_function(self, pm, qn, fn, donated) -> List[Finding]:
        # donating calls directly in this function body;
        # (name, call_start, call_end, callee) — a rebind anywhere from
        # the call statement's first line on revives the name (the
        # `state, aux = step(state, ...)` idiom may span lines)
        kills: List[Tuple[str, int, int, str]] = []
        for node in _walk_shallow(fn):
            if not isinstance(node, ast.Call):
                continue
            callee = dotted_name(node.func)
            if callee is None:
                continue
            nums = donated.get(callee)
            if not nums:
                continue
            for pos in nums:
                if pos < len(node.args) and isinstance(
                        node.args[pos], ast.Name):
                    kills.append((
                        node.args[pos].id,
                        node.lineno,
                        getattr(node, "end_lineno", node.lineno),
                        callee,
                    ))
        if not kills:
            return []
        ev = _NameEvents()
        for stmt in fn.body:
            ev.visit(stmt)
        out: List[Finding] = []
        for name, kstart, kend, callee in kills:
            revive = [ln for n, ln in ev.stores
                      if n == name and ln >= kstart]
            for n, ln in ev.loads:
                if n != name or ln <= kend:
                    continue
                if any(r <= ln for r in revive):
                    continue
                out.append(Finding(
                    self.name, pm.relpath, ln,
                    f"{name!r} read after being DONATED to {callee!r} "
                    f"(line {kstart}) — the buffer is invalidated by "
                    f"donate_argnums; rebind the result or snapshot "
                    f"before the call",
                    qn,
                ))
                break   # one finding per (kill, name) is enough
        return out
