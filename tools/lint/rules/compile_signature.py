"""Rule ``compile-signature``: each kernel family's abstract input
signature matches the checked-in signature ledger.

A jitted step compiles ONCE per abstract signature. The recompile-storm
bug class (the retrace rule's runtime sibling): a refactor changes a
batch operand's dtype on one call path, or a megastep's flat-args
packing, and the "same" step silently becomes two executables — every
flip between them is a multi-second trace+compile in the dispatch loop.
The AST retrace rule catches compiles written inside loops; this rule
pins WHAT each canonical family compiles against:
``tools/lint/ledgers/signatures.json`` records the comma-joined
``aval.str_short()`` of every flattened input leaf (human-readable, so
the ledger diff in review shows exactly which leaf moved — f32[8] ->
f32[16] — not just a hash; the sha256 digest rides along for compact
comparison in CI output).

A signature change is sometimes right (you resized the canonical grid,
added a state plane) — record it with ``--update-ledger`` so the diff
is reviewed next to the code. Not suppressible, same reasoning as
op-budget: the ledger is the escape hatch.
"""

from __future__ import annotations

from typing import Dict, List

from tools.lint.core import Finding, LintInternalError, RepoTree, Rule
from tools.lint.kernel_audit import get_audit, load_ledger, write_ledger

LEDGER_PATH = "tools/lint/ledgers/signatures.json"


def _first_diff(a: str, b: str) -> str:
    """Human pointer at the first differing leaf of two signatures."""
    la, lb = a.split(","), b.split(",")
    for i, (x, y) in enumerate(zip(la, lb)):
        if x != y:
            return f"leaf {i}: {x} -> {y}"
    if len(la) != len(lb):
        return (f"leaf count {len(la)} -> {len(lb)} (extra: "
                f"{(la + lb)[min(len(la), len(lb))]})")
    return "identical leaves in different order"


class CompileSignatureRule(Rule):
    name = "compile-signature"
    title = ("each kernel family's abstract input signature matches the "
             "signature ledger (no accidental recompile-storm splits)")
    established = "PR 10"
    tier = "trace"
    suppressible = False
    update_ledger = False     # set by the CLI's --update-ledger flag

    def check(self, tree: RepoTree) -> List[Finding]:
        audit = get_audit(tree)
        if audit is None:
            return []
        actual: Dict[str, Dict[str, str]] = {
            name: {"digest": tr.digest, "signature": tr.signature}
            for name, tr in audit.traces.items()
        }
        if self.update_ledger:
            if tree.root is None:
                raise LintInternalError(
                    "--update-ledger needs a disk tree to write to")
            write_ledger(tree.root, LEDGER_PATH, {"families": actual})
            return []
        out: List[Finding] = []
        data = load_ledger(tree, LEDGER_PATH)
        if data is None:
            out.append(Finding(
                self.name, LEDGER_PATH, 1,
                f"signature ledger missing — generate it with "
                f"'python -m tools.lint --rule {self.name} "
                f"--update-ledger' and commit it",
            ))
            return out
        ledger: Dict[str, Dict[str, str]] = data.get("families", {})
        for name in sorted(set(actual) | set(ledger)):
            if name not in ledger:
                tr = audit.traces[name]
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r} has no recorded compile "
                    f"signature — record it (--update-ledger) so an "
                    f"accidental signature split is caught",
                    tr.builder or "<family>",
                ))
                continue
            if name not in actual:
                out.append(Finding(
                    self.name, LEDGER_PATH, 1,
                    f"signature ledger lists unknown kernel family "
                    f"{name!r} — stale entry (or a hand edit without "
                    f"--update-ledger); regenerate the ledger",
                ))
                continue
            want = ledger[name].get("signature", "")
            got = actual[name]["signature"]
            if want != got:
                tr = audit.traces[name]
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r} abstract signature changed "
                    f"({ledger[name].get('digest', '?')} -> "
                    f"{actual[name]['digest']}; {_first_diff(want, got)})"
                    f" — a call-path disagreeing with the recorded "
                    f"signature means a second compile of the same step "
                    f"(recompile storm); if the new signature is the "
                    f"design, rerun with --update-ledger",
                    tr.builder or "<family>",
                ))
        return out
