"""Rule ``hot-path-sync``: no host synchronization in hot-path modules.

The step loop's whole performance story is that update steps dispatch
asynchronously and nothing reads device memory between barriers — a
single stray ``block_until_ready()``, ``.item()``, or
``np.asarray(<device array>)`` in a kernel or step builder serializes
the pipeline and costs a fixed ~70ms tunnel round trip per call on the
TPU runtime (ISSUE 2; tools/microbench_d2h.py measured it). This rule
fails the build when one of those host-sync constructs appears in the
hot-path modules:

    flink_tpu/ops/**.py          (device kernels)
    flink_tpu/runtime/step.py    (compiled step builders)
    flink_tpu/runtime/ingest.py  (pipelined ingest / device staging)
    flink_tpu/runtime/elastic.py (elastic re-plan helpers)

outside an allowlisted barrier section.

Round 12 (resident drain loop) extends the detected constructs: the
drain's host sections — ring publish/release in ingest.py and the
drain-group assembly feeding ``build_window_resident_drain`` — must
stay sync-free for the one-dispatch-per-ring-drain story to hold, so
``jax.device_get(...)`` (the D2H fetch a stray eager fire consumption
would spell) and ``np.array(<device array>)`` (materializes like
``np.asarray``) now flag alongside the original three. The staging
ring's transfer-completion wait keeps its inline marker: it blocks on
the INGEST thread by design, never the step loop.

Allowlisting, in order of preference:

  1. Naming convention — functions whose name contains ``host`` or ends
     with ``_np`` are host-side by contract (hash64_host, estimate_np,
     ...); the hot path never calls them per step.
  2. The explicit ALLOWLIST below — (relative path, function qualname)
     pairs for documented host-facing APIs that don't fit the naming
     convention (e.g. segment.grouped_reduce, the batch DataSet seam).
  3. An inline ``# host-sync-ok: <reason>`` comment on the flagged line
     for true one-offs (the pre-framework spelling, kept so existing
     markers stay honored) — or the framework-wide
     ``# lint: allow(hot-path-sync): <reason>``.

Detection is AST-based (not grep) so strings/comments can't false-
positive and aliasing ``numpy as np`` is resolved per call site.

Migrated from tools/check_hot_path_sync.py (ISSUE 2) into the shared
framework (ISSUE 9) without weakening: same constructs, same paths,
same allowlists. tools/check_hot_path_sync.py remains as a thin shim.
"""

from __future__ import annotations

import os
from typing import List, NamedTuple, Tuple

import ast

from tools.lint.core import Finding, QualnameVisitor, RepoTree, Rule

# hot-path locations, relative to the repo root
HOT_PATHS = (
    "flink_tpu/ops",
    "flink_tpu/runtime/step.py",
    "flink_tpu/runtime/ingest.py",
    # elastic re-plan helpers (ISSUE 8): imported by the executor's
    # recovery path; the one legitimate wait — the recovery-path device
    # health probe — carries the inline marker
    "flink_tpu/runtime/elastic.py",
    # drain flight-recorder host half (ISSUE 14): the consume-path
    # unpack target. Its whole contract is pure host arithmetic over
    # ALREADY-FETCHED numpy payloads — the lagged telemetry channel must
    # never introduce a fresh device sync, so the module is held to the
    # same standard as the kernels it observes. (The publish-time stamps
    # stay inside ingest.py's two allowlisted ingest-thread blocks.)
    "flink_tpu/metrics/drain_stats.py",
    # pipeline doctor rule engine (ISSUE 17): pure dict arithmetic over
    # already-assembled snapshots — a rule that synced the device would
    # turn a diagnostics scrape into a pipeline stall, so the module is
    # held to hot-path discipline alongside drain_stats.py
    "flink_tpu/metrics/doctor.py",
    # stage-graph planner (ISSUE 16): setup-time only, but its plan
    # products (specs, codecs, snapshot/restore payloads) feed the
    # chained drain directly — hold it to hot-path discipline so no
    # per-drain device sync sneaks in through a planner helper
    "flink_tpu/runtime/stages.py",
    # self-tuning runtime controller (ISSUE 19): serviced at the poll-
    # cycle boundary on the step-loop thread — its whole contract is
    # host-side arithmetic over ALREADY-FETCHED telemetry (regime/heat
    # EWMAs, doctor findings), so a device sync in a decision would
    # stall the very pipeline it tunes
    "flink_tpu/runtime/controller.py",
)

# hot SECTIONS (ISSUE 20b): function-scoped coverage for modules that
# are legitimately host-heavy overall but contain drain-boundary code
# held to hot-path discipline. dcn.py is a host control loop — its
# lockstep poll/pad path syncs freely by design — but the per-host
# RESIDENT drain boundary multiplies every stray sync by the drain
# depth, so the new boundary functions are scanned with the same rule;
# their few legitimate barriers (the stop/drained fetch, the fire-
# payload unpack, the source-poll timestamp math) carry inline
# ``# host-sync-ok:`` markers documenting WHY each one is a boundary.
HOT_SECTIONS = {
    "flink_tpu/runtime/dcn.py": (
        "_RebalanceRing._frame_deadline_s",
        "_DCNRunnerBase._poll_chunk",
        "_DCNRunnerBase._run_resident",
        "_DCNRunnerBase._gslots",
        "DCNWindowRunner._emit_local_slots",
    ),
}

# documented host-facing seams that live in hot-path modules but are
# never called from inside the step loop
ALLOWLIST: set = {
    # host-side key encode: runs in prep_batch on numpy inputs
    ("flink_tpu/ops/hashing.py", "splitmix64"),
    ("flink_tpu/ops/hashing.py", "key_identity64"),
    # batch DataSet/Table aggregation API: documented to return numpy
    ("flink_tpu/ops/segment.py", "grouped_reduce"),
    # sketch host mirrors: query-path estimates over fetched registers
    ("flink_tpu/ops/sketches.py", "CountMinSketch.__init__"),
    ("flink_tpu/ops/sketches.py", "_numeric"),
}

SYNC_ATTRS = ("block_until_ready", "item")
INLINE_MARKER = "host-sync-ok"


class Violation(NamedTuple):
    path: str
    line: int
    func: str
    what: str

    def __str__(self):
        return (f"{self.path}:{self.line}: {self.what} in {self.func!r} "
                f"— host sync on the hot path (allowlist it only if this "
                f"is a documented barrier section; see "
                f"tools/lint/rules/hot_path_sync.py)")


def _is_np_asarray(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr in ("asarray", "array")
        and isinstance(f.value, ast.Name)
        and f.value.id in ("np", "numpy")
    )


def _is_device_get(call: ast.Call) -> bool:
    f = call.func
    return (
        isinstance(f, ast.Attribute)
        and f.attr == "device_get"
        and isinstance(f.value, ast.Name)
        and f.value.id == "jax"
    )


def _is_sync_attr(call: ast.Call) -> bool:
    f = call.func
    return isinstance(f, ast.Attribute) and f.attr in SYNC_ATTRS


class _Scanner(QualnameVisitor):
    def __init__(self, relpath: str, lines: List[str], sections=None):
        super().__init__()
        self.relpath = relpath
        self.lines = lines
        self.sections = sections   # qualname prefixes, or None = whole file
        self.out: List[Violation] = []

    def _in_section(self) -> bool:
        if self.sections is None:
            return True
        qn = self.qualname()
        return any(qn == s or qn.startswith(s + ".")
                   for s in self.sections)

    def _allowed(self, node: ast.Call) -> bool:
        qn = self.qualname()
        # naming convention: host-side helpers
        for part in self.stack:
            if "host" in part or part.endswith("_np"):
                return True
        if (self.relpath, qn) in ALLOWLIST:
            return True
        line = (
            self.lines[node.lineno - 1]
            if 0 < node.lineno <= len(self.lines) else ""
        )
        return INLINE_MARKER in line

    def visit_Call(self, node: ast.Call):
        what = None
        if _is_sync_attr(node):
            what = f".{node.func.attr}()"
        elif _is_np_asarray(node):
            what = f"np.{node.func.attr}(...)"
        elif _is_device_get(node):
            what = "jax.device_get(...)"
        if what is not None and self._in_section() \
                and not self._allowed(node):
            self.out.append(Violation(
                self.relpath, node.lineno, self.qualname(), what
            ))
        self.generic_visit(node)


def check_source(src: str, relpath: str) -> List[Violation]:
    tree = ast.parse(src, filename=relpath)
    sc = _Scanner(relpath, src.splitlines())
    sc.visit(tree)
    return sc.out


def hot_path_files(root: str) -> List[Tuple[str, str]]:
    """[(abs_path, rel_path)] of every hot-path module under `root`."""
    out = []
    for hp in HOT_PATHS:
        full = os.path.join(root, hp)
        if os.path.isfile(full):
            out.append((full, hp))
        elif os.path.isdir(full):
            for dirpath, _dirs, files in os.walk(full):
                for f in sorted(files):
                    if not f.endswith(".py"):
                        continue
                    p = os.path.join(dirpath, f)
                    out.append((p, os.path.relpath(p, root)))
    return out


def check_tree(root: str) -> List[Violation]:
    violations: List[Violation] = []
    for path, rel in hot_path_files(root):
        with open(path) as f:
            violations.extend(
                check_source(f.read(), rel.replace(os.sep, "/"))
            )
    for rel, sections in HOT_SECTIONS.items():
        full = os.path.join(root, rel)
        if not os.path.isfile(full):
            continue
        with open(full) as f:
            src = f.read()
        sc = _Scanner(rel, src.splitlines(), sections=sections)
        sc.visit(ast.parse(src, filename=rel))
        violations.extend(sc.out)
    return violations


class HotPathSyncRule(Rule):
    name = "hot-path-sync"
    title = ("no block_until_ready/.item()/np.asarray/np.array/"
             "jax.device_get host sync in hot-path modules outside "
             "allowlisted barrier sections")
    established = "PR 2"

    def check(self, tree: RepoTree) -> List[Finding]:
        out: List[Finding] = []
        for pm in tree.walk(*HOT_PATHS):
            sc = _Scanner(pm.relpath, pm.lines)
            sc.visit(pm.tree)
            out.extend(
                Finding(self.name, v.path, v.line, str(v), v.func)
                for v in sc.out
            )
        for rel, sections in HOT_SECTIONS.items():
            for pm in tree.walk(rel):
                sc = _Scanner(pm.relpath, pm.lines, sections=sections)
                sc.visit(pm.tree)
                out.extend(
                    Finding(self.name, v.path, v.line, str(v), v.func)
                    for v in sc.out
                )
        return out


def main(argv=None) -> int:
    """Back-compat CLI (tools/check_hot_path_sync.py)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        description="Static check: no host synchronization in hot-path "
                    "modules.")
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))),
    )
    args = ap.parse_args(argv)
    violations = check_tree(args.root)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} hot-path host-sync violation(s)",
              file=sys.stderr)
        return 1
    return 0
