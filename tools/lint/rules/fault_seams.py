"""Rule ``fault-seam``: raw IO in the durability layers sits within or
adjacent to a ``faults.inject`` point.

The chaos soak (PR 4) and the MTTR drill (PR 6) assert exactly-once
UNDER injected faults — but they can only reach the failure modes that
have a ``faults.inject("<point>")`` seam in front of them. A raw
``open``/``os.replace``/socket call added to the checkpoint or DCN
path without a seam silently shrinks the soak's reach: the new IO can
fail in production in a way no test can schedule. This rule pins the
seam coverage:

Every raw IO call (builtin ``open``, ``os.replace``/``os.rename``,
socket ``send``/``sendall``/``recv``/``recv_into``/``sendto``/
``recvfrom``) inside ``flink_tpu/checkpointing/`` or
``flink_tpu/runtime/dcn.py`` must be

  * in a function that contains a ``faults.inject(...)`` call (the
    seam guards the whole operation), or
  * in a helper whose intra-module callers ALL contain one (the
    ``_send_all`` pattern: the seam fires once per frame at the call
    site, outside the retry-slice loop), or
  * suppressed with a reasoned ``# lint: allow(fault-seam): ...`` for
    IO that is genuinely outside the fault story (e.g. a CLI's final
    result dump).

Established by PR 4 (failure containment); unified here (ISSUE 9).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.lint.core import (
    Finding, RepoTree, Rule, dotted_name, functions_in,
)

SCOPE = (
    "flink_tpu/checkpointing",
    "flink_tpu/runtime/dcn.py",
)

SOCKET_ATTRS = {
    "send", "sendall", "sendto", "recv", "recv_into", "recvfrom",
    "recvmsg", "sendmsg",
}
OS_IO = {"os.replace", "os.rename"}


def _io_call(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Name) and f.id == "open":
        return "open(...)"
    dn = dotted_name(f)
    if dn in OS_IO:
        return f"{dn}(...)"
    if isinstance(f, ast.Attribute) and f.attr in SOCKET_ATTRS:
        return f".{f.attr}(...)"
    return None


def _has_inject(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            dn = dotted_name(node.func)
            if dn is not None and (
                dn == "inject" or dn.endswith(".inject")
            ):
                return True
    return False


class FaultSeamRule(Rule):
    name = "fault-seam"
    title = ("raw IO in checkpointing/ and runtime/dcn.py is covered by "
             "a faults.inject seam (directly or at every call site)")
    established = "PR 4"

    def check(self, tree: RepoTree) -> List[Finding]:
        out: List[Finding] = []
        for pm in tree.walk(*SCOPE):
            funcs = functions_in(pm.tree)
            inject_by_name: Dict[str, bool] = {}
            callers: Dict[str, List[str]] = {}
            for qn, fn in funcs:
                short = qn.rsplit(".", 1)[-1]
                inject_by_name[short] = (
                    inject_by_name.get(short, False) or _has_inject(fn)
                )
            for qn, fn in funcs:
                caller = qn.rsplit(".", 1)[-1]
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call):
                        dn = dotted_name(node.func)
                        if dn is None:
                            continue
                        callee = dn.rsplit(".", 1)[-1]
                        if callee in inject_by_name and callee != caller:
                            callers.setdefault(callee, []).append(caller)

            # innermost enclosing function per IO call
            spans: List[Tuple[str, ast.AST]] = funcs
            for node in ast.walk(pm.tree):
                if not isinstance(node, ast.Call):
                    continue
                what = _io_call(node)
                if what is None:
                    continue
                qn, fn = self._innermost(spans, node)
                if fn is not None and _has_inject(fn):
                    continue
                short = qn.rsplit(".", 1)[-1] if fn is not None else None
                if short is not None:
                    cs = callers.get(short, [])
                    if cs and all(inject_by_name.get(c, False)
                                  for c in cs):
                        continue
                out.append(Finding(
                    self.name, pm.relpath, node.lineno,
                    f"raw IO {what} in {qn if fn is not None else '<module>'!r} "
                    f"has no faults.inject seam within or adjacent — the "
                    f"chaos soak cannot schedule this failure; add a "
                    f"named injection point (see "
                    f"flink_tpu/testing/faults.py catalog) or suppress "
                    f"with a reason",
                    qn if fn is not None else "<module>",
                ))
        return out

    @staticmethod
    def _innermost(spans, node) -> Tuple[str, Optional[ast.AST]]:
        best_qn, best_fn, best_size = "<module>", None, None
        for qn, fn in spans:
            start = fn.lineno
            end = getattr(fn, "end_lineno", start)
            if start <= node.lineno <= end:
                size = end - start
                if best_size is None or size < best_size:
                    best_qn, best_fn, best_size = qn, fn, size
        return best_qn, best_fn
