"""Rule ``donation-effective``: every donated state arg must actually
alias in the compiled program.

The AST ``donation`` rule polices the CALLER side of the contract (no
read-after-donate); this rule closes the loop on the CALLEE side: a
``donate_argnums`` annotation is a *request*, and XLA silently falls
back to a copy whenever it cannot alias the buffer (output
shape/dtype/layout mismatch, an output that still reads the input,
backend refusal). A donated-but-copied 100MB+ state shard doubles the
step's HBM traffic and nobody notices — the program is still correct,
just slow, which is exactly the regression class this trace tier exists
to catch.

Evidence (tools/lint/kernel_audit.py): every donated leaf of every
canonical kernel family must appear in the LOWERED StableHLO
input/output alias table (``tf.aliasing_output`` on the ``@main``
params — an unusable donation drops out of this table at lower time);
the ``deep`` representative families are additionally COMPILED and
checked against the executable's ``input_output_alias`` table (what XLA
actually kept). Each finding carries a cross-tier note naming the
``donate_argnums`` source line the AST donation rule attributes the
builder's donation to — one finding, both tiers' evidence.
"""

from __future__ import annotations

from typing import List

from tools.lint.core import Finding, RepoTree, Rule
from tools.lint.kernel_audit import get_audit
from tools.lint.rules.donation import donate_sites


class DonationEffectiveRule(Rule):
    name = "donation-effective"
    title = ("every donated kernel state arg aliases in the lowered "
             "(and, for deep families, compiled) program")
    established = "PR 10"
    tier = "trace"

    def check(self, tree: RepoTree) -> List[Finding]:
        audit = get_audit(tree)
        if audit is None:
            return []
        sites = donate_sites(tree)
        out: List[Finding] = []
        for name in sorted(audit.traces):
            tr = audit.traces[name]
            if not tr.donated:
                continue
            rep = audit.donation_report(name)
            site = sites.get(tr.builder)
            note = (f"AST donation rule attributes this donate to "
                    f"{site[0]}:{site[1]} ({tr.builder})" if site else "")
            for leaf in rep["missing_lowered"]:
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r}: donated leaf {leaf} is "
                    f"absent from the lowered input/output alias table "
                    f"— XLA will COPY this buffer every step "
                    f"(donate_argnums was requested but is not usable; "
                    f"check output shapes/dtypes against the donated "
                    f"input)",
                    tr.builder or "<family>", note,
                ))
            for leaf in rep["dropped_by_executable"]:
                out.append(Finding(
                    self.name, tr.path, tr.line,
                    f"kernel family {name!r}: donated leaf {leaf} "
                    f"aliased at lower time but the compiled "
                    f"executable's input_output_alias table dropped it "
                    f"— the compiler decided it must copy",
                    tr.builder or "<family>", note,
                ))
        return out
