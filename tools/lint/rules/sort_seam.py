"""Rule ``sort-seam``: device sorts in ops/ live ONLY in segment.py.

The update kernel's pre-combine design is "pay ONE sort per micro-batch
and feed every consumer from it": the accumulator scatter, the fire-
eligibility (touched) plane, the kg_dirty changelog bits, and the
kg_fill skew telemetry all ride the single ``segment.segment_sort``
permutation (window_kernels.update; ISSUE 7). A sort is the most
expensive reordering primitive the kernels use — XLA's CPU sort costs
~4.5ms per 16k lanes, and on TPU it is the whole pre-combine budget —
so a second sort quietly added to a kernel doubles exactly the cost the
shared-sort seam exists to pay once.

This rule fails the build when a sort primitive (``jnp.sort`` /
``jnp.argsort`` / ``jnp.lexsort`` / ``jax.lax.sort`` /
``jax.lax.sort_key_val``, under any of the conventional module aliases)
appears in ``flink_tpu/ops`` outside ``segment.py``. Kernels order
lanes through the segment.py wrappers instead (``segment_sort``,
``sort_values``, ``argsort_ids``, ``invert_permutation``), which keeps
every sort call site greppable in one file and the one-sort-per-batch
contract reviewable at the seam.

There is deliberately NO escape hatch — not the inline marker, and not
the framework's ``# lint: allow`` either (``suppressible = False``): a
new sort in a kernel is a design decision that belongs in segment.py,
not an annotation.

Migrated from tools/check_segment_sort_seam.py (ISSUE 7) into the
shared framework (ISSUE 9) without weakening. The old path remains as
a thin shim.
"""

from __future__ import annotations

import os
from typing import List, NamedTuple, Optional, Tuple

import ast

from tools.lint.core import Finding, QualnameVisitor, RepoTree, Rule

# the scanned tree and the one file sorts may live in
OPS_PATH = "flink_tpu/ops"
SORT_HOME = "flink_tpu/ops/segment.py"

# sort primitives by attribute name; the owning module alias is checked
# against the conventional jax/jnp/lax spellings so dict.sort() false
# positives (list.sort is a bare Name call anyway) cannot fire
SORT_ATTRS = ("sort", "argsort", "lexsort", "sort_key_val", "top_k")
SORT_MODULES = ("jnp", "jax", "lax", "numpy", "np")


class Violation(NamedTuple):
    path: str
    line: int
    func: str
    what: str

    def __str__(self):
        return (
            f"{self.path}:{self.line}: {self.what} in {self.func!r} — "
            f"device sorts in ops/ belong in segment.py (the one-sort "
            f"pre-combine seam; see tools/lint/rules/sort_seam.py)"
        )


def _sort_call(call: ast.Call) -> Optional[str]:
    """Return 'mod.attr' when this call is a sort primitive, else None."""
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in SORT_ATTRS:
        return None
    v = f.value
    # jnp.sort / np.argsort
    if isinstance(v, ast.Name) and v.id in SORT_MODULES:
        return f"{v.id}.{f.attr}"
    # jax.lax.sort / jax.numpy.argsort
    if (
        isinstance(v, ast.Attribute)
        and isinstance(v.value, ast.Name)
        and v.value.id in SORT_MODULES
    ):
        return f"{v.value.id}.{v.attr}.{f.attr}"
    return None


class _Scanner(QualnameVisitor):
    def __init__(self, relpath: str):
        super().__init__()
        self.relpath = relpath
        self.out: List[Violation] = []

    def visit_Call(self, node: ast.Call):
        what = _sort_call(node)
        if what is not None:
            self.out.append(
                Violation(self.relpath, node.lineno, self.qualname(), what)
            )
        self.generic_visit(node)


def check_source(src: str, relpath: str) -> List[Violation]:
    if relpath.replace(os.sep, "/") == SORT_HOME:
        return []
    tree = ast.parse(src, filename=relpath)
    sc = _Scanner(relpath.replace(os.sep, "/"))
    sc.visit(tree)
    return sc.out


def ops_files(root: str) -> List[Tuple[str, str]]:
    """[(abs_path, rel_path)] of every module under flink_tpu/ops."""
    out = []
    full = os.path.join(root, OPS_PATH)
    for dirpath, _dirs, files in os.walk(full):
        for f in sorted(files):
            if f.endswith(".py"):
                p = os.path.join(dirpath, f)
                out.append((p, os.path.relpath(p, root)))
    return out


def check_tree(root: str) -> List[Violation]:
    violations: List[Violation] = []
    for path, rel in ops_files(root):
        with open(path) as f:
            violations.extend(check_source(f.read(), rel))
    return violations


class SortSeamRule(Rule):
    name = "sort-seam"
    title = ("jnp/lax sort primitives in flink_tpu/ops appear only in "
             "segment.py — the one-sort pre-combine seam")
    established = "PR 5"
    suppressible = False   # a new sort is a design decision, not an allow

    def check(self, tree: RepoTree) -> List[Finding]:
        out: List[Finding] = []
        for pm in tree.walk(OPS_PATH):
            if pm.relpath == SORT_HOME:
                continue
            sc = _Scanner(pm.relpath)
            sc.visit(pm.tree)
            out.extend(
                Finding(self.name, v.path, v.line, str(v), v.func)
                for v in sc.out
            )
        return out


def main(argv=None) -> int:
    """Back-compat CLI (tools/check_segment_sort_seam.py)."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        description="Static check: device sorts in ops/ live ONLY in "
                    "segment.py.")
    ap.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))),
    )
    args = ap.parse_args(argv)
    violations = check_tree(args.root)
    for v in violations:
        print(v, file=sys.stderr)
    if violations:
        print(f"{len(violations)} ops/ sort-seam violation(s)",
              file=sys.stderr)
        return 1
    return 0
