"""Rule ``thread-state``: state mutated from the named background
threads is lock-covered or registered single-writer.

The runtime keeps four long-lived background threads next to the step
loop — the ingest producer (runtime/ingest.py), the checkpoint
materializer (checkpointing/materializer.py), the watchdog monitor
(runtime/watchdog.py), and the web monitor's handler threads
(runtime/web.py). PR 3's KeyCodec-lock and mon_watch-deque bugs were
both the same shape: an attribute the step loop reads, quietly mutated
from one of those threads with nothing declaring the discipline. This
rule makes the discipline structural:

Every ``self.<attr>`` mutation (assign, augmented assign, subscript
store/delete, or a known mutator-method call like ``.append``/
``.pop``) reachable from a background-thread entry point must be

  * lexically inside ``with self.<lock>:`` where ``<lock>`` is an
    attribute the module assigns from ``threading.Lock/RLock/
    Condition/Semaphore`` — auto-detected, no annotation needed; or
  * a call on an attribute that IS a synchronization/queue primitive
    (``threading.Event``, ``queue.Queue`` — their methods are the
    sanctioned cross-thread mechanism); or
  * registered in :data:`flink_tpu.runtime.thread_state.SHARED_STATE`
    (parsed as a literal — the linter never imports runtime code) as
    ``single-writer:<thread>`` or ``locked-by-caller:<lock>`` with a
    reason.

Thread entry points are found structurally: ``threading.Thread(
target=self.X)`` in a scoped module makes method ``X`` (plus every
same-class method it transitively calls through ``self``) background-
thread code; ``do_GET``-style handler methods are web-thread entries.
The analysis is self-attribute-scoped by design — mutations through
local aliases or foreign objects are out of reach and the registry
documents the contract for those.

Established by PR 3 (ingest pipelining); unified here (ISSUE 9).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.lint.core import Finding, RepoTree, Rule, dotted_name

SCOPE = (
    "flink_tpu/runtime/ingest.py",
    "flink_tpu/runtime/watchdog.py",
    "flink_tpu/runtime/web.py",
    "flink_tpu/checkpointing/materializer.py",
)

REGISTRY_MODULE = "flink_tpu/runtime/thread_state.py"
REGISTRY_NAME = "SHARED_STATE"

HANDLER_ENTRIES = {
    "do_GET", "do_POST", "do_PUT", "do_DELETE", "do_PATCH", "do_HEAD",
    "log_message", "handle_one_request",
}

MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "clear", "update",
    "setdefault", "sort", "reverse", "put_nowait",
}

SYNC_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
    "threading.Event", "queue.Queue", "queue.SimpleQueue",
    "queue.LifoQueue", "queue.PriorityQueue",
}
LOCK_FACTORIES = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "threading.Semaphore", "threading.BoundedSemaphore",
}


def load_registry(tree: RepoTree) -> Dict[str, str]:
    """SHARED_STATE from the annotation registry module, parsed as an
    AST literal — {'Class.attr': 'policy — reason'}."""
    pm = tree.module(REGISTRY_MODULE)
    if pm is None:
        return {}
    for node in pm.tree.body:
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == REGISTRY_NAME:
                    try:
                        v = ast.literal_eval(node.value)
                    except (ValueError, TypeError, SyntaxError):
                        return {}
                    if isinstance(v, dict):
                        return {str(k): str(val) for k, val in v.items()}
    return {}


class _ClassInfo:
    def __init__(self, name: str):
        self.name = name
        self.methods: Dict[str, ast.FunctionDef] = {}
        self.sync_attrs: Set[str] = set()    # Event/Queue/Lock/... attrs
        self.lock_attrs: Set[str] = set()    # with-able lock attrs
        self.thread_entries: Set[str] = set()


def _self_attr(node: ast.AST) -> Optional[str]:
    """'attr' when node is ``self.attr``, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _collect_classes(mod_tree: ast.AST) -> List[_ClassInfo]:
    out: List[_ClassInfo] = []
    for node in ast.walk(mod_tree):
        if not isinstance(node, ast.ClassDef):
            continue
        ci = _ClassInfo(node.name)
        for sub in ast.walk(node):
            if isinstance(sub, ast.FunctionDef):
                ci.methods.setdefault(sub.name, sub)
            # self.X = threading.Lock() / Event() / queue.Queue()
            if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, ast.Call):
                factory = dotted_name(sub.value.func)
                for t in sub.targets:
                    attr = _self_attr(t)
                    if attr is None or factory is None:
                        continue
                    if factory in SYNC_FACTORIES:
                        ci.sync_attrs.add(attr)
                    if factory in LOCK_FACTORIES:
                        ci.lock_attrs.add(attr)
            # threading.Thread(target=self.X)
            if isinstance(sub, ast.Call) and dotted_name(
                    sub.func) == "threading.Thread":
                for kw in sub.keywords:
                    if kw.arg == "target":
                        attr = _self_attr(kw.value)
                        if attr is not None:
                            ci.thread_entries.add(attr)
        for mname in ci.methods:
            if mname in HANDLER_ENTRIES:
                ci.thread_entries.add(mname)
        out.append(ci)
    return out


def _thread_reachable(ci: _ClassInfo) -> Dict[str, str]:
    """{method name: entry it is reachable from} via self.X() calls."""
    reach: Dict[str, str] = {}
    work = [(e, e) for e in ci.thread_entries if e in ci.methods]
    while work:
        mname, entry = work.pop()
        if mname in reach:
            continue
        reach[mname] = entry
        body = ci.methods[mname]
        for node in ast.walk(body):
            if isinstance(node, ast.Call):
                attr = _self_attr(node.func)
                if attr is not None and attr in ci.methods:
                    work.append((attr, entry))
    return reach


class _MutationScanner(ast.NodeVisitor):
    """Mutations of self attributes in one method, with lock coverage."""

    def __init__(self, ci: _ClassInfo):
        self.ci = ci
        self.lock_depth = 0
        # (attr, lineno, kind, covered_by_with_lock)
        self.out: List[Tuple[str, int, str, bool]] = []

    def visit_With(self, node):
        covers = any(
            _self_attr(item.context_expr) in self.ci.lock_attrs
            for item in node.items
        )
        if covers:
            self.lock_depth += 1
        self.generic_visit(node)
        if covers:
            self.lock_depth -= 1

    def _rec(self, attr: str, lineno: int, kind: str):
        self.out.append((attr, lineno, kind, self.lock_depth > 0))

    def visit_Assign(self, node):
        for t in node.targets:
            self._target(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._target(node.target, node.lineno, kind="augmented assign")
        self.generic_visit(node)

    def visit_Delete(self, node):
        for t in node.targets:
            self._target(t, node.lineno, kind="delete")
        self.generic_visit(node)

    def _target(self, t: ast.AST, lineno: int, kind: str = "assign"):
        attr = _self_attr(t)
        if attr is not None:
            self._rec(attr, lineno, kind)
            return
        # self.attr[i] = ... / del self.attr[i]
        if isinstance(t, ast.Subscript):
            attr = _self_attr(t.value)
            if attr is not None:
                self._rec(attr, lineno, f"subscript {kind}")

    def visit_Call(self, node):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in MUTATOR_METHODS:
            attr = _self_attr(f.value)
            if attr is not None and attr not in self.ci.sync_attrs:
                self._rec(attr, node.lineno, f".{f.attr}() call")
        self.generic_visit(node)

    def visit_FunctionDef(self, node):
        pass          # nested defs: separate analysis scope

    visit_AsyncFunctionDef = visit_FunctionDef


class ThreadStateRule(Rule):
    name = "thread-state"
    title = ("attributes mutated from the ingest/materializer/watchdog/"
             "web threads are lock-covered or registered single-writer")
    established = "PR 3"

    def check(self, tree: RepoTree) -> List[Finding]:
        registry = load_registry(tree)
        out: List[Finding] = []
        for pm in tree.walk(*SCOPE):
            for ci in _collect_classes(pm.tree):
                reach = _thread_reachable(ci)
                for mname, entry in reach.items():
                    sc = _MutationScanner(ci)
                    for stmt in ci.methods[mname].body:
                        sc.visit(stmt)
                    for attr, lineno, kind, covered in sc.out:
                        if covered:
                            continue
                        key = f"{ci.name}.{attr}"
                        if key in registry:
                            continue
                        out.append(Finding(
                            self.name, pm.relpath, lineno,
                            f"{key} mutated ({kind}) on the background "
                            f"thread entered via {ci.name}.{entry} "
                            f"without a covering lock — wrap it in "
                            f"`with self.<lock>:` or register it in "
                            f"{REGISTRY_MODULE} with a policy + reason",
                            f"{ci.name}.{mname}",
                        ))
        return out
