"""Rule ``retrace``: jit-boundary retrace/realloc hazards in the
dispatch hot sections.

The PR 3 frozen-mask-template bug class: the per-batch prep path
allocated a fresh ``np.ones`` valid-mask per call and padded it, so
every dispatch paid a host allocation + copy that the hoisted template
(``valid_tmpl`` + a view slice) provides for free. More generally,
anything FRESH the host builds per call and feeds into a jitted
callable — a new numpy array, a compile inside the loop, a
Python-varying scalar — either costs a per-step allocation/transfer or,
for a compile, a full retrace (~seconds) per iteration.

Three checks, scoped to the dispatch path:

  1. fresh host allocations (``np.ones/zeros/empty/full/arange``)
     anywhere inside the declared HOT_SECTIONS functions (the step
     loop's per-dispatch bodies). A deliberate tiny-vector exception —
     run_update's ``wmv``, which rides the step's queued input transfer
     precisely so it does NOT cost an eager device op — carries a
     reasoned ``# lint: allow(retrace): ...``.
  2. compiling in a loop: ``jax.jit(...)`` or a ``build_*`` step
     factory invoked inside a ``for``/``while`` body anywhere in the
     scoped modules (each iteration traces + compiles afresh), or
     invoked at all inside a HOT_SECTIONS function.
  3. Python-varying scalars (``time.*()``, ``random.*()``) or fresh
     numpy allocations passed DIRECTLY as arguments to a callable the
     module resolvably compiled with ``jax.jit`` or obtained from a
     ``build_*`` factory.

Scope: flink_tpu/runtime/step.py and flink_tpu/runtime/executor.py —
the modules that own the compiled-step boundary. Established by PR 3
(pipelined ingest); unified here (ISSUE 9).
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from tools.lint.core import (
    Finding, QualnameVisitor, RepoTree, Rule, dotted_name,
)

SCOPE = (
    "flink_tpu/runtime/step.py",
    "flink_tpu/runtime/executor.py",
)

# per-dispatch bodies of the step loop, by (module, innermost function
# name). Everything these run is paid once per micro-batch (or per
# fused megastep) — the budget the whole round-5/7 effort bought back.
HOT_SECTIONS: Dict[str, Set[str]] = {
    "flink_tpu/runtime/executor.py": {"run_update", "run_update_fused"},
}

ALLOC_ATTRS = ("ones", "zeros", "empty", "full", "arange")
NP_NAMES = ("np", "numpy")
VARYING_MODULES = ("time", "random")


def _is_np_alloc(call: ast.Call) -> Optional[str]:
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and f.attr in ALLOC_ATTRS
        and isinstance(f.value, ast.Name)
        and f.value.id in NP_NAMES
    ):
        return f"{f.value.id}.{f.attr}"
    return None


def _is_varying_scalar(call: ast.Call) -> Optional[str]:
    f = call.func
    if (
        isinstance(f, ast.Attribute)
        and isinstance(f.value, ast.Name)
        and f.value.id in VARYING_MODULES
    ):
        return f"{f.value.id}.{f.attr}()"
    return None


def _is_jit_constructor(call: ast.Call) -> Optional[str]:
    """'jax.jit(...)', 'jit(...)', 'partial(jax.jit, ...)' or a
    'build_*' step-factory call — anything that traces + compiles."""
    dn = dotted_name(call.func)
    if dn in ("jax.jit", "jit"):
        return dn
    if dn == "partial" and call.args:
        inner = dotted_name(call.args[0])
        if inner in ("jax.jit", "jit"):
            return "partial(jax.jit, ...)"
    if dn is not None:
        last = dn.rsplit(".", 1)[-1]
        if last.startswith("build_"):
            return dn
    return None


def collect_jitted_names(tree: ast.AST) -> Set[str]:
    """Names resolvably bound to a compiled callable in this module:
    ``f = jax.jit(...)``, ``f = build_*(...)``, ``self.x = build_*(...)``
    (as 'self.x'), and defs decorated with jax.jit/partial(jax.jit)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
            if _is_jit_constructor(node.value):
                for t in node.targets:
                    dn = dotted_name(t)
                    if dn:
                        out.add(dn)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                dn = dotted_name(dec)
                if dn in ("jax.jit", "jit"):
                    out.add(node.name)
                elif isinstance(dec, ast.Call) and _is_jit_constructor(dec):
                    out.add(node.name)
    return out


class _Scanner(QualnameVisitor):
    def __init__(self, rule: "RetraceRule", relpath: str,
                 jitted: Set[str], hot_funcs: Set[str]):
        super().__init__()
        self.rule = rule
        self.relpath = relpath
        self.jitted = jitted
        self.hot_funcs = hot_funcs
        self.loop_depth = 0
        self.out: List[Finding] = []

    def _in_hot_section(self) -> bool:
        return any(part in self.hot_funcs for part in self.stack)

    def _emit(self, node, msg):
        self.out.append(Finding(
            self.rule.name, self.relpath, node.lineno, msg,
            self.qualname(),
        ))

    def visit_For(self, node):
        self.loop_depth += 1
        self.generic_visit(node)
        self.loop_depth -= 1

    visit_While = visit_For
    visit_AsyncFor = visit_For

    def visit_FunctionDef(self, node):
        # a nested def inside a loop body is deferred work, not per-
        # iteration work: reset the loop depth inside it
        saved, self.loop_depth = self.loop_depth, 0
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()
        self.loop_depth = saved

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Call(self, node: ast.Call):
        hot = self._in_hot_section()
        alloc = _is_np_alloc(node)
        if alloc and hot:
            self._emit(node, (
                f"{alloc}(...) allocated per dispatch in hot section "
                f"{self.qualname()!r} — hoist it to setup (the PR 3 "
                f"frozen-template fix) or justify it with an allow "
                f"reason"
            ))
        jc = _is_jit_constructor(node)
        if jc is not None and (self.loop_depth > 0 or hot):
            where = ("inside a loop" if self.loop_depth > 0
                     else f"in hot section {self.qualname()!r}")
            self._emit(node, (
                f"{jc}(...) invoked {where} — each call traces and "
                f"compiles afresh (a retrace storm); compile once at "
                f"setup and reuse the callable"
            ))
        # fresh/varying values flowing directly into a compiled callable
        callee = dotted_name(node.func)
        if callee in self.jitted:
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Call):
                    what = _is_np_alloc(arg) or _is_varying_scalar(arg)
                    if what:
                        self._emit(arg, (
                            f"{what} built fresh in the argument list of "
                            f"compiled callable {callee!r} — per-call "
                            f"host work on the jit boundary; hoist or "
                            f"stage it"
                        ))
        self.generic_visit(node)


class RetraceRule(Rule):
    name = "retrace"
    title = ("no fresh host allocations, in-loop compiles, or varying "
             "scalars on the jitted dispatch boundary")
    established = "PR 3"

    def check(self, tree: RepoTree) -> List[Finding]:
        out: List[Finding] = []
        for pm in tree.walk(*SCOPE):
            jitted = collect_jitted_names(pm.tree)
            sc = _Scanner(self, pm.relpath, jitted,
                          HOT_SECTIONS.get(pm.relpath, set()))
            sc.visit(pm.tree)
            out.extend(sc.out)
        return out
