"""Shared AST-analysis framework for the hot-path invariant linter.

PRs 1-8 established a set of invariants that keep the step loop
saturated and the fault story honest — no host syncs on the hot path,
one sort per micro-batch, no use-after-donate, declared-and-documented
config keys, lock-or-single-writer discipline on cross-thread state,
and a ``faults.inject`` seam on every raw IO call the chaos soak is
supposed to reach. Two of those used to be guarded by copy-pasted
one-off AST scripts; the rest were convention. This package makes them
ONE framework:

  * :class:`RepoTree` — parses each module ONCE and shares the cache
    across every rule (the <5s tier-1 wall-time budget is a test).
  * :class:`Rule` — the plugin interface: a rule declares its name, the
    invariant it protects, which PR established it, and a ``check``
    over the shared tree returning :class:`Finding`\\ s.
  * Suppressions — ``# lint: allow(<rule>): <reason>`` on the flagged
    line (or the line directly above) silences one finding; the reason
    is MANDATORY — a reasonless allow is itself reported (as the
    pseudo-rule ``suppression``), and rules may opt out of being
    suppressible at all (the sort-seam rule does: a new sort in a
    kernel is a design decision, not an annotation).
  * CLI — ``python -m tools.lint [--rule X] [--json]``; exit 0 clean,
    1 findings, 2 internal error (distinct so CI can tell "the tree is
    dirty" from "the linter is broken").

Wired into tier-1 via tests/test_lint.py: one parametrized module runs
every rule against the repo (must be clean) and against a red-team
fixture pair (must flag the bad snippet, pass the good one). Rule
catalog: docs/static-analysis.md.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

SUPPRESS_RE = re.compile(
    r"#\s*lint:\s*allow\(\s*(?P<rule>[A-Za-z0-9_-]+)\s*\)"
    r"(?::\s*(?P<reason>\S.*))?"
)


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a repo-relative path + line.

    ``note`` carries cross-tier evidence: when the trace tier
    (tools/lint/kernel_audit.py) flags a compiled-program property, the
    note names the source construct the AST tier attributed it to (e.g.
    a donation-effective finding names the donate_argnums line the AST
    donation rule found) — one finding, both tiers' evidence.
    """

    rule: str
    path: str          # repo-relative, '/'-separated
    line: int
    message: str
    func: str = "<module>"
    note: str = ""

    def __str__(self) -> str:
        base = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        return f"{base}\n    note: {self.note}" if self.note else base


@dataclass
class ParsedModule:
    """A parsed python module plus the raw text every rule shares."""

    relpath: str       # '/'-separated, relative to the tree root
    source: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)

    def __post_init__(self):
        if not self.lines:
            self.lines = self.source.splitlines()

    def line_at(self, lineno: int) -> str:
        if 0 < lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""


class LintInternalError(Exception):
    """The linter itself failed (unparseable module, unknown rule, bad
    root). Distinct from findings so the CLI can exit 2, not 1."""


class RepoTree:
    """Parse-once module cache over the repo (or a virtual overlay).

    Disk mode: ``RepoTree(root)``. Virtual mode (fixtures/tests):
    ``RepoTree(files={relpath: source})`` — rules see exactly the given
    files and nothing else, so a red-team snippet can impersonate
    ``flink_tpu/runtime/step.py`` without touching the real tree.
    """

    def __init__(self, root: Optional[str] = None,
                 files: Optional[Dict[str, str]] = None):
        if (root is None) == (files is None):
            raise LintInternalError("RepoTree needs exactly one of "
                                    "root= or files=")
        self.root = root
        self._virtual = dict(files) if files is not None else None
        self._cache: Dict[str, Optional[ParsedModule]] = {}

    # -- raw text (conf files, docs) -----------------------------------
    def exists(self, relpath: str) -> bool:
        if self._virtual is not None:
            return relpath in self._virtual
        return os.path.exists(os.path.join(self.root, relpath))

    def read_text(self, relpath: str) -> Optional[str]:
        if self._virtual is not None:
            return self._virtual.get(relpath)
        p = os.path.join(self.root, relpath)
        if not os.path.isfile(p):
            return None
        try:
            with open(p, encoding="utf-8", errors="replace") as f:
                return f.read()
        except OSError as e:
            raise LintInternalError(f"cannot read {relpath}: {e}") from e

    # -- parsed modules -------------------------------------------------
    def module(self, relpath: str) -> Optional[ParsedModule]:
        """Parse one module (cached; every rule shares the one parse).
        Returns None when the file does not exist; raises
        LintInternalError on a syntax error — an unparseable production
        module is a broken build, not a finding."""
        relpath = relpath.replace(os.sep, "/")
        if relpath in self._cache:
            return self._cache[relpath]
        src = self.read_text(relpath)
        if src is None:
            self._cache[relpath] = None
            return None
        try:
            tree = ast.parse(src, filename=relpath)
        except SyntaxError as e:
            raise LintInternalError(
                f"cannot parse {relpath}: {e}"
            ) from e
        pm = ParsedModule(relpath=relpath, source=src, tree=tree)
        self._cache[relpath] = pm
        return pm

    def walk(self, *prefixes: str) -> List[ParsedModule]:
        """Every .py module under the given relative files/directories,
        parsed via the shared cache, sorted by relpath."""
        rels: List[str] = []
        for prefix in prefixes:
            prefix = prefix.replace(os.sep, "/")
            if self._virtual is not None:
                for rp in self._virtual:
                    if rp == prefix or (
                        rp.startswith(prefix.rstrip("/") + "/")
                        and rp.endswith(".py")
                    ):
                        rels.append(rp)
                continue
            full = os.path.join(self.root, prefix)
            if os.path.isfile(full):
                rels.append(prefix)
            elif os.path.isdir(full):
                for dirpath, _dirs, files in os.walk(full):
                    for f in sorted(files):
                        if f.endswith(".py"):
                            rels.append(os.path.relpath(
                                os.path.join(dirpath, f), self.root
                            ).replace(os.sep, "/"))
        out = []
        for rp in sorted(set(rels)):
            pm = self.module(rp)
            if pm is not None:
                out.append(pm)
        return out


class Rule:
    """Plugin interface. Subclasses set the class attributes and
    implement ``check``; the framework owns suppression filtering."""

    name: str = ""
    title: str = ""            # one-line invariant statement
    established: str = ""      # which PR established the invariant
    suppressible: bool = True  # sort-seam opts out: no escape hatch
    # "ast" rules read source; "trace" rules (ISSUE 11) build the real
    # kernel families and read the jaxpr / lowered / compiled program.
    # The CLI's --tier flag filters on this.
    tier: str = "ast"

    def check(self, tree: RepoTree) -> List[Finding]:
        raise NotImplementedError


def _suppression_for(tree: RepoTree, path: str, line: int):
    """The ``# lint: allow(rule): reason`` match covering ``line`` (the
    line itself or the one directly above), else None. Works on any
    text file with '#' comments — .py and the flat conf yaml alike."""
    text = tree.read_text(path)
    if text is None:
        return None
    lines = text.splitlines()
    for ln in (line, line - 1):
        if 0 < ln <= len(lines):
            m = SUPPRESS_RE.search(lines[ln - 1])
            if m:
                return m
    return None


def apply_suppressions(tree: RepoTree, rules: Sequence[Rule],
                       findings: Iterable[Finding]) -> List[Finding]:
    """Drop findings covered by a reasoned allow-comment; emit a
    ``suppression`` pseudo-finding for every reasonless allow."""
    suppressible = {r.name for r in rules if r.suppressible}
    out: List[Finding] = []
    seen_bad_allows: set = set()
    for f in findings:
        m = _suppression_for(tree, f.path, f.line)
        if m is None or f.rule not in suppressible:
            out.append(f)
            continue
        if m.group("rule") != f.rule:
            out.append(f)      # allow for a different rule: no cover
            continue
        if not (m.group("reason") or "").strip():
            key = (f.path, f.line)
            if key not in seen_bad_allows:
                seen_bad_allows.add(key)
                out.append(Finding(
                    "suppression", f.path, f.line,
                    f"allow({f.rule}) without a reason — the reason is "
                    f"mandatory: '# lint: allow({f.rule}): <why>'",
                    f.func,
                ))
            # the underlying finding stays suppressed: the author
            # clearly intended it; the missing reason is the violation
    return out


def run_rules(tree: RepoTree, rules: Sequence[Rule]) -> List[Finding]:
    """Run ``rules`` over ``tree`` and return post-suppression findings
    sorted by (path, line). The shared RepoTree cache means each module
    is parsed exactly once no matter how many rules scan it."""
    raw: List[Finding] = []
    for rule in rules:
        raw.extend(rule.check(tree))
    out = apply_suppressions(tree, rules, raw)
    # message joins the key so --json diffs are byte-deterministic even
    # when one line carries several findings of one rule
    out.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    return out


# -- small AST helpers shared by rules ---------------------------------

class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that maintains a class/function qualname stack —
    the walking boilerplate the two pre-framework checkers each
    copy-pasted."""

    def __init__(self):
        self.stack: List[str] = []

    def qualname(self) -> str:
        return ".".join(self.stack) if self.stack else "<module>"

    def visit_ClassDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef


def dotted_name(node: ast.AST) -> Optional[str]:
    """'a.b.c' for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def functions_in(tree: ast.AST) -> List[Tuple[str, ast.AST]]:
    """[(qualname, FunctionDef)] for every function in the module."""
    out: List[Tuple[str, ast.AST]] = []

    class V(QualnameVisitor):
        def visit_FunctionDef(self, node):
            self.stack.append(node.name)
            out.append((".".join(self.stack), node))
            self.generic_visit(node)
            self.stack.pop()

        visit_AsyncFunctionDef = visit_FunctionDef

    V().visit(tree)
    return out
