"""CLI: ``python -m tools.lint [--rule X] [--json] [--root R]``.

Exit codes are DISTINCT so CI can tell a dirty tree from a broken
linter:

    0  clean (no unsuppressed findings)
    1  findings (printed one per line, or as JSON with --json)
    2  internal error (unknown rule, unparseable module, bad root)
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from tools.lint import (
    DEFAULT_ROOT, LintInternalError, RepoTree, all_rules, rule_by_name,
    run_rules,
)

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL = 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="Unified hot-path invariant linter "
                    "(docs/static-analysis.md)",
    )
    ap.add_argument("--rule", help="run only this rule (by name)")
    ap.add_argument("--json", action="store_true",
                    help="emit findings as a JSON array")
    ap.add_argument("--root", default=DEFAULT_ROOT,
                    help="repo root to scan")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules():
            print(f"{r.name:15s} [{r.established}] {r.title}")
        return EXIT_CLEAN

    try:
        rules = [rule_by_name(args.rule)] if args.rule else all_rules()
        t0 = time.perf_counter()
        findings = run_rules(RepoTree(args.root), rules)
        dt = time.perf_counter() - t0
    except LintInternalError as e:
        print(f"lint: internal error: {e}", file=sys.stderr)
        return EXIT_INTERNAL
    except Exception as e:  # noqa: BLE001 — any crash is exit 2, not 1
        print(f"lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return EXIT_INTERNAL

    if args.json:
        print(json.dumps([
            {"rule": f.rule, "path": f.path, "line": f.line,
             "func": f.func, "message": f.message}
            for f in findings
        ], indent=2))
    else:
        for f in findings:
            print(f)
        print(
            f"lint: {len(findings)} finding(s), {len(rules)} rule(s), "
            f"{dt:.2f}s", file=sys.stderr,
        )
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
