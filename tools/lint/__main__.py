"""CLI: ``python -m tools.lint [--tier T] [--rule X] [--json]
[--update-ledger] [--root R]`` (also installed as ``flink-tpu-lint``).

Runs BOTH tiers by default: the AST rules (source-level invariants) and
the trace rules (compiled-graph invariants, ISSUE 11 — these build the
canonical kernel families on the CPU backend, so a full run costs a few
seconds of tracing). ``--tier ast`` keeps the sub-second source-only
pass; ``--tier trace`` audits just the compiled contracts.

``--update-ledger`` rewrites the golden ledgers (op budgets, compile
signatures) from a fresh trace instead of diffing against them — the
sanctioned way to record a DELIBERATE structural change; commit the
ledger diff with the kernel change that caused it.

Exit codes are DISTINCT so CI can tell a dirty tree from a broken
linter:

    0  clean (no unsuppressed findings)
    1  findings (printed one per line, or as JSON with --json)
    2  internal error (unknown rule, unparseable module, bad root)

``--json`` emits a versioned envelope (``schema``, the rule names run,
and the findings sorted by path/line/rule/message) so ledger and CI
diffs are deterministic.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from tools.lint import (
    DEFAULT_ROOT, LintInternalError, RepoTree, all_rules, rule_by_name,
    run_rules,
)

EXIT_CLEAN = 0
EXIT_FINDINGS = 1
EXIT_INTERNAL = 2

# bump when the --json envelope shape changes
JSON_SCHEMA_VERSION = 2


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.lint",
        description="Unified hot-path invariant linter "
                    "(docs/static-analysis.md)",
    )
    ap.add_argument("--rule", help="run only this rule (by name)")
    ap.add_argument("--tier", choices=("ast", "trace"),
                    help="run only one analysis tier (default: both)")
    ap.add_argument("--json", action="store_true",
                    help="emit a versioned JSON findings envelope")
    ap.add_argument("--update-ledger", action="store_true",
                    help="rewrite the golden ledgers (op budgets, "
                         "compile signatures) from a fresh trace "
                         "instead of diffing against them")
    ap.add_argument("--root", default=DEFAULT_ROOT,
                    help="repo root to scan")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for r in all_rules(args.tier):
            print(f"{r.name:20s} [{r.tier:5s}] [{r.established}] "
                  f"{r.title}")
        return EXIT_CLEAN

    try:
        if args.rule:
            rules = [rule_by_name(args.rule)]
            if args.tier and rules[0].tier != args.tier:
                raise LintInternalError(
                    f"rule {args.rule!r} is tier "
                    f"{rules[0].tier!r}, not {args.tier!r}"
                )
        else:
            rules = all_rules(args.tier)
        if args.update_ledger:
            for r in rules:
                if hasattr(r, "update_ledger"):
                    r.update_ledger = True
        t0 = time.perf_counter()
        findings = run_rules(RepoTree(args.root), rules)
        dt = time.perf_counter() - t0
    except LintInternalError as e:
        print(f"lint: internal error: {e}", file=sys.stderr)
        return EXIT_INTERNAL
    except Exception as e:  # noqa: BLE001 — any crash is exit 2, not 1
        print(f"lint: internal error: {type(e).__name__}: {e}",
              file=sys.stderr)
        return EXIT_INTERNAL

    if args.json:
        print(json.dumps({
            "schema": JSON_SCHEMA_VERSION,
            "tier": args.tier or "all",
            "rules": [r.name for r in rules],
            "findings": [
                {"rule": f.rule, "path": f.path, "line": f.line,
                 "func": f.func, "message": f.message, "note": f.note}
                for f in findings
            ],
        }, indent=2))
    else:
        for f in findings:
            print(f)
        print(
            f"lint: {len(findings)} finding(s), {len(rules)} rule(s), "
            f"{dt:.2f}s", file=sys.stderr,
        )
    return EXIT_FINDINGS if findings else EXIT_CLEAN


if __name__ == "__main__":
    sys.exit(main())
