#!/usr/bin/env python
"""Static check: device sorts in ops/ live ONLY in segment.py.

THIN SHIM (ISSUE 9): the checker migrated into the unified invariant
linter as the ``sort-seam`` rule — run ``python -m tools.lint`` for
all 7 rules, or this script for the one check. Public API
(check_source, check_tree, ops_files, main) is re-exported unchanged
for tests/test_sort_seam.py and any other caller. Rule implementation:
tools/lint/rules/sort_seam.py; catalog: docs/static-analysis.md.
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.rules.sort_seam import (  # noqa: E402,F401
    OPS_PATH,
    SORT_ATTRS,
    SORT_HOME,
    SORT_MODULES,
    SortSeamRule,
    Violation,
    check_source,
    check_tree,
    main,
    ops_files,
)

if __name__ == "__main__":
    sys.exit(main())
