"""Direct device-time microbenchmark of the window update/fire steps.

Times N dispatches of build_window_update_step with block_until_ready,
isolating pure device step time from bench.py's host pipeline. Sweep
batch and capacity to find the throughput-optimal config.
"""

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=262_144)
    ap.add_argument("--capacity", type=int, default=1 << 22)
    ap.add_argument("--probe", type=int, default=16)
    ap.add_argument("--ring", type=int, default=8)
    ap.add_argument("--iters", type=int, default=12)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec, build_window_fire_step, build_window_update_step,
        init_sharded_state,
    )

    B = args.batch
    ctx = MeshContext.create(len(jax.devices()), 128)
    win = wk.WindowSpec(size_ticks=5000, slide_ticks=5000, ring=args.ring,
                        fires_per_step=4, lateness_ticks=0, overflow=0)
    red = wk.ReduceSpec(kind="sum")
    spec = WindowStageSpec(win=win, red=red, capacity_per_shard=args.capacity,
                           probe_len=args.probe)
    state = init_sharded_state(ctx, spec)
    upd = build_window_update_step(ctx, spec)
    upd_fast = build_window_update_step(ctx, spec, insert=False)
    fire = build_window_fire_step(ctx, spec)

    rng = np.random.default_rng(0)
    N_KEYS = 1_000_000

    def mk(i):
        idx = np.arange(i * B, (i + 1) * B, dtype=np.int64)
        keys = (idx * 2862933555777941757) % N_KEYS
        h = keys.astype(np.uint64) * np.uint64(0x9E3779B97F4A7C15)
        hi = (h >> np.uint64(32)).astype(np.uint32)
        lo = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
        ts = (idx // 2000).astype(np.int32)
        vals = np.ones(B, np.float32)
        valid = np.ones(B, bool)
        return hi, lo, ts, vals, valid

    wmv = jnp.full((ctx.n_shards,), np.int32(-(2**31) + 1))
    batches = [mk(i) for i in range(4)]
    dev_batches = [
        tuple(jnp.asarray(a) for a in b) for b in batches
    ]

    # warmup/compile
    state, ovf = upd(state, *dev_batches[0], wmv)
    jax.block_until_ready(ovf)

    t0 = time.perf_counter()
    for i in range(args.iters):
        state, ovf = upd(state, *dev_batches[i % 4], wmv)
    jax.block_until_ready(ovf)
    dt = (time.perf_counter() - t0) / args.iters
    print(f"update step (insert): {dt*1e3:.2f} ms/step -> "
          f"{B/dt/1e6:.2f} M events/s (B={B}, cap={args.capacity}, "
          f"probe={args.probe}, ring={args.ring})")

    state, ovf = upd_fast(state, *dev_batches[0], wmv)
    jax.block_until_ready(ovf)
    t0 = time.perf_counter()
    for i in range(args.iters):
        state, ovf = upd_fast(state, *dev_batches[i % 4], wmv)
    jax.block_until_ready(ovf)
    dt = (time.perf_counter() - t0) / args.iters
    print(f"update step (fast):   {dt*1e3:.2f} ms/step -> "
          f"{B/dt/1e6:.2f} M events/s")

    # host->device transfer cost for one batch
    t0 = time.perf_counter()
    for i in range(args.iters):
        arrs = [jnp.asarray(a) for a in batches[i % 4]]
    jax.block_until_ready(arrs)
    dt_x = (time.perf_counter() - t0) / args.iters
    print(f"h2d transfer: {dt_x*1e3:.2f} ms/batch")

    # fire step cost (all 1M keys resident)
    st2, cf = fire(state, jnp.full((ctx.n_shards,), np.int32(10_000)))
    jax.block_until_ready(cf.counts)
    t0 = time.perf_counter()
    st3, cf = fire(st2, jnp.full((ctx.n_shards,), np.int32(10_001)))
    jax.block_until_ready(cf.counts)
    print(f"fire step: {(time.perf_counter()-t0)*1e3:.2f} ms")


if __name__ == "__main__":
    main()
