#!/bin/bash
# Poll the TPU tunnel; whenever it answers, run the north-star bench and
# the four-config bench back-to-back and persist the results IN THE REPO:
#   BENCH_SESSION_r04.json  — freshest north-star JSON line (+ run log)
#   BENCH_r04.json          — SAME line (the official end-of-round artifact
#                             must never read 0 when a real number exists;
#                             the driver overwrites it if it manages a live
#                             run of its own at round end)
#   BENCH_CONFIGS_r04.jsonl — one JSON line per config
# Then keeps watching: after a success it sleeps 30 min and re-runs, so a
# later code improvement or a quieter tunnel refreshes the numbers.
cd "$(dirname "$0")/.."
ROUND=${ROUND:-r05}
while true; do
  if timeout 60 python - <<'PYEOF' 2>/dev/null
import subprocess, sys
r = subprocess.run([sys.executable, "-c", "import jax; jax.devices()"],
                   timeout=45, capture_output=True)
sys.exit(0 if r.returncode == 0 else 1)
PYEOF
  then
    echo "$(date -u +%FT%TZ) tunnel up — running benches" >&2
    # No sweep, pre-calibrated batch: the r5 opening up-window lasted only
    # ~10 minutes and the 3-candidate sweep ate most of it before the
    # tunnel dropped mid-final-run. The sweep's verdict (larger batch
    # amortizes the tunneled dispatch RTT; winner 1048576, see
    # BENCH_SWEEP_r05.json) is baked in so a short window yields the
    # official full-run row in ~3 minutes (compile served from
    # /tmp/jax_cache after the first window).
    timeout 1800 python bench.py --events 30000000 --baseline-events 2000000 \
        --no-sweep --batch 1048576 \
        --init-deadline 60 > /tmp/bench_north_tpu.txt 2>&1
    line=$(grep -h '"metric"' /tmp/bench_north_tpu.txt | tail -1)
    captured=0
    if [ -n "$line" ] && ! echo "$line" | grep -q '"error"'; then
      captured=1
      echo "$line" > BENCH_SESSION_${ROUND}.json
      echo "$line" > BENCH_${ROUND}.json
      cp /tmp/bench_north_tpu.txt BENCH_SESSION_${ROUND}.log
      echo "$(date -u +%FT%TZ) north-star captured: $line" >&2
    else
      echo "$(date -u +%FT%TZ) north-star run failed/outage" >&2
    fi
    # exploration: the r5 sweep showed throughput still rising at the
    # largest candidate (tunnel RTT amortization), so probe 2M/4M
    # micro-batches after the official row; short runs, appended rows
    if [ "$captured" = 1 ]; then
      explore() {  # explore <events> <extra bench args...>
        local ev=$1; shift
        timeout 900 python bench.py --events "$ev" \
            --baseline-events 200000 --no-sweep --init-deadline 45 \
            "$@" > /tmp/bench_explore_tpu.txt 2>&1
        local eline
        eline=$(grep -h '"metric"' /tmp/bench_explore_tpu.txt | tail -1)
        if [ -n "$eline" ] && ! echo "$eline" | grep -q '"error"'; then
          echo "$eline" >> BENCH_EXPLORE_${ROUND}.jsonl
          echo "$(date -u +%FT%TZ) explore $*: $eline" >&2
        fi
      }
      # larger micro-batches amortize the tunneled dispatch RTT further
      explore 83886080 --batch 2097152
      explore 167772160 --batch 4194304
      # deeper in-flight pipelining overlaps dispatch RTTs outright
      explore 41943040 --batch 1048576 --inflight 4
      explore 41943040 --batch 1048576 --inflight 8
    fi
    timeout 1800 python bench_configs.py --init-deadline 60 \
        > /tmp/bench_configs_tpu.txt 2>&1
    if grep -qh '"config"' /tmp/bench_configs_tpu.txt; then
      grep -h '"config"' /tmp/bench_configs_tpu.txt \
          > BENCH_CONFIGS_${ROUND}.jsonl
      echo "$(date -u +%FT%TZ) configs captured" >&2
    fi
    # commit any captured artifacts so a session end can't lose them
    if [ "$captured" = 1 ] || grep -qh '"config"' /tmp/bench_configs_tpu.txt 2>/dev/null; then
      for f in BENCH_${ROUND}.json BENCH_SESSION_${ROUND}.json \
               BENCH_SESSION_${ROUND}.log BENCH_CONFIGS_${ROUND}.jsonl \
               BENCH_EXPLORE_${ROUND}.jsonl; do
        [ -f "$f" ] && git add "$f"
      done
      git diff --cached --quiet || \
          git commit -m "Capture TPU bench results (${ROUND} watcher)" >&2
    fi
    # long refresh pause only after a real capture; a mid-bench tunnel
    # drop goes back to the fast probe cadence (short up-windows matter)
    if [ "$captured" = 1 ]; then sleep 1800; else sleep 90; fi
  else
    sleep 90
  fi
done
