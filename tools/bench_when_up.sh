#!/bin/bash
# Adaptive TPU bench watcher (round 5 rewrite).
#
# The round-4 postmortem and the round-5 opening window both showed the same
# tunnel regime: up-windows of ~3-10 minutes separated by long outages. A
# watcher that captures one artifact and then sleeps 30 minutes wastes
# whole windows while official deliverables are still missing. This version
# does ONE unit of work per successful probe, highest-priority first, and
# re-probes between units so a mid-window drop costs one short run, not the
# whole batch:
#   1. official north-star row  -> BENCH_${ROUND}.json + BENCH_SESSION_*.json
#   2. each missing config row  -> BENCH_CONFIGS_${ROUND}.jsonl (row-merged,
#      one bench_configs.py --only <name> run per unit, partial windows keep
#      whatever rows they caught)
#   3. each exploration step    -> BENCH_EXPLORE_${ROUND}.jsonl (larger
#      micro-batches + deeper in-flight pipelining; short runs, each row
#      tagged with its explore_id so the done-set derives from the committed
#      artifact itself and survives watcher restarts)
#   4. steady state: keep-best refresh of the official full row with the
#      best-throughput explored (batch, inflight), alternating with a
#      round-robin keep-best refresh of one config row, so later code
#      improvements refresh ALL official artifacts, not just the north-star
# Failure semantics: a unit failing while the tunnel still answers counts
# toward a per-unit retry cap (3); at the cap the unit records its error row
# (configs) or is marked done (explores) so a deterministically failing unit
# cannot livelock the priority ladder. Failures during an outage (probe dead
# right after) never count. Every capture is committed immediately so a
# session end cannot lose it.
cd "$(dirname "$0")/.."
ROUND=${ROUND:-r05}
FAIL_STATE=/tmp/bench_fail_counts_${ROUND}
MAX_UNIT_FAILS=3
touch "$FAIL_STATE"

probe() {
  timeout 60 python - <<'PYEOF' 2>/dev/null
import subprocess, sys
r = subprocess.run([sys.executable, "-c", "import jax; jax.devices()"],
                   timeout=45, capture_output=True)
sys.exit(0 if r.returncode == 0 else 1)
PYEOF
}

commit_artifacts() {
  # pathspec'd commit: the builder may have unrelated work staged in the
  # same repo while the watcher runs — sweep ONLY the bench artifacts
  local files=()
  for f in BENCH_${ROUND}.json BENCH_SESSION_${ROUND}.json \
           BENCH_SESSION_${ROUND}.log BENCH_CONFIGS_${ROUND}.jsonl \
           BENCH_EXPLORE_${ROUND}.jsonl; do
    [ -f "$f" ] && git add "$f" && files+=("$f")
  done
  [ ${#files[@]} -gt 0 ] || return 0
  git diff --cached --quiet -- "${files[@]}" || \
      git commit -m "$1" -- "${files[@]}" >&2
}

fail_count() { grep -c "^$1\$" "$FAIL_STATE"; }
note_fail() {
  # Count a unit failure only if the tunnel still answers (deterministic
  # failure); outage failures retry for free.
  if probe; then echo "$1" >> "$FAIL_STATE"; fi
}

official_value() {  # current recorded north-star value (0 if absent/error)
  python - <<PYEOF 2>/dev/null || echo 0
import json
try:
    d = json.load(open("BENCH_${ROUND}.json"))
    print(d.get("value", 0) if "error" not in d else 0)
except Exception:
    print(0)
PYEOF
}

have_config() {  # $1 = config name; 0 if any row (incl. capped error) exists
  [ -f BENCH_CONFIGS_${ROUND}.jsonl ] && \
    grep -q "\"config\": \"$1\"" BENCH_CONFIGS_${ROUND}.jsonl
}

config_row() {  # "eps events" for config $1 ("0 0" if absent/error)
  python - "$1" <<PYEOF 2>/dev/null || echo "0 0"
import json, sys
eps = ev = 0
try:
    for l in open("BENCH_CONFIGS_${ROUND}.jsonl"):
        if not l.strip():
            continue
        d = json.loads(l)
        if d.get("config") == sys.argv[1] and "error" not in d:
            eps, ev = d.get("subject_eps", 0), d.get("events", 0)
except FileNotFoundError:
    pass
print(eps, ev)
PYEOF
}

merge_config_row() {  # $1 = config name, $2 = json line
  python - "$1" "$2" <<PYEOF
import sys
name, line = sys.argv[1], sys.argv[2]
path = "BENCH_CONFIGS_${ROUND}.jsonl"
rows = []
try:
    rows = [l for l in open(path) if l.strip()]
except FileNotFoundError:
    pass
# drop the replaced config's row and any backend-outage {"config": "all"}
# error rows bench_configs.py emits when the probe fails
rows = [l for l in rows
        if '"config": "%s"' % name not in l and '"config": "all"' not in l]
rows.append(line + "\n")
open(path, "w").writelines(rows)
PYEOF
}

run_official() {  # $1 = batch, $2 = inflight ('' = default), $3 = keep_best
  local batch=$1 inflight=$2 keep_best=$3 args=""
  [ -n "$inflight" ] && args="--inflight $inflight"
  timeout 1200 python bench.py --events 30000000 --baseline-events 2000000 \
      --no-sweep --batch "$batch" $args \
      --init-deadline 45 > /tmp/bench_north_tpu.txt 2>&1
  local line
  line=$(grep -h '"metric"' /tmp/bench_north_tpu.txt | tail -1)
  if [ -n "$line" ] && ! echo "$line" | grep -q '"error"'; then
    local newv oldv
    newv=$(echo "$line" | python -c "import json,sys; print(json.load(sys.stdin)['value'])")
    oldv=$(official_value)
    if [ "$keep_best" = 1 ] && [ "$(python -c "print(1 if float('$newv') <= float('$oldv') else 0)")" = 1 ]; then
      echo "$(date -u +%FT%TZ) refresh $newv did not beat $oldv — keeping" >&2
      # record the attempt, tagged so best_explored ignores it
      echo "$line" | python -c "import json,sys; d=json.load(sys.stdin); d['refresh']=True; print(json.dumps(d))" \
        >> BENCH_EXPLORE_${ROUND}.jsonl
    else
      echo "$line" > BENCH_SESSION_${ROUND}.json
      echo "$line" > BENCH_${ROUND}.json
      cp /tmp/bench_north_tpu.txt BENCH_SESSION_${ROUND}.log
      echo "$(date -u +%FT%TZ) north-star captured: $line" >&2
    fi
    commit_artifacts "Capture TPU bench results (${ROUND} watcher)"
    return 0
  fi
  echo "$(date -u +%FT%TZ) official run failed/outage" >&2
  note_fail official
  return 1
}

run_config() {  # $1 = config name, $2 = keep_best (refresh mode)
  local name=$1 keep_best=${2:-0} evargs=""
  if [ "$keep_best" = 0 ]; then
    # FIRST capture at half scale: a short up-window should land
    # several rows (still millions of events — representative); the
    # keep-best refresh phase re-runs at full scale and upgrades
    case $name in
      socket_wc) evargs="--events 1000000" ;;
      count_min|sessions) evargs="--events 2000000" ;;
      cep|cep_event_time) evargs="--events 200000" ;;
    esac
  fi
  timeout 900 python bench_configs.py --only "$name" --init-deadline 45 \
      $evargs > /tmp/bench_cfg_${name}.txt 2>&1
  local line
  line=$(grep -h '"config"' /tmp/bench_cfg_${name}.txt | tail -1)
  if [ -n "$line" ] && ! echo "$line" | grep -q '"error"'; then
    if [ "$keep_best" = 1 ]; then
      local neweps newev oldeps oldev
      read -r neweps newev <<< "$(echo "$line" | python -c "import json,sys; d=json.load(sys.stdin); print(d.get('subject_eps',0), d.get('events',0))")"
      read -r oldeps oldev <<< "$(config_row "$name")"
      # a full-scale row always upgrades a half-scale first capture;
      # at equal scale, keep the best throughput
      if [ "$(python -c "print(1 if int('$newev') <= int('$oldev') and float('$neweps') <= float('$oldeps') else 0)")" = 1 ]; then
        echo "$(date -u +%FT%TZ) config $name refresh $neweps@$newev did not beat $oldeps@$oldev — keeping" >&2
        return 0
      fi
    fi
    merge_config_row "$name" "$line"
    echo "$(date -u +%FT%TZ) config $name: $line" >&2
    commit_artifacts "Capture TPU config bench row: ${name} (${ROUND} watcher)"
    return 0
  fi
  echo "$(date -u +%FT%TZ) config $name failed/outage" >&2
  note_fail "cfg_$name"
  if [ "$(fail_count "cfg_$name")" -ge "$MAX_UNIT_FAILS" ]; then
    # deterministic failure: synthesize THIS config's error row (the raw
    # failure line may be bench_configs' {"config": "all"} outage row,
    # which would never satisfy have_config and gets purged on the next
    # merge) so the ladder moves on with a durable record
    local detail
    detail=$(echo "$line" | python -c "import json,sys
try: print(json.load(sys.stdin).get('error','')[:200])
except Exception: print('')" 2>/dev/null)
    merge_config_row "$name" "$(python -c "import json,sys; print(json.dumps({'config': sys.argv[1], 'error': 'capped after $MAX_UNIT_FAILS failures: ' + sys.argv[2]}))" "$name" "${detail:-no output}")"
    commit_artifacts "Record failing TPU config bench row: ${name} (${ROUND} watcher)"
  fi
  return 1
}

explore_done() {  # derived from the committed artifact (survives restarts)
  [ -f BENCH_EXPLORE_${ROUND}.jsonl ] && \
    grep -q "\"explore_id\": \"$1\"" BENCH_EXPLORE_${ROUND}.jsonl
}

run_explore() {  # $1 = step id, $2 = events, rest = bench args
  local id=$1 ev=$2; shift 2
  timeout 900 python bench.py --events "$ev" --baseline-events 200000 \
      --no-sweep --init-deadline 45 "$@" > /tmp/bench_explore_tpu.txt 2>&1
  local line
  line=$(grep -h '"metric"' /tmp/bench_explore_tpu.txt | tail -1)
  if [ -n "$line" ] && ! echo "$line" | grep -q '"error"'; then
    echo "$line" | python -c "import json,sys; d=json.load(sys.stdin); d['explore_id']='$id'; print(json.dumps(d))" \
      >> BENCH_EXPLORE_${ROUND}.jsonl
    echo "$(date -u +%FT%TZ) explore $id: $line" >&2
    commit_artifacts "Capture TPU exploration row: ${id} (${ROUND} watcher)"
    return 0
  fi
  echo "$(date -u +%FT%TZ) explore $id failed/outage" >&2
  note_fail "exp_$id"
  if [ "$(fail_count "exp_$id")" -ge "$MAX_UNIT_FAILS" ]; then
    # deterministic failure (e.g. OOM at this batch): mark done with an
    # error row so the remaining steps and the refresh unblock
    echo "{\"explore_id\": \"$id\", \"error\": \"capped after $MAX_UNIT_FAILS failures\"}" \
      >> BENCH_EXPLORE_${ROUND}.jsonl
    commit_artifacts "Record failing TPU exploration step: ${id} (${ROUND} watcher)"
  fi
  return 1
}

best_explored() {  # echo "batch inflight" of the best exploration row
  python - <<PYEOF 2>/dev/null
import json
best = None
try:
    for l in open("BENCH_EXPLORE_${ROUND}.jsonl"):
        if not l.strip():
            continue
        d = json.loads(l)
        if "error" in d or not d.get("value") or d.get("refresh"):
            continue
        if best is None or d["value"] > best["value"]:
            best = d
except FileNotFoundError:
    pass
if best:
    infl = best.get("max_inflight")
    print(best.get("batch", 1048576), infl if infl is not None else "")
PYEOF
}

CONFIG_ORDER="socket_wc count_min sessions cep cep_event_time"
explore_step() {
  case $1 in
    b2m) run_explore b2m 41943040 --batch 2097152 ;;
    b4m) run_explore b4m 50331648 --batch 4194304 ;;
    i4)  run_explore i4 41943040 --batch 1048576 --inflight 4 ;;
    i8)  run_explore i8 41943040 --batch 1048576 --inflight 8 ;;
    b2i4) run_explore b2i4 50331648 --batch 2097152 --inflight 4 ;;
  esac
}

refresh_rr=0
while true; do
  if ! probe; then sleep 90; continue; fi
  # ---- pick exactly one unit of work, highest priority first ----
  if [ "$(official_value)" = 0 ] && [ "$(fail_count official)" -lt "$MAX_UNIT_FAILS" ]; then
    run_official 1048576 "" 0
    sleep 5; continue
  fi
  next_cfg=""
  for c in $CONFIG_ORDER; do
    if ! have_config "$c" && [ "$(fail_count "cfg_$c")" -lt "$MAX_UNIT_FAILS" ]; then
      next_cfg=$c; break
    fi
  done
  if [ -n "$next_cfg" ]; then
    run_config "$next_cfg" 0
    sleep 5; continue
  fi
  next_exp=""
  for e in b2m b4m i4 i8 b2i4; do
    if ! explore_done "$e" && [ "$(fail_count "exp_$e")" -lt "$MAX_UNIT_FAILS" ]; then
      next_exp=$e; break
    fi
  done
  if [ -n "$next_exp" ]; then
    explore_step "$next_exp"
    sleep 5; continue
  fi
  # ---- everything captured: alternate keep-best refreshes ----
  if [ $((refresh_rr % 2)) = 0 ]; then
    read -r bb bi <<< "$(best_explored)"
    [ -n "$bb" ] || bb=1048576
    echo "$(date -u +%FT%TZ) refresh north-star with batch=$bb inflight=${bi:-default}" >&2
    run_official "$bb" "$bi" 1
  else
    idx=$(( (refresh_rr / 2) % 5 + 1 ))
    rc=$(echo $CONFIG_ORDER | cut -d' ' -f$idx)
    echo "$(date -u +%FT%TZ) refresh config $rc" >&2
    run_config "$rc" 1
  fi
  refresh_rr=$((refresh_rr + 1))
  sleep 1500
done
