#!/bin/bash
# Poll the TPU tunnel; when it answers, run the four-config bench and the
# north-star bench back-to-back, saving results. One-shot.
cd "$(dirname "$0")/.."
for i in $(seq 1 200); do
  if timeout 60 python - <<'EOF' 2>/dev/null
import subprocess, sys
r = subprocess.run([sys.executable, "-c", "import jax; jax.devices()"],
                   timeout=45, capture_output=True)
sys.exit(0 if r.returncode == 0 else 1)
EOF
  then
    echo "tunnel up after $i probes" >&2
    timeout 560 python bench_configs.py --init-deadline 60 \
        > /tmp/bench_configs_tpu.txt 2>&1
    timeout 560 python bench.py --events 30000000 --baseline-events 3000000 \
        --init-deadline 60 > /tmp/bench_north_tpu.txt 2>&1
    echo DONE >&2
    exit 0
  fi
  sleep 90
done
echo "tunnel never came up" >&2
exit 1
