#!/bin/bash
# Poll the TPU tunnel; when it answers, run the four-config bench and the
# north-star bench back-to-back. Results land IN THE REPO so an
# end-of-round commit captures them even if the tunnel recovers late.
cd "$(dirname "$0")/.."
for i in $(seq 1 120); do
  if timeout 60 python - <<'PYEOF' 2>/dev/null
import subprocess, sys
r = subprocess.run([sys.executable, "-c", "import jax; jax.devices()"],
                   timeout=45, capture_output=True)
sys.exit(0 if r.returncode == 0 else 1)
PYEOF
  then
    echo "tunnel up after $i probes" >&2
    timeout 560 python bench_configs.py --init-deadline 60 \
        > /tmp/bench_configs_tpu.txt 2>&1
    grep -h '"config"' /tmp/bench_configs_tpu.txt \
        > BENCH_CONFIGS_r03.jsonl || true
    timeout 560 python bench.py --events 30000000 --baseline-events 3000000 \
        --init-deadline 60 > /tmp/bench_north_tpu.txt 2>&1
    grep -h '"metric"' /tmp/bench_north_tpu.txt \
        >> BENCH_CONFIGS_r03.jsonl || true
    echo DONE >&2
    exit 0
  fi
  sleep 90
done
echo "tunnel never came up" >&2
exit 1
