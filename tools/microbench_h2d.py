"""Host->device transfer bandwidth curve on the tunneled TPU runtime."""

import sys
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)
    for mb in (1, 4, 16, 64):
        a = rng.integers(0, 255, size=mb * 1024 * 1024, dtype=np.uint8)
        d = jax.device_put(a)
        jax.block_until_ready(d)
        t0 = time.perf_counter()
        iters = 3
        for _ in range(iters):
            d = jax.device_put(a)
            jax.block_until_ready(d)
        dt = (time.perf_counter() - t0) / iters
        print(f"{mb:3d} MB: {dt*1e3:8.1f} ms  ->  {mb/dt:7.1f} MB/s")

    # async overlap: dispatch N device_puts without blocking, then sync once
    a = rng.integers(0, 255, size=4 * 1024 * 1024, dtype=np.uint8)
    t0 = time.perf_counter()
    ds = [jax.device_put(a) for _ in range(8)]
    jax.block_until_ready(ds)
    dt = time.perf_counter() - t0
    print(f"8x 4MB async: {dt*1e3:8.1f} ms -> {32/dt:7.1f} MB/s aggregate")

    # d2h for comparison
    t0 = time.perf_counter()
    _ = np.asarray(ds[0])
    dt = time.perf_counter() - t0
    print(f"d2h 4MB: {dt*1e3:8.1f} ms -> {4/dt:7.1f} MB/s")


if __name__ == "__main__":
    main()
