"""Device->host transfer characterization on the tunneled TPU runtime."""

import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(0)

    def t_once(f):
        t0 = time.perf_counter()
        out = f()
        return (time.perf_counter() - t0) * 1e3, out

    for kb in (4, 64, 1024, 4096):
        n = kb * 1024
        d = jax.device_put(rng.integers(0, 255, size=n, dtype=np.uint8))
        jax.block_until_ready(d)
        ms1, _ = t_once(lambda: np.asarray(d))
        ms2, _ = t_once(lambda: jax.device_get(d))
        ms3, _ = t_once(lambda: np.asarray(d))
        print(f"{kb:5d} KB: np.asarray {ms1:9.1f} ms | device_get "
              f"{ms2:9.1f} ms | again {ms3:9.1f} ms "
              f"-> {kb/1024/ (ms3/1e3):6.1f} MB/s")

    # is it the transfer or the sync? time a tiny readback after big compute
    big = jax.device_put(rng.random((4096, 4096)).astype(np.float32))

    @jax.jit
    def work(x):
        for _ in range(8):
            x = x @ x
        return x.sum()

    s = work(big)
    jax.block_until_ready(s)
    ms, _ = t_once(lambda: float(work(big)))
    print(f"scalar readback after matmul chain: {ms:9.1f} ms")

    # jit output already on device; read slices of growing size
    d = jax.device_put(rng.integers(0, 255, size=1 << 24, dtype=np.uint8))
    jax.block_until_ready(d)
    for n in (1 << 10, 1 << 16, 1 << 20, 1 << 22):
        sl = d[:n]
        jax.block_until_ready(sl)
        ms, _ = t_once(lambda: np.asarray(sl))
        print(f"slice {n:>9,} B readback: {ms:9.1f} ms "
              f"-> {n/1e6/(ms/1e3):7.1f} MB/s")


if __name__ == "__main__":
    main()
