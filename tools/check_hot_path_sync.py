#!/usr/bin/env python
"""Static check: no host synchronization in hot-path modules.

THIN SHIM (ISSUE 9): the checker migrated into the unified invariant
linter as the ``hot-path-sync`` rule — run ``python -m tools.lint``
for the full rule catalog, or this script for the one check (since
round 12 it also flags ``np.array(<device array>)`` and
``jax.device_get`` — the resident drain loop's host sections must stay
sync-free). Public API
(ALLOWLIST, check_source, check_tree, hot_path_files, main) is
re-exported unchanged for tests/test_hot_path_sync.py and any other
caller. Rule implementation: tools/lint/rules/hot_path_sync.py;
catalog: docs/static-analysis.md.
"""

from __future__ import annotations

import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _ROOT not in sys.path:
    sys.path.insert(0, _ROOT)

from tools.lint.rules.hot_path_sync import (  # noqa: E402,F401
    ALLOWLIST,
    HOT_PATHS,
    INLINE_MARKER,
    SYNC_ATTRS,
    HotPathSyncRule,
    Violation,
    check_source,
    check_tree,
    hot_path_files,
    main,
)

if __name__ == "__main__":
    sys.exit(main())
