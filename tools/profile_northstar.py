"""Phase-level profile of the north-star pipeline (bench.py subject).

Runs the same 1M-key tumbling-window sum as bench.py and prints the
executor's CycleAttribution report (source/host/dispatch/emit EWMAs) plus
wall-clock totals, so optimization targets the measured bottleneck instead
of a guess. Usage:

    python tools/profile_northstar.py [--events N] [--batch B] [--cpu]
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--events", type=int, default=4_000_000)
    ap.add_argument("--batch", type=int, default=262_144)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--trace", default=None,
                    help="directory for a JAX profiler trace of the run")
    args = ap.parse_args()
    if args.cpu:
        os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    import bench
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    bench.BATCH = args.batch

    def gen(offset, n):
        keys, ts, vals = bench.gen_batch(offset, n)
        return {"key": keys, "value": vals}, ts

    cfg = Configuration({"keys.reverse-map": False})
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(len(jax.devices()))
    env.set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1 << 22)
    env.batch_size = args.batch

    sink = CountingSink()
    (
        env.add_source(GeneratorSource(gen, total=args.events))
        .key_by(lambda c: c["key"])
        .time_window(bench.WINDOW_MS)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    if args.trace:
        jax.profiler.start_trace(args.trace)
    t0 = time.perf_counter()
    job = env.execute("profile-northstar")
    dt = time.perf_counter() - t0
    if args.trace:
        jax.profiler.stop_trace()

    rep = env._backpressure_report()
    snap = env.metric_registry.snapshot("jobs.profile-northstar")
    phases = {}
    for k, v in snap.items():
        if "phase_" in k and isinstance(v, dict) and v.get("count"):
            name = k.split("phase_")[1].replace("_ms", "")
            phases[name] = {
                "p50": round(v.get("p50", 0), 1),
                "p95": round(v.get("p95", 0) or 0, 1),
                "max": round(v.get("max", 0), 1),
                "mean": round(v.get("mean", 0), 1),
            }
    print(json.dumps({
        "events_per_s": round(args.events / dt),
        "wall_s": round(dt, 2),
        "steps": job.metrics.steps,
        "steps_fast": job.metrics.steps_fast,
        "fires": job.metrics.fires,
        "classification": rep.get("classification"),
        "phase_hists_ms": phases,
        "busy_cycles": rep.get("busy-cycles"),
    }, indent=2))


if __name__ == "__main__":
    main()
