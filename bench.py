"""North-star benchmark: events/sec/chip on a 1M-key tumbling-window sum.

Subject: flink_tpu's keyed windowed aggregation (columnar source -> keyBy ->
5s event-time tumbling window -> sum -> counting sink) through the real
executor on the default JAX backend (TPU chip under axon; --cpu for the
virtual CPU mesh).

Baseline: the reference's HeapKeyedStateBackend hot path re-implemented
faithfully in-process (per-record: hash -> dict probe -> reduce -> put;
watermark advance -> per-key timer drain; SURVEY §3.2/3.3). The reference
itself (JVM Flink 1.2) cannot run in this image, so the baseline is the same
scalar algorithm in optimized Python; see BASELINE.md.

Prints ONE JSON line:
  {"metric": ..., "value": events_per_sec, "unit": "events/s", "vs_baseline": x}
"""

import argparse
import json
import os
import platform
import subprocess
import sys
import time

import numpy as np

N_KEYS = 1_000_000
WINDOW_MS = 5_000
EVENTS_PER_MS = 2_000          # event-time rate: 10M events per 5s window
BATCH = 262_144
FIRES_PER_STEP = 2
MAX_INFLIGHT = None            # None = runtime default
# candidate micro-batch sizes for the on-TPU calibration sweep: a larger
# batch amortizes the fixed per-step dispatch round trip of the tunneled
# runtime; the sweep measures instead of guessing
SWEEP = (262_144, 524_288, 1_048_576)
PIN_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_PIN.json")


def gen_batch(offset, n):
    idx = np.arange(offset, offset + n, dtype=np.int64)
    keys = (idx * 2862933555777941757) % N_KEYS
    ts = idx // EVENTS_PER_MS
    return keys, ts, np.ones(n, np.float32)


# ---------------------------------------------------------------- backend init
# probe results per cpu-flag: once a probe succeeded (or this process
# initialized the backend itself), later configs in the same run reuse it
# instead of re-spawning probe subprocesses — r5 hung 5x on REPEATED
# probes of a backend the process was already successfully using
_PROBE_CACHE = {}


def probe_backend(cpu: bool, deadline_s: float = 480.0) -> int:
    """Wait for the JAX backend to become initializable; return device count.

    Round-2 postmortem: the TPU tunnel in this environment is transiently
    unavailable — ``jax.devices()`` raised UNAVAILABLE once and hung >5
    minutes on re-test — and the bench shipped a crash instead of a number.
    A hung backend init cannot be cancelled in-process, so each attempt runs
    ``jax.devices()`` in a short-lived subprocess with a hard timeout,
    retrying with JITTERED backoff until ``deadline_s`` (r5 hardening: many
    probes retrying on the same fixed schedule re-collide with whatever
    made the tunnel busy; jitter decorrelates them). Only after a probe
    succeeds does the caller initialize JAX in this process; an
    already-initialized in-process backend short-circuits the probe
    entirely (one probe per run, reused across configs).

    Raises ``RuntimeError`` with the last probe error if the deadline passes.
    """
    import random

    key = bool(cpu)
    if key in _PROBE_CACHE:
        return _PROBE_CACHE[key]
    if "jax" in sys.modules:
        # this process already runs the backend (an earlier config
        # initialized it): reuse instead of dialing the tunnel again.
        # Gate on the backend being ALREADY initialized — calling
        # jax.devices() on a merely-imported jax would trigger the
        # uncancellable in-process init this subprocess probe exists to
        # avoid — and on the live platform matching the request (a cpu
        # probe must not report an accelerator's device count).
        try:
            import jax
            from jax._src import xla_bridge as _xb

            if getattr(_xb, "_backends", None) and (
                not cpu or jax.default_backend() == "cpu"
            ):
                n = len(jax.devices())
                _PROBE_CACHE[key] = n
                return n
        except Exception:
            pass   # fall through to the subprocess probe
    env = dict(os.environ)
    t0 = time.monotonic()
    attempt, last_err, backoff = 0, "no attempts ran", 5.0
    # CPU mode: the JAX_PLATFORMS env var is the ONLY reliable control —
    # the axon plugin re-forces jax_platforms="axon,cpu" during lazy plugin
    # registration inside backends(), overriding an earlier
    # jax.config.update("cpu"); it respects an explicit env var.
    if cpu:
        env["JAX_PLATFORMS"] = "cpu"
        code = ("import jax; jax.config.update('jax_platforms', 'cpu'); "
                "print(len(jax.devices()))")
    else:
        code = "import jax; print(len(jax.devices()))"
    while time.monotonic() - t0 < deadline_s:
        attempt += 1
        per_try = min(90.0, max(15.0, deadline_s - (time.monotonic() - t0)))
        try:
            out = subprocess.run(
                [sys.executable, "-c", code], env=env, timeout=per_try,
                capture_output=True, text=True,
            )
            if out.returncode == 0 and out.stdout.strip().isdigit():
                n = int(out.stdout.strip())
                print(f"backend probe ok after {attempt} attempt(s), "
                      f"{time.monotonic() - t0:.0f}s: {n} device(s)",
                      file=sys.stderr)
                _PROBE_CACHE[key] = n
                return n
            last_err = (out.stderr or out.stdout).strip()[-500:] or \
                f"rc={out.returncode}"
        except subprocess.TimeoutExpired:
            last_err = f"probe hung >{per_try:.0f}s (backend init stuck)"
        print(f"backend probe attempt {attempt} failed: {last_err}",
              file=sys.stderr)
        # jittered: 0.5x-1.5x of the nominal backoff, capped by the
        # remaining deadline budget
        time.sleep(min(backoff * (0.5 + random.random()),
                       max(0.0, deadline_s - (time.monotonic() - t0))))
        backoff = min(backoff * 2, 60.0)
    raise RuntimeError(f"backend unavailable after {attempt} probe(s) over "
                       f"{deadline_s:.0f}s: {last_err}")


# ---------------------------------------------------------------- baseline
def run_baseline(total_events: int):
    """Scalar per-record loop with dict-probe state + per-key fire drain.

    Returns (events/s, fire-latency samples [(n_windows, ms), ...]) where
    latency is watermark-crossing -> emission, chunked every 8192 windows
    of the sequential per-key timer drain (ref WindowOperator.onEventTime:
    one callback per key on the task thread)."""
    state = {}          # (key, pane) -> acc   (the StateTable analog)
    fired = []
    lat = []            # (n_windows, ms) weighted fire-latency samples
    wm_pane = -1
    done = 0
    t0 = time.perf_counter()
    off = 0
    while done < total_events:
        keys, ts, vals = gen_batch(off, min(BATCH, total_events - done))
        off += len(keys)
        kl, tl = keys.tolist(), ts.tolist()
        for i in range(len(kl)):
            k = kl[i]
            pane = tl[i] // WINDOW_MS
            sk = (k, pane)
            cur = state.get(sk)          # HashMap probe
            state[sk] = 1.0 if cur is None else cur + 1.0  # reduce + put
        done += len(kl)
        # watermark advance: fire panes older than max ts (timer drain)
        new_wm_pane = tl[-1] // WINDOW_MS - 1
        if new_wm_pane > wm_pane:
            t_cross = time.perf_counter()
            chunk = 0
            for p in range(wm_pane + 1, new_wm_pane + 1):
                drain = [sk for sk in state if sk[1] == p]
                for sk in drain:
                    fired.append((sk[0], state.pop(sk)))
                    chunk += 1
                    if chunk >= 8192:
                        lat.append(
                            (chunk, (time.perf_counter() - t_cross) * 1e3)
                        )
                        chunk = 0
            if chunk:
                lat.append((chunk, (time.perf_counter() - t_cross) * 1e3))
            wm_pane = new_wm_pane
    # end-of-stream drain of still-open panes (MAX-watermark analog)
    t_cross = time.perf_counter()
    n_left = len(state)
    for sk in list(state):
        fired.append((sk[0], state.pop(sk)))
    if n_left:
        lat.append((n_left, (time.perf_counter() - t_cross) * 1e3))
    dt = time.perf_counter() - t0
    return done / dt, lat


def _weighted_pct(samples, q):
    """Percentile over windows from weighted (n, ms) samples (shared
    implementation with JobMetrics.fire_latency_pct)."""
    from flink_tpu.metrics.latency import weighted_percentile

    return weighted_percentile(samples, q)


# ---------------------------------------------------------------- pinning
def pin_baseline(n_runs: int, events: int):
    """Measure the baseline n_runs times on a quiet host and pin the BEST
    run (throughput and its fire latencies) to BASELINE_PIN.json.

    VERDICT r3 item 3: the co-measured baseline swings ~7x with host load,
    so ratios against it are not defensible. The pinned number is the
    baseline's best case — every future ratio quoted against it is
    conservative. Protocol recorded in the artifact itself."""
    runs = []
    for i in range(n_runs):
        eps, lat = run_baseline(events)
        runs.append({
            "events_per_s": round(eps),
            "fire_p50_ms": round(_weighted_pct(lat, 50) or 0, 2),
            "fire_p99_ms": round(_weighted_pct(lat, 99) or 0, 2),
        })
        print(f"pin run {i + 1}/{n_runs}: {eps:,.0f} events/s",
              file=sys.stderr)
    best = max(runs, key=lambda r: r["events_per_s"])
    pin = {
        "baseline_events_per_s": best["events_per_s"],
        "baseline_fire_p50_ms": best["fire_p50_ms"],
        "baseline_fire_p99_ms": best["fire_p99_ms"],
        "protocol": {
            "runs": n_runs, "pick": "best-of-N throughput",
            "events": events, "batch": BATCH,
            "host": platform.node(), "python": platform.python_version(),
            "pinned_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
        },
        "all_runs": runs,
    }
    with open(PIN_PATH, "w") as f:
        json.dump(pin, f, indent=1)
    print(json.dumps(pin["all_runs"]), file=sys.stderr)
    print(f"pinned best-of-{n_runs} -> {PIN_PATH}", file=sys.stderr)


def load_pin():
    if not os.path.exists(PIN_PATH):
        return None
    with open(PIN_PATH) as f:
        return json.load(f)


# ---------------------------------------------------------------- subject
def run_subject(total_events: int, warmup_events: int, batch: int = None) -> tuple:
    import jax

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    def gen(offset, n):
        keys, ts, vals = gen_batch(offset, n)
        return {"key": keys, "value": vals}, ts

    cfg = Configuration({
        "keys.reverse-map": False,
        # 2 fire lanes per drain step: each lane costs 3 full-capacity
        # pack scatters in the packed variant (the CountingSink rides
        # the ReducedFires drain, where lanes are nearly free), and a
        # tumbling boundary only ever has 1 due end
        "window.fires-per-step": FIRES_PER_STEP,
    })
    if MAX_INFLIGHT is not None:
        # tunable fire-wait vs throughput tradeoff: the p99 drain waits
        # behind up to this many queued update steps
        cfg.set("pipeline.max-inflight-steps", MAX_INFLIGHT)
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(len(jax.devices()))
    env.set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    # capacity == keyspace: keys are ints in [0, N_KEYS), so the auto
    # state layout resolves to the DIRECT-INDEX backend (key == slot — no
    # probe gathers, no insert phase; wk.init_state layout="direct"),
    # the layout a user tuning this job would pick, like choosing the
    # heap vs RocksDB backend in the reference
    env.set_state_capacity(N_KEYS)
    env.batch_size = batch or BATCH

    sink = CountingSink()

    timings = {"t_first": None, "t_start": time.perf_counter()}

    class TimingSource(GeneratorSource):
        def poll(self, max_records):
            out = super().poll(max_records)
            if self.offset >= warmup_events and timings["t_first"] is None:
                timings["t_first"] = time.perf_counter()
            return out

    (
        env.add_source(TimingSource(gen, total=total_events))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW_MS)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    t0 = time.perf_counter()
    job = env.execute("bench-1m-key-window-sum")
    t1 = time.perf_counter()
    measured = total_events - warmup_events
    steady = measured / (t1 - timings["t_first"])
    assert sink.value_sum == total_events, (sink.value_sum, total_events)
    return steady, job, sink


def main():
    global BATCH, FIRES_PER_STEP, MAX_INFLIGHT

    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true", help="CPU mesh instead of TPU")
    ap.add_argument("--events", type=int, default=30_000_000)
    ap.add_argument("--baseline-events", type=int, default=2_000_000)
    ap.add_argument("--batch", type=int, default=None,
                    help="micro-batch size (default BATCH)")
    ap.add_argument("--init-deadline", type=float, default=480.0,
                    help="seconds to keep retrying backend init")
    ap.add_argument("--no-sweep", action="store_true",
                    help="skip the batch-size calibration sweep")
    ap.add_argument("--inflight", type=int, default=None,
                    help="pipeline.max-inflight-steps (p99 vs throughput)")
    ap.add_argument("--fires", type=int, default=FIRES_PER_STEP,
                    help="window.fires-per-step")
    ap.add_argument("--pin-baseline", type=int, default=0, metavar="N",
                    help="measure the baseline N times on this (quiet) "
                         "host, write best-of-N to BASELINE_PIN.json, exit")
    ap.add_argument("--device-ceiling", action="store_true",
                    help="run ONLY the device_update_ceiling microbench "
                         "(pre-staged batch ring, no source): K-fusion x "
                         "duplicate-fraction grid + precombine on/off")
    ap.add_argument("--stages", action="store_true",
                    help="run ONLY the chained 2-stage drain vs "
                         "single-stage comparison at matched dims "
                         "(ISSUE 16): events/s + p99_fire_ms per "
                         "discipline")
    ap.add_argument("--resident", action="store_true",
                    help="run ONLY the resident_loop microbench: ring-"
                         "drain dispatches (one per 32 staged slots) vs "
                         "K=8 fused megasteps at matched dims, stamping "
                         "host dispatches per 1k events + events/s")
    ap.add_argument("--mttr", action="store_true",
                    help="run ONLY the mttr_recovery drill: detect-to-"
                         "first-fire of cold-remote vs local vs warm "
                         "recovery paths, per-phase breakdowns in the "
                         "detail JSON")
    ap.add_argument("--elastic", action="store_true",
                    help="run ONLY the elastic_recovery drill on an "
                         "8-device CPU mesh: kill 1 shard, measure "
                         "degraded-throughput fraction + rescale MTTR "
                         "+ the exactly-once oracle across the "
                         "lose-one -> scale-back cycle")
    ap.add_argument("--tiered", action="store_true",
                    help="run ONLY the tiered key-group state config "
                         "(ISSUE 18): Zipf cold-tail stream with >10x "
                         "more key-groups than the HBM-resident "
                         "budget, events/s as a fraction of the all-"
                         "resident baseline + p99_fire_ms + prefetch "
                         "hit/miss counts")
    ap.add_argument("--selftune", action="store_true",
                    help="run ONLY the self-tuning drill (ISSUE 19): "
                         "skew-shifting stream on a 4-device CPU mesh "
                         "whose hot key-groups migrate mid-run; the "
                         "controller's live rebalance must restore "
                         ">= 0.8x balanced throughput without restart "
                         "while the controller-off run stays degraded")
    ap.add_argument("--scaling", action="store_true",
                    help="run ONLY the chips-vs-events/s curve (ISSUE "
                         "13): the sharded resident drain at matched "
                         "dims on 1/2/4/8 virtual CPU devices, one "
                         "child process per chip count, stamping total "
                         "events/s + parallel efficiency per cell")
    args = ap.parse_args()
    if args.batch:
        BATCH = args.batch
    FIRES_PER_STEP = args.fires
    MAX_INFLIGHT = args.inflight

    if args.pin_baseline:
        pin_baseline(args.pin_baseline, args.baseline_events)
        return

    # persistent XLA compilation cache: repeat bench runs (and the batch
    # sweep's final run) skip the ~20-40s compiles
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

    def fail(msg: str):
        # Backend unreachable at THIS run's moment. If the in-round
        # watcher already captured a REAL measurement this round, replay
        # that row (clearly labeled) instead of erasing it with a zero:
        # the artifact should report the round's best genuine number,
        # not the tunnel's state at the final instant (rounds 2-4 all
        # ended as zeros this way while real mid-round numbers existed).
        session = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "BENCH_SESSION_r05.json",
        )
        try:
            with open(session) as f:
                row = json.load(f)
            if row.get("value") and "error" not in row:
                # machine-readable staleness stamp: consumers must be able
                # to tell a replayed capture from a live measurement
                # without parsing the prose note (r5 replayed a watcher
                # row that was indistinguishable downstream)
                row["stale"] = True
                row["note"] = (
                    "replayed from the in-round watcher capture "
                    "(BENCH_SESSION_r05.json): backend unreachable at "
                    f"this run's moment ({msg})"
                )
                print(json.dumps(row))
                sys.exit(0)
        except (OSError, ValueError):
            pass
        # no real capture exists: emit the structured failure line so the
        # driver records a diagnosable failure, never a bare crash
        # (round-2 postmortem)
        print(json.dumps({
            "metric": "events/sec/chip, 1M-key 5s tumbling-window sum",
            "value": 0,
            "unit": "events/s",
            "vs_baseline": 0,
            "error": msg,
        }))
        sys.exit(0)

    try:
        probe_backend(args.cpu, deadline_s=args.init_deadline)
    except RuntimeError as e:
        fail(f"backend init failed: {e}")

    if args.device_ceiling:
        # device-ceiling mode: the pure on-device update+fire grid (the
        # compute ceiling VERDICT r5 flags), no source / no baseline run
        if args.cpu:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        from bench_configs import (
            DEVICE_CEILING_BATCH,
            run_device_update_ceiling,
        )

        fused_best, split_best = run_device_update_ceiling(
            args.events, args.cpu
        )
        print(json.dumps({
            "metric": "device ceiling, best fused-fire cell vs best "
                      "split-dispatch (PR-5 path) cell, firing stream",
            "value": round(fused_best),
            "unit": "events/s",
            "vs_baseline": (
                round(fused_best / split_best, 2) if split_best else 0
            ),
            "criterion": ">= 1.15",
            "batch": DEVICE_CEILING_BATCH,
        }))
        return

    if args.resident:
        # resident-loop mode (ISSUE 12): ring-drain vs K=8 megastep
        # dispatch disciplines on the firing stream; the detail JSON with
        # the per-cell grid and the per-1k-events dispatch counts prints
        # from inside the config
        if args.cpu:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        from bench_configs import DEVICE_CEILING_BATCH, run_resident_loop

        res_best, fused_best, res_p99, fused_p99 = run_resident_loop(
            args.events, args.cpu
        )
        print(json.dumps({
            "metric": "resident ring-drain best cell vs best K=8 "
                      "fused-megastep (PR-7 path) cell, firing stream",
            "value": round(res_best),
            "unit": "events/s",
            "p99_fire_ms": res_p99,
            "vs_baseline": (
                round(res_best / fused_best, 2) if fused_best else 0
            ),
            "criterion": ">= 1.15",
            "dispatch_drop": 4.0,
            "fused_p99_fire_ms": fused_p99,
            "batch": DEVICE_CEILING_BATCH,
        }))
        # round-20 rows: early-exit while drain vs the scan drain at
        # matched dims, and the per-host DCN-resident mode vs lockstep
        from bench_configs import run_dcn_resident, run_while_drain

        w_eps, s_eps, w_p99, s_p99 = run_while_drain(
            args.events, args.cpu
        )
        print(json.dumps({
            "metric": "early-exit while drain (max_slots=64) vs "
                      "count-gated scan drain (D=32), matched dims, "
                      "firing stream",
            "value": round(w_eps),
            "unit": "events/s",
            "p99_fire_ms": w_p99,
            "vs_baseline": round(w_eps / s_eps, 2) if s_eps else 0,
            "criterion": ">= 1.0 events/s AND >= 1.5x fewer "
                         "dispatches/1k-events (structural 2x)",
            "scan_events_per_s": round(s_eps),
            "scan_p99_fire_ms": s_p99,
            "batch": DEVICE_CEILING_BATCH,
        }))
        r_eps, l_eps, r_cyc, l_cyc = run_dcn_resident(
            args.events, args.cpu
        )
        print(json.dumps({
            "metric": "per-host DCN-resident drains vs single-step "
                      "lockstep rounds",
            "value": round(r_eps),
            "unit": "events/s",
            "vs_baseline": round(r_eps / l_eps, 2) if l_eps else 0,
            "criterion": ">= 1.3x (two-process); see detail.mode for "
                         "the measured topology",
            "cycles": r_cyc,
            "lockstep_cycles": l_cyc,
        }))
        return

    if args.stages:
        # chained-stages mode (ISSUE 16): 2-stage chained drain vs the
        # single-stage resident drain at matched dims; the acceptance
        # criterion is <15% throughput cost for the extra stage, with
        # fire-visibility p99 stamped beside events/s for both
        if args.cpu:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        from bench_configs import DEVICE_CEILING_BATCH, run_chained_stages

        s_eps, c_eps, s_p99, c_p99 = run_chained_stages(
            args.events, args.cpu
        )
        print(json.dumps({
            "metric": "chained 2-stage keyed drain vs single-stage "
                      "resident drain, matched dims, firing stream",
            "value": round(c_eps),
            "unit": "events/s",
            "p99_fire_ms": c_p99,
            "vs_baseline": round(c_eps / s_eps, 2) if s_eps else 0,
            "criterion": ">= 0.85 (<15% throughput cost vs "
                         "single-stage)",
            "single_stage_events_per_s": round(s_eps),
            "single_stage_p99_fire_ms": s_p99,
            "batch": DEVICE_CEILING_BATCH,
        }))
        return

    if args.tiered:
        # tiered-state mode (ISSUE 18): cold-tail stream through the
        # full executor, tiered vs all-resident; the detail JSON with
        # both rows and the acceptance fraction prints from inside the
        # config
        if args.cpu:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        from bench_configs import run_tiered

        t_eps, base_eps, t_p99, tiers = run_tiered(args.events, args.cpu)
        print(json.dumps({
            "metric": "tiered key-group state: Zipf cold-tail stream, "
                      ">10x more key-groups than the HBM-resident "
                      "budget, vs the all-resident baseline",
            "value": round(t_eps),
            "unit": "events/s",
            "p99_fire_ms": t_p99,
            "vs_baseline": round(t_eps / base_eps, 2) if base_eps else 0,
            "criterion": ">= 0.6",
            "all_resident_events_per_s": round(base_eps),
            **tiers,
        }))
        return

    if args.elastic:
        # elasticity drill (ISSUE 8): defined on the 8-device virtual
        # CPU mesh, which must be forced BEFORE JAX initializes — so
        # the drill runs in a CHILD process (this one may already have
        # a live backend), with one retry: the virtual 8-device CPU
        # mesh occasionally segfaults inside XLA under heavy
        # compile/dispatch concurrency (environment-level flake), and
        # the artifact must carry a number or a diagnosable failure
        # line, never a bare crash (round-2 postmortem).
        child_env = dict(os.environ)
        child_env["JAX_PLATFORMS"] = "cpu"
        xla = " ".join(
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        )
        child_env["XLA_FLAGS"] = (
            f"{xla} --xla_force_host_platform_device_count=8".strip()
        )
        # NO persistent compile cache in the drill child: the cache's
        # executable (de)serialization segfaults under the forced
        # 8-device virtual CPU mesh in this jaxlib (reproducible ~90%;
        # clean 0/7 without it) — the drill compiles fresh instead
        child_env.pop("JAX_COMPILATION_CACHE_DIR", None)
        code = (
            "import json, jax; "
            "jax.config.update('jax_platforms', 'cpu'); "
            "from bench_configs import run_elastic_recovery; "
            f"frac, mttr, p99 = run_elastic_recovery({args.events}, True); "
            "print('ELASTIC_RESULT ' + json.dumps([frac, mttr, p99]))"
        )
        result, last_err = None, "no attempts ran"
        for attempt in range(2):
            try:
                r = subprocess.run(
                    [sys.executable, "-c", code], env=child_env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    timeout=1200, capture_output=True, text=True,
                )
            except subprocess.TimeoutExpired:
                last_err = "drill child timed out (>1200s)"
                continue
            sys.stderr.write(r.stderr)
            for line in r.stdout.splitlines():
                if line.startswith("ELASTIC_RESULT "):
                    result = json.loads(line[len("ELASTIC_RESULT "):])
                else:
                    print(line)     # the drill's detail JSON passes up
            if result is not None:
                break
            last_err = (
                f"drill child rc={r.returncode}: "
                f"{(r.stderr or r.stdout).strip()[-300:]}"
            )
            print(f"elastic drill attempt {attempt + 1} failed; "
                  f"retrying: {last_err}", file=sys.stderr)
        if result is None:
            fail(f"elastic drill failed twice: {last_err}")
        frac, mttr_ms, p99_ms = result
        print(json.dumps({
            "metric": "elastic recovery: degraded throughput fraction "
                      "after losing 1 of 8 shards",
            "value": round(frac, 3),
            "unit": "fraction of pre-fault throughput",
            "p99_fire_ms": p99_ms,
            "vs_baseline": round(frac / (7 / 8), 3),
            "criterion": ">= 0.6 * (7/8) = 0.525",
            "rescale_detect_to_first_fire_ms": mttr_ms,
        }))
        return

    if args.selftune:
        # self-tuning drill (ISSUE 19): defined on the 4-device virtual
        # CPU mesh, forced BEFORE JAX initializes — so it runs in a
        # CHILD process with one retry, same segfault workarounds as
        # the elastic drill (no compile cache under the forced mesh)
        child_env = dict(os.environ)
        child_env["JAX_PLATFORMS"] = "cpu"
        xla = " ".join(
            f for f in os.environ.get("XLA_FLAGS", "").split()
            if "host_platform_device_count" not in f
        )
        child_env["XLA_FLAGS"] = (
            f"{xla} --xla_force_host_platform_device_count=4".strip()
        )
        child_env.pop("JAX_COMPILATION_CACHE_DIR", None)
        code = (
            "import json, jax; "
            "jax.config.update('jax_platforms', 'cpu'); "
            "from bench_configs import run_selftune; "
            f"on, off, p99, ctl = run_selftune({args.events}, True); "
            "print('SELFTUNE_RESULT ' + json.dumps([on, off, p99, ctl]))"
        )
        result, last_err = None, "no attempts ran"
        for attempt in range(2):
            try:
                r = subprocess.run(
                    [sys.executable, "-c", code], env=child_env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    timeout=1200, capture_output=True, text=True,
                )
            except subprocess.TimeoutExpired:
                last_err = "selftune child timed out (>1200s)"
                continue
            sys.stderr.write(r.stderr)
            for line in r.stdout.splitlines():
                if line.startswith("SELFTUNE_RESULT "):
                    result = json.loads(line[len("SELFTUNE_RESULT "):])
                else:
                    print(line)     # the drill's detail JSON passes up
            if result is not None:
                break
            last_err = (
                f"selftune child rc={r.returncode}: "
                f"{(r.stderr or r.stdout).strip()[-300:]}"
            )
            print(f"selftune drill attempt {attempt + 1} failed; "
                  f"retrying: {last_err}", file=sys.stderr)
        if result is None:
            fail(f"selftune drill failed twice: {last_err}")
        ratio_on, ratio_off, p99_ms, ctl = result
        print(json.dumps({
            "metric": "self-tuning controller: skew-shifting stream, "
                      "hot key-groups migrate mid-run; live rebalance "
                      "tail throughput vs balanced baseline",
            "value": ratio_on,
            "unit": "fraction of balanced tail throughput",
            "p99_fire_ms": p99_ms,
            "vs_baseline": (
                round(ratio_on / ratio_off, 2) if ratio_off else 0
            ),
            "criterion": ">= 0.8 of balanced throughput without "
                         "restart; controller-off stays degraded",
            "controller_off_fraction": ratio_off,
            **ctl,
        }))
        return

    if args.scaling:
        # real-device probe (ISSUE 20 satellite): with a multi-chip
        # non-CPU backend reachable, each cell slices the FIRST n chips
        # of the REAL mesh — distinct physical cores, so the curve is a
        # genuine chip-count speedup and stamps shared_cores: false.
        # Without one (or under --cpu) the virtual-CPU path below runs
        # unchanged: forced host device counts, shared_cores: true.
        real_backend = None   # (backend, platform, n_devices) or None
        if not args.cpu:
            probe_code = (
                "import json, jax; d = jax.devices(); "
                "print('SCALING_PROBE ' + json.dumps("
                "[jax.default_backend(), d[0].platform, len(d)]))"
            )
            try:
                r = subprocess.run(
                    [sys.executable, "-c", probe_code],
                    env=dict(os.environ),
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                    timeout=600, capture_output=True, text=True,
                )
                for line in r.stdout.splitlines():
                    if line.startswith("SCALING_PROBE "):
                        bk, plat, ndev = json.loads(
                            line[len("SCALING_PROBE "):])
                        if bk != "cpu" and ndev >= 2:
                            real_backend = (bk, plat, ndev)
            except subprocess.TimeoutExpired:
                pass
        curve, p99s, errs = {}, {}, []
        for n_chips in (1, 2, 4, 8):
            child_env = dict(os.environ)
            if real_backend is not None:
                if n_chips > real_backend[2]:
                    continue
                # the real mesh is already the process's device set;
                # the cell slices its first n_chips devices
                child_env.pop("JAX_COMPILATION_CACHE_DIR", None)
                code = (
                    "import json, jax; "
                    "from bench_configs import run_scaling_cell; "
                    f"n, eps, p99 = run_scaling_cell({args.events}, "
                    f"n_devices={n_chips}); "
                    "print('SCALING_RESULT ' + json.dumps("
                    "[n, eps, p99]))"
                )
            else:
                # scaling curve (ISSUE 13): each chip count needs its
                # own forced virtual-device count, set BEFORE JAX
                # initializes — one child process per cell, same
                # segfault workarounds as the elastic drill (no compile
                # cache under the forced mesh, one retry per cell)
                child_env["JAX_PLATFORMS"] = "cpu"
                xla = " ".join(
                    f for f in os.environ.get("XLA_FLAGS", "").split()
                    if "host_platform_device_count" not in f
                )
                child_env["XLA_FLAGS"] = (
                    f"{xla} --xla_force_host_platform_device_count"
                    f"={n_chips}".strip()
                )
                child_env.pop("JAX_COMPILATION_CACHE_DIR", None)
                code = (
                    "import json, jax; "
                    "jax.config.update('jax_platforms', 'cpu'); "
                    "from bench_configs import run_scaling_cell; "
                    f"n, eps, p99 = run_scaling_cell({args.events}); "
                    "print('SCALING_RESULT ' + json.dumps("
                    "[n, eps, p99]))"
                )
            cell = None
            for attempt in range(2):
                try:
                    r = subprocess.run(
                        [sys.executable, "-c", code], env=child_env,
                        cwd=os.path.dirname(os.path.abspath(__file__)),
                        timeout=900, capture_output=True, text=True,
                    )
                except subprocess.TimeoutExpired:
                    errs.append(f"{n_chips}-chip cell timed out")
                    continue
                sys.stderr.write(r.stderr)
                for line in r.stdout.splitlines():
                    if line.startswith("SCALING_RESULT "):
                        cell = json.loads(line[len("SCALING_RESULT "):])
                if cell is not None:
                    break
                errs.append(
                    f"{n_chips}-chip cell rc={r.returncode}: "
                    f"{(r.stderr or r.stdout).strip()[-200:]}"
                )
            if cell is None:
                continue
            n_got, eps, cell_p99 = cell
            if n_got != n_chips:
                errs.append(
                    f"{n_chips}-chip cell got {n_got} devices"
                )
                continue
            curve[str(n_chips)] = round(eps)
            p99s[str(n_chips)] = cell_p99
        if "1" not in curve:
            fail(f"scaling curve has no 1-chip baseline: {errs}")
        one = curve["1"]
        best = max(curve.values())
        best_chips = max(curve, key=curve.get)
        print(json.dumps({
            "metric": "multi-chip scaling: sharded resident drain, "
                      "total events/s at 1/2/4/8 virtual devices",
            "value": best,
            "unit": "events/s",
            "p99_fire_ms": p99s.get(best_chips),
            "vs_baseline": round(best / one, 2),
            "events_per_s_by_chips": curve,
            "p99_fire_ms_by_chips": p99s,
            "parallel_efficiency": {
                c: round(v / (int(c) * one), 3)
                for c, v in curve.items()
            },
            "shared_cores": real_backend is None,
            "backend": (real_backend[0] if real_backend else "cpu"),
            "platform": (real_backend[1] if real_backend else "cpu"),
            "note": (
                f"real {real_backend[0]} mesh: each cell runs the "
                f"sharded drain over the first N of "
                f"{real_backend[2]} physical devices — the curve is "
                f"genuine chip-count speedup"
                if real_backend else
                "all virtual devices share this host's physical "
                "cores, so N-chip cells add shard_map partitioning "
                "overhead without adding compute — the curve "
                "validates the sharded dispatch discipline here; "
                "chip-count speedup needs real chips"
            ),
            "errors": errs,
        }))
        return

    if args.mttr:
        # MTTR drill mode (ISSUE 6): the detail JSON line with per-phase
        # timings prints from inside the config; this summary line is
        # the acceptance number (cold-remote / warm >= 2)
        if args.cpu:
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax

            jax.config.update("jax_platforms", "cpu")
        from bench_configs import run_mttr_recovery

        cold_ms, warm_ms = run_mttr_recovery(args.events, args.cpu)
        print(json.dumps({
            "metric": "MTTR detect-to-first-fire, cold-remote vs warm",
            "value": warm_ms,
            "unit": "ms",
            "vs_baseline": round(cold_ms / warm_ms, 2) if warm_ms else 0,
            "cold_remote_ms": cold_ms,
        }))
        return

    if args.cpu:
        # env var BEFORE jax import: config.update alone is overridden by
        # the axon plugin's lazy registration (see probe_backend)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    def fmt(ms):
        return f"{ms:.1f}ms" if ms is not None else "n/a"

    def rnd(ms):
        return round(ms, 2) if ms is not None else None

    baseline_eps, base_lat = run_baseline(args.baseline_events)
    base_p50 = _weighted_pct(base_lat, 50)
    base_p99 = _weighted_pct(base_lat, 99)
    print(
        f"baseline (scalar heap path): {baseline_eps:,.0f} events/s | "
        f"fire p50={fmt(base_p50)} p99={fmt(base_p99)}",
        file=sys.stderr,
    )

    # -- batch-size calibration sweep (TPU only; CPU smoke is compile-
    # dominated and would mis-calibrate): short steady-state run per
    # candidate, full run at the winner. Events scale with the batch so
    # every candidate measures the same ~18 steady steps.
    sweep_rows = {}
    if not args.cpu and args.batch is None and not args.no_sweep:
        for cand in SWEEP:
            try:
                # warmup in STEPS, not events: compile + any adaptive
                # tiering settle per step (~25 steps), so each candidate
                # must be measured in the same post-settle regime
                eps_c, job_c, _ = run_subject(
                    43 * cand, 25 * cand, batch=cand
                )
            except Exception as e:  # noqa: BLE001 — sweep is best-effort
                print(f"sweep batch={cand} failed: {e}", file=sys.stderr)
                continue
            sweep_rows[cand] = round(eps_c)
            print(
                f"sweep batch={cand}: {eps_c:,.0f} events/s "
                f"(p99 fire {fmt(job_c.metrics.fire_latency_pct(99))})",
                file=sys.stderr,
            )
        if sweep_rows:
            BATCH = max(sweep_rows, key=sweep_rows.get)
            print(f"sweep winner: batch={BATCH}", file=sys.stderr)

    # warmup covers backend init + cold-start key inserts + the adaptive
    # switch to the lookup-only fast tier. The tier switch is STEP-count
    # driven (~25 steps: MON_EVERY x TIER_QUIET_CHECKS sampling), so the
    # warmup floor scales with the batch size the sweep picked;
    # steady-state throughput is what the metric claims
    warmup = min(max(args.events // 3, 25 * BATCH), args.events // 2)
    try:
        subject_eps, job, sink = run_subject(args.events, warmup)
    except Exception as e:  # noqa: BLE001 — one JSON line even on crash
        import traceback

        traceback.print_exc()
        fail(f"subject run failed: {type(e).__name__}: {e}")
    subj_p50 = job.metrics.fire_latency_pct(50)
    subj_p99 = job.metrics.fire_latency_pct(99)
    print(
        f"subject: {subject_eps:,.0f} events/s steady-state | fires={sink.count:,}"
        f" | steps={job.metrics.steps} | late={job.metrics.dropped_late}"
        f" | cap={job.metrics.dropped_capacity}"
        f" | fire p50={fmt(subj_p50)} p99={fmt(subj_p99)}",
        file=sys.stderr,
    )

    # ratio policy (VERDICT r3 item 3): quote against the PINNED quiet-host
    # best-of-N baseline when one exists — conservative and reproducible —
    # and carry the co-measured ratio alongside for context
    pin = load_pin()
    pinned_eps = pin["baseline_events_per_s"] if pin else None
    primary = pinned_eps or baseline_eps
    out = {
        "metric": "events/sec/chip, 1M-key 5s tumbling-window sum",
        "value": round(subject_eps),
        "unit": "events/s",
        "vs_baseline": round(subject_eps / primary, 2),
        "baseline_source": "pinned-best-of-N" if pin else "co-measured",
        "vs_baseline_comeasured": round(subject_eps / baseline_eps, 2),
        "p99_fire_ms": rnd(subj_p99),
        "p50_fire_ms": rnd(subj_p50),
        "baseline_p99_fire_ms": rnd(base_p99),
        "baseline_p50_fire_ms": rnd(base_p50),
        "batch": BATCH,
        "fires_per_step": FIRES_PER_STEP,
        "max_inflight": MAX_INFLIGHT,
    }
    if pin:
        out["baseline_pinned_events_per_s"] = pinned_eps
        out["baseline_pinned_p99_fire_ms"] = pin["baseline_fire_p99_ms"]
    if sweep_rows:
        out["sweep"] = {str(k): v for k, v in sweep_rows.items()}
    print(json.dumps(out))


if __name__ == "__main__":
    main()
