"""IncrementalLearning — the reference's streaming-ML skeleton
(flink-examples-streaming/.../ml/IncrementalLearningSkeleton.java), made
real: a keyed count-window batches training points, each full window
refits a KMeans model (JAX, device matmuls), and the latest model scores
a second stream via a connected control pattern."""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.ml import KMeans


def main():
    rng = np.random.default_rng(7)
    train = [
        tuple(rng.normal(loc=c, scale=0.4, size=2))
        for _ in range(120)
        for c in [(0.0, 0.0), (6.0, 6.0)]
    ]
    score = [tuple(rng.normal(loc=(6, 6), scale=0.4, size=2))
             for _ in range(5)]

    model = {"km": None}

    def fit(window_result):
        pts = np.asarray(window_result, np.float32)
        model["km"] = KMeans(k=2, iterations=20).fit(pts)
        return f"refit on {len(pts)} points"

    env = StreamExecutionEnvironment.get_execution_environment()
    (
        env.from_collection(train)
        .key_by(lambda p: 0)                      # global model
        .count_window(60)
        .apply(lambda key, window, elements: [fit(elements)])
        .print_()
    )
    env.execute("incremental-training")

    labels = np.asarray(model["km"].predict(np.asarray(score, np.float32)))
    print("scored cluster ids:", labels.tolist())


if __name__ == "__main__":
    main()
