"""ALS recommendation example (ref flink-ml ALS / the MusicProfiles
example family): factorize a sparse ratings matrix on the MXU and rank
unseen items per user.

Run: JAX_PLATFORMS=cpu python examples/movie_recommendation.py
"""

import numpy as np

from flink_tpu.ml import ALS

MOVIES = ["Metropolis", "Stalker", "Alien", "Heat", "Clue",
          "Brazil", "Tampopo", "Ran"]


def main():
    rng = np.random.default_rng(42)
    n_users = 30
    # two taste clusters with noise
    taste = rng.integers(0, 2, n_users)
    ratings = []
    for u in range(n_users):
        for m in range(len(MOVIES)):
            if rng.random() < 0.6:
                base = 4.5 if (m % 2 == taste[u]) else 1.5
                ratings.append((u, m, base + rng.normal(0, 0.3)))

    model = ALS(num_factors=2, lambda_=0.05, iterations=15).fit(ratings)
    print(f"trained on {len(ratings)} ratings | "
          f"risk={model.empirical_risk(ratings):.1f}")

    seen = {(u, m) for u, m, _ in ratings}
    for u in (0, 1, 2):
        unseen = [m for m in range(len(MOVIES)) if (u, m) not in seen]
        if not unseen:
            continue
        scores = model.predict([(u, m) for m in unseen])
        best = unseen[int(np.argmax(scores))]
        print(f"user {u} (cluster {taste[u]}): recommend "
              f"{MOVIES[best]!r} ({scores.max():.2f})")


if __name__ == "__main__":
    main()
