"""Message-queue connectors end to end (ref the reference's RabbitMQ /
Redis connector examples): publish order events over real AMQP 0-9-1,
window them per customer, and land the totals in Redis over real RESP2.
Runs against the in-repo MiniRabbit broker and MiniRedis server (the
same public wire protocols over real TCP); point host/port at genuine
services and nothing else changes."""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.rabbitmq import MiniRabbit, RMQSink, RMQSource
from flink_tpu.connectors.redis import MiniRedis, RedisMapper, RedisSink

CUSTOMERS = ["acme", "bolt", "cray", "dyne"]


def main():
    rabbit, redis = MiniRabbit(), MiniRedis()
    rabbit.start()
    redis.start()
    try:
        # producer half: 400 orders over AMQP, correlation ids stamped
        # so the consuming side can be exactly-once
        producer = RMQSink(
            "127.0.0.1", rabbit.port, "orders",
            serializer=lambda o: f"{o[0]},{o[1]},{o[2]}".encode(),
            correlation_id_from=lambda o: f"order-{o[2]}",
        )
        producer.open()
        producer.invoke_batch([
            (CUSTOMERS[i % 4], 100 + i % 7, i) for i in range(400)
        ])
        producer.close()

        # pipeline half: AMQP source -> per-customer 1s windowed revenue
        # -> Redis hash
        env = StreamExecutionEnvironment.get_execution_environment()
        env.set_parallelism(1)
        env.batch_size = 64
        (
            env.add_source(RMQSource(
                "127.0.0.1", rabbit.port, "orders",
                deserializer=lambda b: b.decode().split(","),
                uses_correlation_id=True,
                idle_eof_polls=30,
            ))
            .assign_timestamps_and_watermarks(lambda o: int(o[2]) * 10)
            .key_by(lambda o: o[0])
            .time_window(1000)
            .sum(lambda o: float(o[1]))
            .add_sink(RedisSink(
                "127.0.0.1", redis.port,
                RedisMapper(
                    "HSET",
                    key_from=lambda r: f"{r.key}@{r.window_end_ms}",
                    value_from=lambda r: f"{r.value:.0f}",
                    additional_key="revenue",
                ),
            ))
        )
        env.execute("mq-revenue")

        landed = redis.hashes.get("revenue", {})
        total = sum(float(v) for v in landed.values())
        print(f"windows landed in redis: {len(landed)}, "
              f"total revenue: {total:.0f}")
        expected = float(sum(100 + i % 7 for i in range(400)))
        assert total == expected, (total, expected)
        print("OK")
    finally:
        rabbit.stop()
        redis.stop()


if __name__ == "__main__":
    main()
