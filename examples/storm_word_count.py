"""Storm-compatibility example: the classic word-count topology running
unchanged on the flink_tpu runtime (ref flink-storm WordCountTopology).

Run: JAX_PLATFORMS=cpu python examples/storm_word_count.py
"""

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.storm import (
    BasicBolt, BasicSpout, FlinkTopology, TopologyBuilder,
)

SENTENCES = [
    "the cow jumped over the moon",
    "an apple a day keeps the doctor away",
    "four score and seven years ago",
    "snow white and the seven dwarfs",
    "i am at two with nature",
] * 4


class SentenceSpout(BasicSpout):
    def open(self, collector):
        self.collector = collector
        self.i = 0

    def next_tuple(self):
        if self.i >= len(SENTENCES):
            return False
        self.collector.emit((SENTENCES[self.i],))
        self.i += 1
        return True


class SplitBolt(BasicBolt):
    def execute(self, tup):
        for word in tup[0].split():
            self.collector.emit((word, 1))


class CountBolt(BasicBolt):
    def prepare(self, collector):
        super().prepare(collector)
        self.counts = {}

    def execute(self, tup):
        word, n = tup
        self.counts[word] = self.counts.get(word, 0) + n
        self.collector.emit((word, self.counts[word]))


def main():
    builder = TopologyBuilder()
    builder.set_spout("sentences", SentenceSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("sentences")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 16
    env.set_parallelism(1)
    results = FlinkTopology(builder).execute(env)

    finals = {}
    for word, n in results:
        finals[word] = max(finals.get(word, 0), n)
    for word, n in sorted(finals.items(), key=lambda kv: -kv[1])[:10]:
        print(f"{word:>10}: {n}")


if __name__ == "__main__":
    main()
