"""Dynamic rule enrichment via the broadcast state pattern (ref
KeyedBroadcastProcessFunction — the canonical rules+events shape):
a control stream of (currency, rate) updates broadcast to every parallel
instance; the keyed payment stream converts each amount with whatever
rates have ARRIVED by then and flags the rest UNPRICED.

Stream semantics on display: the two sources interleave in arrival
order, so early payments (the first EUR/GBP below) race their own rates
and print UNPRICED, while later ones (EUR 42 after the EUR rate landed)
convert — exactly the behavior a production rules stream has, and why
such jobs replay or side-output unpriced events."""

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.datastream.functions import KeyedBroadcastProcessFunction
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.state.descriptors import MapStateDescriptor

RATES = [("EUR", 1.09), ("GBP", 1.27), ("JPY", 0.0067)]
PAYMENTS = [
    ("EUR", 100.0), ("GBP", 250.0),     # race their own rates: UNPRICED
    ("JPY", 10000.0), ("EUR", 42.0),    # arrive after the rates: convert
    ("CHF", 7.0),                       # never gets a rate
]


class ConvertToUsd(KeyedBroadcastProcessFunction):
    def process_element(self, payment, ctx, out):
        currency, amount = payment
        rate = ctx.broadcast_state("rates").get(currency)
        if rate is None:
            out.collect(("UNPRICED", currency, amount))
        else:
            out.collect(("USD", currency, round(amount * rate, 2)))

    def process_broadcast_element(self, update, ctx, out):
        currency, rate = update
        ctx.broadcast_state("rates")[currency] = rate


def main():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(1)
    env.batch_size = 4
    sink = CollectSink()
    rates = env.from_collection(RATES)
    payments = env.from_collection(PAYMENTS).key_by(lambda p: p[0])
    desc = MapStateDescriptor("rates", str, float)
    payments.connect(rates.broadcast(desc)).process(
        ConvertToUsd()
    ).add_sink(sink)
    env.execute("dynamic-rules")
    for row in sink.results:
        print(row)


if __name__ == "__main__":
    main()
