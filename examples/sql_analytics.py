"""SQL analytics with the round-4 expression breadth: scalar functions,
non-equi join residuals, and EXPLAIN physical plans (ref flink-table's
WordCountSQL + the Calcite operator table slice)."""

from flink_tpu.table.table import TableEnvironment

ORDERS = {
    "id": [1, 2, 3, 4, 5],
    "cust": [10, 20, 10, 30, 20],
    "amount": [99.5, 15.0, 250.0, 75.0, 300.0],
    "ts": [0, 3_600_000, 7_200_000, 86_400_000, 90_000_000],
    "note": [" rush ", "std", "RUSH", "std", "bulk "],
}
CUSTOMERS = {
    "cust": [10, 20, 30],
    "name": ["ada", "bob", "cyd"],
    "credit": [100.0, 400.0, 50.0],
}

QUERY = (
    "SELECT UPPER(name) AS who, ROUND(amount, 0) AS amt, "
    "EXTRACT(DAY FROM ts) AS d, TRIM(note) AS note "
    "FROM orders JOIN customers ON orders.cust = customers.cust "
    "AND orders.amount < customers.credit "
    "WHERE NOT note LIKE '%bulk%' "
    "ORDER BY amt DESC LIMIT 3"
)


def main():
    tenv = TableEnvironment.create()
    tenv.register_table("orders", tenv.from_columns(ORDERS))
    tenv.register_table("customers", tenv.from_columns(CUSTOMERS))
    print(tenv.explain(QUERY))
    print()
    for row in tenv.sql_query(QUERY).to_dicts():
        print(row)


if __name__ == "__main__":
    main()
