"""YARN session deployment end to end (ref flink-yarn: yarn-session.sh
-> submit jobs -> shutdown): deploy a session cluster through the
public RM REST API, run a windowed job in a worker container, and tear
the application down. Runs against the in-repo MiniYarnRM (which plays
RM + NodeManager, launching real OS processes); point the descriptor at
a genuine RM and the AM/worker processes land in real containers."""

import glob
import os
import tempfile

from flink_tpu.deploy.yarn import MiniYarnRM, YarnClusterDescriptor

JOBS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "..", "tests", "process_jobs.py")


def main():
    work = tempfile.mkdtemp(prefix="yarn-example-")
    rm = MiniYarnRM(os.path.join(work, "yarn"))
    rm.start()
    try:
        print(f"RM REST endpoint: {rm.url}")
        desc = YarnClusterDescriptor(rm.url)
        client = desc.deploy_session_cluster("example-session")
        report = client.app_report()
        print(f"application {client.app_id} is {report['state']}, "
              f"AM tracking {report['trackingUrl']}")

        out = os.path.join(work, "out")
        wid = client.submit_job(
            f"{os.path.abspath(JOBS)}:build_window_job",
            "yarn-example-job", os.path.join(work, "chk"),
            extra_env={
                "FLINK_TPU_TEST_OUT": out,
                "FLINK_TPU_TEST_TOTAL": "20000",
            },
        )
        status = client.wait_job(wid, timeout_s=180)
        containers = client.rest.list_containers(client.app_id)
        print(f"job {wid}: {status}; ran in container "
              f"{containers[0]['id']}")

        total = 0.0
        for path in glob.glob(os.path.join(out, "**", "part-0"),
                              recursive=True):
            with open(path) as f:
                total += sum(float(l.strip().split(",")[2]) for l in f)
        assert status == "FINISHED" and total == 20000.0, (status, total)

        final = client.shutdown_cluster()
        print(f"application torn down: {final['state']}")
        print("OK")
    finally:
        rm.stop()


if __name__ == "__main__":
    main()
