"""CEP fraud detection — small-amount probe followed by a large charge on
the same account within a time window (the canonical flink-cep example
shape)."""

from collections import namedtuple

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.cep import CEP, Pattern
from flink_tpu.core.time import TimeCharacteristic

Tx = namedtuple("Tx", ["ts", "account", "amount"])


def main():
    txs = [
        Tx(1000, "acct-1", 0.5), Tx(2000, "acct-1", 812.0),   # fraud shape
        Tx(1500, "acct-2", 42.0), Tx(3000, "acct-2", 55.0),   # normal
        Tx(4000, "acct-3", 0.9), Tx(90_000, "acct-3", 700.0),  # too far apart
        Tx(120_000, "flush", 0.0),
    ]
    pattern = (
        Pattern.begin("probe").where(lambda t: t.amount < 1.0)
        .followed_by("charge").where(lambda t: t.amount > 500.0)
        .within(60_000)
    )
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    stream = (
        env.from_collection(txs)
        .assign_timestamps_and_watermarks(lambda t: t.ts)
        .key_by(lambda t: t.account)
    )
    CEP.pattern(stream, pattern).select(
        lambda m: f"ALERT {m['probe'].account}: probe "
                  f"{m['probe'].amount} then charge {m['charge'].amount}"
    ).print_()
    env.execute("fraud-detection")


if __name__ == "__main__":
    main()
