"""The round-5 SQL logical planner: rewrite rules visible in EXPLAIN.

A selective filter over a wide join shows all three families of rewrites
firing — constant-filter reduction, filter pushdown through the join
(with outer-join legality), and column pruning at the scans — and the
measured physical plan proves the probe side shrank (ref
FlinkPlannerImpl.scala:46 / the Calcite rule pipeline).

Run: JAX_PLATFORMS=cpu python examples/planner_explain.py
"""

import numpy as np

from flink_tpu.table.table import TableEnvironment


def main():
    tenv = TableEnvironment.create()
    rng = np.random.default_rng(7)
    n = 100_000
    tenv.register_table("clicks", tenv.from_columns({
        "user_id": rng.integers(0, 500, n),
        "dwell_ms": rng.uniform(0, 60_000, n).round(0),
        "referrer": rng.integers(0, 9, n),
        **{f"unused{i}": np.zeros(n) for i in range(6)},
    }))
    tenv.register_table("users", tenv.from_columns({
        "user_id": np.arange(500),
        "tier": np.arange(500) % 4,
        "signup_day": np.arange(500) % 365,
    }))

    query = (
        "SELECT user_id, tier FROM clicks "
        "JOIN users ON clicks.user_id = users.user_id "
        "WHERE dwell_ms > 59000.0 AND 1 = 1"
    )
    print(tenv.explain(query))
    print()
    t = tenv.sql_query(query)
    t_raw = tenv.sql_query(query, optimize=False)
    assert sorted(map(tuple, t.to_rows())) == sorted(
        map(tuple, t_raw.to_rows()))
    print(f"{t.n} rows; optimized and unoptimized plans agree")


if __name__ == "__main__":
    main()
