"""External sinks over real wire protocols: the same windowed pipeline
delivered to Elasticsearch (REST `_bulk`) and Cassandra (CQL v3 binary
frames), both against in-repo spec servers — swap host:port for a real
cluster (ref flink-connector-elasticsearch2 / flink-connector-cassandra).

Deterministic document ids / primary keys make checkpoint replay
idempotent — the reference's exactly-once recipe for both stores.

Run: JAX_PLATFORMS=cpu python examples/sink_catalog.py
"""

import struct

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.cassandra import (
    CassandraSink, CqlConnection, MiniCassandra,
)
from flink_tpu.connectors.elasticsearch import (
    ElasticsearchSink, MiniElasticsearch,
)
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sources import GeneratorSource


def build_env(*sinks):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_parallelism(2).set_max_parallelism(32)
    env.set_state_capacity(512)
    env.batch_size = 256

    def gen(off, n):
        idx = np.arange(off, off + n)
        return ({"page": idx % 8, "ms": np.ones(n, np.float32)},
                (idx * 3).astype(np.int64))

    stream = (
        env.add_source(GeneratorSource(gen, total=8000))
        .key_by(lambda c: c["page"])
        .time_window(1000)
        .sum(lambda c: c["ms"])
    )
    for s in sinks:
        stream.add_sink(s)
    return env


def main():
    es = MiniElasticsearch()
    es.start()
    cass = MiniCassandra()
    cass.start()

    es_sink = ElasticsearchSink(
        "127.0.0.1", es.port,
        emitter=lambda r: {
            "index": "page-views",
            "id": f"{r.key}@{r.window_end_ms}",
            "source": {"page": int(r.key), "end": int(r.window_end_ms),
                       "views": float(r.value)},
        },
        flush_max_actions=64,
    )
    cass_sink = CassandraSink(
        "127.0.0.1", cass.port,
        insert_cql="INSERT INTO views (wk, total) VALUES (?, ?)",
        # bind types must match the declared columns (bigint here):
        # the wire subset is schema-free, like a driver without metadata
        extractor=lambda r: (f"{r.key}@{r.window_end_ms}", int(r.value)),
        setup_cql=["CREATE TABLE IF NOT EXISTS views "
                   "(wk text, total bigint, PRIMARY KEY (wk))"],
    )
    build_env(es_sink, cass_sink).execute("sink-catalog")

    hits = es_sink._request(
        "POST", "/page-views/_search",
        b'{"query": {"term": {"page": 5}}}'
    )["hits"]
    conn = CqlConnection("127.0.0.1", cass.port)
    rows = conn.query("SELECT total FROM views WHERE wk = '5@3000'")
    cql_val = struct.unpack(">q", rows[0][0])[0]
    conn.close()
    print(f"Elasticsearch: {es.doc_count('page-views')} window docs, "
          f"{hits['total']} for page 5")
    print(f"Cassandra:     {cass.row_count('views')} rows, "
          f"views('5@3000') = {cql_val}")
    es.stop()
    cass.stop()


if __name__ == "__main__":
    main()
