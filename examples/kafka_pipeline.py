"""Kafka wire-protocol pipeline (ref the reference's Kafka examples):
produce click events over the public Kafka binary protocol, consume them
into a keyed rolling count, and write the results back to a second
topic. Runs against the in-repo MiniKafkaBroker (same public spec over
real TCP); point host/port at a genuine cluster and nothing else
changes."""

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.kafka import (
    KafkaConsumer,
    KafkaProducerSink,
    MiniKafkaBroker,
)
from flink_tpu.runtime.sinks import CollectSink

USERS = ["ada", "bob", "cyd"]


def main():
    broker = MiniKafkaBroker(topics={"clicks": 2, "counts": 1})
    try:
        # producer half: 90 click events over the wire, two partitions
        for p in (0, 1):
            out = KafkaProducerSink(broker.host, broker.port, "clicks",
                                    partition=p)
            out.invoke_batch([USERS[i % 3] for i in range(45)])
            out.close()

        # consumer half: keyed rolling count through the framework
        env = StreamExecutionEnvironment.get_execution_environment()
        env.set_parallelism(1)
        env.batch_size = 16
        sink = CollectSink()
        src = KafkaConsumer(broker.host, broker.port, "clicks")
        (
            env.add_source(src)
            .key_by(lambda u: u)
            .reduce(lambda a, b: a + b, extractor=lambda u: 1.0)
            .add_sink(sink)
        )
        env.execute("kafka-click-count")
        src.close()

        finals = {}
        for user, count in sink.results:
            finals[user] = max(finals.get(user, 0), count)
        result_sink = KafkaProducerSink(broker.host, broker.port, "counts")
        result_sink.invoke_batch(
            [f"{u}={int(c)}" for u, c in sorted(finals.items())]
        )
        result_sink.close()
        for _key, value in broker.logs[("counts", 0)]:
            print(value.decode())
    finally:
        broker.shutdown()


if __name__ == "__main__":
    main()
