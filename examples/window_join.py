"""WindowJoin — the reference's two-stream join example
(flink-examples-streaming/.../join/WindowJoin.java): a grades stream joined
with a salaries stream per person per window."""

import random

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic

NAMES = ["tom", "jerry", "alice", "bob", "john", "grace"]


def main():
    rng = random.Random(0)
    grades = [
        (t * 100, rng.choice(NAMES), rng.randint(1, 5)) for t in range(100)
    ]
    salaries = [
        (t * 100 + 50, rng.choice(NAMES), rng.randint(30_000, 120_000))
        for t in range(100)
    ]

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    g = env.from_collection(grades).assign_timestamps_and_watermarks(
        lambda e: e[0]
    )
    s = env.from_collection(salaries).assign_timestamps_and_watermarks(
        lambda e: e[0]
    )
    (
        g.join(s)
        .where(lambda e: e[1]).equal_to(lambda e: e[1])
        .time_window(2000)
        .apply(lambda grade, salary: (grade[1], grade[2], salary[2]))
        .print_()
    )
    env.execute("window-join")


if __name__ == "__main__":
    main()
