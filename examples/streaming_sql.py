"""Streaming SQL example: windowed GROUP BY through the device kernels.

Mirrors the reference's StreamSQLExample / WindowWordCount-in-SQL shape:
a ticks stream aggregated per symbol over tumbling event-time windows,
written as SQL.

Run: JAX_PLATFORMS=cpu python examples/streaming_sql.py
"""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.table import StreamTableEnvironment


def build():
    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 1024

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return ({
            "sym": idx % 5,
            "px": ((idx * 7919) % 100).astype(np.float32) / 10.0,
            "rowtime": idx,                    # 1ms per tick
        }, None)

    return env, env.add_source(GeneratorSource(gen, total=20_000))


def main():
    tenv = StreamTableEnvironment.create()
    tenv.register_stream("ticks", build)
    result = tenv.sql_query(
        "SELECT sym, SUM(px) AS volume FROM ticks "
        "WHERE px > 1 "
        "GROUP BY sym, TUMBLE(rowtime, INTERVAL '5' SECOND)"
    )
    for row in sorted(result.to_rows())[:10]:
        print(row)
    print(f"... {result.count()} (sym, window) rows total")


if __name__ == "__main__":
    main()
