"""PageRank on the device graph library (ref flink-examples-batch
PageRank.java / flink-gelly PageRank)."""

from flink_tpu.gelly import Graph


def main():
    edges = [
        ("news", "blog"), ("blog", "news"), ("wiki", "news"),
        ("wiki", "blog"), ("shop", "news"), ("blog", "wiki"),
    ]
    pr = Graph.from_edge_list(edges).page_rank(num_iterations=50)
    for page, rank in sorted(pr.items(), key=lambda kv: -kv[1]):
        print(f"{page:6s} {rank:.4f}")


if __name__ == "__main__":
    main()
