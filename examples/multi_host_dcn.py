"""Multi-host execution over the DCN plane (ref the reference's
multi-TaskManager deployments): this launcher spawns TWO worker
processes that join ONE global mesh, each ingesting a disjoint key
slice; the keyed shuffle rides a single collective, so keys ingested by
process A fire from process B. On real hardware the same two commands
run on two hosts of a pod — only --coordinator changes.

Run: PYTHONPATH=. JAX_PLATFORMS=cpu python examples/multi_host_dcn.py
"""

import os
import socket
import subprocess
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NPROC = 2
N_KEYS = 101
TOTAL_PER_HOST = 20_000
WIN_MS = 1_000


def spec():
    """Builder run BY EACH worker process (--builder examples/...:spec)."""
    from flink_tpu.runtime.dcn import (
        DCNJobSpec,
        GeneratorPartitionSource,
    )

    def source(pid, nproc):
        per_host = N_KEYS // nproc

        def gen(offset, n):
            idx = np.arange(offset, offset + n, dtype=np.int64)
            keys = pid + nproc * (idx % per_host)   # disjoint per host
            return keys, idx // 8, np.ones(n, np.float32)

        return GeneratorPartitionSource(gen, TOTAL_PER_HOST)

    return DCNJobSpec(
        source_factory=source,
        size_ms=WIN_MS,
        capacity_per_shard=2048,
        max_parallelism=64,
        batch_per_host=2048,
    )


def main():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        coord = f"127.0.0.1:{s.getsockname()[1]}"
    work = tempfile.mkdtemp(prefix="dcn-example-")
    outs = [os.path.join(work, f"out-{p}.npz") for p in range(NPROC)]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, "-m", "flink_tpu.runtime.dcn",
             "--coordinator", coord, "--num-processes", str(NPROC),
             "--process-id", str(p),
             "--builder", os.path.abspath(__file__) + ":spec",
             "--out", outs[p]],
            env=env,
        )
        for p in range(NPROC)
    ]
    try:
        codes = [p.wait(timeout=420) for p in procs]
    finally:
        for p in procs:               # never orphan the sibling worker
            if p.poll() is None:
                p.kill()
    if any(codes):
        raise SystemExit(f"worker exit codes: {codes}")

    total, crossed, windows = 0.0, 0, set()
    for host, path in enumerate(outs):
        data = np.load(path)
        for k64, e, v in zip(data["key_id"], data["window_end_ms"],
                             data["value"]):
            total += float(v)
            windows.add((int(k64), int(e)))
            if int(k64) % NPROC != host:
                crossed += 1
    expected = float(NPROC * TOTAL_PER_HOST)
    print(f"hosts: {NPROC}, windows fired: {len(windows)}, "
          f"records: {total:.0f}/{expected:.0f}, "
          f"fires that crossed the DCN hop: {crossed}")
    assert total == expected, (total, expected)
    assert crossed > 0
    print("OK")


if __name__ == "__main__":
    main()
