"""Batch WordCount (ref flink-examples-batch WordCount.java)."""

from flink_tpu.dataset import ExecutionEnvironment

TEXT = [
    "to be or not to be that is the question",
    "whether tis nobler in the mind to suffer",
]


def main():
    env = ExecutionEnvironment.get_execution_environment()
    (
        env.from_collection(TEXT)
        .flat_map(str.split)
        .map(lambda w: (w, 1))
        .group_by(0)
        .sum(1)
        .sort_partition(1, ascending=False)
        .print_()
    )


if __name__ == "__main__":
    main()
