"""SocketWindowWordCount — the reference's flagship streaming example
(flink-examples-streaming/.../socket/SocketWindowWordCount.java:76-79,
BASELINE config #1):

    nc -lk 9999                 # feed words
    python examples/socket_window_word_count.py --port 9999

Lines are split into words, keyed by word, counted over a 5s processing-
time tumbling window, and printed.
"""

import argparse

from flink_tpu import StreamExecutionEnvironment


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--host", default="localhost")
    ap.add_argument("--port", type=int, default=9999)
    args = ap.parse_args()

    env = StreamExecutionEnvironment.get_execution_environment()
    (
        env.socket_text_stream(args.host, args.port)
        .flat_map(str.split)
        .key_by(lambda w: w)
        .time_window(5000)
        .count()
        .map(lambda r: f"{r.key} : {int(r.value)}")
        .print_()
    )
    env.execute("socket-window-word-count")


if __name__ == "__main__":
    main()
