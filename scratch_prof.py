"""Microbenchmark the hot-path primitives on the real device."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

print("devices:", jax.devices(), flush=True)

C = 1 << 22
R = 8
N = C * R


def timeit(name, fn, *args, reps=5):
    fn_j = jax.jit(fn)
    out = fn_j(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(reps):
        out = fn_j(*args)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    print(f"{name:55s} {dt*1e3:9.2f} ms", flush=True)
    return dt


rng = np.random.default_rng(0)

# dispatch overhead
x_small = jnp.ones(8)
timeit("dispatch (tiny add)", lambda x: x + 1, x_small, reps=20)

acc = jnp.zeros(N, jnp.float32)

for B in (65_536, 262_144, 1_048_576):
    idx = jnp.asarray(rng.integers(0, N, B).astype(np.int32))
    idx_sorted = jnp.sort(idx)
    vals = jnp.ones(B, jnp.float32)
    print(f"--- B={B}")
    timeit("scatter-add random dup", lambda a, i, v: a.at[i].add(v), acc, idx, vals)
    timeit("scatter-add sorted dup",
           lambda a, i, v: a.at[i].add(v, indices_are_sorted=True),
           acc, idx_sorted, vals)
    uq = jnp.asarray(np.unique(rng.integers(0, N, B).astype(np.int32))[:B])
    uv = jnp.ones(uq.shape, jnp.float32)
    timeit("scatter-add sorted unique",
           lambda a, i, v: a.at[i].add(v, indices_are_sorted=True,
                                       unique_indices=True),
           acc, uq, uv)
    timeit("scatter-set sorted unique",
           lambda a, i, v: a.at[i].set(v, indices_are_sorted=True,
                                       unique_indices=True),
           acc, uq, uv)
    timeit("sort B int32", lambda i: jnp.sort(i), idx)
    timeit("argsort B int32", lambda i: jnp.argsort(i), idx)
    k64 = jnp.asarray(rng.integers(0, 2**63, B).astype(np.int64))
    timeit("sort B int64", lambda i: jnp.sort(i), k64)
    tbl = jnp.full((C, 2), 0xFFFFFFFF, jnp.uint32)
    cand = jnp.asarray(rng.integers(0, C, (B, 16)).astype(np.int32))
    timeit("[B,16] gather rows", lambda t, c: t[c], tbl, cand)
    seg = jnp.concatenate([jnp.ones((1,), bool),
                           idx_sorted[1:] != idx_sorted[:-1]])

    def segsum(v, s):
        def comb(a, b):
            af, av = a
            bf, bv = b
            return af | bf, jnp.where(bf, bv, av + bv)
        return jax.lax.associative_scan(comb, (s, v))[1]

    timeit("assoc-scan segsum", segsum, vals, seg)
    big = jnp.zeros((R, C), jnp.float32)
    timeit("full-state where-sweep [R,C]",
           lambda a: jnp.where(jnp.zeros((R, 1), bool), 0.0, a), big)

# the actual update step, isolated, B=65536
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops import hashtable

spec_win = wk.WindowSpec(size_ticks=5000, slide_ticks=5000, ring=R,
                         fires_per_step=2)
spec_red = wk.ReduceSpec("sum", jnp.float32)
state = wk.init_state(C, 16, spec_win, spec_red)

for B in (65_536, 262_144):
    hi = jnp.asarray(rng.integers(0, 2**32, B).astype(np.uint32))
    lo = jnp.asarray(rng.integers(0, 2**32, B).astype(np.uint32))
    ts = jnp.asarray(rng.integers(0, 5000, B).astype(np.int32))
    vals = jnp.ones(B, jnp.float32)
    valid = jnp.ones(B, bool)
    print(f"--- update step B={B}")
    timeit("hashtable.upsert",
           lambda tk, h, l, v: hashtable._upsert_impl(tk, h, l, (C, 16, 4), v),
           state.table.keys, hi, lo, valid, reps=3)
    timeit("wk.update full",
           lambda s, h, l, t, v, m: wk.update(s, spec_win, spec_red, h, l, t, v, m),
           state, hi, lo, ts, vals, valid, reps=3)
