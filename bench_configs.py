"""BASELINE.md configs #1/#3/#4/#5: subject vs scalar-reference baseline.

Five measured rows (the north-star config #2 lives in bench.py):
  socket_wc    SocketWindowWordCount: socket text -> split -> keyBy word ->
               5s tumbling count (ref flink-examples SocketWindowWordCount
               .java:76-79)
  count_min    sliding-window Count-Min sketch aggregation (8s/4s)
  sessions     event-time session windows, mergeable sum, 500ms gap
  cep          CEP pattern a -> followed_by b over a keyed stream
               (ref flink-cep NFA.java:132)
  cep_event_time  the same pattern on an out-of-order EVENT-TIME stream
               (round 5: host reorder buffer fronting the device NFA,
               baseline = per-key ts-sorted host NFA)

Each baseline re-implements the reference's scalar hot path in-process
(per-record dict/NFA work — the HeapKeyedStateBackend / NFA analog, see
BASELINE.md). Prints ONE JSON line per config:
  {"config": ..., "subject_eps": ..., "baseline_eps": ..., "ratio": ...}

Usage: python bench_configs.py [--cpu] [--only NAME] [--events N]
"""

import argparse
import json
import socket
import sys
import threading
import time

import numpy as np

from bench import probe_backend

WORDS = [f"w{i:04d}" for i in range(500)]


# ------------------------------------------------------------ socket WC
def run_socket_wc(total_events: int, cpu: bool):
    """Lines of "<ts_ms> <word> <word> ..." over a real TCP socket."""
    words_per_line = 8
    n_lines = total_events // words_per_line
    rng = np.random.default_rng(0)
    widx = rng.integers(0, len(WORDS), total_events)
    lines = []
    for i in range(n_lines):
        ws = widx[i * words_per_line:(i + 1) * words_per_line]
        lines.append(
            (f"{i * 2} " + " ".join(WORDS[j] for j in ws) + "\n").encode()
        )
    payload = b"".join(lines)

    # baseline: scalar split -> dict[(word, window)] += 1 with drains
    t0 = time.perf_counter()
    state, fired, wm_pane = {}, 0, -1
    for i in range(n_lines):
        parts = lines[i].decode().split()
        ts = int(parts[0])
        pane = ts // 5000
        for w in parts[1:]:
            k = (w, pane)
            state[k] = state.get(k, 0) + 1
        if pane - 1 > wm_pane:
            wm_pane = pane - 1
            for k in [k for k in state if k[1] <= wm_pane]:
                fired += 1
                state.pop(k)
    fired += len(state)
    base_dt = time.perf_counter() - t0
    baseline_eps = total_events / base_dt

    # subject: real socket ingestion through the framework's columnar
    # word source — the native one-pass tokenizer
    # (native/src/textparse.cpp) plays the reference flatMap's
    # split/parse role (SocketWindowWordCount.java:76-79), keys are
    # 64-bit token identities, and the window count runs on device;
    # word strings materialize lazily via source.word_of()
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import SocketWordsSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def feed():
        conn, _ = srv.accept()
        with conn:
            conn.sendall(payload)

    t = threading.Thread(target=feed, daemon=True)
    t.start()

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(32)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(4096)
    env.batch_size = 32768
    sink = CountingSink()
    t0 = time.perf_counter()
    (
        env.add_source(SocketWordsSource("127.0.0.1", port))
        .assign_timestamps_and_watermarks(
            lambda c: c["ts"],
            WatermarkStrategy.for_monotonous_timestamps(),
        )
        .key_by(lambda c: c["key"])
        .time_window(5000)
        .count()
        .add_sink(sink)
    )
    env.execute("socket-wc")
    dt = time.perf_counter() - t0
    srv.close()
    assert sink.count > 0
    return total_events / dt, baseline_eps


# ------------------------------------------------------------ count-min
def run_count_min(total_events: int, cpu: bool):
    depth, width = 4, 1024
    rng = np.random.default_rng(1)
    items = rng.zipf(1.3, total_events).astype(np.int64) % 100_000
    ts = (np.arange(total_events, dtype=np.int64) // 500)

    # baseline: scalar CM update (depth hashes + row increments per item)
    from flink_tpu.ops.hashing import splitmix64

    seeds = splitmix64(np.arange(1, depth + 1, dtype=np.uint64))
    t0 = time.perf_counter()
    sketch = np.zeros((depth, width), np.int64)
    wm_pane = -1
    n_done = 0
    CH = 65536
    for off in range(0, total_events, CH):
        it = items[off:off + CH].tolist()
        tss = ts[off:off + CH]
        seed_i = [int(s) for s in seeds]
        for i in range(len(it)):
            x = it[i]
            for d in range(depth):
                h = (((x * seed_i[d]) & 0xFFFFFFFFFFFFFFFF)
                     >> (64 - 10)) % width
                sketch[d, h] += 1
            n_done += 1
        pane = int(tss[-1]) // 4000 - 1
        if pane > wm_pane:
            wm_pane = pane
            sketch[:] = 0          # pane rotation stand-in
    base_dt = time.perf_counter() - t0
    baseline_eps = total_events / base_dt

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(32)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(64)
    env.batch_size = 131_072
    sink = CountingSink()

    def gen(offset, n):
        s = slice(offset, offset + n)
        m = len(items[s])
        return {"key": np.zeros(m, np.int32), "item": items[s]}, ts[s]

    t0 = time.perf_counter()
    (
        env.add_source(GeneratorSource(gen, total=total_events))
        .key_by(lambda cols: cols["key"])
        .time_window(8000, 4000)
        .count_min(lambda cols: cols["item"], depth=depth, width=width,
                   query=[1, 2, 3])
        .add_sink(sink)
    )
    env.execute("count-min")
    dt = time.perf_counter() - t0
    assert sink.count > 0
    return total_events / dt, baseline_eps


# ------------------------------------------------------------- sessions
def run_sessions(total_events: int, cpu: bool):
    n_keys = 50_000
    gap = 500
    rng = np.random.default_rng(2)
    keys = rng.integers(0, n_keys, total_events).astype(np.int64)
    ts = (np.arange(total_events, dtype=np.int64) // 200)
    vals = np.ones(total_events, np.float32)

    # baseline: scalar session tracking (key -> [start, last, acc]),
    # close-on-gap at watermark advances (per-key timer analog)
    t0 = time.perf_counter()
    live = {}
    closed = 0
    CH = 65536
    kl, tl = keys.tolist(), ts.tolist()
    last_scan_wm = -1
    for off in range(0, total_events, CH):
        hi_i = min(off + CH, total_events)
        for i in range(off, hi_i):
            k = kl[i]
            t = tl[i]
            s = live.get(k)
            if s is None:
                live[k] = [t, t, 1.0]
            elif t - s[1] > gap:
                closed += 1
                live[k] = [t, t, 1.0]
            else:
                s[1] = t
                s[2] += 1.0
        wm = tl[hi_i - 1]
        if wm - last_scan_wm >= gap:       # timer sweep
            last_scan_wm = wm
            for k in [k for k, s in live.items() if wm - s[1] > gap]:
                closed += 1
                live.pop(k)
    closed += len(live)
    base_dt = time.perf_counter() - t0
    baseline_eps = total_events / base_dt

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.datastream.window.assigners import EventTimeSessionWindows
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(32)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1 << 18)   # load ~0.2 at 50k live sessions
    env.batch_size = 131_072
    sink = CountingSink()

    def gen(offset, n):
        s = slice(offset, offset + n)
        return {"key": keys[s], "value": vals[s]}, ts[s]

    t0 = time.perf_counter()
    (
        env.add_source(GeneratorSource(gen, total=total_events))
        .key_by(lambda c: c["key"])
        .window(EventTimeSessionWindows.with_gap(gap))
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("sessions-bench")
    dt = time.perf_counter() - t0
    assert sink.count > 0
    return total_events / dt, baseline_eps


# ------------------------------------------------------------------ CEP
def run_cep(total_events: int, cpu: bool):
    from flink_tpu.cep import CEP

    events = _cep_events(total_events, seed=3)
    baseline_eps, n_matches = _cep_host_baseline(events, total_events)

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.runtime.sinks import CountingSink

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.batch_size = 16_384
    sink = CountingSink()
    stream = env.from_collection(events).key_by(lambda e: e.key)
    t0 = time.perf_counter()
    CEP.pattern(stream, _cep_pattern()).select(lambda m: 1.0).add_sink(
        sink)
    job = env.execute("cep-bench")
    dt = time.perf_counter() - t0
    assert job.metrics.cep_device_steps > 0, "device CEP path not taken"
    assert sink.count == n_matches, (sink.count, n_matches)
    return total_events / dt, baseline_eps


def _cep_events(total_events, seed, ooo=0):
    """Shared CEP bench stream: names/keys from `seed`; ooo>0 shuffles
    arrival order within +-ooo of timestamp order."""
    rng = np.random.default_rng(seed)
    names = rng.choice(["a", "b", "x", "y"], total_events,
                       p=[0.05, 0.05, 0.45, 0.45])
    keyarr = rng.integers(0, 1000, total_events)

    class Ev:
        __slots__ = ("name", "key", "ts")

        def __init__(self, name, key, ts):
            self.name = name
            self.key = key
            self.ts = ts

    order = (np.argsort(np.arange(total_events)
                        + rng.uniform(0, ooo, total_events))
             if ooo else range(total_events))
    return [Ev(str(names[i]), int(keyarr[i]), int(i)) for i in order]


def _cep_pattern():
    """Scalar per-record predicates — the baseline host NFA's form (the
    reference's SimpleCondition is per-record by construction)."""
    from flink_tpu.cep import Pattern

    return (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )


def _cep_host_baseline(events, total_events, ordered=False):
    """Per-record host NFA (the reference's NFA.process path); with
    `ordered`, per-key ts-sorted feed (the event-time operator's work)."""
    from flink_tpu.cep import NFA

    nfa = NFA(_cep_pattern())
    t0 = time.perf_counter()
    # the ts-sort IS part of the event-time operator's work: time it
    feed = sorted(events, key=lambda e: e.ts) if ordered else events
    partials = {}
    n_matches = 0
    for e in feed:
        p = partials.get(e.key, [])
        p, ms = nfa.process(p, e, e.ts)
        partials[e.key] = p
        n_matches += len(ms)
    return total_events / (time.perf_counter() - t0), n_matches


def run_cep_event_time(total_events: int, cpu: bool):
    """Event-time device CEP (round 5): the host reorder buffer fronting
    the count-NFA kernel, measured against the per-record host NFA fed
    the same timestamp-ordered stream."""
    from flink_tpu.cep import CEP
    from flink_tpu.core.time import TimeCharacteristic

    events = _cep_events(total_events, seed=5, ooo=16)
    baseline_eps, n_matches = _cep_host_baseline(
        events, total_events, ordered=True)

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 16_384
    sink = CountingSink()
    stream = (
        env.from_collection(events)
        .assign_timestamps_and_watermarks(
            lambda e: e.ts,
            WatermarkStrategy.for_bounded_out_of_orderness(16))
        .key_by(lambda e: e.key)
    )
    t0 = time.perf_counter()
    CEP.pattern(stream, _cep_pattern()).select(lambda m: 1.0).add_sink(
        sink)
    job = env.execute("cep-et-bench")
    dt = time.perf_counter() - t0
    assert job.metrics.cep_engine == "device", job.metrics.cep_engine
    assert sink.count == n_matches, (sink.count, n_matches)
    return total_events / dt, baseline_eps


# ------------------------------------------------- checkpoint overhead
def run_checkpoint_overhead(total_events: int, cpu: bool):
    """Checkpoint-overhead config (flink_tpu/checkpointing): the same
    keyed windowed sum run with checkpointing off / sync-full /
    async-incremental at a fixed step interval. Reports steady-state
    throughput and the step-loop stall a checkpoint causes (the
    sync-phase ms of every checkpoint; async mode only stalls for the
    staging fetch, sync mode for the whole serialize+write).

    subject = async-incremental eps, baseline = sync-full eps; a detail
    JSON line carries per-mode eps + max/mean stall for BENCH_*.json.
    """
    import shutil
    import tempfile

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    n_keys = 10_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 48271) % n_keys,
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 4096) * 1000

    def run(mode):
        cfg = Configuration()
        ckpt_dir = None
        if mode != "off":
            ckpt_dir = tempfile.mkdtemp(prefix=f"ckbench-{mode}-")
            cfg.set("checkpoint.mode",
                    "incremental" if mode == "async_incremental" else "full")
            cfg.set("checkpoint.async", mode == "async_incremental")
        env = StreamExecutionEnvironment(cfg)
        env.set_parallelism(1)
        env.set_max_parallelism(128)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1 << 15)
        env.batch_size = 32768
        if ckpt_dir:
            env.enable_checkpointing(8, ckpt_dir)
        sink = CountingSink()
        t0 = time.perf_counter()
        (
            env.add_source(GeneratorSource(gen, total=total_events))
            .key_by(lambda c: c["key"])
            .time_window(10_000)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute(f"ckpt-bench-{mode}")
        dt = time.perf_counter() - t0
        stats = env.last_job.metrics.checkpoint_stats or []
        stalls = [s["sync_ms"] for s in stats]
        if ckpt_dir:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        assert sink.count > 0
        return {
            "eps": round(total_events / dt),
            "checkpoints": len(stats),
            "max_stall_ms": round(max(stalls), 2) if stalls else 0.0,
            "mean_stall_ms": round(
                sum(stalls) / len(stalls), 2) if stalls else 0.0,
            "bytes_written": sum(s["bytes"] for s in stats),
        }

    detail = {m: run(m) for m in ("off", "sync_full", "async_incremental")}
    print(json.dumps(
        {"config": "checkpoint_overhead", "detail": detail}), flush=True)
    return (detail["async_incremental"]["eps"],
            detail["sync_full"]["eps"])


# -------------------------------------------------- pipelined ingest
def run_ingest_pipeline(total_events: int, cpu: bool):
    """Pipelined-ingest config (ISSUE 3, runtime/ingest.py): the 1M-key
    tumbling-window sum run with prefetch off / on / on+checkpointing
    (incremental+async, the production configuration). Prefetch overlaps
    source poll + encode + device staging with the device step;
    epoch-tagged applied-offset cuts make the overlap legal while
    checkpoints are being written.

    subject = prefetch-on **with** checkpointing eps, baseline =
    prefetch-on without — the acceptance criterion is ratio >= 0.90
    (checkpointing must not give the overlap back). The detail line
    additionally carries the prefetch-off row (the escape hatch /
    pre-pipelining throughput) and per-mode checkpoint stalls.
    """
    import shutil
    import tempfile

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    n_keys = 1 << 20

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 2654435761) % n_keys,
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 32768) * 1000

    def run(mode):
        cfg = Configuration()
        cfg.set("pipeline.prefetch",
                "off" if mode == "prefetch_off" else "on")
        cfg.set("keys.reverse-map", False)   # 1M-key columnar fast path
        ckpt_dir = None
        if mode == "prefetch_on_ckpt":
            ckpt_dir = tempfile.mkdtemp(prefix="ingestbench-")
            cfg.set("checkpoint.mode", "incremental")
            cfg.set("checkpoint.async", True)
        env = StreamExecutionEnvironment(cfg)
        env.set_parallelism(1)
        env.set_max_parallelism(128)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1 << 21)
        env.batch_size = 131072
        if ckpt_dir:
            env.enable_checkpointing(8, ckpt_dir)
        sink = CountingSink()
        t0 = time.perf_counter()
        (
            env.add_source(GeneratorSource(gen, total=total_events))
            .key_by(lambda c: c["key"])
            .time_window(10_000)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute(f"ingest-bench-{mode}")
        dt = time.perf_counter() - t0
        stats = env.last_job.metrics.checkpoint_stats or []
        if ckpt_dir:
            shutil.rmtree(ckpt_dir, ignore_errors=True)
        assert sink.count > 0
        return {
            "eps": round(total_events / dt),
            "checkpoints": len(stats),
            "max_stall_ms": round(
                max((s["sync_ms"] for s in stats), default=0.0), 2),
        }

    detail = {
        m: run(m)
        for m in ("prefetch_off", "prefetch_on", "prefetch_on_ckpt")
    }
    print(json.dumps(
        {"config": "ingest_pipeline", "detail": detail}), flush=True)
    return (detail["prefetch_on_ckpt"]["eps"],
            detail["prefetch_on"]["eps"])


# ---------------------------------------------- observability overhead
def run_observability_overhead(total_events: int, cpu: bool):
    """Observability-overhead config (ISSUE 2): the same keyed windowed
    sum run with span tracing off / sampled (every 64th cycle) / every
    step, so the "negligible overhead" claim is measured, not asserted.
    The always-on telemetry (kg_fill scatter + sampled monitoring fetch)
    is present in every mode — the off row IS the shipping default.

    subject = sampled-tracing eps, baseline = tracing-off eps (the ratio
    is the sampled overhead; the every-step row rides the detail line).
    """
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    n_keys = 10_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 48271) % n_keys,
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 4096) * 1000

    def run(mode):
        cfg = Configuration()
        if mode != "off":
            cfg.set("observability.tracing", True)
            cfg.set("observability.trace-sample-every",
                    64 if mode == "sampled" else 1)
        env = StreamExecutionEnvironment(cfg)
        env.set_parallelism(1)
        env.set_max_parallelism(128)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1 << 15)
        env.batch_size = 32768
        sink = CountingSink()
        t0 = time.perf_counter()
        (
            env.add_source(GeneratorSource(gen, total=total_events))
            .key_by(lambda c: c["key"])
            .time_window(10_000)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute(f"obs-bench-{mode}")
        dt = time.perf_counter() - t0
        assert sink.count > 0
        tracer = env._span_tracer
        return {
            "eps": round(total_events / dt),
            "spans": len(tracer) if tracer is not None else 0,
            "spans_dropped": tracer.dropped if tracer is not None else 0,
        }

    detail = {m: run(m) for m in ("off", "sampled", "every_step")}
    detail["resident_drain_stats"] = _resident_drain_stats_rows()
    detail["chained_drain_stats"] = _chained_drain_stats_rows()
    print(json.dumps(
        {"config": "observability_overhead", "detail": detail}),
        flush=True)
    return detail["sampled"]["eps"], detail["off"]["eps"]


def _resident_drain_stats_rows():
    """Round-14 rows: the drain-interior flight recorder measured at the
    PR 12 matched dims (B/C/ring/slide/D of ``run_resident_loop``, full
    ring drains, lagged fire consumption). Three modes:

    * ``off`` — ``drain_stats=False``: the kernel compiles WITHOUT the
      telemetry payload (the trace-tier ledger pins this byte-identical
      to pre-PR), so this row is the shipping default;
    * ``sampled`` — payload compiled in, host fetches every 8th drain
      (the ``observability.drain-stats-every`` default);
    * ``every_drain`` — payload fetched with every fire batch.

    The sampled-vs-off ratio is the acceptance criterion (<= 2%
    events/s): the payload is element ops and tiny reductions over
    fields the fused body already materialized, and the fetch rides the
    existing lagged device_get, so the steady-state cost must stay in
    the noise."""
    from collections import deque as _dq

    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_resident_drain,
        init_sharded_state,
    )

    n_dev = len(jax.devices())
    ctx = MeshContext.create(n_dev, 128)
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    BPP, D = 4, 32
    n_groups = 6
    n_batches = n_groups * D
    spec = WindowStageSpec(
        win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=C, layout="direct", precombine=False,
    )

    rng = np.random.default_rng(11)
    batches, wms = [], []
    for j in range(n_batches):
        p = j // BPP
        n_hot = B // 2
        lo = np.concatenate([
            rng.integers(0, C - 1, B - n_hot),
            rng.integers(0, 64, n_hot),
        ]).astype(np.uint32)
        rng.shuffle(lo)
        ts = np.full(B, p * SLIDE + SLIDE // 2, np.int32)
        batches.append(tuple(jax.device_put(a) for a in (
            np.zeros(B, np.uint32), lo, ts,
            np.ones(B, np.float32), np.ones(B, bool),
        )))
        wms.append(np.int32(p * SLIDE - 1))

    def measure(drain_stats, fetch_every):
        step = build_window_resident_drain(
            ctx, spec, D, reduced=True, drain_stats=drain_stats
        )

        def run_once():
            state = init_sharded_state(ctx, spec)
            t0 = time.perf_counter()
            handles = _dq()
            mon = None
            for g in range(n_groups):
                sel = range(g * D, (g + 1) * D)
                flat = [a for i in sel for a in batches[i]]
                wmv = np.tile(
                    np.asarray([wms[i] for i in sel], np.int32),
                    (n_dev, 1),
                )
                res = step(state, *flat, wmv, np.int32(D))
                state, mon, fires = res[:3]
                ds = (res[3] if drain_stats
                      and (g + 1) % fetch_every == 0 else None)
                handles.append((fires, ds))
                if len(handles) > 1:
                    cf, ds_h = handles.popleft()
                    payload = (cf.counts, cf.lane_valid,
                               cf.window_end_ticks, cf.value_sums)
                    jax.device_get(
                        payload + (ds_h,) if ds_h is not None
                        else payload
                    )
            while handles:
                cf, ds_h = handles.popleft()
                payload = (cf.counts, cf.lane_valid,
                           cf.window_end_ticks, cf.value_sums)
                jax.device_get(
                    payload + (ds_h,) if ds_h is not None else payload
                )
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        dt = min(run_once() for _ in range(3))
        return round(B * n_batches / dt)

    rows = {
        "off": measure(False, 0),
        "sampled": measure(True, 8),
        "every_drain": measure(True, 1),
        "B": B, "C": C, "ring_depth": D, "n_batches": n_batches,
        "fetch_every_sampled": 8,
    }
    rows["sampled_over_off"] = round(
        rows["sampled"] / max(rows["off"], 1), 4
    )
    rows["criterion"] = "sampled >= 0.98x off (<= 2% overhead)"
    return rows


def _chained_drain_stats_rows():
    """Round-17 rows: the STAGE-AWARE flight recorder measured inside
    the 2-stage chained drain at the round-16 matched dims (B=512 /
    C=4096 / ring depth D=32, firing rollup stream). Three modes,
    mirroring ``_resident_drain_stats_rows``:

    * ``off`` — ``drain_stats=False``: the chained kernel compiles
      WITHOUT the telemetry payload (op_budget_pre_stage_stats.json
      pins this byte-identical to pre-PR);
    * ``sampled`` — stage-0 per-slot payload + per-downstream-stage
      records compiled in, host fetches every 8th drain;
    * ``every_drain`` — both payload planes fetched with every drain.

    The sampled-vs-off ratio is the acceptance criterion (<= 2%
    events/s): the stage tail's record is six scalar reductions over
    planes the edge pack already materialized, riding the same lagged
    fetch as the stage-0 block."""
    from collections import deque as _dq

    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_chained_drain,
        init_sharded_state,
    )

    n_dev = len(jax.devices())
    ctx = MeshContext.create(n_dev, 128)
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    BPP, D = 4, 32
    ROLLUP, KEYSPACE, EX_LANES = 4, 256, 2048
    n_groups = 6
    n_batches = n_groups * D
    spec1 = WindowStageSpec(
        win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=C, layout="direct", precombine=False,
    )
    s2 = ROLLUP * SLIDE
    slack = (D * spec1.win.fires_per_step * SLIDE) // s2 + 2
    spec2 = WindowStageSpec(
        win=wk.WindowSpec(s2, s2, ring=max(8, 2 + slack, 4),
                          fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=C, layout="direct", precombine=False,
    )

    rng = np.random.default_rng(11)
    batches, wms = [], []
    for j in range(n_batches):
        p = j // BPP
        n_hot = B // 2
        lo = np.concatenate([
            rng.integers(0, KEYSPACE, B - n_hot),
            rng.integers(0, 64, n_hot),
        ]).astype(np.uint32)
        rng.shuffle(lo)
        ts = np.full(B, p * SLIDE + SLIDE // 2, np.int32)
        batches.append(tuple(jax.device_put(a) for a in (
            np.zeros(B, np.uint32), lo, ts,
            np.ones(B, np.float32), np.ones(B, bool),
        )))
        wms.append(np.int32(p * SLIDE - 1))

    def measure(drain_stats, fetch_every):
        step = build_window_chained_drain(
            ctx, (spec1, spec2), D, exchange_lanes=EX_LANES,
            drain_stats=drain_stats,
        )

        def run_once():
            state = (init_sharded_state(ctx, spec1),
                     init_sharded_state(ctx, spec2))
            t0 = time.perf_counter()
            handles = _dq()
            mon = None
            for g in range(n_groups):
                sel = range(g * D, (g + 1) * D)
                flat = [a for i in sel for a in batches[i]]
                wmv = np.tile(
                    np.asarray([wms[i] for i in sel], np.int32),
                    (n_dev, 1),
                )
                res = step(state, *flat, wmv, np.int32(D))
                state, mon, fires = res[:3]
                ds = (res[3] if drain_stats
                      and (g + 1) % fetch_every == 0 else None)
                handles.append((fires, ds))
                if len(handles) > 1:
                    cf, ds_h = handles.popleft()
                    payload = (cf.counts, cf.lane_valid,
                               cf.window_end_ticks, cf.value_sums)
                    jax.device_get(
                        payload + (ds_h,) if ds_h is not None
                        else payload
                    )
            while handles:
                cf, ds_h = handles.popleft()
                payload = (cf.counts, cf.lane_valid,
                           cf.window_end_ticks, cf.value_sums)
                jax.device_get(
                    payload + (ds_h,) if ds_h is not None else payload
                )
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        dt = min(run_once() for _ in range(3))
        return round(B * n_batches / dt)

    rows = {
        "off": measure(False, 0),
        "sampled": measure(True, 8),
        "every_drain": measure(True, 1),
        "B": B, "C": C, "ring_depth": D, "n_batches": n_batches,
        "n_stages": 2, "exchange_lanes": EX_LANES,
        "fetch_every_sampled": 8,
    }
    rows["sampled_over_off"] = round(
        rows["sampled"] / max(rows["off"], 1), 4
    )
    rows["criterion"] = "sampled >= 0.98x off (<= 2% overhead)"
    return rows


# ------------------------------------------------- containment overhead
def run_fault_overhead(total_events: int, cpu: bool):
    """Failure-containment overhead config (ISSUE 4): the PR 3
    production path (prefetch + async-incremental checkpointing) run
    with the watchdog OFF vs ON — fault injection disabled in both, the
    failure budget active in both (its bookkeeping is always-on). The
    delta is the per-cycle phase arming plus the monitor thread, which
    is the entire cost a healthy job pays for hang attribution.

    subject = watchdog-on eps, baseline = watchdog-off eps; the
    acceptance criterion is ratio >= 0.98 (<2% containment tax on the
    PR 3 throughput path).
    """
    import shutil
    import tempfile

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    n_keys = 1 << 20

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 2654435761) % n_keys,
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 32768) * 1000

    def run(mode):
        cfg = Configuration()
        cfg.set("pipeline.prefetch", "on")
        cfg.set("keys.reverse-map", False)
        cfg.set("checkpoint.mode", "incremental")
        cfg.set("checkpoint.async", True)
        cfg.set("checkpoint.tolerable-failures", 3)
        cfg.set("watchdog.enabled", mode == "watchdog_on")
        ckpt_dir = tempfile.mkdtemp(prefix="faultbench-")
        env = StreamExecutionEnvironment(cfg)
        env.set_parallelism(1)
        env.set_max_parallelism(128)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1 << 21)
        env.batch_size = 131072
        env.enable_checkpointing(8, ckpt_dir)
        sink = CountingSink()
        t0 = time.perf_counter()
        (
            env.add_source(GeneratorSource(gen, total=total_events))
            .key_by(lambda c: c["key"])
            .time_window(10_000)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute(f"fault-bench-{mode}")
        dt = time.perf_counter() - t0
        m = env.last_job.metrics
        shutil.rmtree(ckpt_dir, ignore_errors=True)
        assert sink.count > 0
        assert m.checkpoints_aborted == 0    # no faults were injected
        return {
            "eps": round(total_events / dt),
            "checkpoints": len(m.checkpoint_stats or []),
            "watchdog_trips": m.watchdog_trips,
        }

    detail = {m: run(m) for m in ("watchdog_off", "watchdog_on")}
    print(json.dumps(
        {"config": "fault_overhead", "detail": detail}), flush=True)
    return (detail["watchdog_on"]["eps"], detail["watchdog_off"]["eps"])


# ---------------------------------------------------- MTTR drill
def run_mttr_recovery(total_events: int, cpu: bool):
    """MTTR drill (ISSUE 6): detect-to-first-fire of the three recovery
    paths, measured through the recovery tracker's per-attempt phase
    spans (metrics/recovery.py).

      cold_remote  a FRESH process-equivalent start (new executor, full
                   XLA recompile) restoring from primary storage with an
                   injected per-directory fetch latency (the
                   ckpt.read.primary fault point models remote object-
                   store RTT; local cache off)
      cold_local   the same fresh start, but the task-local snapshot
                   cache is primed — every chain member reads from
                   verified local disk and the injected remote latency
                   is never paid
      warm         a mid-stream TRANSIENT failure (injected ingest-
                   thread kill): in-process restart reusing the live
                   jitted kernels, local fetch, dirty-only re-stage

    subject = cold_remote detect-to-first-fire ms, baseline = warm ms;
    acceptance is ratio >= 2 (the local+warm path beats cold-remote by
    2x or more). The detail JSON carries the per-phase breakdowns.
    """
    import shutil
    import tempfile

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource
    from flink_tpu.testing import faults
    from flink_tpu.testing.faults import FaultInjector, FaultRule

    n_keys = 1 << 14
    events = min(total_events, 2_000_000)
    READ_DELAY_S = 0.25      # injected per-chain-member remote fetch RTT

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 48271) % n_keys,
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 8192) * 1000

    def build(ckpt_dir, local_on, extra_cfg=None):
        cfg = Configuration({
            "checkpoint.mode": "incremental",
            "checkpoint.async": True,
            "checkpoint.local.enabled": local_on,
            "pipeline.prefetch": "on",
            "keys.reverse-map": False,
            **(extra_cfg or {}),
        })
        env = StreamExecutionEnvironment(cfg)
        env.set_parallelism(1)
        env.set_max_parallelism(128)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1 << 16)
        env.batch_size = 32768
        env.enable_checkpointing(4, ckpt_dir)
        return env

    def wire(env, total):
        sink = CountingSink()
        (
            env.add_source(GeneratorSource(gen, total=total))
            .key_by(lambda c: c["key"])
            .time_window(10_000)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        return sink

    def attempt_row(env, mode_filter=None):
        rep = env._recovery_report()
        rows = [a for a in rep["attempts"] if a["first_fire_ms"]]
        if mode_filter:
            rows = [a for a in rows
                    if (a["mode"] or "").startswith(mode_filter)]
        a = rows[-1]
        return {
            "detect_to_first_fire_ms": a["first_fire_ms"],
            "mode": a["mode"],
            "phases_ms": a["phases_ms"],
            "local_cache": rep["local-cache"],
        }

    # ---- prime: one complete run leaves a restorable chain behind -----
    ckpt_dir = tempfile.mkdtemp(prefix="mttr-")
    env = build(ckpt_dir, local_on=True)
    sink = wire(env, events)
    env.execute("mttr-prime")
    assert sink.count > 0
    local_dir = ckpt_dir.rstrip("/\\") + "-local"

    remote_rules = [FaultRule("ckpt.read.primary", action="sleep",
                              delay_s=READ_DELAY_S, every=1, times=10**9)]

    detail = {"events": events, "read_delay_ms": READ_DELAY_S * 1e3}

    # ---- cold_remote: fresh start, no cache, remote fetch latency -----
    shutil.rmtree(local_dir, ignore_errors=True)   # cache absent
    env = build(ckpt_dir, local_on=False)
    sink = wire(env, events * 2)
    with faults.active(FaultInjector(remote_rules)):
        env.execute("mttr-cold-remote", restore_from=ckpt_dir)
    assert sink.count > 0
    detail["cold_remote"] = attempt_row(env)

    # ---- cold_local: fresh start, cache re-primed by the run above ----
    # (the cold_remote run wrote checkpoints with local off; re-prime by
    # restoring once more WITH the cache on — its own checkpoints mirror)
    env = build(ckpt_dir, local_on=True)
    sink = wire(env, events * 3)
    env.execute("mttr-prime-cache", restore_from=ckpt_dir)
    env = build(ckpt_dir, local_on=True)
    sink = wire(env, events * 4)
    with faults.active(FaultInjector(list(remote_rules))):
        env.execute("mttr-cold-local", restore_from=ckpt_dir)
    assert sink.count > 0
    detail["cold_local"] = attempt_row(env)

    # ---- warm: mid-stream transient failure, in-process restart -------
    env = build(ckpt_dir, local_on=True, extra_cfg={
        "restart-strategy": "exponential-backoff",
        "restart-strategy.exponential-backoff.initial-delay": 0.01,
        "restart-strategy.exponential-backoff.max-delay": 0.05,
    })
    sink = wire(env, events * 5)
    rules = [FaultRule("ingest.producer", action="kill", at=30)] + \
        list(remote_rules)
    with faults.active(FaultInjector(rules)):
        env.execute("mttr-warm", restore_from=ckpt_dir)
    assert sink.count > 0
    detail["warm"] = attempt_row(env, mode_filter="warm")

    print(json.dumps(
        {"config": "mttr_recovery", "detail": detail}), flush=True)
    # subject/baseline slots carry the two MTTR numbers; "ratio" is the
    # acceptance number (cold_remote / warm >= 2)
    return (detail["cold_remote"]["detect_to_first_fire_ms"],
            detail["warm"]["detect_to_first_fire_ms"])


# ------------------------------------------------------ elasticity drill
def run_elastic_recovery(total_events: int, cpu: bool):
    """Elasticity drill (ISSUE 8, ``bench.py --elastic``): kill one
    shard of an 8-device mesh mid-stream and measure the lose-one ->
    degraded run -> scale-back cycle end to end.

    Phases (one job, one stream):

      pre       8-shard steady state (throughput sampled)
      kill      the ``device_loss`` fault class fires at a step
                dispatch — shard 5's device is declared dead
      degraded  elastic recovery re-sliced the key-group ranges over
                the 7 survivors, rebuilt the compiled step family, and
                rescaled-restored the last durable cut (preferring the
                PR 6 task-local cache); the job keeps serving
      scale-back once degraded throughput is established, the drill
                requests scale-up and the job performs a savepoint-cut
                live rescale back to 8 shards

    Stamps: degraded-throughput fraction (criterion >= 0.6 x 7/8 =
    0.525 of pre-fault), the rescaled-recovery detect-to-first-fire
    alongside PR 6's MTTR tiers, and the exactly-once oracle — the
    emission set across the whole cycle equals the unfaulted analytic
    oracle. Returns (degraded_fraction, rescale_first_fire_ms,
    p99_fire_ms) — the p99 is the job's weighted fire-latency
    percentile across the whole cycle (ISSUE 17: the latency half of
    the north-star metric stamped in the headline)."""
    import tempfile

    import jax

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource
    from flink_tpu.testing import faults
    from flink_tpu.testing.faults import FaultInjector, FaultRule

    N_DEV = 8
    if len(jax.devices()) < N_DEV:
        raise RuntimeError(
            f"elastic_recovery needs an {N_DEV}-device mesh; found "
            f"{len(jax.devices())} (bench.py --elastic forces the "
            f"virtual CPU mesh via XLA_FLAGS before JAX initializes)"
        )
    n_keys = 1 << 14
    B = 16384
    WINDOW = 10_000
    events = min(total_events, 2_000_000)
    KILL_SHARD, KILL_AT = 5, 30

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 48271) % n_keys,
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 8192) * 1000

    def expected(total):
        idx = np.arange(total)
        keys = (idx * 48271) % n_keys
        we = ((idx // 8192) * 1000 // WINDOW + 1) * WINDOW
        pair = keys.astype(np.int64) * (1 << 34) + we
        uniq, counts = np.unique(pair, return_counts=True)
        return {
            (int(p >> 34), int(p & ((1 << 34) - 1))): float(c)
            for p, c in zip(uniq.tolist(), counts.tolist())
        }

    ckpt_dir = tempfile.mkdtemp(prefix="elastic-")
    cfg = Configuration({
        "checkpoint.mode": "incremental",
        "checkpoint.async": True,
        "checkpoint.local.enabled": True,
        "pipeline.prefetch": "on",
        "keys.reverse-map": False,
        "restart-strategy": "exponential-backoff",
        "restart-strategy.exponential-backoff.initial-delay": 0.01,
        "restart-strategy.exponential-backoff.max-delay": 0.05,
    })
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(N_DEV)
    env.set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    # capacity == keyspace: the direct-index layout (key == slot), the
    # bench.py configuration — no insert phase, no adaptive tier flip
    # to pollute the phase throughput windows
    env.set_state_capacity(n_keys)
    env.batch_size = B
    env.enable_checkpointing(2, ckpt_dir)

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=events))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )

    marks = {"t_kill": None, "t_deg0": None, "t_scale_req": None}
    samples = []                  # (t_perf, records_in)
    stop = threading.Event()

    def sampler():
        while not stop.is_set():
            m = getattr(env, "_live_metrics", None)
            if m is not None:
                samples.append((time.perf_counter(), m.records_in))
            time.sleep(0.025)

    def scale_up_trigger():
        """Request scale-back once degraded throughput is established:
        the measurement window opens only after real post-replan
        progress (past the re-plan's compile burst + replay catch-up),
        so the degraded slope measures steady degraded serving."""
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline and not stop.is_set():
            ctl = getattr(env, "_elastic_controller", None)
            m = getattr(env, "_live_metrics", None)
            if ctl is not None and ctl.degraded and m is not None:
                r0 = m.records_in
                while time.monotonic() < deadline and not stop.is_set():
                    if marks["t_deg0"] is None and \
                            m.records_in >= r0 + 4 * B:
                        marks["t_deg0"] = time.perf_counter()
                        r0 = m.records_in
                    if marks["t_deg0"] is not None and \
                            m.records_in >= r0 + 16 * B and \
                            time.perf_counter() - marks["t_deg0"] >= 1.0:
                        marks["t_scale_req"] = time.perf_counter()
                        ctl.request_scale_up()
                        return
                    time.sleep(0.025)
                return
            time.sleep(0.025)

    rules = [
        FaultRule("step.dispatch", action="call",
                  fn=lambda _ctx: marks.__setitem__(
                      "t_kill", time.perf_counter()),
                  at=KILL_AT),
        faults.device_loss_rule(shard=KILL_SHARD, at=KILL_AT),
    ]
    threads = [threading.Thread(target=sampler, daemon=True),
               threading.Thread(target=scale_up_trigger, daemon=True)]
    for t in threads:
        t.start()
    try:
        with faults.active(FaultInjector(rules)):
            env.execute("elastic-drill")
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)

    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    exp = expected(events)
    missing = sum(1 for k, v in exp.items() if got.get(k) != v)
    extra = sum(1 for k in got if k not in exp)
    oracle_ok = not missing and not extra

    def slope_eps(t_start, t_end):
        """records/s over the sample window [t_start, t_end)."""
        if t_start is None or t_end is None:
            return None
        win = [(t, r) for t, r in samples if t_start <= t < t_end]
        if len(win) < 4 or win[-1][0] - win[0][0] < 0.2:
            return None
        return (win[-1][1] - win[0][1]) / (win[-1][0] - win[0][0])

    # pre window: the last 3s of the 8-shard steady state, clamped to
    # after the first real progress (the initial compile burst is flat)
    t_first = next((t for t, r in samples if r >= 2 * B), None)
    pre_eps = (
        slope_eps(max(t_first, marks["t_kill"] - 3.0), marks["t_kill"])
        if t_first is not None and marks["t_kill"] is not None else None
    )
    degraded_eps = slope_eps(marks["t_deg0"], marks["t_scale_req"])
    frac = (
        degraded_eps / pre_eps if pre_eps and degraded_eps else 0.0
    )

    rep = env._recovery_report()
    rescaled = [a for a in rep["attempts"]
                if (a["mode"] or "").startswith("rescale")]
    first_fire_ms = (
        rescaled[-1]["first_fire_ms"] if rescaled
        and rescaled[-1]["first_fire_ms"] else 0.0
    )
    el = env._elasticity_report()
    live_m = getattr(env, "_live_metrics", None)
    p99 = live_m.fire_latency_pct(99) if live_m is not None else None
    p99 = round(p99, 2) if p99 is not None else None
    detail = {
        "events": events,
        "devices": N_DEV,
        "killed_shard": KILL_SHARD,
        "p99_fire_ms": p99,
        "pre_fault_eps": round(pre_eps) if pre_eps else None,
        "degraded_eps": round(degraded_eps) if degraded_eps else None,
        "degraded_fraction": round(frac, 3),
        "criterion": ">= 0.6 * (7/8) = 0.525",
        "rescale_detect_to_first_fire_ms": first_fire_ms,
        "rescale_phases_ms": (
            rescaled[-1]["phases_ms"] if rescaled else None
        ),
        "exactly_once": bool(oracle_ok),
        # diagnosable on failure: which side diverged and by how much
        "oracle_missing_or_wrong": int(missing),
        "oracle_extra": int(extra),
        "finished_at_shards": el["current-shards"],
        "rescales": el["rescales"],
        "local_cache": rep["local-cache"],
    }
    print(json.dumps(
        {"config": "elastic_recovery", "detail": detail}), flush=True)
    assert oracle_ok, (
        "exactly-once oracle FAILED across kill -> degraded -> "
        "scale-back"
    )
    return frac, first_fire_ms, p99


# ------------------------------------------------ device update ceiling
DEVICE_CEILING_BATCH = 512   # bench.py --device-ceiling reports this


def run_device_update_ceiling(total_events: int, cpu: bool):
    """Device update + fire ceiling (ISSUE 5, extended by ISSUE 7): a
    pre-staged synthetic batch stream feeds the compiled steps directly
    — no source, no prefetch, no emit path, no tunnel-quietness
    dependence — so the compute ceiling is measured per-round as a
    first-class number.

    Three blocks:

    * ``fusion`` / ``precombine`` — the PR-5 QUIET grid, unchanged for
      trajectory continuity: K in {1,4,8} megasteps x duplicate-key
      fraction, sentinel watermark (no fires mid-loop), plus the
      precombine on/off pair per duplicate fraction.
    * ``fire_grid`` — the ISSUE-7 acceptance grid: a FIRING workload
      (event time advances ~1 pane per ``BPP`` batches, watermark
      trailing, so windows really close mid-stream) run through BOTH
      dispatch disciplines on the same K/dup grid:
        - ``split``: the PR-5 runtime's pattern — the fused group breaks
          at every pane-boundary crossing (partial groups dispatch as
          sequential single steps), then a separate fire dispatch plus
          the blocking small-field fetch the split drain pays;
        - ``fused``: resident-pipeline megasteps (fire folded into the
          scan, build_window_megastep_fired), fire payload handles
          consumed LAGGED like the executor's consume_fires.
      ``acceptance`` stamps best(fused) / best(split) — the "PR 5 best
      cell" is the best the split discipline achieves on this container,
      same K/dup grid, best-of-3 — criterion >= 1.15.
    * ``state_planes`` — the kernel-variant sweep at the base firing
      cell (K=8, dup=0.5, direct, f32-sum, pane-major, split planes),
      varying one axis at a time: packed planes, i32-count accumulators
      (plain + packed), the hash table layout, and slot-major
      accumulator order — so the platform-gated auto defaults (packed /
      precombine off on CPU, on for accelerators) stay grounded in this
      artifact instead of asserted.
    """
    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_fire_reduced_step,
        build_window_fire_step,
        build_window_megastep,
        build_window_megastep_fired,
        build_window_update_step,
        init_sharded_state,
    )

    n_dev = len(jax.devices())
    ctx = MeshContext.create(n_dev, 128)
    # dispatch-overhead regime: small enough that the fixed per-dispatch
    # cost is a measurable share of the step (on the tunneled TPU that
    # cost is ~100ms and ANY batch size sits in this regime); ring 9
    # holds the 8 cycling panes without evicting unfired data
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    N_SLOTS = 8
    BPP = 4            # firing stream: batches per pane (crossing cadence)
    iters = max(128, min(8192, total_events // B))
    # firing cells pre-stage every batch (panes advance monotonically,
    # so batches cannot be reused across iterations like the quiet ring)
    iters_f = max(96, iters // 8)

    def _spec(K=1, dup=0.0, precombine=False, layout="direct",
              red=None, packed=False, acc_layout="pane"):
        return WindowStageSpec(
            win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4,
                              acc_layout=acc_layout),
            red=red or wk.ReduceSpec("sum", jnp.float32),
            capacity_per_shard=C, layout=layout, precombine=precombine,
            packed=packed,
        )

    def _keys(dup, rng, layout):
        n_hot = int(B * dup)
        lo = np.concatenate([
            rng.integers(0, C - 1, B - n_hot),
            rng.integers(0, 64, n_hot),
        ]).astype(np.uint32)
        rng.shuffle(lo)
        if layout == "direct":
            return np.zeros(B, np.uint32), lo
        from flink_tpu.ops.hashing import hash64_host

        h = hash64_host(lo.astype(np.int64))
        return ((h >> np.uint64(32)).astype(np.uint32),
                (h & np.uint64(0xFFFFFFFF)).astype(np.uint32))

    # ---------------------------------------------------- quiet grid (PR 5)
    def make_ring(dup, rng):
        """N_SLOTS pre-staged batches; slot i's records land in pane i,
        so the slot cycle exercises the pane-ring rotation without ever
        evicting unfired data."""
        slots = []
        for i in range(N_SLOTS):
            hi, lo = _keys(dup, rng, "direct")
            ts = np.full(B, i * SLIDE + SLIDE // 2, np.int32)
            slots.append(tuple(jax.device_put(a) for a in (
                hi, lo, ts, np.ones(B, np.float32), np.ones(B, bool),
            )))
        return slots

    WM_MIN = np.int32(-(2**31) + 1)   # sentinel: no fires mid-loop

    def measure_quiet(K, dup, precombine):
        spec = _spec(K, dup, precombine)
        step = (
            build_window_update_step(ctx, spec) if K == 1
            else build_window_megastep(ctx, spec, K)
        )
        fire = build_window_fire_step(ctx, spec)
        state = init_sharded_state(ctx, spec)
        slots = make_ring(dup, np.random.default_rng(7))
        wm = np.full(n_dev, WM_MIN)
        wmv = np.tile(WM_MIN, (n_dev, K))

        def disp(state, it):
            if K == 1:
                return step(state, *slots[it % N_SLOTS], wm)
            flat = [a for j in range(K)
                    for a in slots[(it * K + j) % N_SLOTS]]
            return step(state, *flat, wmv)

        for w in range(3):                      # compile + settle
            state, mon = disp(state, w)
        # compile the fire step too (the sentinel watermark fires
        # nothing, so the live state is untouched)
        state, fr = fire(state, wm)
        jax.block_until_ready(fr.counts)
        # best-of-3: each cell recompiles its own step variant, and a
        # single short pass is at the mercy of host scheduling noise —
        # the ceiling claimed is the best the device actually did
        n_disp = max(1, iters // K)
        upd_dt = float("inf")
        for _rep in range(3):
            t0 = time.perf_counter()
            for it in range(n_disp):
                state, mon = disp(state, it)
            jax.block_until_ready(mon[1])
            upd_dt = min(upd_dt, time.perf_counter() - t0)
        # fire probe: one fire dispatch over the full key population
        t1 = time.perf_counter()
        state, fr = fire(state, np.full(n_dev, np.int32(2**31 - 5)))
        jax.block_until_ready(fr.counts)
        fire_ms = (time.perf_counter() - t1) * 1e3
        return B * n_disp * K / upd_dt, fire_ms

    # ------------------------------------------------- firing-stream cells
    def make_stream(dup, rng, n_batches, layout):
        """Pre-staged batches whose panes ADVANCE (pane j//BPP) with the
        watermark trailing one pane, so windows fire mid-stream — the
        workload the resident pipeline exists for."""
        batches, wms = [], []
        for j in range(n_batches):
            p = j // BPP
            hi, lo = _keys(dup, rng, layout)
            ts = np.full(B, p * SLIDE + SLIDE // 2, np.int32)
            batches.append(tuple(jax.device_put(a) for a in (
                hi, lo, ts, np.ones(B, np.float32), np.ones(B, bool),
            )))
            wms.append(np.int32(p * SLIDE - 1))
        return batches, wms

    def measure_split_fire(K, dup, layout="direct", red=None,
                           packed=False, acc_layout="pane",
                           reduced=False):
        """The PR-5 dispatch discipline on the firing stream: groups
        break at every crossing (partials dispatch as singles), each
        crossing pays a separate fire dispatch + the blocking
        small-field fetch of the split drain. ``reduced`` uses the
        on-chip-reduced fire variant (device_reduce sink topology) —
        the split path's best case, so the acceptance comparison never
        flatters the resident pipeline."""
        spec = _spec(K, dup, layout=layout, red=red, packed=packed,
                     acc_layout=acc_layout)
        step1 = build_window_update_step(ctx, spec)
        mega = build_window_megastep(ctx, spec, K) if K > 1 else None
        fire = (
            build_window_fire_reduced_step(ctx, spec) if reduced
            else build_window_fire_step(ctx, spec)
        )
        n_batches = iters_f * max(1, K)
        batches, wms = make_stream(dup, np.random.default_rng(11),
                                   n_batches, layout)

        def run_once():
            state = init_sharded_state(ctx, spec)
            t0 = time.perf_counter()
            pend = []
            last_wm = WM_MIN
            mon = None
            for j in range(n_batches):
                pend.append(j)
                crossing = wms[j] > last_wm
                if crossing or len(pend) == K:
                    if len(pend) == K and mega is not None:
                        flat = [a for i in pend for a in batches[i]]
                        wmv = np.tile(
                            np.asarray([wms[i] for i in pend], np.int32),
                            (n_dev, 1),
                        )
                        state, mon = mega(state, *flat, wmv)
                    else:
                        for i in pend:
                            state, mon = step1(
                                state, *batches[i],
                                np.full(n_dev, wms[i]),
                            )
                    pend = []
                    if crossing:
                        state, cf = fire(state, np.full(n_dev, wms[j]))
                        # the split drain's blocking small-field fetch
                        jax.device_get((cf.counts, cf.lane_valid,
                                        cf.window_end_ticks,
                                        cf.value_sums))
                        last_wm = wms[j]
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        dt = min(run_once() for _ in range(3))
        return B * n_batches / dt

    def measure_fused_fire(K, dup, layout="direct", red=None,
                           packed=False, acc_layout="pane",
                           reduced=False):
        """The resident pipeline on the same firing stream: full fired
        megasteps throughout (crossings fire IN the scan), payload
        handles consumed lagged like executor.consume_fires.
        ``reduced`` surfaces ReducedFires — no payload stacking, the
        device_reduce topology's path."""
        from collections import deque as _dq

        spec = _spec(K, dup, layout=layout, red=red, packed=packed,
                     acc_layout=acc_layout)
        mega = build_window_megastep_fired(ctx, spec, K, reduced=reduced)
        n_disp = iters_f
        n_batches = n_disp * K
        batches, wms = make_stream(dup, np.random.default_rng(11),
                                   n_batches, layout)

        def consume(cf):
            jax.device_get((cf.counts, cf.lane_valid,
                            cf.window_end_ticks, cf.value_sums))

        def run_once():
            state = init_sharded_state(ctx, spec)
            t0 = time.perf_counter()
            handles = _dq()
            mon = None
            for g in range(n_disp):
                sel = range(g * K, (g + 1) * K)
                flat = [a for i in sel for a in batches[i]]
                wmv = np.tile(
                    np.asarray([wms[i] for i in sel], np.int32),
                    (n_dev, 1),
                )
                state, mon, fires = mega(state, *flat, wmv)
                handles.append(fires)
                if len(handles) > 1:
                    consume(handles.popleft())
            while handles:
                consume(handles.popleft())
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        dt = min(run_once() for _ in range(3))
        return B * n_batches / dt

    platform = jax.default_backend()
    pre_default = platform != "cpu"    # the executor's auto resolutions
    packed_default = platform != "cpu"
    detail = {"platform": platform, "B": B, "C": C,
              "iters": iters, "iters_firing": iters_f, "bpp": BPP,
              "n_devices": n_dev,
              "precombine_auto": pre_default,
              "packed_planes_auto": packed_default,
              "fusion": {}, "precombine": {},
              "fire_grid": {"split": {}, "fused": {}},
              "state_planes": {}}
    for dup in (0.0, 0.5, 0.9):
        row = {}
        for K in (1, 4, 8):
            eps, fire_ms = measure_quiet(K, dup, pre_default)
            row[f"K{K}"] = round(eps)
            if K == 1:
                row["fire_ms"] = round(fire_ms, 2)
        row["K4_vs_K1"] = round(row["K4"] / row["K1"], 2)
        row["K8_vs_K1"] = round(row["K8"] / row["K1"], 2)
        detail["fusion"][f"dup_{dup}"] = row
    for dup in (0.0, 0.5, 0.9):
        on, _ = measure_quiet(1, dup, True)
        off, _ = measure_quiet(1, dup, False)
        detail["precombine"][f"dup_{dup}"] = {
            "on": round(on), "off": round(off),
            "ratio": round(on / off, 2),
        }

    # the ISSUE-7 acceptance grid: both dispatch disciplines, both fire
    # payload modes, same K/dup cells. The headline acceptance compares
    # the device_reduce (on-chip-reduced) topology — the reference
    # northstar bench's path and BOTH disciplines' best case; the
    # compact-payload pair is stamped alongside for the general
    # (key-emitting) topology.
    detail["fire_grid"]["split_reduced"] = {}
    detail["fire_grid"]["fused_reduced"] = {}
    bests = {k: (None, 0.0) for k in
             ("split", "fused", "split_reduced", "fused_reduced")}
    for dup in (0.0, 0.5, 0.9):
        for K in (4, 8):
            cell = f"K{K}_dup_{dup}"
            for mode, eps in (
                ("split", measure_split_fire(K, dup)),
                ("fused", measure_fused_fire(K, dup)),
                ("split_reduced", measure_split_fire(K, dup,
                                                     reduced=True)),
                ("fused_reduced", measure_fused_fire(K, dup,
                                                     reduced=True)),
            ):
                detail["fire_grid"][mode][cell] = round(eps)
                if eps > bests[mode][1]:
                    bests[mode] = (cell, eps)
    best_split = bests["split_reduced"]
    best_fused = bests["fused_reduced"]
    detail["acceptance"] = {
        "topology": "device_reduce (on-chip-reduced fires)",
        "pr5_best_cell": {"cell": best_split[0],
                          "eps": round(best_split[1])},
        "fused_fire_best_cell": {"cell": best_fused[0],
                                 "eps": round(best_fused[1])},
        "ratio": round(best_fused[1] / max(best_split[1], 1.0), 2),
        "criterion": ">= 1.15",
    }
    detail["acceptance_compact"] = {
        "topology": "compact payloads (key-emitting sinks)",
        "pr5_best_cell": {"cell": bests["split"][0],
                          "eps": round(bests["split"][1])},
        "fused_fire_best_cell": {"cell": bests["fused"][0],
                                 "eps": round(bests["fused"][1])},
        "ratio": round(
            bests["fused"][1] / max(bests["split"][1], 1.0), 2
        ),
    }

    # state-plane sweep: one axis at a time off the base firing cell
    KB, DB = 8, 0.5
    i32 = wk.ReduceSpec("count", jnp.int32)
    plane_cells = {
        "base_f32_split": dict(),
        "packed": dict(packed=True),
        "i32_count": dict(red=i32),
        "packed_i32": dict(red=i32, packed=True),
        "hash_table": dict(layout="hash"),
        "slot_major": dict(acc_layout="slot"),
    }
    for name, kw in plane_cells.items():
        detail["state_planes"][name] = {
            "split_fire": round(measure_split_fire(KB, DB, **kw)),
            "fused_fire": round(measure_fused_fire(KB, DB, **kw)),
        }

    # structural stamp (ISSUE 11): grouped op counts, signature digest
    # and compiled memory_analysis bytes for three representative
    # ceiling kernels AT THE BENCH DIMS — so the perf artifact carries
    # a structural trajectory (did a sort appear? did the temp
    # footprint move?) next to events/s. Telemetry only: a stamp
    # failure never changes the bench verdict.
    try:
        from tools.lint.kernel_audit import kernel_structural_stamp

        sds = jax.ShapeDtypeStruct
        batch_sig = (sds((B,), jnp.uint32), sds((B,), jnp.uint32),
                     sds((B,), jnp.int32), sds((B,), jnp.float32),
                     sds((B,), jnp.bool_))
        wm_sig = sds((n_dev,), jnp.int32)
        wmv_sig = sds((n_dev, KB), jnp.int32)
        spec_a = _spec(KB, DB, pre_default)
        st = init_sharded_state(ctx, spec_a)
        detail["audit"] = {
            "update_K1": kernel_structural_stamp(
                build_window_update_step(ctx, spec_a),
                (st,) + batch_sig + (wm_sig,)),
            f"megastep_fired_K{KB}_reduced": kernel_structural_stamp(
                build_window_megastep_fired(ctx, spec_a, KB,
                                            reduced=True),
                (st,) + batch_sig * KB + (wmv_sig,)),
            "fire_reduced": kernel_structural_stamp(
                build_window_fire_reduced_step(ctx, spec_a),
                (st, wm_sig)),
        }
    except Exception as ex:  # noqa: BLE001 — never the bench verdict
        detail["audit"] = {"error": f"{type(ex).__name__}: {ex}"}

    print(json.dumps(
        {"config": "device_update_ceiling", "detail": detail}), flush=True)
    return (best_fused[1], best_split[1])


def run_resident_loop(total_events: int, cpu: bool):
    """Resident ring-drain discipline vs K-megastep dispatch (ISSUE 12):
    the same pre-staged FIRING stream as ``device_update_ceiling``'s
    fire grid, run through

    * ``fused_k8`` — the PR 7 best discipline: K=8
      ``build_window_megastep_fired`` megasteps, fire handles consumed
      lagged (one host dispatch per 8 batches), and
    * ``resident`` — the round-12 drain: ``build_window_resident_drain``
      at ring depth D=32, ONE count-gated dispatch retiring 32 staged
      slots (the steady-state full-ring drain the executor issues when
      the prefetch thread keeps the HBM ring ahead of the device).

    Matched dims throughout (same B/C/ring/slide/BPP, same stream
    generator, same lagged fire consumption), so the delta is purely the
    dispatch discipline. The headline compares the device_reduce
    (on-chip-reduced fires) topology — both disciplines' best case — and
    stamps the compact-payload pair alongside. ``dispatch`` carries host
    dispatches per 1k events for both paths: structural counts (the loop
    issues exactly n_batches/K and n_batches/D dispatches), so the >= 4x
    drop criterion is auditable from the artifact alone."""
    from collections import deque as _dq

    import jax
    import jax.numpy as jnp

    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_megastep_fired,
        build_window_resident_drain,
        init_sharded_state,
    )

    n_dev = len(jax.devices())
    ctx = MeshContext.create(n_dev, 128)
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    BPP = 4
    K, D = 8, 32            # PR 7 best megastep depth vs drain ring depth
    iters = max(128, min(8192, total_events // B))
    # full groups only for BOTH disciplines (steady state: the prefetch
    # ring stays ahead), so n_batches is a multiple of lcm(K, D) = D and
    # the dispatch-count ratio is structurally D/K
    n_groups = max(3, max(96, iters // 8) // D)
    n_batches = n_groups * D

    def _spec():
        return WindowStageSpec(
            win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4),
            red=wk.ReduceSpec("sum", jnp.float32),
            capacity_per_shard=C, layout="direct", precombine=False,
        )

    def _keys(dup, rng):
        n_hot = int(B * dup)
        lo = np.concatenate([
            rng.integers(0, C - 1, B - n_hot),
            rng.integers(0, 64, n_hot),
        ]).astype(np.uint32)
        rng.shuffle(lo)
        return np.zeros(B, np.uint32), lo

    def make_stream(dup, rng):
        batches, wms = [], []
        for j in range(n_batches):
            p = j // BPP
            hi, lo = _keys(dup, rng)
            ts = np.full(B, p * SLIDE + SLIDE // 2, np.int32)
            batches.append(tuple(jax.device_put(a) for a in (
                hi, lo, ts, np.ones(B, np.float32), np.ones(B, bool),
            )))
            wms.append(np.int32(p * SLIDE - 1))
        return batches, wms

    def consume(cf):
        got = jax.device_get((cf.counts, cf.lane_valid,
                              cf.window_end_ticks, cf.value_sums))
        return max(int(np.asarray(got[1]).sum()), 1)

    def measure(group, build, dup, reduced):
        """One discipline at group size ``group``: n_batches/group
        dispatches over the shared stream, lagged fire consumption,
        best-of-3. Also samples fire-VISIBILITY latency — dispatch of
        the producing group to its fires host-fetched, the lag the
        discipline actually imposes on the emit path — weighted by
        live fire lanes, so p99 stamps beside events/s (ISSUE 16
        satellite: latency as a first-class acceptance axis)."""
        spec = _spec()
        step = build(spec, reduced)
        batches, wms = make_stream(dup, np.random.default_rng(11))
        n_disp = n_batches // group
        lat = []

        def run_once():
            state = init_sharded_state(ctx, spec)
            t0 = time.perf_counter()
            handles = _dq()
            mon = None
            for g in range(n_disp):
                sel = range(g * group, (g + 1) * group)
                flat = [a for i in sel for a in batches[i]]
                wmv = np.tile(
                    np.asarray([wms[i] for i in sel], np.int32),
                    (n_dev, 1),
                )
                if group == D:
                    # count-gated drain: full ring, all slots live
                    state, mon, fires = step(
                        state, *flat, wmv, np.int32(group)
                    )
                else:
                    state, mon, fires = step(state, *flat, wmv)
                handles.append((time.perf_counter(), fires))
                if len(handles) > 1:
                    t_d, cf = handles.popleft()
                    lat.append((consume(cf),
                                (time.perf_counter() - t_d) * 1e3))
            while handles:
                t_d, cf = handles.popleft()
                lat.append((consume(cf),
                            (time.perf_counter() - t_d) * 1e3))
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        lat.clear()                              # drop compile-run samples
        dt = min(run_once() for _ in range(3))
        return B * n_batches / dt, lat

    def m_fused(dup, reduced=True):
        return measure(
            K, lambda s, r: build_window_megastep_fired(ctx, s, K,
                                                        reduced=r),
            dup, reduced,
        )

    def m_resident(dup, reduced=True):
        return measure(
            D, lambda s, r: build_window_resident_drain(ctx, s, D,
                                                        reduced=r),
            dup, reduced,
        )

    detail = {
        "platform": jax.default_backend(), "B": B, "C": C,
        "k_megastep": K, "ring_depth": D, "n_batches": n_batches,
        "bpp": BPP, "n_devices": n_dev,
        "fused_k8": {}, "resident_d32": {},
        # structural dispatch accounting: the measurement loops above
        # issue EXACTLY these counts (full groups only), so the per-1k
        # numbers are exact, not sampled
        "dispatch": {
            "fused_k8_per_1k_events": round(1000.0 / (B * K), 4),
            "resident_per_1k_events": round(1000.0 / (B * D), 4),
            "drop": round(D / K, 2),
            "criterion": ">= 4x",
        },
    }
    from flink_tpu.metrics.latency import weighted_percentile

    def _p99(lat):
        p = weighted_percentile(lat, 99)
        return round(p, 2) if p is not None else None

    bests = {"fused": (None, 0.0, []), "resident": (None, 0.0, [])}
    for dup in (0.0, 0.5, 0.9):
        cell = f"dup_{dup}"
        ef, lf = m_fused(dup)
        er, lr = m_resident(dup)
        detail["fused_k8"][cell] = {"eps": round(ef),
                                    "p99_fire_ms": _p99(lf)}
        detail["resident_d32"][cell] = {"eps": round(er),
                                        "p99_fire_ms": _p99(lr)}
        if ef > bests["fused"][1]:
            bests["fused"] = (cell, ef, lf)
        if er > bests["resident"][1]:
            bests["resident"] = (cell, er, lr)
    # compact-payload (key-emitting sink) pair at the base cell, stamped
    # for the general topology next to the reduced headline
    detail["compact_dup_0.5"] = {
        "fused_k8": round(m_fused(0.5, reduced=False)[0]),
        "resident_d32": round(m_resident(0.5, reduced=False)[0]),
    }
    res_p99 = _p99(bests["resident"][2])
    fused_p99 = _p99(bests["fused"][2])
    detail["acceptance"] = {
        "topology": "device_reduce (on-chip-reduced fires)",
        "pr7_fused_best_cell": {"cell": bests["fused"][0],
                                "eps": round(bests["fused"][1]),
                                "p99_fire_ms": fused_p99},
        "resident_best_cell": {"cell": bests["resident"][0],
                               "eps": round(bests["resident"][1]),
                               "p99_fire_ms": res_p99},
        "ratio": round(
            bests["resident"][1] / max(bests["fused"][1], 1.0), 2
        ),
        "criterion": ">= 1.15",
        "dispatch_drop": round(D / K, 2),
        "dispatch_criterion": ">= 4x",
    }
    print(json.dumps(
        {"config": "resident_loop", "detail": detail}), flush=True)
    return (bests["resident"][1], bests["fused"][1], res_p99, fused_p99)


def run_while_drain(total_events: int, cpu: bool):
    """Early-exit while drain vs the count-gated scan drain (ISSUE 20):
    matched dims (B=512 / C=4096 / scan ring depth D=32, the
    ``run_resident_loop`` firing stream), two dispatch disciplines:

    * ``scan_d32`` — ``build_window_resident_drain`` at D=32, one
      count-gated dispatch per 32 staged slots (the round-12 steady
      state), and
    * ``while_ms64`` — ``build_window_while_drain`` at
      max_slots=2xD=64 (the executor's default
      pipeline.while-drain.max-slots resolution): the publish cursor
      runs ahead of the drain base, so one dispatch retires the whole
      64-slot burst the accumulator groups under sustained ingest.

    The dispatch accounting is structural (full groups only):
    1000/(B*D) vs 1000/(B*MS) host dispatches per 1k events, a 2x cut
    against the >= 1.5x criterion. The throughput criterion is parity
    or better (>= 1.0x) — the while lowering must not tax the per-slot
    body — and fire-VISIBILITY p99 stamps beside events/s for both
    disciplines (the while drain holds fires until the loop exits, so
    its emit lag is the number the criterion guards)."""
    from collections import deque as _dq

    import jax
    import jax.numpy as jnp

    from flink_tpu.metrics.latency import weighted_percentile
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_resident_drain,
        build_window_while_drain,
        init_sharded_state,
    )

    n_dev = len(jax.devices())
    ctx = MeshContext.create(n_dev, 128)
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    BPP = 4
    D = 32                  # scan ring depth (matched with PR 12)
    MS = 2 * D              # while-drain bound: the executor default
    iters = max(128, min(8192, total_events // B))
    n_groups = max(2, max(96, iters // 8) // MS)
    n_batches = n_groups * MS

    def _spec():
        return WindowStageSpec(
            win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4),
            red=wk.ReduceSpec("sum", jnp.float32),
            capacity_per_shard=C, layout="direct", precombine=False,
        )

    def _keys(rng, dup=0.5):
        n_hot = int(B * dup)
        lo = np.concatenate([
            rng.integers(0, C - 1, B - n_hot),
            rng.integers(0, 64, n_hot),
        ]).astype(np.uint32)
        rng.shuffle(lo)
        return np.zeros(B, np.uint32), lo

    def make_stream(rng):
        batches, wms = [], []
        for j in range(n_batches):
            p = j // BPP
            hi, lo = _keys(rng)
            ts = np.full(B, p * SLIDE + SLIDE // 2, np.int32)
            batches.append(tuple(jax.device_put(a) for a in (
                hi, lo, ts, np.ones(B, np.float32), np.ones(B, bool),
            )))
            wms.append(np.int32(p * SLIDE - 1))
        return batches, wms

    def consume(cf):
        got = jax.device_get((cf.counts, cf.lane_valid,
                              cf.window_end_ticks, cf.value_sums))
        return max(int(np.asarray(got[1]).sum()), 1)

    def measure(group, step, is_while):
        batches, wms = make_stream(np.random.default_rng(11))
        n_disp = n_batches // group
        lat = []

        def run_once():
            state = init_sharded_state(ctx, spec)
            t0 = time.perf_counter()
            handles = _dq()
            mon = None
            for g in range(n_disp):
                sel = range(g * group, (g + 1) * group)
                flat = [a for i in sel for a in batches[i]]
                wmv = np.tile(
                    np.asarray([wms[i] for i in sel], np.int32),
                    (n_dev, 1),
                )
                if is_while:
                    # steady state: the publish cursor committed the
                    # whole staged burst (absolute seqs; base = the
                    # group's first ring seq)
                    base = g * group
                    state, mon, fires, _consumed = step(
                        state, *flat, wmv,
                        np.full(1, base + group, np.int32),
                        np.int32(base), np.int32(group),
                    )
                else:
                    state, mon, fires = step(
                        state, *flat, wmv, np.int32(group)
                    )
                handles.append((time.perf_counter(), fires))
                if len(handles) > 1:
                    t_d, cf = handles.popleft()
                    lat.append((consume(cf),
                                (time.perf_counter() - t_d) * 1e3))
            while handles:
                t_d, cf = handles.popleft()
                lat.append((consume(cf),
                            (time.perf_counter() - t_d) * 1e3))
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        lat.clear()
        dt = min(run_once() for _ in range(3))
        return B * n_batches / dt, lat

    def _p99(lat):
        p = weighted_percentile(lat, 99)
        return round(p, 2) if p is not None else None

    spec = _spec()
    scan_eps, scan_lat = measure(
        D, build_window_resident_drain(ctx, spec, D, reduced=True),
        False,
    )
    while_eps, while_lat = measure(
        MS, build_window_while_drain(ctx, spec, MS, reduced=True),
        True,
    )
    scan_p99, while_p99 = _p99(scan_lat), _p99(while_lat)
    detail = {
        "platform": jax.default_backend(), "B": B, "C": C,
        "scan_ring_depth": D, "while_max_slots": MS,
        "n_batches": n_batches, "bpp": BPP, "n_devices": n_dev,
        "scan_d32": {"eps": round(scan_eps), "p99_fire_ms": scan_p99},
        "while_ms64": {"eps": round(while_eps),
                       "p99_fire_ms": while_p99},
        # structural dispatch accounting (full groups only — exact)
        "dispatch": {
            "scan_per_1k_events": round(1000.0 / (B * D), 4),
            "while_per_1k_events": round(1000.0 / (B * MS), 4),
            "cut": round(MS / D, 2),
            "criterion": ">= 1.5x fewer",
        },
        "throughput_ratio": round(while_eps / max(scan_eps, 1.0), 2),
        "throughput_criterion": ">= 1.0",
    }
    print(json.dumps(
        {"config": "while_drain", "detail": detail}), flush=True)
    return while_eps, scan_eps, while_p99, scan_p99


def run_dcn_resident(total_events: int, cpu: bool):
    """Per-host DCN-resident mode vs the single-step lockstep fallback
    (ISSUE 20b). The honest form is a two-process ensemble (each host
    stacks up to ring-depth locally-polled chunks into one drain per
    lockstep round; >= 1.3x wall-clock criterion vs lockstep); on
    backends without cross-process collectives (this container's CPU
    runtime) the row degrades to a SINGLE-process measurement of the
    same two runners — the same drain kernel, real collectives across
    the local shards — and stamps ``mode`` so the artifact says which
    topology produced the numbers. Cycle counts are exact either way:
    the resident runner's cycles are drain dispatches, the lockstep
    runner's are single-chunk rounds, so the dispatch cut is auditable
    from the artifact alone."""
    import os

    import jax

    from flink_tpu.runtime.dcn import (
        DCNJobSpec,
        GeneratorPartitionSource,
        runner_for_spec,
    )

    n_keys, ts_div, win_ms = 977, 16, 1000
    total = max(8192, min(total_events, 40_000))

    def source_factory(pid, nproc, _total=total):
        per_host = n_keys // nproc

        def gen(offset, n):
            idx = np.arange(offset, offset + n, dtype=np.int64)
            return (pid + nproc * (idx % per_host), idx // ts_div,
                    np.ones(n, np.float32))

        return GeneratorPartitionSource(gen, _total)

    def _spec(resident):
        return DCNJobSpec(
            source_factory=source_factory,
            size_ms=win_ms,
            capacity_per_shard=2048,
            max_parallelism=64,
            batch_per_host=2048,
            fires_per_step=4,
            resident=resident,
            resident_ring_depth=4,
        )

    def run_single(resident):
        r = runner_for_spec(_spec(resident), 0, 1)
        t0 = time.perf_counter()
        out = r.run()
        dt = time.perf_counter() - t0
        return total / dt, int(out["cycles"])

    def _two_proc_supported():
        import sys as _sys

        tests_dir = os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "tests")
        if tests_dir not in _sys.path:
            _sys.path.insert(0, tests_dir)
        try:
            from dcn_probe import multiprocess_collectives_supported
            return multiprocess_collectives_supported()
        except Exception:  # noqa: BLE001 — probe absent: assume not
            return False

    def run_two_proc(builder):
        """One 2-process ensemble (tests/dcn_jobs.py builders — the
        same specs the gated ensemble tests run); wall-clock covers the
        whole run, cycles come from the workers' stats line."""
        import sys as _sys

        repo = os.path.dirname(os.path.abspath(__file__))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            coord = f"127.0.0.1:{s.getsockname()[1]}"
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        outs = [os.path.join(tempfile.mkdtemp(), f"out-{p}.npz")
                for p in range(2)]
        t0 = time.perf_counter()
        procs = [subprocess.Popen(
            [_sys.executable, "-m", "flink_tpu.runtime.dcn",
             "--coordinator", coord, "--num-processes", "2",
             "--process-id", str(p), "--builder",
             os.path.join(repo, "tests", "dcn_jobs.py") + ":" + builder,
             "--out", outs[p]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ) for p in range(2)]
        cycles = None
        for p in procs:
            out, _ = p.communicate(timeout=600)
            if p.returncode != 0:
                raise RuntimeError(out.decode(errors="replace")[-2000:])
            for line in out.decode(errors="replace").splitlines():
                if line.startswith("{"):
                    cycles = json.loads(line)["cycles"]
        dt = time.perf_counter() - t0
        # two hosts x TOTAL_PER_HOST records (tests/dcn_jobs.py)
        return 80_000 / dt, int(cycles)

    if _two_proc_supported():
        import socket
        import subprocess
        import tempfile

        res_eps, res_cycles = run_two_proc("two_host_window_resident")
        lock_eps, lock_cycles = run_two_proc("two_host_window")
        mode, note = "two_process", "real cross-process ensemble"
    else:
        # compile-and-settle once per discipline, then measure
        run_single(True)
        res_eps, res_cycles = run_single(True)
        run_single(False)
        lock_eps, lock_cycles = run_single(False)
        mode = "single_process_fallback"
        note = ("cross-process collectives unavailable on this "
                "backend; same kernels, one host over the local mesh")
    detail = {
        "platform": jax.default_backend(),
        "mode": mode,
        "note": note,
        "total_events": total,
        "resident": {"eps": round(res_eps), "cycles": res_cycles},
        "lockstep": {"eps": round(lock_eps), "cycles": lock_cycles},
        "cycle_cut": round(lock_cycles / max(res_cycles, 1), 2),
        "throughput_ratio": round(res_eps / max(lock_eps, 1.0), 2),
        "criterion": ">= 1.3x vs lockstep (two-process); cycle cut "
                     "~ring-depth structurally",
    }
    print(json.dumps(
        {"config": "dcn_resident", "detail": detail}), flush=True)
    return res_eps, lock_eps, res_cycles, lock_cycles


def run_chained_stages(total_events: int, cpu: bool):
    """Chained 2-stage drain vs the single-stage resident drain at
    matched dims (ISSUE 16): B=512 / C=4096 / ring depth D=32, the same
    firing stream, compact fire payload on BOTH sides (the chained
    drain's final stage emits compact fires, so the single-stage
    comparator runs ``reduced=False`` for a like-for-like topology).

    The chained discipline is ``build_window_chained_drain`` over
    (1s tumbling sum) -> device edge -> (4s tumbling rollup): the
    drain's stacked stage-1 fires pack once per drain into the edge
    lanes and feed one stage-2 update + advance (the per-drain stage
    tail). The stream is the multi-level-rollup shape the chain
    exists for: a bounded key population at the aggregation level
    (256 distinct keys + a 64-key hot set, dup ~0.5) — both
    disciplines consume the SAME stream, so the ratio isolates the
    cost of carrying the second stage. The acceptance criterion is
    <15% throughput cost, and fire-VISIBILITY p50/p99 (dispatch of
    the producing drain to fires host-fetched, lagged one dispatch —
    the emit-path lag the discipline imposes) stamps beside
    events/s."""
    from collections import deque as _dq

    import jax
    import jax.numpy as jnp

    from flink_tpu.metrics.latency import weighted_percentile
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_chained_drain,
        build_window_resident_drain,
        init_sharded_state,
    )

    n_dev = len(jax.devices())
    ctx = MeshContext.create(n_dev, 128)
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    BPP, D = 4, 32
    ROLLUP = 4               # stage-2 tumbling size, in stage-1 panes
    KEYSPACE = 256           # distinct keys at the rollup level
    # per-DRAIN edge budget: one drain closes D/BPP = 8 stage-1 panes,
    # each firing <= KEYSPACE distinct keys -> <= 2048 edge records per
    # drain (verified drop-free: edge overflow counts into the stage-2
    # dropped_capacity counter, which stays 0 on this stream)
    EX_LANES = 2048
    iters = max(128, min(8192, total_events // B))
    n_groups = max(3, max(96, iters // 8) // D)
    n_batches = n_groups * D

    spec1 = WindowStageSpec(
        win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=C, layout="direct", precombine=False,
    )
    # stage-2 ring sized by the StageGraph.plan_specs rule: the stage
    # tail advances once per drain, so the ring absorbs a whole drain's
    # worth of upstream fires (D slots x F pane-ends, the catch-up
    # worst case) on top of the live window span
    s2 = ROLLUP * SLIDE
    ppw = 1
    slack = (D * spec1.win.fires_per_step * SLIDE) // s2 + 2
    spec2 = WindowStageSpec(
        win=wk.WindowSpec(s2, s2, ring=max(8, 2 * ppw + slack, ppw + 3),
                          fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=C, layout="direct", precombine=False,
    )

    def _keys(rng):
        n_hot = B // 2
        lo = np.concatenate([
            rng.integers(0, KEYSPACE, B - n_hot),
            rng.integers(0, 64, n_hot),
        ]).astype(np.uint32)
        rng.shuffle(lo)
        return np.zeros(B, np.uint32), lo

    def make_stream(rng):
        batches, wms = [], []
        for j in range(n_batches):
            p = j // BPP
            hi, lo = _keys(rng)
            ts = np.full(B, p * SLIDE + SLIDE // 2, np.int32)
            batches.append(tuple(jax.device_put(a) for a in (
                hi, lo, ts, np.ones(B, np.float32), np.ones(B, bool),
            )))
            wms.append(np.int32(p * SLIDE - 1))
        return batches, wms

    def consume(cf):
        got = jax.device_get((cf.counts, cf.lane_valid,
                              cf.window_end_ticks, cf.value_sums))
        return max(int(np.asarray(got[1]).sum()), 1)

    def prep(step, init_state):
        """Compile + settle one discipline; returns (run_once, lat) so
        the timed reps of BOTH disciplines can interleave — host load
        drift then hits single and chained alike instead of biasing
        whichever ran second."""
        batches, wms = make_stream(np.random.default_rng(11))
        n_disp = n_batches // D
        lat = []

        def run_once():
            state = init_state()
            t0 = time.perf_counter()
            handles = _dq()
            mon = None
            for g in range(n_disp):
                sel = range(g * D, (g + 1) * D)
                flat = [a for i in sel for a in batches[i]]
                wmv = np.tile(
                    np.asarray([wms[i] for i in sel], np.int32),
                    (n_dev, 1),
                )
                state, mon, fires = step(state, *flat, wmv, np.int32(D))
                handles.append((time.perf_counter(), fires))
                if len(handles) > 1:
                    t_d, cf = handles.popleft()
                    lat.append((consume(cf),
                                (time.perf_counter() - t_d) * 1e3))
            while handles:
                t_d, cf = handles.popleft()
                lat.append((consume(cf),
                            (time.perf_counter() - t_d) * 1e3))
            jax.block_until_ready(mon[1])
            return time.perf_counter() - t0

        run_once()                               # compile + settle
        lat.clear()                              # drop compile-run samples
        return run_once, lat

    def _pct(lat, q):
        p = weighted_percentile(lat, q)
        return round(p, 2) if p is not None else None

    single_step = build_window_resident_drain(ctx, spec1, D,
                                              reduced=False)
    run_s, s_lat = prep(
        single_step, lambda: init_sharded_state(ctx, spec1)
    )
    chained_step = build_window_chained_drain(
        ctx, (spec1, spec2), D, exchange_lanes=EX_LANES
    )
    run_c, c_lat = prep(
        chained_step,
        lambda: (init_sharded_state(ctx, spec1),
                 init_sharded_state(ctx, spec2)),
    )
    t_s, t_c = [], []
    for _ in range(4):
        t_s.append(run_s())
        t_c.append(run_c())
    s_eps = B * n_batches / min(t_s)
    c_eps = B * n_batches / min(t_c)

    detail = {
        "platform": jax.default_backend(), "B": B, "C": C,
        "ring_depth": D, "n_batches": n_batches, "bpp": BPP,
        "n_devices": n_dev, "rollup_panes": ROLLUP,
        "keyspace": KEYSPACE, "exchange_lanes": EX_LANES,
        "single_stage": {"events_per_s": round(s_eps),
                         "p50_fire_ms": _pct(s_lat, 50),
                         "p99_fire_ms": _pct(s_lat, 99)},
        "chained_2stage": {"events_per_s": round(c_eps),
                           "p50_fire_ms": _pct(c_lat, 50),
                           "p99_fire_ms": _pct(c_lat, 99)},
        "acceptance": {
            "ratio": round(c_eps / max(s_eps, 1.0), 3),
            "criterion": ">= 0.85 (<15% throughput cost for the "
                         "second chained stage)",
        },
    }
    print(json.dumps(
        {"config": "chained_stages", "detail": detail}), flush=True)
    return (s_eps, c_eps, _pct(s_lat, 99), _pct(c_lat, 99))


def run_scaling_cell(total_events: int, n_devices=None):
    """ONE cell of the chips-vs-events/s curve (ISSUE 13): the sharded
    resident drain (``build_window_sharded_drain``) at THIS process's
    device count, matched dims with ``run_resident_loop`` (same B per
    shard / C / ring / slide, ring depth D=32), pre-routed per-shard
    batches so every staged row lands on its owning shard — weak
    scaling, each chip drains its own full ring slice. The caller
    (``bench.py --scaling``) forces the device count per child process;
    this function just measures where it lands and returns
    (n_devices, events/s, p99_fire_ms) — the p99 is the weighted
    dispatch-to-consume fire latency over emitted lanes (ISSUE 17:
    both halves of the north-star metric stamped in the headline)."""
    from collections import deque as _dq

    import jax
    import jax.numpy as jnp

    from flink_tpu.core.keygroups import assign_to_key_group
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.ops.hashing import route_hash
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec,
        build_window_sharded_drain,
        init_sharded_state,
    )

    # virtual-CPU path: the caller forces the process device count and
    # n_devices stays None. Real-device path (ISSUE 20 satellite): the
    # caller passes n_devices to slice the FIRST n chips of the real
    # mesh — distinct physical cores, so the curve measures genuine
    # chip-count speedup, not shard_map partitioning overhead
    n = int(n_devices) if n_devices else len(jax.devices())
    MAXP = 128
    ctx = MeshContext.create(n, MAXP)
    B, C, RING, SLIDE = DEVICE_CEILING_BATCH, 4096, 9, 1000
    D = 32
    spec = WindowStageSpec(
        win=wk.WindowSpec(SLIDE, SLIDE, ring=RING, fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=C, layout="direct", precombine=False,
    )
    drain = build_window_sharded_drain(ctx, spec, D, reduced=True)

    # per-shard key pools: draw a lo pool, route it with the SAME
    # host-side key-group math the ingest planner uses, and bucket by
    # owning shard — staged rows are then sampled per shard from its own
    # bucket, so the drain's ownership mask never drops a row and the
    # events/s denominator is exact
    rng = np.random.default_rng(11)
    pool = rng.integers(0, C, 1 << 16).astype(np.uint32)
    kg = assign_to_key_group(
        route_hash(np.zeros_like(pool), pool, np), MAXP, np)
    shard_of = ctx.shard_of_key_groups(kg)
    buckets = [pool[shard_of == s] for s in range(n)]
    assert all(len(b) for b in buckets), "key pool missed a shard"

    iters = max(2 * D, min(4096, total_events // (B * n)))
    n_batches = (iters // D) * D
    batches, wmvs = [], []
    for j in range(n_batches):
        p = j // 4                      # BPP=4 batches per pane
        lo = np.stack([
            rng.choice(buckets[s], B) for s in range(n)
        ])
        batches.append(tuple(jax.device_put(a) for a in (
            np.zeros((n, B), np.uint32), lo,
            np.full((n, B), p * SLIDE + SLIDE // 2, np.int32),
            np.ones((n, B), np.float32), np.ones((n, B), bool),
        )))
        wmvs.append(np.int32(p * SLIDE - 1))

    def consume(cf):
        got = jax.device_get((cf.counts, cf.lane_valid,
                              cf.window_end_ticks, cf.value_sums))
        return int(np.asarray(got[1]).sum())

    counts = np.full(n, D, np.int32)    # full ring, every shard live

    def run_once(lat=None):
        state = init_sharded_state(ctx, spec)
        t0 = time.perf_counter()
        handles = _dq()
        mon = None
        for g in range(n_batches // D):
            sel = range(g * D, (g + 1) * D)
            flat = [a for i in sel for a in batches[i]]
            wmv = np.tile(
                np.asarray([wmvs[i] for i in sel], np.int32), (n, 1))
            state, mon, fires = drain(state, *flat, wmv, counts)
            handles.append((fires, time.perf_counter()))
            if len(handles) > 1:
                cf, t_pub = handles.popleft()
                lanes = consume(cf)
                if lat is not None and lanes:
                    lat.append(
                        (lanes, (time.perf_counter() - t_pub) * 1e3))
        while handles:
            cf, t_pub = handles.popleft()
            lanes = consume(cf)
            if lat is not None and lanes:
                lat.append((lanes, (time.perf_counter() - t_pub) * 1e3))
        jax.block_until_ready(mon[1])
        return time.perf_counter() - t0

    from flink_tpu.metrics.latency import weighted_percentile

    run_once()                           # compile + settle
    lat = []
    dt = min(run_once(lat) for _ in range(3))
    p99 = weighted_percentile(lat, 99)
    return n, n * B * n_batches / dt, (
        round(p99, 2) if p99 is not None else None)


def run_tiered(total_events: int, cpu: bool):
    """Tiered key-group state under a cold-tail working set (ISSUE 18):
    the same Zipf-skewed keyed windowed sum run twice through the full
    executor — once all-resident (the baseline every earlier PR ships)
    and once with ``state.tiers.resident-key-groups`` capping the HBM
    hot set at BUDGET of MAXP key-groups (~13x more groups than the
    budget, inside the >= 10x acceptance floor). The stream is the shape
    the
    tier exists for: a handful of Zipf-hot keys carry ~90%% of the
    traffic and hash into few enough groups to fit the budget, while
    the cold tail sprays the whole group space — so the manager must
    keep the hot set pinned, demote the tail to the host pane stores,
    and promote ahead of each pane close off the watermark.

    subject = tiered eps, baseline = all-resident eps; the acceptance
    fraction (>= 0.6x all-resident) stamps in the detail JSON next to
    p99 fire latency and the prefetch hit/miss counters pulled from the
    job's tiers report."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    MAXP, BUDGET = 64, 5
    N_KEYS = 4096
    WINDOW_MS = 1000
    BATCH = 32768
    ZIPF_A = 2.5
    total = int(min(total_events, 2_000_000))

    # Zipf(2.5) key pool, drawn once: top-4 keys ~95% of traffic; the
    # rest spreads over N_KEYS keys -> all MAXP key-groups get touched
    rng = np.random.default_rng(7)
    pool = (np.minimum(rng.zipf(ZIPF_A, size=total), N_KEYS) - 1).astype(
        np.int64)

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": pool[offset:offset + n],
            "value": np.ones(n, np.float32),
        }
        # one pane per batch: steady watermark advance -> ~60 pane
        # closes over the run, each a promote-ahead opportunity
        return cols, (idx // (BATCH // 8)) * (WINDOW_MS // 8)

    def run(budget):
        # best-of-2 per config: the first rep pays the XLA compiles
        # (the tiered build is a distinct kernel family, so compile
        # cost would otherwise masquerade as tier overhead); the claim
        # is SUSTAINED throughput, which is the second rep
        opts = {}
        if budget:
            opts = {"state.tiers.resident-key-groups": budget}
        best = None
        for _ in range(2):
            env = StreamExecutionEnvironment(Configuration(opts))
            env.set_parallelism(1)
            env.set_max_parallelism(MAXP)
            env.set_stream_time_characteristic(
                TimeCharacteristic.EventTime)
            env.set_state_capacity(1 << 14)
            env.batch_size = BATCH
            sink = CountingSink()
            t0 = time.perf_counter()
            (
                env.add_source(GeneratorSource(gen, total=total))
                .key_by(lambda c: c["key"])
                .time_window(WINDOW_MS)
                .sum(lambda c: c["value"])
                .add_sink(sink)
            )
            job = env.execute(f"tiered-bench-budget{budget}")
            dt = time.perf_counter() - t0
            assert sink.value_sum == total, (sink.value_sum, total)
            if best is not None and total / dt <= best["events_per_s"]:
                continue
            p99 = job.metrics.fire_latency_pct(99)
            rep = env._pipeline_report()
            best = {
                "events_per_s": total / dt,
                "p99_fire_ms": (round(p99, 2) if p99 is not None
                                else None),
                "tiers": (rep.get("tiers") if isinstance(rep, dict)
                          else None),
            }
        best["events_per_s"] = round(best["events_per_s"])
        return best

    base = run(0)
    tiered = run(BUDGET)
    ratio = tiered["events_per_s"] / max(base["events_per_s"], 1)
    detail = {
        "events": total, "batch": BATCH, "n_keys": N_KEYS,
        "max_parallelism": MAXP, "resident_budget": BUDGET,
        "group_to_budget_ratio": round(MAXP / BUDGET, 1),
        "zipf_a": ZIPF_A,
        "all_resident": base,
        "tiered": tiered,
        "acceptance": {
            "ratio": round(ratio, 3),
            "criterion": ">= 0.6 of all-resident throughput at >= 10x "
                         "more key-groups than the resident budget",
        },
    }
    print(json.dumps({"config": "tiered_state", "detail": detail}),
          flush=True)
    t = tiered["tiers"] or {}
    return (tiered["events_per_s"], base["events_per_s"],
            tiered["p99_fire_ms"],
            {"prefetch_hits": int(t.get("prefetch_hits", 0)),
             "prefetch_misses": int(t.get("prefetch_misses", 0)),
             "demotes": int(t.get("demotes", 0)),
             "promotes": int(t.get("promotes", 0)),
             "tier_faults": int(t.get("faults", 0))})


# ---------------------------------------------------- self-tuning drill
def run_selftune(total_events: int, cpu: bool):
    """Self-healing runtime drill (ISSUE 19, ``bench.py --selftune``):
    a skew-shifting keyed windowed stream on a 4-shard TIERED mesh
    (``state.tiers.resident-key-groups`` caps each shard's HBM hot set
    at BUDGET key-groups). Each phase concentrates ALL traffic on 16
    hot groups packed inside HALF the mesh's default ranges — phase A
    in shards 0-1, then (mid-run) migrating into shards 2-3. Eight hot
    groups per shard against a budget of six means a quarter of every
    batch dives to the overflow ring and the host pane stores while
    the tier planner churns the remainder — host-bound degradation
    that bites even on the shared-core virtual CPU mesh.

    Three runs, same config modulo keys/controller:

      balanced       uniform keys over the 4*BUDGET default-resident
                     groups (the throughput the slicing should buy
                     back: zero tier faults, sharded route),
                     controller off
      skewed, off    the degradation floor: the hot set fights two
                     shards' residency budgets end to end
      skewed, on     the controller's rebalance arm re-slices the
                     shard ranges LIVE (heat-balanced contiguous
                     partition through the savepoint-cut rescale) —
                     once per hot phase — spreading the hot groups 4
                     per shard, back under every budget, WITHOUT a
                     restart. The healed slicing reproduces the
                     balanced run's residency profile exactly, so the
                     recovered tail rate is directly comparable.

    Measured: steady tail throughput (the last 15%% of each run's
    record progress, sampler slope; the controller-on window
    additionally starts after the last rebalance settles, so the cut
    and its recompile burst are not billed against the recovered
    rate). Acceptance: controller-on tail >= 0.8x balanced while
    controller-off stays under the same bar. Returns
    (ratio_on, ratio_off, p99_fire_ms, controller counters)."""
    import jax

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.keygroups import assign_to_key_group
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    N_DEV = 4
    if len(jax.devices()) < N_DEV:
        raise RuntimeError(
            f"selftune needs a {N_DEV}-device mesh; found "
            f"{len(jax.devices())} (bench.py --selftune forces the "
            f"virtual CPU mesh via XLA_FLAGS before JAX initializes)"
        )
    MAXP = 64
    BUDGET = 6                 # resident key-groups per shard
    B = 4096
    WINDOW = 10_000
    TAIL = 0.15
    SETTLE_S = 2.5             # post-rebalance settle before the tail
    total = int(min(total_events, 2_000_000))

    # key-group census: the identity key encode (hi=0, lo=k) means
    # group(k) = murmur3(k) % MAXP — the SAME math the ingest planner
    # uses, so hot keys can be picked per TARGET GROUP
    cand = np.arange(4096, dtype=np.int64)
    kg = assign_to_key_group(cand.astype(np.uint32), MAXP, np)

    def keys_in(groups, per_group):
        out = []
        for g in groups:
            ks = cand[kg == g]
            if len(ks) < per_group:
                raise RuntimeError(
                    f"key-group {g} has only {len(ks)} candidate keys")
            out.append(ks[:per_group])
        return np.concatenate(out)

    # default equal slicing of 64 groups over 4 shards: shard 0 owns
    # [0..15], shard 3 owns [48..63], and each shard's initial
    # resident set is the FIRST `BUDGET` groups of its range. Each
    # phase's 16 hot groups interleave across HALF the mesh (the
    # greedy prefix partition needs cut points between them), 8 per
    # shard against a budget of 6: phase A lives in shards 0-1's
    # ranges, phase B in shards 2-3's — the mid-run migration the
    # controller must chase. The healed 4-per-shard spread fits every
    # budget with slack, so an imperfect first re-slice (stale EWMA
    # heat from the previous phase skews the prefix boundaries) still
    # lands every hot group resident.
    HOT_A = tuple(range(1, 32, 2))
    HOT_B = tuple(range(33, 64, 2))
    # the balanced pool covers exactly the default-resident groups, so
    # the baseline runs fault-free without any planner help
    RESIDENT0 = tuple(
        s * (MAXP // N_DEV) + i for s in range(N_DEV) for i in range(BUDGET)
    )
    hot_a = keys_in(HOT_A, 2)
    hot_b = keys_in(HOT_B, 2)
    balanced_keys = keys_in(RESIDENT0, 4)

    rng = np.random.default_rng(11)
    cold_pool = balanced_keys[rng.integers(0, len(balanced_keys), total)]
    hot_pick = rng.integers(0, len(hot_a), total)
    # the migration lands at one THIRD: detecting + re-slicing phase A
    # is cheap (no stale heat yet), while phase B pays the full chase —
    # stale decay, re-slice, recompile, tier re-promotion — and the
    # recovered tail must still have runway to measure
    skew_pool = np.where(np.arange(total) < total // 3,
                         hot_a[hot_pick], hot_b[hot_pick])

    def gen_of(pool):
        def gen(offset, n):
            idx = np.arange(offset, offset + n)
            cols = {
                "key": pool[offset:offset + n],
                "value": np.ones(n, np.float32),
            }
            # steady watermark advance: one pane per batch
            return cols, (idx // (B // 8)) * (WINDOW // 8)
        return gen

    BASE_CFG = {
        "pipeline.prefetch": "on",
        "pipeline.device-staging": "on",
        "pipeline.resident-loop": "on",
        "pipeline.ring-depth": 4,
        "pipeline.data-parallel": "on",
        # the tier is the degradation mechanism: a phase's 2*BUDGET hot
        # groups crammed into one shard's range can never all be
        # resident, so half the traffic rides the overflow ring into
        # the host pane stores until the controller re-slices
        "state.tiers.resident-key-groups": BUDGET,
        "state.tiers.min-dwell-cycles": 1,
        "state.tiers.max-swaps-per-cycle": 4,
        "observability.drain-stats": True,
        "observability.kg-stats": True,
        # fast heat: the drill's phases are seconds apart, not
        # minutes, and stale heat from the finished phase must decay
        # before it can distort the next re-slice's prefix boundaries
        # (one sample at alpha 0.8 leaves 20% stale weight — small
        # enough that the greedy prefix still fits every budget)
        "observability.kg-heat-alpha": 0.8,
        "keys.reverse-map": False,
    }
    CTL_CFG = {
        "controller.enabled": True,
        "controller.interval-cycles": 8,
        "controller.probation-cycles": 8,
        "controller.cooldown-cycles": 32,
        # a phase's onset reads as skew 2.0 (two shards carry all the
        # heat) or worse, while the healed spread plus residual stale
        # heat stays near 1.3 — the threshold sits between the two
        "controller.rebalance-threshold": 1.6,
        # one re-slice per hot phase: a live rescale recompiles the
        # step family, so marginal touch-ups cost more than they buy
        "controller.min-rebalance-interval": 4.0,
        "controller.min-gain": 1.25,
    }

    def run(pool, controller):
        opts = dict(BASE_CFG)
        if controller:
            opts.update(CTL_CFG)
        env = StreamExecutionEnvironment(Configuration(opts))
        env.set_parallelism(N_DEV)
        env.set_max_parallelism(MAXP)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1 << 13)
        env.batch_size = B
        sink = CountingSink()
        (
            env.add_source(GeneratorSource(gen_of(pool), total=total))
            .key_by(lambda c: c["key"])
            .time_window(WINDOW)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        # wall-clock samples: the controller ledger stamps decisions
        # with time.time(), and the on-run tail window is keyed off
        # the LAST rebalance stamp, so both must share a clock
        samples = []                 # (t_wall, records_in)
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                m = getattr(env, "_live_metrics", None)
                if m is not None:
                    samples.append((time.time(), m.records_in))
                time.sleep(0.01)

        th = threading.Thread(target=sampler, daemon=True)
        th.start()
        t0 = time.perf_counter()
        job = env.execute(f"selftune-{'on' if controller else 'off'}")
        dt = time.perf_counter() - t0
        stop.set()
        th.join(timeout=2)
        # every event lands in exactly one window of the analytic sum
        assert sink.value_sum == total, (sink.value_sum, total)
        rep_fn = getattr(env, "_controller_report", None)
        rep = rep_fn() if rep_fn is not None else {}
        p99 = job.metrics.fire_latency_pct(99)
        eps_total = total / dt
        # steady tail slope over the sampler's last TAIL fraction of
        # record progress (records_in may exceed `total` when a rescale
        # cut replays prefetched batches, so the window keys off the
        # final sample, not the event count). The controller-on window
        # additionally starts SETTLE_S after the last rebalance stamp:
        # the claim is the recovered steady rate, not the cost of the
        # cut + recompile burst that bought it.
        def slope(xs, win_s=3.0):
            """Best sustained rate: max slope over >=win_s/2 sliding
            windows — the steady measure is robust to one GC pause or
            checkpoint hiccup landing inside the region (every run is
            scored the same way)."""
            best = None
            j = 0
            for i in range(len(xs)):
                while xs[i][0] - xs[j][0] > win_s:
                    j += 1
                dt_w = xs[i][0] - xs[j][0]
                if dt_w >= win_s / 2 and xs[i][1] > xs[j][1]:
                    sl = (xs[i][1] - xs[j][1]) / dt_w
                    if best is None or sl > best:
                        best = sl
            return best

        tail = None
        if samples:
            r_final = samples[-1][1]
            xs = [p for p in samples if p[1] >= (1 - TAIL) * r_final]
            t_rb = [e.get("t_wall") for e in rep.get("ledger", [])
                    if e.get("kind") == "rebalance" and e.get("t_wall")]
            if t_rb:
                clipped = [p for p in samples
                           if p[0] >= max(t_rb) + SETTLE_S]
                tail = slope(clipped)
            tail = tail if tail is not None else slope(xs)
        return {
            "events_per_s": round(eps_total),
            "tail_events_per_s": round(tail if tail else eps_total),
            "p99_fire_ms": (round(p99, 2) if p99 is not None
                            else None),
            "controller": ({
                "rebalances": int(rep.get("rebalances", 0)),
                "actions": int(rep.get("actions", 0)),
                "reverts": int(rep.get("reverts", 0)),
                "rebalance_skips": int(rep.get("rebalance_skips", 0)),
                "ledger_tail": [
                    {k: e.get(k) for k in ("kind", "cycle", "evidence")}
                    for e in rep.get("ledger", [])[-6:]
                ],
            } if rep.get("available") else None),
        }

    balanced = run(cold_pool, controller=False)
    off = run(skew_pool, controller=False)
    on = run(skew_pool, controller=True)
    base_t = max(balanced["tail_events_per_s"], 1)
    ratio_on = on["tail_events_per_s"] / base_t
    ratio_off = off["tail_events_per_s"] / base_t
    detail = {
        "events": total, "batch": B, "max_parallelism": MAXP,
        "n_shards": N_DEV, "resident_groups_per_shard": BUDGET,
        "hot_groups_phase_a": list(HOT_A),
        "hot_groups_phase_b": list(HOT_B),
        "tail_fraction": TAIL, "settle_s": SETTLE_S,
        "balanced": balanced,
        "skewed_controller_off": off,
        "skewed_controller_on": on,
        "acceptance": {
            "ratio_on": round(ratio_on, 3),
            "ratio_off": round(ratio_off, 3),
            "criterion": "controller-on tail >= 0.8 of balanced; "
                         "controller-off stays degraded",
        },
    }
    print(json.dumps({"config": "selftune", "detail": detail}),
          flush=True)
    ctl = on["controller"] or {}
    return (round(ratio_on, 3), round(ratio_off, 3), on["p99_fire_ms"],
            {"rebalances": int(ctl.get("rebalances", 0)),
             "actions": int(ctl.get("actions", 0)),
             "reverts": int(ctl.get("reverts", 0))})


CONFIGS = {
    "socket_wc": (run_socket_wc, 2_000_000),
    "count_min": (run_count_min, 4_000_000),
    "sessions": (run_sessions, 4_000_000),
    "cep": (run_cep, 400_000),
    "cep_event_time": (run_cep_event_time, 400_000),
    "checkpoint_overhead": (run_checkpoint_overhead, 2_000_000),
    "observability_overhead": (run_observability_overhead, 2_000_000),
    "ingest_pipeline": (run_ingest_pipeline, 4_000_000),
    "fault_overhead": (run_fault_overhead, 4_000_000),
    "device_update_ceiling": (run_device_update_ceiling, 2_000_000),
    "resident_loop": (run_resident_loop, 2_000_000),
    "mttr_recovery": (run_mttr_recovery, 2_000_000),
    "elastic_recovery": (run_elastic_recovery, 2_000_000),
    "selftune": (run_selftune, 2_000_000),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--only", default=None, choices=list(CONFIGS))
    ap.add_argument("--events", type=int, default=None)
    ap.add_argument("--init-deadline", type=float, default=300.0)
    args = ap.parse_args()

    try:
        probe_backend(args.cpu, deadline_s=args.init_deadline)
    except RuntimeError as e:
        print(json.dumps({"config": "all", "error": str(e)}))
        return
    if args.cpu:
        import os

        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")

    for name, (fn, default_events) in CONFIGS.items():
        if args.only and name != args.only:
            continue
        n = args.events or default_events
        try:
            subj, base = fn(n, args.cpu)
            print(json.dumps({
                "config": name,
                "events": n,
                "subject_eps": round(subj),
                "baseline_eps": round(base),
                "ratio": round(subj / base, 2),
            }), flush=True)
        except Exception as e:  # noqa: BLE001 — a row per config, always
            import traceback

            traceback.print_exc()
            print(json.dumps({"config": name, "error": f"{type(e).__name__}: {e}"}),
                  flush=True)


if __name__ == "__main__":
    main()
