#!/bin/bash
# Deploy a flink_tpu session cluster on YARN
# (ref bin/yarn-session.sh; flink-yarn/.../cli/FlinkYarnSessionCli.java).
#
#   bin/yarn-session.sh --rm http://rm-host:8088 [--name N] [...]
cd "$(dirname "$0")/.."
# default config dir (ref config.sh: FLINK_CONF_DIR fallback)
export FLINK_TPU_CONF_DIR="${FLINK_TPU_CONF_DIR:-$PWD/conf}"
exec python -m flink_tpu.deploy.yarn "$@"
