#!/bin/bash
# Deploy a flink_tpu session cluster on YARN
# (ref bin/yarn-session.sh; flink-yarn/.../cli/FlinkYarnSessionCli.java).
#
#   bin/yarn-session.sh --rm http://rm-host:8088 [--name N] [...]
cd "$(dirname "$0")/.."
exec python -m flink_tpu.deploy.yarn "$@"
