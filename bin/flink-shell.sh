#!/bin/bash
# Interactive flink-tpu shell (ref bin/start-scala-shell.sh).
#
#   bin/flink-shell.sh [--controller HOST:PORT] [--execute FILE]
cd "$(dirname "$0")/.."
# default config dir (ref config.sh: FLINK_CONF_DIR fallback)
export FLINK_TPU_CONF_DIR="${FLINK_TPU_CONF_DIR:-$PWD/conf}"
exec python -m flink_tpu.shell "$@"
