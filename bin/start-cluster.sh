#!/bin/bash
# Start the flink-tpu process-cluster controller (ref bin/start-cluster.sh).
#
#   bin/start-cluster.sh [--host 0.0.0.0] [--port 6123]
#                        [--advertise-host HOST] [--ha-dir DIR]
#
# The controller prints its control endpoint; point workers and the CLI at
# it. Multi-host: bind 0.0.0.0 and advertise the machine's reachable IP.
cd "$(dirname "$0")/.."
# default config dir (ref config.sh: FLINK_CONF_DIR fallback)
export FLINK_TPU_CONF_DIR="${FLINK_TPU_CONF_DIR:-$PWD/conf}"
exec python -m flink_tpu.runtime.process_cluster "$@"
