#!/bin/bash
# Start a worker process registering with a controller
# (ref bin/taskmanager.sh; TaskManager.scala:296 registration).
#
#   bin/taskmanager.sh --controller HOST:PORT --worker-id W1 [...]
cd "$(dirname "$0")/.."
exec python -m flink_tpu.runtime.worker "$@"
