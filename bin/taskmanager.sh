#!/bin/bash
# Start a worker process registering with a controller
# (ref bin/taskmanager.sh; TaskManager.scala:296 registration).
#
#   bin/taskmanager.sh --controller HOST:PORT --worker-id W1 [...]
cd "$(dirname "$0")/.."
# default config dir (ref config.sh: FLINK_CONF_DIR fallback)
export FLINK_TPU_CONF_DIR="${FLINK_TPU_CONF_DIR:-$PWD/conf}"
exec python -m flink_tpu.runtime.worker "$@"
