"""tools/check_hot_path_sync.py wired as a tier-1 test (ISSUE 2
satellite): an unintended host sync (`block_until_ready`, `.item()`,
`np.asarray`/`np.array` on device arrays, `jax.device_get` — the last
two added with the round-12 resident drain loop, whose host sections
must stay sync-free) in the hot-path modules fails the suite instead of
silently costing a ~70ms round trip per step."""

import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from check_hot_path_sync import (  # noqa: E402
    ALLOWLIST,
    check_source,
    check_tree,
    hot_path_files,
    main,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_hot_paths_are_clean():
    violations = check_tree(ROOT)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_checker_scans_the_real_hot_paths():
    rels = {rel.replace(os.sep, "/") for _p, rel in hot_path_files(ROOT)}
    assert "flink_tpu/runtime/step.py" in rels
    assert "flink_tpu/runtime/ingest.py" in rels
    assert "flink_tpu/ops/window_kernels.py" in rels
    assert len(rels) > 5


def test_ingest_staging_path_has_no_unmarked_sync():
    """The two transfer-completion waits — the staging ring's and the
    sharded batch ring's publish commit, both on the INGEST thread —
    are the ONLY allowed blocks in runtime/ingest.py, and each must
    carry the inline marker: stripping the markers must make the
    checker flag exactly those two."""
    path = os.path.join(ROOT, "flink_tpu", "runtime", "ingest.py")
    with open(path) as f:
        src = f.read()
    assert check_source(src, "flink_tpu/runtime/ingest.py") == []
    stripped = src.replace("# host-sync-ok:", "# stripped:")
    vs = check_source(stripped, "flink_tpu/runtime/ingest.py")
    assert {(v.func, v.what) for v in vs} == {
        ("StagingRing.stage", ".block_until_ready()"),
        ("ShardedDeviceBatchRing.publish_batch", ".block_until_ready()"),
    }


def test_checker_flags_sync_constructs():
    src = (
        "import numpy as np\n"
        "import jax\n"
        "def kernel(x):\n"
        "    x.block_until_ready()\n"
        "    n = x.ovf_n.item()\n"
        "    a = np.asarray(x.acc)\n"
        "    b = numpy.asarray(x.acc)\n"
        "    c = np.array(x.acc)\n"
        "    d = jax.device_get(x.acc)\n"
        "    return n, a, b, c, d\n"
    )
    vs = check_source(src, "flink_tpu/ops/fake.py")
    assert [v.line for v in vs] == [4, 5, 6, 7, 8, 9]
    assert {v.what for v in vs} == {
        ".block_until_ready()", ".item()", "np.asarray(...)",
        "np.array(...)", "jax.device_get(...)",
    }


def test_checker_respects_allowlists():
    # naming convention: host helpers are exempt
    src = (
        "import numpy as np\n"
        "def decode_host(x):\n"
        "    return np.asarray(x)\n"
        "def to_np(x):\n"
        "    return np.asarray(x)\n"
    )
    assert check_source(src, "flink_tpu/ops/fake.py") == []
    # inline marker: one-off barrier sections are exempt WITH a reason
    src2 = (
        "import numpy as np\n"
        "def kernel(x):\n"
        "    return np.asarray(x)  # host-sync-ok: step-boundary barrier\n"
    )
    assert check_source(src2, "flink_tpu/ops/fake.py") == []
    # explicit allowlist entries resolve by (path, qualname)
    path, qual = sorted(ALLOWLIST)[0]
    fn = qual.split(".")[-1]
    src3 = f"import numpy as np\ndef {fn}(x):\n    return np.asarray(x)\n"
    assert check_source(src3, path) == []


def test_checker_ignores_strings_and_comments():
    src = (
        "def kernel(x):\n"
        "    '''mentions np.asarray( and .item() in prose'''\n"
        "    # np.asarray(x) in a comment\n"
        "    s = 'x.block_until_ready()'\n"
        "    return s\n"
    )
    assert check_source(src, "flink_tpu/ops/fake.py") == []


def test_checker_does_not_flag_items_or_jnp():
    src = (
        "import jax.numpy as jnp\n"
        "def kernel(d):\n"
        "    for k, v in d.items():\n"         # .items() != .item()
        "        pass\n"
        "    return jnp.asarray([1])\n"        # jnp stays on device
    )
    assert check_source(src, "flink_tpu/ops/fake.py") == []


def test_cli_entrypoint():
    assert main(["--root", ROOT]) == 0
    rc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools",
                                      "check_hot_path_sync.py")],
        capture_output=True,
    )
    assert rc.returncode == 0, rc.stderr.decode()
