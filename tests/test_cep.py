"""CEP golden semantics (ref flink-cep NFATest / CEPITCase patterns)."""

from collections import namedtuple

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.cep import CEP, NFA, Pattern
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink

Event = namedtuple("Event", ["ts", "name", "value"])


def _run_nfa(pattern, events):
    """Drive an NFA directly with (event, ts) pairs; return all matches."""
    nfa = NFA(pattern)
    partials, out = nfa.initial_state(), []
    for e in events:
        partials, matches = nfa.process(partials, e, e.ts)
        out.extend(matches)
    return out


def test_strict_contiguity_next():
    """`a next b`: only immediately-adjacent pairs match."""
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    events = [
        Event(0, "a", 1), Event(1, "b", 2),   # adjacent: match
        Event(2, "a", 3), Event(3, "x", 0),   # broken by x: no match
        Event(4, "b", 4),
    ]
    out = _run_nfa(p, events)
    assert len(out) == 1
    assert (out[0]["a"].value, out[0]["b"].value) == (1, 2)


def test_relaxed_contiguity_followed_by_branches():
    """`a followedBy b` on [a, b1, b2] yields BOTH (a,b1) and (a,b2) —
    the reference's ignore-transition branching."""
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = [Event(0, "a", 1), Event(1, "x", 0), Event(2, "b", 2),
              Event(3, "b", 3)]
    out = _run_nfa(p, events)
    pairs = sorted((m["a"].value, m["b"].value) for m in out)
    assert pairs == [(1, 2), (1, 3)]


def test_three_stage_with_where_conjunction():
    p = (
        Pattern.begin("first").where(lambda e: e.name == "a")
        .followed_by("mid").where(lambda e: e.name == "b")
        .where(lambda e: e.value > 10)         # ANDed predicate
        .followed_by("last").where(lambda e: e.name == "c")
    )
    events = [
        Event(0, "a", 1), Event(1, "b", 5),    # mid rejected (value <= 10)
        Event(2, "b", 20), Event(3, "c", 7),
    ]
    out = _run_nfa(p, events)
    assert len(out) == 1
    assert out[0]["mid"].value == 20


def test_or_predicate():
    p = Pattern.begin("x").where(lambda e: e.name == "a").or_(
        lambda e: e.value > 100
    )
    events = [Event(0, "a", 1), Event(1, "z", 500), Event(2, "z", 3)]
    out = _run_nfa(p, events)
    assert len(out) == 2


def test_within_prunes_expired_partials():
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
        .within(10)
    )
    events = [Event(0, "a", 1), Event(100, "b", 2),   # expired
              Event(101, "a", 3), Event(105, "b", 4)]  # in window
    out = _run_nfa(p, events)
    assert len(out) == 1
    assert out[0]["a"].value == 3


def test_cep_end_to_end_event_time_out_of_order():
    """Keyed CEP through the DataStream API with out-of-order input:
    the event-time buffer must sort by timestamp before the NFA sees
    elements (ref AbstractKeyedCEPPatternOperator watermark drain)."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 16
    sink = CollectSink()
    # per key "k1": warn(ts=1) -> crit(ts=2); arrival order scrambled
    events = [
        Event(2, "crit", 1), Event(1, "warn", 1),          # k1 out of order
        Event(5, "warn", 2), Event(7, "ok", 2), Event(9, "crit", 2),
        Event(20, "flush", 99),
    ]
    pattern = (
        Pattern.begin("w").where(lambda e: e.name == "warn")
        .followed_by("c").where(lambda e: e.name == "crit")
        .within(10)
    )
    stream = (
        env.from_collection(events)
        .assign_timestamps_and_watermarks(lambda e: e.ts)
        .key_by(lambda e: e.value)
    )
    CEP.pattern(stream, pattern).select(
        lambda m: (m["w"].value, m["w"].ts, m["c"].ts)
    ).add_sink(sink)
    env.execute("cep")
    assert sorted(sink.results) == [(1, 1, 2), (2, 5, 9)]


def test_cep_processing_time_arrival_order():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    sink = CollectSink()
    events = [Event(0, "a", 1), Event(0, "b", 1), Event(0, "a", 2)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(
        lambda m: m["a"].value
    ).add_sink(sink)
    env.execute("cep-proc")
    assert sink.results == [1]


def test_cep_non_keyed_stream():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    sink = CollectSink()
    pattern = (
        Pattern.begin("lo").where(lambda e: e < 10)
        .followed_by("hi").where(lambda e: e > 100)
    )
    CEP.pattern(
        env.from_collection([5, 50, 200]), pattern
    ).select(lambda m: (m["lo"], m["hi"])).add_sink(sink)
    env.execute("cep-global")
    assert sink.results == [(5, 200)]


def test_where_batch_equivalent_to_where():
    """Vectorized where_batch conditions produce exactly the matches of
    the scalar where form, through BOTH the host NFA and the device
    engine, including mixed scalar+batch conjunction and or_."""
    import numpy as np

    rng = np.random.default_rng(21)
    names = rng.choice(["a", "b", "x"], 2000, p=[0.2, 0.2, 0.6])
    events = [Event(int(i), str(names[i]), int(i % 7)) for i in range(2000)]

    scalar = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
        .where(lambda e: e.value != 3)
    )
    vec = (
        Pattern.begin("a")
        .where_batch(lambda evs: np.asarray(
            [e.name for e in evs]) == "a")
        .followed_by("b")
        .where_batch(lambda evs: np.asarray(
            [e.name for e in evs]) == "b")
        .where(lambda e: e.value != 3)        # mixed conjunction
    )
    # host NFA equivalence
    assert _run_nfa(scalar, events) == _run_nfa(vec, events)

    # device engine equivalence end to end
    def run(pattern):
        env = StreamExecutionEnvironment.get_execution_environment()
        env.batch_size = 256
        sink = CollectSink()
        stream = env.from_collection(events).key_by(lambda e: e.value)
        CEP.pattern(stream, pattern).select(
            lambda m: (m["a"].ts, m["b"].ts)
        ).add_sink(sink)
        job = env.execute("cep-vec")
        assert job.metrics.cep_device_steps > 0
        return sorted(sink.results)

    assert run(scalar) == run(vec)

    # or_ interplay: batch-AND base OR scalar alternative
    scalar_or = (
        Pattern.begin("s").where(lambda e: e.name == "a")
        .or_(lambda e: e.value == 5)
        .followed_by("t").where(lambda e: e.name == "b")
    )
    vec_or = (
        Pattern.begin("s")
        .where_batch(lambda evs: np.asarray(
            [e.name for e in evs]) == "a")
        .or_(lambda e: e.value == 5)
        .followed_by("t")
        .where_batch(lambda evs: np.asarray(
            [e.name for e in evs]) == "b")
    )
    assert _run_nfa(scalar_or, events) == _run_nfa(vec_or, events)
