"""Queryable state: live point lookups into device window/rolling state and
heap process state, locally and over the web monitor (ref SURVEY §2.2
KvStateRegistry/QueryableStateClient; asQueryableState:578)."""

import time

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.functions import ProcessFunction
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.state.descriptors import ValueStateDescriptor


def _poll_until(fn, timeout_s: float = 60.0):
    """First device step compiles (~seconds on the CPU mesh); poll until the
    queryable state materializes."""
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            v = fn()
        except KeyError:   # stage not registered yet
            v = None
        if v is not None:
            return v
        time.sleep(0.2)
    raise AssertionError("state never became queryable")


def test_queryable_rolling_state_after_job():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 16
    data = [("a", 1.0), ("b", 2.0), ("a", 3.0), ("a", 5.0)]
    (
        env.from_collection(data)
        .key_by(lambda e: e[0])
        .as_queryable_state("latest-value", extractor=lambda e: e[1])
    )
    env.execute("queryable")
    assert env.query_state("latest-value", "a") == 5.0
    assert env.query_state("latest-value", "b") == 2.0
    assert env.query_state("latest-value", "zzz") is None


def test_queryable_sum_state():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 16
    (
        env.from_collection([("a", 1.0), ("b", 2.0), ("a", 3.0)])
        .key_by(lambda e: e[0])
        .as_queryable_state("running-sum", extractor=lambda e: e[1],
                            kind="sum")
    )
    env.execute("queryable-sum")
    assert env.query_state("running-sum", "a") == 4.0


def test_queryable_window_panes_live():
    """Open (unfired) window panes are queryable WHILE the job runs; after
    the end-of-stream flush they are purged (fired state is gone, matching
    the reference's cleanup-on-fire semantics)."""
    from flink_tpu.runtime.cluster import MiniCluster

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 64
    env.set_state_capacity(2048)

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        time.sleep(0.003)
        return (
            {"key": idx % 10, "value": np.ones(n, np.float32)},
            (idx * 2).astype(np.int64),
        )

    (
        env.add_source(GeneratorSource(gen))        # infinite
        .key_by(lambda c: c["key"])
        .time_window(60_000)                        # stays open
        .sum(lambda c: c["value"])
        .add_sink(CollectSink())
    )
    cluster = MiniCluster()
    jid = cluster.submit(env, "live-window-query")
    try:
        res = _poll_until(lambda: env.query_state("window_sum", 3))
        assert sum(v for v in res["panes"].values()) > 0
        assert env.query_state("window_sum", 12345) is None
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)


def test_queryable_includes_spill_tier():
    """Keys whose window contributions live (partly or wholly) in the host
    SpillStore tier must still be queryable: with 512 keys through a
    64-slot table, most keys' state is spill-resident, and before the
    round-2 ADVICE fix kv_read silently returned None for them."""
    from flink_tpu.core.config import Configuration
    from flink_tpu.runtime.cluster import MiniCluster

    n_keys, capacity = 512, 64
    env = StreamExecutionEnvironment(Configuration({"keys.reverse-map": True}))
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 64
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_state_capacity(capacity)

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        time.sleep(0.002)
        return (
            {"key": idx % n_keys, "value": np.ones(n, np.float32)},
            (idx // 1000).astype(np.int64),     # one open 60s pane
        )

    (
        env.add_source(GeneratorSource(gen))    # infinite
        .key_by(lambda c: c["key"])
        .time_window(60_000)
        .sum(lambda c: c["value"])
        .add_sink(CollectSink())
    )
    cluster = MiniCluster()
    jid = cluster.submit(env, "spill-query")
    try:
        probe = list(range(0, n_keys, 16))      # 32 keys across the range

        def all_present():
            vals = [env.query_state("window_sum", k) for k in probe]
            return vals if all(v is not None for v in vals) else None

        vals = _poll_until(all_present, timeout_s=120)
        for v in vals:
            assert sum(v["panes"].values()) > 0
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)


def test_queryable_heap_process_state():
    class Counter(ProcessFunction):
        def open(self, ctx):
            self.count = ctx.get_state(ValueStateDescriptor("count", default=0))

        def process_element(self, e, ctx, out):
            self.count.update(self.count.value() + 1)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    (
        env.from_collection(["x", "y", "x", "x"])
        .key_by(lambda e: e)
        .process(Counter())
        .add_sink(CollectSink())
    )
    env.execute("heap-queryable")
    assert env.query_state("count", "x") == 3
    assert env.query_state("count", "y") == 1


def test_queryable_lazily_created_state():
    """States first created on a record (not in open()) must be queryable
    too — the registry resolves against the backend's live table set."""
    class LazyCounter(ProcessFunction):
        def open(self, ctx):
            self.rt = ctx   # keep the RuntimeContext; create state later

        def process_element(self, e, ctx, out):
            # state created lazily on first record, per element kind
            st = self.rt.get_state(
                ValueStateDescriptor(f"lazy-{e}", default=0)
            )
            st.update(st.value() + 1)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    (
        env.from_collection(["x", "y", "x"])
        .key_by(lambda e: e)
        .process(LazyCounter())
        .add_sink(CollectSink())
    )
    env.execute("lazy-queryable")
    assert env.query_state("lazy-x", "x") == 2
    assert env.query_state("lazy-y", "y") == 1
    assert "lazy-x" in env._kv_registry.names()


def test_queryable_over_web_monitor():
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.queryable import QueryableStateClient
    from flink_tpu.runtime.web import WebMonitor

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 64
    env.set_state_capacity(2048)

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        time.sleep(0.003)
        return {"key": idx % 10, "value": np.ones(n, np.float32)}, None

    (
        env.add_source(GeneratorSource(gen))    # infinite
        .key_by(lambda c: c["key"])
        .as_queryable_state("live-latest", extractor=lambda c: c["value"])
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "live-query")
    try:
        client = QueryableStateClient("127.0.0.1", port)
        v = _poll_until(
            lambda: client.get_kv_state(jid, "live-latest", 3)
        )
        assert v == 1.0
        with pytest.raises(KeyError):
            client.get_kv_state(jid, "no-such-state", 3)
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)
        web.stop()
