"""Guard the jax compatibility seam (core/compat.py): a jax bump that
moves a shimmed symbol again must fail in THIS file, not as collection
errors across every module that uses it."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P


def test_shard_map_resolves():
    from flink_tpu.core import compat

    assert callable(compat.shard_map)


def test_shard_map_runs_on_installed_jax():
    """The resolved symbol must actually be shard_map (trace + run a
    trivial sharded body), not merely an attribute that exists."""
    from flink_tpu.core.compat import shard_map

    mesh = Mesh(np.asarray(jax.devices()[:1]), ("shards",))
    f = shard_map(
        lambda x: x + 1, mesh=mesh, in_specs=(P("shards"),),
        out_specs=P("shards"),
    )
    out = f(jnp.zeros((1, 4), jnp.int32))
    assert int(np.asarray(out).sum()) == 4


def test_importing_modules_use_the_seam():
    """Every module that needs shard_map must import it from the seam —
    `from jax import shard_map` at module scope is exactly the breakage
    this seam exists to prevent."""
    import pathlib

    root = pathlib.Path(__file__).resolve().parent.parent / "flink_tpu"
    offenders = []
    for path in root.rglob("*.py"):
        if path.name == "compat.py":
            continue
        if "from jax import shard_map" in path.read_text():
            offenders.append(str(path))
    assert not offenders, (
        f"modules importing shard_map from jax instead of "
        f"flink_tpu.core.compat: {offenders}"
    )
