"""ICI record exchange (parallel/exchange.py): the all_to_all keyed shuffle
must produce the same windowed state/fires as replicate-and-mask, with each
device updating only O(B/n) lanes (ref KeyGroupStreamPartitioner.java:53 —
the keyed shuffle is the reference's defining runtime exchange)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from flink_tpu.ops import window_kernels as wk
from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.runtime.step import (
    WindowStageSpec,
    build_window_fire_step,
    build_window_update_step,
    build_window_update_step_exchange,
    init_sharded_state,
)

N_DEV = 8


def _ctx():
    if len(jax.devices()) < N_DEV:
        pytest.skip("needs 8 virtual devices")
    return MeshContext.create(N_DEV, max_parallelism=128,
                              devices=jax.devices()[:N_DEV])


def _batch(rng, B, n_keys=300, t_hi=3000):
    keys = rng.integers(0, n_keys, B).astype(np.uint64)
    h = keys * np.uint64(0x9E3779B97F4A7C15) + np.uint64(1)
    hi = (h >> np.uint64(32)).astype(np.uint32)
    lo = (h & np.uint64(0xFFFFFFFF)).astype(np.uint32)
    ts = rng.integers(0, t_hi, B).astype(np.int32)
    vals = rng.random(B).astype(np.float32)
    return hi, lo, ts, vals


def _fires_dict(cf):
    counts = np.asarray(cf.counts)
    out = {}
    for sh in range(counts.shape[0]):
        for f in range(counts.shape[1]):
            n = int(counts[sh, f])
            if n == 0:
                continue
            khi = np.asarray(cf.key_hi[sh, f, :n])
            klo = np.asarray(cf.key_lo[sh, f, :n])
            end = int(np.asarray(cf.window_end_ticks[sh, f]))
            vals = np.asarray(cf.values[sh, f, :n])
            for a, b, v in zip(khi, klo, vals):
                out[(int(a), int(b), end)] = float(v)
    return out


def test_exchange_matches_mask_and_scales_work():
    ctx = _ctx()
    B = 1024
    spec = WindowStageSpec(
        win=wk.WindowSpec(size_ticks=1000, slide_ticks=1000, ring=8,
                          fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=512,
    )
    upd_mask = build_window_update_step(ctx, spec)
    upd_ex = build_window_update_step_exchange(ctx, spec, B // N_DEV,
                                               capacity_factor=4.0)
    fire = build_window_fire_step(ctx, spec)

    # per-device receive width must be far below B (B/n scaling), here
    # n*cap = 8 * 4*(128/8) = 512 = B/2 with the generous test factor
    assert upd_ex.recv_lanes < B

    rng = np.random.default_rng(7)
    batches = [_batch(rng, B) for _ in range(4)]
    wm = jnp.full((N_DEV,), np.int32(2999))

    s_mask = init_sharded_state(ctx, spec)
    s_ex = init_sharded_state(ctx, spec)
    for hi, lo, ts, vals in batches:
        valid = np.ones(B, bool)
        s_mask, _ = upd_mask(s_mask, jnp.asarray(hi), jnp.asarray(lo),
                          jnp.asarray(ts), jnp.asarray(vals),
                          jnp.asarray(valid), wm)
        s_ex, _ = upd_ex(s_ex, jnp.asarray(hi), jnp.asarray(lo),
                      jnp.asarray(ts), jnp.asarray(vals),
                      jnp.asarray(valid), wm)

    assert int(np.asarray(s_ex.dropped_capacity).sum()) == 0
    assert int(np.asarray(s_mask.dropped_capacity).sum()) == 0

    s_mask, cf_mask = fire(s_mask, wm)
    s_ex, cf_ex = fire(s_ex, wm)
    d_mask = _fires_dict(cf_mask)
    d_ex = _fires_dict(cf_ex)
    assert set(d_mask) == set(d_ex)
    for k in d_mask:
        assert d_mask[k] == pytest.approx(d_ex[k], rel=1e-5), k
    assert len(d_mask) > 0


def test_exchange_mode_end_to_end():
    """Full executor pipeline with exchange.mode=all_to_all must produce
    exactly the same window sums as the default path."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    if len(jax.devices()) < N_DEV:
        pytest.skip("needs 8 virtual devices")

    N = 40_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return (
            {"key": idx % 97, "value": np.ones(n, np.float32)},
            idx // 4,   # 4 events/ms -> 10s span
        )

    def run(mode):
        cfg = Configuration({"exchange.mode": mode,
                             "exchange.capacity-factor": 6.0})
        env = StreamExecutionEnvironment(cfg)
        env.set_parallelism(N_DEV)
        env.set_max_parallelism(128)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(1024)
        env.batch_size = 2048
        sink = CollectSink()
        (
            env.add_source(GeneratorSource(gen, total=N))
            .key_by(lambda c: c["key"])
            .time_window(1000)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute(f"exchange-{mode}")
        return {(r.key, r.window_end_ms): r.value for r in sink.results}

    d_mask = run("mask")
    d_ex = run("all_to_all")
    assert sum(d_mask.values()) == N
    assert d_mask == d_ex


def test_exchange_overflow_is_counted_not_lost_silently():
    ctx = _ctx()
    B = 512
    spec = WindowStageSpec(
        win=wk.WindowSpec(size_ticks=1000, slide_ticks=1000, ring=8,
                          fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=512,
    )
    # capacity_factor tiny -> guaranteed overflow with one hot key
    upd_ex = build_window_update_step_exchange(ctx, spec, B // N_DEV,
                                               capacity_factor=0.25)
    rng = np.random.default_rng(3)
    hi, lo, ts, vals = _batch(rng, B, n_keys=1)   # all lanes -> one shard
    wm = jnp.full((N_DEV,), np.int32(0))
    s = init_sharded_state(ctx, spec)
    s, _ = upd_ex(s, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(ts),
               jnp.asarray(vals), jnp.asarray(np.ones(B, bool)), wm)
    dropped = int(np.asarray(s.dropped_capacity).sum())
    assert dropped > 0
    # survivors + dropped == B
    total = float(np.asarray(s.acc).sum())  # all values were the survivors
    # count survivors via touched lanes' accumulated count is not direct;
    # instead: dropped lanes + lanes that made it should cover all B
    assert dropped < B
