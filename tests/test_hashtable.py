"""Device hash-slot table: batched lookup/upsert against a dict model."""

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.ops import hashtable
from flink_tpu.ops.hashing import hash64_host


def split(h):
    h = np.asarray(h, dtype=np.uint64)
    return (h >> np.uint64(32)).astype(np.uint32), (h & np.uint64(0xFFFFFFFF)).astype(
        np.uint32
    )


def test_upsert_then_lookup_roundtrip(rng):
    t = hashtable.create(1024, probe_len=16)
    keys = rng.integers(0, 2**63, 300, dtype=np.int64)
    hi, lo = split(hash64_host(keys))
    valid = np.ones(300, bool)

    t, slot, ok = hashtable.upsert(t, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert bool(ok.all())
    slot2, found = hashtable.lookup(t, jnp.asarray(hi), jnp.asarray(lo))
    assert bool(found.all())
    assert np.array_equal(np.asarray(slot), np.asarray(slot2))
    # distinct keys -> distinct slots
    assert len(np.unique(np.asarray(slot))) == len(np.unique(keys))


def test_duplicate_keys_same_slot(rng):
    t = hashtable.create(256)
    keys = np.array([7, 7, 7, 9, 9, 7], dtype=np.int64)
    hi, lo = split(hash64_host(keys))
    t, slot, ok = hashtable.upsert(t, jnp.asarray(hi), jnp.asarray(lo),
                                   jnp.ones(6, dtype=bool))
    slot = np.asarray(slot)
    assert bool(ok.all())
    assert slot[0] == slot[1] == slot[2] == slot[5]
    assert slot[3] == slot[4] != slot[0]


def test_invalid_lanes_ignored(rng):
    t = hashtable.create(256)
    keys = np.arange(10, dtype=np.int64)
    hi, lo = split(hash64_host(keys))
    valid = np.zeros(10, bool)
    valid[:3] = True
    t, slot, ok = hashtable.upsert(t, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(valid))
    assert np.asarray(ok).sum() == 3
    # unseeded keys are not present
    _, found = hashtable.lookup(t, jnp.asarray(hi[3:]), jnp.asarray(lo[3:]))
    assert not bool(found.any())


def test_incremental_batches_accumulate(rng):
    t = hashtable.create(4096)
    all_slots = {}
    for step in range(5):
        keys = rng.integers(0, 500, 256, dtype=np.int64)  # heavy overlap
        hi, lo = split(hash64_host(keys))
        t, slot, ok = hashtable.upsert(
            t, jnp.asarray(hi), jnp.asarray(lo), jnp.ones(256, bool)
        )
        assert bool(ok.all())
        for k, s in zip(keys.tolist(), np.asarray(slot).tolist()):
            if k in all_slots:
                assert all_slots[k] == s, "slot must be stable across batches"
            all_slots[k] = s
    used = np.asarray(t.used_mask()).sum()
    assert used == len(all_slots)


def test_table_overflow_reports_not_ok():
    t = hashtable.create(64, probe_len=4)
    keys = np.arange(200, dtype=np.int64)
    hi, lo = split(hash64_host(keys))
    t, slot, ok = hashtable.upsert(t, jnp.asarray(hi), jnp.asarray(lo),
                                   jnp.ones(200, bool))
    ok = np.asarray(ok)
    assert not ok.all()  # can't fit 200 keys in 64 slots
    # the ones that reported ok are genuinely findable
    slot2, found = hashtable.lookup(t, jnp.asarray(hi), jnp.asarray(lo))
    assert np.array_equal(np.asarray(found), ok)


def test_capacity_must_be_power_of_two():
    with pytest.raises(ValueError):
        hashtable.create(1000)
