"""Direct-index state layout: key == slot for bounded non-negative int
keys (wk.init_state layout="direct"; auto-selected by the executor from
the first batch's key identities). No probe gathers, no insert phase;
out-of-bound keys take the overflow ring -> spill tier.
"""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink, CountingSink
from flink_tpu.runtime.sources import GeneratorSource


def _env(capacity, **cfg):
    env = StreamExecutionEnvironment(Configuration(cfg))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(capacity)
    return env


def _sums(results):
    got = {}
    for r in results:
        got[(r.key, r.window_end_ms)] = got.get((r.key, r.window_end_ms),
                                                0) + r.value
    return got


def _expected(total, n_keys, ts_div, win):
    exp = {}
    for i in range(total):
        k, w = i % n_keys, ((i // ts_div) // win + 1) * win
        exp[(k, w)] = exp.get((k, w), 0) + 1.0
    return exp


def test_auto_selects_direct_and_results_exact():
    B, n_keys, total = 128, 200, 128 * 30

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return {"key": idx % n_keys, "value": np.ones(n, np.float32)}, idx // 32

    env = _env(256)
    env.batch_size = B
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(40)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("direct-auto")
    assert job.metrics.state_layout == "direct"
    assert _sums(sink.results) == _expected(total, n_keys, 32, 40)
    assert job.metrics.dropped_capacity == 0


def test_auto_falls_back_to_hash_for_unbounded_keys():
    B, total = 64, 64 * 6

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        # 64-bit ids far above capacity -> hash layout
        return ({"key": (idx % 16) * 10_000_000_019,
                 "value": np.ones(n, np.float32)}, idx // 16)

    env = _env(256)
    env.batch_size = B
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("hash-fallback")
    assert job.metrics.state_layout == "hash"
    assert sum(r.value for r in sink.results) == total


def test_direct_out_of_bound_keys_take_spill_tier():
    """Keys beyond capacity spill (overflow ring -> host stores) and still
    emit exact sums — the same degraded-mode contract as hash overflow."""
    B, total = 64, 64 * 20
    cap = 64

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        # first batches fit (auto picks direct), then keys 0..199 rotate:
        # 136 of them are out of the 64-slot bound every batch
        key = idx % 200 if offset > 0 else idx % 50
        return {"key": key, "value": np.ones(n, np.float32)}, idx // 16

    env = _env(cap)
    env.batch_size = B
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("direct-spill")
    assert job.metrics.state_layout == "direct"
    assert job.metrics.dropped_capacity == 0
    assert sum(r.value for r in sink.results) == total


def test_direct_checkpoint_restore_roundtrip(tmp_path):
    """Snapshot in direct layout restores exactly (identity table
    rebuilt, pane values scattered by key)."""
    from flink_tpu.runtime import checkpoint as ckpt
    from flink_tpu.ops import window_kernels as wk
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime.step import (
        WindowStageSpec, build_window_update_step, init_sharded_state,
    )
    import jax
    import jax.numpy as jnp

    ctx = MeshContext.create(1, 8)
    win = wk.WindowSpec(size_ticks=100, slide_ticks=100, ring=8,
                        fires_per_step=2, overflow=16)
    red = wk.ReduceSpec(kind="sum")
    spec = WindowStageSpec(win=win, red=red, capacity_per_shard=64,
                           layout="direct")
    state = init_sharded_state(ctx, spec)
    upd = build_window_update_step(ctx, spec)

    keys = np.asarray([3, 7, 3, 60], np.uint32)
    hi = np.zeros(4, np.uint32)
    ts = np.asarray([0, 10, 20, 130], np.int32)
    vals = np.asarray([1.0, 2.0, 4.0, 8.0], np.float32)
    wm = np.full((1,), np.int32(-(2**31) + 1))
    state, _ = upd(state, hi, keys, ts, vals, np.ones(4, bool), wm)

    entries, scalars = ckpt.snapshot_window_state(state, win)
    restored = ckpt.restore_window_state(entries, scalars, ctx, spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.table.keys)),
        np.asarray(jax.device_get(state.table.keys)),
    )
    np.testing.assert_allclose(
        np.asarray(jax.device_get(restored.acc)),
        np.asarray(jax.device_get(state.acc)),
    )
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(restored.touched)),
        np.asarray(jax.device_get(state.touched)),
    )


def test_direct_layout_multi_device_with_exchange():
    """Direct layout at parallelism 8 under the default adaptive
    exchange: each shard owns its key groups at slot == key; results
    must be exact and the ICI route must engage for balanced batches."""
    B, n_keys, total = 96, 60, 96 * 25

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return {"key": idx % n_keys, "value": np.ones(n, np.float32)}, idx // 12

    env = _env(256, **{"exchange.capacity-factor": 4.0})
    env.set_parallelism(8)
    env.batch_size = B
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(50)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("direct-multidev")
    assert job.metrics.state_layout == "direct"
    assert job.metrics.exchange_mode == "adaptive"
    assert job.metrics.steps_exchanged > 0
    assert _sums(sink.results) == _expected(total, n_keys, 12, 50)
    assert job.metrics.dropped_capacity == 0


def test_direct_layout_job_checkpoint_restore_roundtrip(tmp_path):
    """Kill-and-recover a direct-layout job: the checkpoint records the
    layout and restore resumes in it (aux['state_layout'])."""
    B, n_keys, total = 64, 40, 64 * 30

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return {"key": idx % n_keys, "value": np.ones(n, np.float32)}, idx // 8

    class FailingSink(CollectSink):
        armed = [True]

        def invoke_batch(self, elements):
            if FailingSink.armed[0] and len(self.results) > 0:
                FailingSink.armed[0] = False
                raise RuntimeError("injected")
            super().invoke_batch(elements)

        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results = list(state)

    env = _env(64, **{
        "restart-strategy": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
    })
    env.batch_size = B
    env.checkpoint_dir = str(tmp_path / "ck")
    env.checkpoint_interval_steps = 3
    sink = FailingSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(40)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("direct-ck")
    assert job.metrics.state_layout == "direct"
    assert job.metrics.restarts >= 1
    assert _sums(sink.results) == _expected(total, n_keys, 8, 40)
