"""Self-healing runtime (ISSUE 19): the closed-loop controller.

Unit tests drive :class:`RuntimeController` with fake sensors, clocks
and rebalancers — the hill-climb/probation/auto-revert/cooldown state
machine, the heat-balanced prefix partition, the rebalance gates
(threshold, rate limit, min-gain, skip dedup) and the failure ledger
are all pinned without a device in sight. The e2e tests then run a
real skewed windowed job on the virtual CPU mesh and assert the
controller re-slices the shard ranges LIVE (no restart) with the
analytic exactly-once oracle intact — including through an injected
``controller.apply`` crash mid-rebalance (restart from the last cut,
pre-rebalance slicing re-latched, then the retry succeeds)."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.controller import (
    ACTUATOR_NAMES,
    Actuator,
    RuntimeController,
    plan_balanced_slices,
    predicted_gain,
    shard_heats,
)
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule

# ------------------------------------------------------------ actuators


def _holder_actuator(name="ring-fill-target", value=8, lo=1, hi=16,
                     step="geometric"):
    box = [value]
    return box, Actuator(name, lambda: box[0],
                         lambda v: box.__setitem__(0, v),
                         lo=lo, hi=hi, step=step)


def test_actuator_move_geometric_and_additive():
    _, act = _holder_actuator(value=8, lo=1, hi=16)
    assert act.move("up") == (8, 16)
    assert act.move("down") == (8, 4)
    box, act = _holder_actuator(value=16, lo=1, hi=16)
    assert act.move("up") == (16, 16)      # clamped at hi
    box[0] = 1
    assert act.move("down") == (1, 1)      # clamped at lo (1//2=0 -> 1)
    _, add = _holder_actuator(value=3, lo=0, hi=4, step="additive")
    assert add.move("up") == (3, 4)
    assert add.move("down") == (3, 2)


def test_unknown_actuator_rejected():
    _, act = _holder_actuator(name="ring-fill-target")
    with pytest.raises(ValueError, match="unregistered"):
        RuntimeController({"warp-factor": act}, sensor=lambda: {})
    # every declared name is accepted
    for name in ACTUATOR_NAMES:
        if name == "rebalance-key-groups":
            continue          # the rebalance arm, not a knob
        _, a = _holder_actuator(name=name)
        RuntimeController({name: a}, sensor=lambda: {})


# ------------------------------------------------- balanced partitioning


def test_balanced_slices_uniform_heat_is_even():
    starts, ends = plan_balanced_slices(np.ones(64), 4)
    assert starts == [0, 16, 32, 48]
    assert ends == [15, 31, 47, 63]


@pytest.mark.parametrize("n_shards", [1, 2, 3, 4, 7])
def test_balanced_slices_cover_and_monotone(n_shards):
    rng = np.random.default_rng(3)
    heat = rng.exponential(1.0, 32) * (rng.random(32) < 0.3)
    starts, ends = plan_balanced_slices(heat, n_shards)
    assert starts[0] == 0 and ends[-1] == 31
    for s in range(n_shards):
        assert ends[s] >= starts[s]          # every shard non-empty
        if s:
            assert starts[s] == ends[s - 1] + 1
    assert ends == sorted(ends)
    assert len(set(ends)) == n_shards        # strictly increasing


def test_balanced_slices_concentrated_heat():
    heat = np.zeros(64)
    heat[[1, 3, 5, 7]] = 100.0
    starts, ends = plan_balanced_slices(heat, 4)
    # closest-boundary prefix partition: one hot group per shard
    new = shard_heats(heat, starts, ends)
    assert new == [100.0, 100.0, 100.0, 100.0]
    gain = predicted_gain(heat, [0, 16, 32, 48], [15, 31, 47, 63],
                          starts, ends)
    assert gain == pytest.approx(4.0)


def test_balanced_slices_too_few_groups_raises():
    with pytest.raises(ValueError, match="cannot slice"):
        plan_balanced_slices(np.ones(3), 4)


def test_predicted_gain_identity():
    heat = np.array([4.0, 0.0, 0.0, 4.0])
    assert predicted_gain(heat, [0, 2], [1, 3], [0, 2], [1, 3]) == 1.0


# --------------------------------------------------- controller units


class _Rig:
    """Fake world: a records counter, a manual clock, a knob, and
    switchable doctor findings."""

    def __init__(self, **ctl_kw):
        self.t = [0.0]
        self.records = [0]
        self.findings = []
        self.heat = None
        self.kg = ([0, 4], [3, 7])
        self.rebalance_calls = []
        self.rebalance_exc = None
        self.box, self.act = _holder_actuator(value=8, lo=1, hi=16)
        kw = dict(interval_cycles=1, probation_cycles=2,
                  cooldown_cycles=4, rebalance_threshold=1.5,
                  min_rebalance_interval=10.0, min_gain=1.2,
                  clock=lambda: self.t[0])
        kw.update(ctl_kw)
        self.ctl = RuntimeController(
            {"ring-fill-target": self.act}, self.sensor,
            findings_fn=lambda: self.findings,
            rebalancer=self.rebalance, **kw)

    def sensor(self):
        starts, ends = self.kg
        return {"records": self.records[0], "duty": 0.2, "starved": 0.0,
                "heat": self.heat, "kg_starts": list(starts),
                "kg_ends": list(ends)}

    def rebalance(self, starts, ends):
        if self.rebalance_exc is not None:
            raise self.rebalance_exc
        self.rebalance_calls.append((list(starts), list(ends)))

    def tick(self, dt=1.0, drecords=1000):
        self.t[0] += dt
        self.records[0] += drecords
        self.ctl.service()


def test_tune_probation_autorevert_and_cooldown():
    rig = _Rig()
    rig.tick()                         # primes the trailing rate sample
    rig.findings = [{"rule": "ring-starved",
                     "action": {"actuator": "ring-fill-target",
                                "direction": "down"}}]
    rig.tick()                         # tune fires: 8 -> 4, probation
    assert rig.ctl.actions == 1 and rig.box[0] == 4
    assert rig.ctl.report()["probation"]["actuator"] == "ring-fill-target"
    # the move made things worse: rate collapses 1000/s -> 10/s
    rig.tick(drecords=10)              # probation window not over yet
    assert rig.ctl.reverts == 0
    rig.tick(drecords=10)              # window over -> auto-revert
    assert rig.ctl.reverts == 1
    assert rig.box[0] == 8             # knob restored
    kinds = [e["kind"] for e in rig.ctl.report()["ledger"]]
    assert kinds == ["tune", "revert"]
    ev = rig.ctl.report()["ledger"][-1]["evidence"]
    assert ev["rate_after"] < ev["rate_before"]
    # (actuator, direction) sits out the cooldown: findings still ask
    # for it, but no new move fires...
    for _ in range(3):
        rig.tick()
    assert rig.ctl.actions == 1
    # ...until the cooldown expires
    rig.tick()
    assert rig.ctl.actions == 2 and rig.box[0] == 4


def test_probation_pass_keeps_move():
    rig = _Rig()
    rig.tick()
    rig.findings = [{"rule": "device-saturated",
                     "action": {"actuator": "ring-fill-target",
                                "direction": "up"}}]
    rig.tick()                         # tune 8 -> 16
    assert rig.box[0] == 16
    rig.findings = []
    rig.tick()
    rig.tick()                         # rate held -> probation passes
    assert rig.ctl.reverts == 0 and rig.box[0] == 16
    assert [e["kind"] for e in rig.ctl.report()["ledger"]] == \
        ["tune", "probation-pass"]


def test_ledger_persists_and_merges_across_restart(tmp_path):
    """ISSUE 20 satellite: decisions survive the restart that applied
    them — the jsonl ledger rides the checkpoint dir, a fresh
    controller reloads the tail, and report() serves ONE totally-
    ordered merged history with per-run stamps."""
    import json

    rig = _Rig(persist_dir=str(tmp_path))
    rig.tick()
    rig.findings = [{"rule": "device-saturated",
                     "action": {"actuator": "ring-fill-target",
                                "direction": "up"}}]
    rig.tick()                         # tune 8 -> 16, persisted
    rig.findings = []
    rig.tick()
    rig.tick()                         # probation passes, persisted
    path = tmp_path / "controller-ledger.jsonl"
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert [e["kind"] for e in lines] == ["tune", "probation-pass"]
    assert all(e["run"] == 1 for e in lines)

    # restart: a fresh controller over the same dir serves the MERGED
    # history and keeps appending with a bumped run / continued seq
    rig2 = _Rig(persist_dir=str(tmp_path))
    rep = rig2.ctl.report()
    assert rep["run"] == 2 and rep["restored_entries"] == 2
    assert [e["kind"] for e in rep["ledger"]] == \
        ["tune", "probation-pass"]
    rig2.tick()
    rig2.findings = [{"rule": "device-saturated",
                      "action": {"actuator": "ring-fill-target",
                                 "direction": "up"}}]
    rig2.tick()                        # run-2 tune
    merged = rig2.ctl.report()["ledger"]
    assert [(e["run"], e["kind"]) for e in merged] == \
        [(1, "tune"), (1, "probation-pass"), (2, "tune")]
    seqs = [e["seq"] for e in merged]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    # a torn tail line (crash mid-append) is skipped, never fatal
    with open(path, "a") as f:
        f.write('{"seq": 99, "k')
    rig3 = _Rig(persist_dir=str(tmp_path))
    assert rig3.ctl.report()["restored_entries"] == 3
    assert rig3.ctl.report()["run"] == 3


def test_regime_fallback_picks_ring_fill_target():
    rig = _Rig()
    rig.tick()
    orig = rig.sensor

    def starved_sensor():
        s = orig()
        s["starved"] = 0.9
        return s

    rig.ctl.sensor = starved_sensor
    rig.tick()
    assert rig.ctl.actions == 1
    assert rig.ctl.report()["ledger"][-1]["evidence"]["why"] == \
        "regime:ring-starved"
    assert rig.box[0] == 4             # down: drain earlier


def test_rebalance_applies_and_rate_limits():
    rig = _Rig()
    # all heat in groups 0 and 4, current split [0..5] | [6..7]:
    # shard 0 carries everything -> skew 2.0 over the threshold
    rig.heat = np.array([60.0, 0, 0, 0, 40.0, 0, 0, 0])
    rig.kg = ([0, 6], [5, 7])
    rig.tick()
    assert rig.ctl.rebalances == 1
    (starts, ends), = rig.rebalance_calls
    assert ends == [0, 7]              # greedy prefix: 60 | 40
    ev = rig.ctl.report()["ledger"][-1]["evidence"]
    assert ev["ends_before"] == [5, 7] and ev["ends_after"] == [0, 7]
    assert ev["predicted_gain"] == pytest.approx(100 / 60, abs=0.01)
    # the sensor still reports the old slicing (we never updated kg):
    # same skew, but the rate limiter blocks a re-fire...
    rig.tick()
    assert rig.ctl.rebalances == 1
    # ...until min_rebalance_interval passes on the fake clock
    rig.tick(dt=20.0)
    assert rig.ctl.rebalances == 2


def test_rebalance_skip_dedup_on_unchanged_slices():
    rig = _Rig()
    rig.heat = np.ones(8)
    rig.kg = ([0, 4], [3, 7])          # already balanced
    # doctor ASKS for a rebalance (skew below threshold): planner finds
    # nothing better -> one deduped skip entry, not one per cycle
    rig.findings = [{"rule": "kg-heat-skew",
                     "action": {"actuator": "rebalance-key-groups"}}]
    rig.tick()
    rig.tick()
    rig.tick()
    assert rig.ctl.rebalances == 0
    assert rig.ctl.rebalance_skips == 1
    skips = [e for e in rig.ctl.report()["ledger"]
             if e["kind"] == "rebalance-skip"]
    assert len(skips) == 1


def test_rebalance_failure_ledgered_and_propagates():
    rig = _Rig()
    rig.heat = np.array([60.0, 0, 0, 0, 40.0, 0, 0, 0])
    rig.kg = ([0, 6], [5, 7])
    rig.rebalance_exc = RuntimeError("device fell over mid-cut")
    rig.t[0] += 1.0
    rig.records[0] += 1000
    with pytest.raises(RuntimeError, match="mid-cut"):
        rig.ctl.service()
    assert rig.ctl.rebalances == 0
    assert rig.ctl.rebalance_failures == 1
    assert rig.ctl.report()["ledger"][-1]["kind"] == "rebalance-failed"


def test_interval_gating_and_ledger_bound():
    calls = [0]

    def sensor():
        calls[0] += 1
        return {"records": 0}

    ctl = RuntimeController({}, sensor, interval_cycles=4)
    for _ in range(8):
        ctl.service()
    assert calls[0] == 2               # every 4th cycle only
    for i in range(150):
        ctl._log("noise", i=i)
    assert len(ctl.report()["ledger"]) == 100
    rep = ctl.report()
    for key in ("available", "cycle", "actions", "reverts",
                "rebalances", "actuators", "cooldowns", "probation"):
        assert key in rep


# ----------------------------------------------------------------- e2e

MAXP = 64
WINDOW = 10_000
B = 256

_CAND = np.arange(2048, dtype=np.int64)
_KG = assign_to_key_group(_CAND.astype(np.uint32), MAXP, np)


def _keys_in(groups, per_group=2):
    return np.concatenate(
        [_CAND[_KG == g][:per_group] for g in groups])


def _skew_pool(total, seed=7):
    """90% of traffic on four groups inside shard 0's default range
    [0..15], 10% uniform over every group — the cold plane keeps all
    64 groups owned while the hot set concentrates the heat."""
    hot = _keys_in((1, 5, 9, 13))
    rng = np.random.default_rng(seed)
    pool = _CAND[rng.integers(0, len(_CAND), total)]
    hot_mask = rng.random(total) < 0.9
    pool[hot_mask] = hot[rng.integers(0, len(hot), hot_mask.sum())]
    return pool


def _expected(pool):
    ts = (np.arange(len(pool)) // 50) * 1000
    out = {}
    for k, t in zip(pool.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


CTL_E2E = {
    "pipeline.prefetch": "on",
    # the heat plane lives in the drain flight recorder: the rebalance
    # arm needs the resident loop + drain-stats + kg-stats all on
    "pipeline.resident-loop": "on",
    "pipeline.ring-depth": 4,
    "pipeline.data-parallel": "on",
    "observability.kg-stats": True,
    "observability.drain-stats": True,
    "observability.kg-heat-alpha": 0.5,
    # the unequal-reslice-with-tiers edge rides along: two resident
    # groups per shard re-seed against the REBALANCED (non-uniform)
    # ranges inside the savepoint cut
    "state.tiers.resident-key-groups": 2,
    "state.tiers.min-dwell-cycles": 1,
    "controller.enabled": True,
    "controller.interval-cycles": 2,
    "controller.probation-cycles": 2,
    "controller.cooldown-cycles": 4,
    "controller.rebalance-threshold": 1.5,
    "controller.min-rebalance-interval": 1.0,
    "controller.min-gain": 1.1,
}


def _run_skewed(pool, extra_cfg=None, ckpt_dir=None, interval=0):
    cfg = dict(CTL_E2E)
    cfg.update(extra_cfg or {})
    env = StreamExecutionEnvironment(Configuration(cfg))
    env.set_parallelism(4).set_max_parallelism(MAXP)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = B
    if ckpt_dir is not None:
        env.enable_checkpointing(interval, str(ckpt_dir))

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        return ({"key": pool[offset:offset + n],
                 "value": np.ones(n, np.float32)},
                (idx // 50) * 1000)

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=len(pool)))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("controller-e2e")
    out = {(r.key, r.window_end_ms): r.value for r in sink.results}
    return env, out


@pytest.mark.slow
def test_live_rebalance_e2e_exactly_once():
    total = 16384
    pool = _skew_pool(total)
    env, out = _run_skewed(pool)
    assert out == _expected(pool)
    rep = env._controller_report()
    assert rep["available"]
    assert rep["rebalances"] >= 1
    # the re-slice happened LIVE: no restart was taken
    assert env.last_job.metrics.restarts == 0
    rb = [e for e in rep["ledger"] if e["kind"] == "rebalance"]
    assert rb and rb[0]["evidence"]["ends_after"] != \
        rb[0]["evidence"]["ends_before"]
    assert rb[0]["evidence"]["predicted_gain"] >= 1.1


@pytest.mark.slow
def test_rebalance_crash_recovers_exactly_once(tmp_path):
    """``controller.apply`` chaos: the crash lands mid-rebalance BEFORE
    the savepoint cut. The executor re-latches the pre-rebalance
    slicing, recovery restores from the last completed checkpoint, and
    the controller's NEXT decision re-attempts the re-slice (the fault
    rule is exhausted) — results stay bit-exact vs the oracle.

    Checkpoint every step: the controller's first decision fires within
    a handful of poll cycles, and recovery is (correctly) refused when
    no completed cut exists yet."""
    total = 16384
    pool = _skew_pool(total, seed=13)
    inj = FaultInjector([FaultRule("controller.apply")])
    with faults.active(inj):
        env, out = _run_skewed(
            pool,
            extra_cfg={
                "restart-strategy": "exponential-backoff",
                "restart-strategy.exponential-backoff.initial-delay":
                    0.01,
                "restart-strategy.exponential-backoff.max-delay": 0.05,
            },
            ckpt_dir=tmp_path / "ck", interval=1)
    assert out == _expected(pool)
    assert inj.hits("controller.apply") >= 2     # crashed, then retried
    assert env.last_job.metrics.restarts >= 1
    rep = env._controller_report()
    assert rep["rebalance_failures"] >= 1
    assert rep["rebalances"] >= 1
    failed = [e for e in rep["ledger"] if e["kind"] == "rebalance-failed"]
    assert failed
