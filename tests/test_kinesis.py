"""Kinesis wire connector vs the in-repo spec server: SigV4-signed
JSON-over-HTTP protocol, MD5 hash-key shard routing, per-shard
sequence-number checkpoint state, PutRecords failed-subset retry.

Ref: flink-streaming-connectors/flink-connector-kinesis/
FlinkKinesisConsumer.java (sequenceNumsToRestore snapshot/restore),
FlinkKinesisProducer.java (at-least-once buffered puts)."""

import hashlib

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.kinesis import (
    MAX_HASH_KEY,
    KinesisApiError,
    KinesisClient,
    KinesisSink,
    KinesisSource,
    MiniKinesis,
    PutUndelivered,
    sign_v4,
)


@pytest.fixture
def mk():
    server = MiniKinesis(shards=3)
    server.create_stream("events")
    server.start()
    yield server
    server.stop()


def _sink(mk, **kw):
    return KinesisSink(
        "127.0.0.1", mk.port, "events",
        emitter=lambda e: (str(e[0]), str(e[1]).encode()), **kw,
    )


def _source(mk, **kw):
    return KinesisSource("127.0.0.1", mk.port, "events", **kw)


# ------------------------------------------------------------------ SigV4
def test_sigv4_known_answer():
    """Derived-key chain against a hand-computed vector (the spec's
    example keys), locking the implementation to the algorithm rather
    than to itself."""
    auth = sign_v4(
        "POST", "/",
        {"Host": "kinesis.us-east-1.amazonaws.com",
         "X-Amz-Date": "20130524T000000Z"},
        b"{}", "us-east-1", "kinesis",
        "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        "20130524T000000Z",
    )
    assert auth.startswith(
        "AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/20130524/us-east-1/"
        "kinesis/aws4_request, SignedHeaders=host;x-amz-date, Signature=")
    # deterministic: same inputs, same signature
    assert auth == sign_v4(
        "POST", "/",
        {"Host": "kinesis.us-east-1.amazonaws.com",
         "X-Amz-Date": "20130524T000000Z"},
        b"{}", "us-east-1", "kinesis",
        "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        "20130524T000000Z",
    )
    # any input change changes the signature
    assert auth != sign_v4(
        "POST", "/",
        {"Host": "kinesis.us-east-1.amazonaws.com",
         "X-Amz-Date": "20130524T000000Z"},
        b"{x}", "us-east-1", "kinesis",
        "AKIDEXAMPLE", "wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
        "20130524T000000Z",
    )


def test_server_verifies_signature(mk):
    """The spec server recomputes SigV4: a wrong secret is a 403 — the
    client's signing is proven against an independent verifier."""
    good = KinesisClient("127.0.0.1", mk.port)
    assert good.list_shards("events")
    assert mk.auth_failures == 0

    bad = KinesisClient("127.0.0.1", mk.port, secret_key="WRONG")
    with pytest.raises(KinesisApiError):
        bad.list_shards("events")
    assert mk.auth_failures == 1
    good.close()
    bad.close()


# ------------------------------------------------------------- wire basics
def test_put_get_roundtrip_across_shards(mk):
    sink = _sink(mk, flush_max_records=8)
    sink.open()
    sink.invoke_batch([(i, i * 10) for i in range(20)])
    sink.close()
    assert sink.stats["records"] == 20

    src = _source(mk)
    src.open()
    out, end = src.poll(100)
    src.close()
    assert sorted(int(v) for v in out) == [i * 10 for i in range(20)]
    assert end is False                    # open shards never exhaust
    # records actually spread over the 3 shards by MD5 hash-key routing
    assert sum(1 for s in mk.streams["events"] if s) >= 2


def test_md5_hash_key_routing(mk):
    """Partition-key -> shard mapping is the public MD5 range spec."""
    for pk in ("a", "b", "user-17", "zzz"):
        sid = mk.shard_for_key("events", pk)
        lo, hi = mk.shard_ranges["events"][sid]
        hk = int(hashlib.md5(pk.encode()).hexdigest(), 16)
        assert lo <= hk < hi
    assert mk.shard_ranges["events"][-1][1] == MAX_HASH_KEY


def test_same_partition_key_ordered_within_shard(mk):
    sink = _sink(mk, flush_max_records=4)
    sink.open()
    sink.invoke_batch([("k", i) for i in range(9)])
    sink.close()
    sid = mk.shard_for_key("events", "k")
    shard = mk.streams["events"][sid]
    assert [int(r["SequenceNumber"]) for r in shard] == list(range(9))


# ------------------------------------------------------------- consumer
def test_sequence_state_snapshot_restore_exactly_once(mk):
    """The FlinkKinesisConsumer story: the checkpoint cut carries the
    per-shard sequence map; a restored source resumes AFTER it —
    no record lost, none re-emitted."""
    sink = _sink(mk)
    sink.open()
    sink.invoke_batch([(i, i) for i in range(10)])
    sink.close()

    src = _source(mk)
    src.open()
    first, _ = src.poll(6)                 # ~2 records per shard
    first = list(first)
    cut = src.snapshot_offsets()
    src.close()

    # more records arrive after the cut
    sink2 = _sink(mk)
    sink2.open()
    sink2.invoke_batch([(i, i) for i in range(10, 14)])
    sink2.close()

    restored = _source(mk)
    restored.restore_offsets(cut)
    restored.open()
    rest = []
    for _ in range(10):
        out, _end = restored.poll(100)
        rest.extend(out)
    restored.close()
    assert sorted(int(v) for v in first + rest) == list(range(14))


def test_latest_iterator_skips_history(mk):
    sink = _sink(mk)
    sink.open()
    sink.invoke_batch([(i, i) for i in range(5)])
    sink.close()
    src = _source(mk, initial_position="LATEST")
    src.open()
    assert src.poll(100)[0] == []
    sink2 = _sink(mk)
    sink2.open()
    sink2.invoke_batch([(99, 99)])
    sink2.close()
    assert [int(v) for v in src.poll(100)[0]] == [99]
    src.close()


def test_deserializer_seam(mk):
    sink = _sink(mk)
    sink.open()
    sink.invoke_batch([(7, "x")])
    sink.close()
    src = _source(mk, deserializer=lambda data, pk: (pk, data))
    src.open()
    assert src.poll(10)[0] == [("7", b"x")]
    src.close()


# ------------------------------------------------------------- producer
def test_whole_request_throttle_backoff(mk):
    mk.throttle_next_puts = 2
    sink = _sink(mk, flush_max_records=4, max_retries=4)
    sink.open()
    sink.invoke_batch([(i, i) for i in range(4)])
    sink.close()
    assert sink.stats["retries"] >= 2
    assert sink.stats["records"] == 4


def test_failed_subset_retried_without_duplicates(mk):
    """Per-record ErrorCode results: ONLY the failed subset is resent
    (resending acknowledged records would duplicate — Kinesis has no
    idempotent write)."""
    mk.throttle_next_records = 3
    sink = _sink(mk, flush_max_records=8, max_retries=4)
    sink.open()
    sink.invoke_batch([(i, i) for i in range(8)])
    sink.close()
    total = sum(len(s) for s in mk.streams["events"])
    assert total == 8                       # no loss, no duplicates
    assert sink.stats["records"] == 8
    assert sink.stats["retries"] >= 1


def test_retry_exhaustion_rebuffers_unsent_only(mk):
    mk.throttle_next_puts = 99
    sink = _sink(mk, flush_max_records=4, max_retries=1)
    sink.open()
    with pytest.raises(PutUndelivered):
        sink.invoke_batch([(i, i) for i in range(4)])
    assert len(sink._buf) == 4              # nothing silently dropped
    mk.throttle_next_puts = 0
    sink.flush()
    sink.close()
    assert sum(len(s) for s in mk.streams["events"]) == 4


def test_flush_on_checkpoint(mk):
    sink = _sink(mk, flush_max_records=100)
    sink.open()
    sink.invoke_batch([(i, i) for i in range(3)])
    assert sum(len(s) for s in mk.streams["events"]) == 0   # buffered
    sink.snapshot_state()
    assert sum(len(s) for s in mk.streams["events"]) == 3   # barrier-clean
    sink.close()


def test_oversized_batch_splits_at_api_limit(mk):
    sink = _sink(mk, flush_max_records=600)   # clamped to the API's 500
    assert sink.flush_max_records == 500
    sink.open()
    sink.invoke_batch([(i, i) for i in range(501)])
    sink.close()
    assert sink.stats["put_requests"] == 2
    assert sum(len(s) for s in mk.streams["events"]) == 501


# ------------------------------------------------------------- pipeline
def test_pipeline_end_to_end(mk):
    """Streaming job -> windowed sums -> Kinesis, read back over the
    signed wire by the consumer."""
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sources import GeneratorSource

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_parallelism(2).set_max_parallelism(32)
    env.set_state_capacity(256)
    env.batch_size = 64

    def gen(off, n):
        idx = np.arange(off, off + n)
        return ({"key": idx % 5, "value": np.ones(n, np.float32)},
                (idx * 10).astype(np.int64))

    sink = KinesisSink(
        "127.0.0.1", mk.port, "events",
        emitter=lambda r: (
            str(int(r.key)),
            f"{int(r.key)}:{int(r.window_end_ms)}:{float(r.value)}"
            .encode(),
        ),
        flush_max_records=16,
    )
    (
        env.add_source(GeneratorSource(gen, total=1000))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("to-kinesis")
    # 1000 records, ts = idx*10 -> 10 windows x 5 keys
    src = _source(mk)
    src.open()
    rows = []
    for _ in range(5):
        out, _end = src.poll(1000)
        rows.extend(out)
    src.close()
    assert len(rows) == 50
    by_key = {}
    for r in rows:
        k, _, total = r.split(":")
        by_key[k] = by_key.get(k, 0.0) + float(total)
    assert by_key == {str(k): 200.0 for k in range(5)}


def test_consumer_through_streaming_job(mk):
    """Kinesis -> KinesisSource (bounded) -> keyed reduce -> sink through
    the real executor: the Source contract (poll -> (elements, end)) is
    exercised end-to-end, not just by direct calls."""
    from flink_tpu.runtime.sinks import CollectSink

    sink_w = _sink(mk)
    sink_w.open()
    sink_w.invoke_batch([(f"w{i % 6}", f"w{i % 6}") for i in range(120)])
    sink_w.close()

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(1)
    env.batch_size = 16
    out = CollectSink()
    src = _source(mk, bounded=True)
    (
        env.add_source(src)
        .key_by(lambda w: w)
        .reduce(lambda a, b: a + b, extractor=lambda w: 1.0)
        .add_sink(out)
    )
    env.execute("kinesis-wordcount")
    finals = {}
    for key, value in out.results:
        finals[key] = max(finals.get(key, 0), value)
    assert finals == {f"w{j}": 20.0 for j in range(6)}
    src.close()
