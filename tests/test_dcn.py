"""Cross-host data plane (runtime/dcn.py): two worker PROCESSES, each with
its own source partition and its own 4 local devices, form one 8-device
global mesh; the keyed all_to_all routes records between processes (the
collective transport is the DCN hop). Proves:

  * records that ENTER on host A fire from host B's shards (disjoint
    per-host key slices; every emission is checked against which host
    ingested that key),
  * exact per-(key, window) sums across the union of both hosts' sinks,
  * kill-and-restart of the whole ensemble resumes from the latest
    complete lockstep checkpoint with exactly-once results (the
    reference's full-job-restart failure model,
    CheckpointCoordinator.restoreLatestCheckpointedState).

Ref: RecordWriter.java:82 (keyed shuffle), TaskManager.scala:296
(worker registration), FlinkKafkaConsumerBase.java:65 (per-subtask
partition assignment).
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from dcn_jobs import N_KEYS, expected  # noqa: E402
from dcn_probe import (  # noqa: E402
    SKIP_REASON,
    multiprocess_collectives_supported,
)

# collection-time capability gate: a backend that cannot run ANY
# cross-process collective fails every ensemble test identically on
# every commit — skip with the explicit reason instead, so tier-1 stays
# green and real regressions stop hiding behind "same failures as parent"
pytestmark = pytest.mark.skipif(
    not multiprocess_collectives_supported(), reason=SKIP_REASON
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUILDER = os.path.join(REPO, "tests", "dcn_jobs.py") + ":two_host_window"
NPROC = 2


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn(pid, coord, out, extra=()):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.dcn",
         "--coordinator", coord, "--num-processes", str(NPROC),
         "--process-id", str(pid), "--builder", BUILDER, "--out", out,
         *extra],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def _wait_all(procs, timeout=420):
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        remain = max(1, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=remain)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    return outs


def _merge(paths):
    got = {}
    by_host = {}
    for host, path in enumerate(paths):
        data = np.load(path)
        for k64, w, v in zip(data["key_id"], data["window_end_ms"],
                             data["value"]):
            key = (int(k64), int(w))
            assert key not in got, f"duplicate emission {key}"
            got[key] = float(v)
            by_host[key] = host
    return got, by_host


def test_records_cross_hosts_and_sums_exact(tmp_path):
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    procs = [_spawn(p, coord, outs[p]) for p in range(NPROC)]
    logs = _wait_all(procs)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]
    got, by_host = _merge(outs)
    exp = expected(NPROC)
    assert {k: v for k, v in got.items()} == exp
    # key k was ingested ONLY by host (k % NPROC); count emissions where
    # the firing host differs from the ingesting host — the records
    # provably crossed the process boundary through the all_to_all
    crossed = sum(
        1 for (k, _w), host in by_host.items() if host != k % NPROC
    )
    assert crossed > len(got) // 4, (crossed, len(got))
    # both hosts fired something (key groups span both ICI islands)
    assert len(set(by_host.values())) == NPROC


def test_kill_recover_round_trip(tmp_path):
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]

    coord = f"127.0.0.1:{_free_port()}"
    extra = ["--checkpoint-dir", ckpt, "--ckpt-every", "3"]
    procs = [_spawn(p, coord, outs[p], extra) for p in range(NPROC)]
    # wait until at least one complete checkpoint exists, then kill the
    # whole ensemble mid-flight (a dead process wedges the collective, so
    # the failure unit is the job — the reference's full-restart model)
    deadline = time.time() + 300
    while time.time() < deadline:
        chks = [d for d in os.listdir(ckpt) if d.startswith("chk-")]
        complete = [
            d for d in chks
            if all(os.path.exists(os.path.join(ckpt, d, f"proc-{p}.meta.json"))
                   for p in range(NPROC))
        ]
        if complete:
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.2)
    alive = [p for p in procs if p.poll() is None]
    assert complete, "no complete checkpoint appeared before the kill"
    assert alive, "workers finished before the kill — raise TOTAL_PER_HOST"
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
    for p in procs:
        p.wait(timeout=60)

    # respawn the ensemble with --restore: every process resumes from the
    # latest checkpoint that ALL processes completed
    coord2 = f"127.0.0.1:{_free_port()}"
    procs2 = [
        _spawn(p, coord2, outs[p], extra + ["--restore"])
        for p in range(NPROC)
    ]
    logs = _wait_all(procs2)
    for p, log in zip(procs2, logs):
        assert p.returncode == 0, log[-2000:]
    got, by_host = _merge(outs)
    assert got == expected(NPROC)
