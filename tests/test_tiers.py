"""Tiered key-group state (ISSUE 18): exactly-once + placement units.

* property test: the tiered job (HBM budget of 2 key-groups out of 8)
  is bit-exact against the all-resident oracle job across
  {hash, direct} layouts x packed planes x 1/2-shard meshes — the tier
  swap is a placement action, never a semantic one;
* exactly-once across the tier fault seams: a crash at
  ``tier.demote.write`` (between a demote and its checkpoint), a crash
  at ``tier.promote.read`` (the restore-adjacent read half), and a
  chaos soak with both seams firing repeatedly — restore replays from
  the last cut, nothing skipped, nothing double-counted;
* TierManager planner units: budget validation, watermark-urgent
  promotion beating dwell hysteresis, rescale re-slicing residency,
  prefetch hit/miss accounting.
"""

import numpy as np
import pytest

pytest.importorskip("jax")

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime import tiers as tiers_mod
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule

N_KEYS = 512
WINDOW_MS = 1000
EVENTS_PER_KEY = 6
TOTAL = N_KEYS * EVENTS_PER_KEY


def _gen(offset, n):
    idx = np.arange(offset, offset + n, dtype=np.int64)
    keys = idx % N_KEYS
    # event time sweeps 4 windows over the stream: every key-group
    # carries pending panes, so demotes always have entries to fold
    ts = (idx * 4 * WINDOW_MS) // TOTAL
    return {"key": keys, "value": np.ones(n, np.float32)}, ts


def run_job(tiers=0, n_shards=1, packed=None, layout=None,
            n_keys=N_KEYS, capacity=1024, ckpt_dir=None, restart=None,
            total=TOTAL):
    opts = {"keys.reverse-map": True}
    if tiers:
        opts["state.tiers.resident-key-groups"] = tiers
        opts["state.tiers.min-dwell-cycles"] = 1
    if packed is not None:
        opts["state.packed-planes"] = packed
    if layout is not None:
        opts["state.backend.layout"] = layout
    if restart:
        opts.update({
            "restart-strategy": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": restart,
            "restart-strategy.fixed-delay.delay": 0,
        })
    env = StreamExecutionEnvironment(Configuration(opts))
    env.set_parallelism(n_shards)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(capacity)
    env.batch_size = 256
    if ckpt_dir:
        # every step: the tier seams fire early in the run, and recovery
        # needs a completed cut to restart from
        env.enable_checkpointing(1, str(ckpt_dir))

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = idx % n_keys
        ts = (idx * 4 * WINDOW_MS) // total
        return {"key": keys, "value": np.ones(n, np.float32)}, ts

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW_MS)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("tiers-job")
    got = {(int(r.key), int(r.window_end_ms)): float(r.value)
           for r in sink.results}
    return env, got


def expected(n_keys=N_KEYS, total=TOTAL):
    idx = np.arange(total)
    keys = idx % n_keys
    ts = (idx * 4 * WINDOW_MS) // total
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW_MS + 1) * WINDOW_MS
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


# --------------------------------- property: bit-exact vs all-resident

@pytest.mark.parametrize("kwargs", [
    dict(n_shards=1),
    dict(n_shards=1, layout="direct", n_keys=200, capacity=256),
    dict(n_shards=2),
    dict(n_shards=1, packed="on"),
], ids=["hash", "direct", "two-shard", "packed"])
def test_tiered_bit_exact_vs_all_resident(kwargs):
    """Budget 2 of 8 key-groups, dwell 1 (maximum churn): every result
    window matches the all-resident oracle job exactly, and the tier
    manager really swapped (demotes > 0, cold traffic existed)."""
    _, base = run_job(tiers=0, **kwargs)
    env, tiered = run_job(tiers=2, **kwargs)
    assert tiered == base
    rep = env._pipeline_report()["tiers"]
    assert rep["budget_per_shard"] == 2
    assert rep["demotes"] > 0 and rep["promotes"] > 0


def test_tiers_require_spillable_overflow():
    """The tier gate is a config error, never a silent downgrade: with
    the overflow ring forced off there is no cold route, so a budget
    refuses to start instead of silently keeping everything resident."""
    env = StreamExecutionEnvironment(Configuration({
        "state.tiers.resident-key-groups": 2,
        "state.backend.overflow-ring": 0,
    }))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(_gen, total=1024))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW_MS)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    with pytest.raises(ValueError, match="state.tiers"):
        env.execute("tiers-gate")


# ------------------------------------ exactly-once across tier faults

def test_demote_crash_before_checkpoint_restores_exactly_once(tmp_path):
    """Crash at ``tier.demote.write`` — after the swap plan committed
    to moving rows but before any later checkpoint covered it. The
    demoted entries lived only in process-local host memory; restore
    re-seeds both tiers from the last cut and replays — nothing
    skipped, nothing double-counted."""
    inj = FaultInjector([
        FaultRule("tier.demote.write",
                  exc=RuntimeError("injected demote crash"), at=1),
    ])
    with faults.active(inj):
        env, got = run_job(tiers=2, ckpt_dir=tmp_path / "chk",
                           restart=3)
    assert inj.fired_at("tier.demote.write"), "demote seam never fired"
    assert env.last_job.metrics.restarts == 1
    assert got == expected()


def test_promote_crash_restores_exactly_once(tmp_path):
    """Crash at ``tier.promote.read`` — a promote died mid-read of the
    pane stores. The stores are rebuilt from the checkpoint on restore
    (promote-during-restore is just the next maintenance cycle), so the
    replayed run converges to the oracle."""
    inj = FaultInjector([
        FaultRule("tier.promote.read",
                  exc=OSError("injected promote read failure"), at=3),
    ])
    with faults.active(inj):
        env, got = run_job(tiers=2, ckpt_dir=tmp_path / "chk",
                           restart=3)
    assert inj.fired_at("tier.promote.read"), "promote seam never fired"
    assert env.last_job.metrics.restarts >= 1
    assert got == expected()


def test_tier_chaos_soak_exactly_once(tmp_path):
    """Both tier seams fire repeatedly across the run (bounded by
    ``times`` so the restart budget survives); every crash lands at a
    different swap. The final window set is still the oracle's."""
    inj = FaultInjector([
        FaultRule("tier.demote.write",
                  exc=RuntimeError("chaos demote"), at=4),
        FaultRule("tier.promote.read",
                  exc=OSError("chaos promote"), at=5),
    ], seed=18)
    with faults.active(inj):
        env, got = run_job(tiers=2, n_shards=2,
                           ckpt_dir=tmp_path / "chk", restart=6)
    fired = {f["point"] for f in inj.fired}
    assert fired == {"tier.demote.write", "tier.promote.read"}
    assert env.last_job.metrics.restarts >= 2
    assert got == expected()


# ------------------------------------------- TierManager planner units

def _mgr(**kw):
    return tiers_mod.TierManager(
        8, np.asarray([0]), np.asarray([7]), kw.pop("budget", 2), **kw)


def test_manager_rejects_zero_budget():
    with pytest.raises(ValueError):
        _mgr(budget=0)


def test_urgent_promote_beats_dwell_and_counts_hits():
    """A cold group with a pane due inside the watermark horizon is
    promoted even though the incumbents' dwell has not expired; a
    promoted group that sees traffic before its next demotion counts a
    prefetch hit, one that never does counts a miss."""
    tm = _mgr(budget=2, min_dwell_cycles=100, prefetch_ahead_panes=2)
    heat = np.asarray([9.0, 8.0, 0.1, 0.0, 0, 0, 0, 0])
    last = np.asarray([0, 0, 0, -1, -1, -1, -1, -1])
    # groups 0..1 resident (default: first-budget); group 2 is cold
    # with a pane closing inside the prefetch horizon, and its dwell
    # clock says "just flipped" — only the urgency exemption can
    # promote it
    tm.note_cold([2], [5])
    tm._last_flip[2] = 0
    plan = tm.plan(heat, last, seq=1, wm_pane=4)
    assert 2 in set(plan.promote)
    assert len(plan.demote) == len(plan.promote)
    tm.apply(plan)
    assert tm.mask()[2]
    # traffic lands on the promoted group -> prefetch hit
    kg_sum = np.zeros(8, np.int64)
    kg_sum[2] = 10
    tm.note_sample(kg_sum)
    assert tm.report()["prefetch_hits"] == 1
    # a cold (non-resident) group absorbing traffic is a tier fault
    victim = plan.demote[0]
    kg_sum2 = np.zeros(8, np.int64)
    kg_sum2[victim] = 3
    tm.note_sample(kg_sum2)
    assert tm.report()["faults"] == 1


def test_rescale_reslices_residency_and_keeps_counters():
    tm = _mgr(budget=2)
    before = tm.report()
    assert before["resident_groups"] == 2
    tm.note_cold([5], [1])
    tm.rescale(np.asarray([0, 4]), np.asarray([3, 7]))
    rep = tm.report()
    # 2 shards x budget 2 = 4 resident groups after the re-slice
    assert rep["resident_groups"] == 4
    assert rep["cold_groups_pending"] == 1   # pending survives rescale
    assert tm.shard_of(5) == 1



def test_max_swaps_cap_carries_residue_forward():
    """``state.tiers.max-swaps-per-cycle`` bounds one plan's moves; the
    truncated residue is re-derived and finished next cycle (ISSUE 19:
    the controller leans on this to keep swap bursts off the poll
    seam). Budget 1, one swap/cycle: cycle 1 spends its swap demoting
    the stale incumbent, cycle 2 promotes the hot group."""
    tm = _mgr(budget=1, min_dwell_cycles=0, max_swaps_per_cycle=1)
    heat = np.zeros(8)
    heat[5] = 100.0
    last = np.full(8, -1, np.int64)
    last[5] = 0
    p1 = tm.plan(heat, last, seq=1)
    assert (p1.demote, p1.promote) == ([0], [])
    tm.apply(p1)
    p2 = tm.plan(heat, last, seq=2)
    assert (p2.demote, p2.promote) == ([], [5])
    tm.apply(p2)
    assert tm.mask()[5] and not tm.mask()[0]
    # unlimited (the default 0): the same shift lands in one plan
    tm2 = _mgr(budget=1, min_dwell_cycles=0)
    p = tm2.plan(heat, last, seq=1)
    assert (p.demote, p.promote) == ([0], [5])


def test_rescale_accepts_unequal_ranges():
    """The live heat-balanced re-slice (ISSUE 19) hands TierManager
    deliberately unequal shard ranges — residency must seed from each
    range's own head and the pending prefetch predictions must reset
    (they were ranked under the old ownership)."""
    tm = tiers_mod.TierManager(
        8, np.asarray([0, 4]), np.asarray([3, 7]), budget=2)
    assert sorted(np.nonzero(tm.mask())[0]) == [0, 1, 4, 5]
    tm._prefetched.add(3)
    tm.rescale(np.asarray([0, 6]), np.asarray([5, 7]))
    assert sorted(np.nonzero(tm.mask())[0]) == [0, 1, 6, 7]
    assert not tm._prefetched
    assert tm.shard_of(5) == 0 and tm.shard_of(6) == 1
