"""Physical broadcast (round 4, VERDICT item 7):

* broadcast STATE pattern — keyed main stream + broadcast control stream
  through KeyedBroadcastProcessFunction, state updates visible to keyed
  processing, checkpointed and restored (ref KeyedBroadcastProcessFunction
  / BroadcastPartitioner.java:30),
* device broadcast JOIN — build side replicated to all 8 shards via the
  mesh sharding declaration, every shard probing its own record slice
  against the FULL table (ref BROADCAST_HASH_FIRST/SECOND join hints).
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.datastream.functions import KeyedBroadcastProcessFunction
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.state.descriptors import MapStateDescriptor


class Enrich(KeyedBroadcastProcessFunction):
    """Control stream carries (word, factor) rules; main stream emits
    value * factor[word] for known words."""

    def process_element(self, value, ctx, out):
        rules = ctx.broadcast_state("rules")
        word, v = value
        if word in rules:
            out.collect((word, v * rules[word]))

    def process_broadcast_element(self, value, ctx, out):
        word, factor = value
        ctx.broadcast_state("rules")[word] = factor


def test_broadcast_state_pattern():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    sink = CollectSink()
    rules = env.from_collection([("a", 10.0), ("b", 100.0)])
    main = env.from_collection(
        [("a", 1.0), ("b", 2.0), ("c", 3.0), ("a", 4.0)]
    ).key_by(lambda e: e[0])
    desc = MapStateDescriptor("rules", str, float)
    main.connect(rules.broadcast(desc)).process(Enrich()).add_sink(sink)
    env.execute("broadcast-enrich")
    # cross-stream arrival order is round-robin (not deterministic wrt
    # rules-vs-records), so assert the order-independent guarantees:
    # every emission used the exact broadcast rule for its word, and the
    # LAST main element — which provably arrives after the (shorter)
    # rules stream drained — was enriched
    assert ("a", 40.0) in sink.results
    assert set(sink.results) <= {("a", 10.0), ("a", 40.0), ("b", 200.0)}


def test_broadcast_state_is_readonly_on_keyed_side():
    class Bad(KeyedBroadcastProcessFunction):
        def process_element(self, value, ctx, out):
            ctx.broadcast_state("rules")["x"] = 1.0   # must raise

        def process_broadcast_element(self, value, ctx, out):
            pass

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 2
    env.set_parallelism(1)
    rules = env.from_collection([("a", 1.0)])
    main = env.from_collection([("a", 1.0)]).key_by(lambda e: e[0])
    desc = MapStateDescriptor("rules", str, float)
    main.connect(rules.broadcast(desc)).process(Bad()).add_sink(CollectSink())
    with pytest.raises(TypeError):
        env.execute("broadcast-readonly")


def test_broadcast_state_checkpoints(tmp_path):
    """Broadcast state rides the operator-state store: snapshot a job
    mid-stream, restore into a fresh run, rules survive."""
    from flink_tpu.runtime.checkpoint import CheckpointStorage

    def build(restore_from=None, rules_ev=(), main_ev=()):
        env = StreamExecutionEnvironment.get_execution_environment()
        env.batch_size = 2
        env.set_parallelism(1)
        env.enable_checkpointing(interval_steps=1, directory=str(tmp_path))
        sink = CollectSink()
        rules = env.from_collection(list(rules_ev))
        main = env.from_collection(list(main_ev)).key_by(lambda e: e[0])
        desc = MapStateDescriptor("rules", str, float)
        main.connect(rules.broadcast(desc)).process(Enrich()).add_sink(sink)
        env.execute("broadcast-ckpt", restore_from=restore_from)
        return sink

    # first run inserts the rules, checkpoints at end of stream
    base_main = [("a", 1.0), ("b", 1.0)]
    build(rules_ev=[("a", 5.0), ("b", 7.0)], main_ev=base_main)
    assert CheckpointStorage(str(tmp_path)).latest() is not None
    # restored run: NO rule events at all, main stream extended past the
    # checkpointed offset — the new elements' enrichment can only come
    # from the RESTORED broadcast state
    sink = build(
        restore_from=str(tmp_path), rules_ev=[],
        main_ev=base_main + [("a", 2.0), ("b", 3.0)],
    )
    assert ("a", 10.0) in sink.results and ("b", 21.0) in sink.results


def test_device_broadcast_join_8_shards():
    import jax

    from flink_tpu.parallel.broadcast import broadcast_join
    from flink_tpu.parallel.mesh import MeshContext

    assert len(jax.devices()) == 8
    ctx = MeshContext.create(8)
    rng = np.random.default_rng(3)
    # build side: 200 dimension rows; stream: 10k records over 300 keys
    tkeys = np.arange(0, 400, 2, dtype=np.int64)         # even keys only
    tvals = (tkeys * 0.5).astype(np.float32)
    keys = rng.integers(0, 300, 10_000).astype(np.int64)
    joined, hit = broadcast_join(keys, tkeys, tvals, ctx)
    # every lane — regardless of which shard probed it — joined against
    # the FULL table: evens matched with key*0.5, odds unmatched
    want_hit = (keys % 2 == 0) & (keys < 400)
    assert np.array_equal(hit, want_hit)
    assert np.allclose(joined[want_hit], keys[want_hit] * 0.5)
    assert np.all(joined[~want_hit] == 0)
