"""Chained keyed stages (ISSUE 16, runtime/stages.py StageGraph +
runtime/step.py chained resident drain):

* 2-stage keyBy -> window -> keyBy -> window pipeline bit-exact against
  a host-chained oracle (stage-1 fires re-windowed at
  ``window_end_ms - 1``), single-shard and sharded,
* exactly-once across a MID-DRAIN crash (the ``step.drain`` fault seam)
  with prefetch + incremental checkpoints — both stages' window states
  ride the cut and the un-retired group replays without loss or double
  count,
* checkpoint cut portability: a fresh process restores a chained cut
  (aux ``chain_stages`` payload) and finishes the stream,
* setup-time StageGraph validation: unsupported shapes fail LOUDLY at
  plan time naming the stage or edge — never a silent wrong answer.
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.runtime.stages import StageGraphError
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule

N_KEYS = 64
W1 = 10_000
W2 = 20_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    """Host-chained oracle: stage-1 tumbling sums, re-keyed into
    stage-2 windows at ts = window_end - 1 (the device edge's
    timestamp assignment)."""
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    s1 = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // W1 + 1) * W1
        s1[(k, we)] = s1.get((k, we), 0.0) + 1.0
    s2 = {}
    for (k, we1), v in s1.items():
        t2 = we1 - 1
        we2 = (t2 // W2 + 1) * W2
        s2[(k, we2)] = s2.get((k, we2), 0.0) + v
    return s2


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None, **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    # 64 keys: 256 slots exercise the same hash/evict paths while both
    # stages' [ring, C, ...] planes stay cheap to compile on 1-core CI
    env.set_state_capacity(256)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(W1)
        .sum(lambda c: c["value"])
        .key_by(lambda r: r.key)
        .time_window(W2)
        .sum(lambda r: r.value)
        .add_sink(sink)
    )
    env.execute("chained-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


RESIDENT_CFG = {
    "pipeline.prefetch": "on",
    "pipeline.device-staging": "on",
    "pipeline.resident-loop": "on",
    "pipeline.ring-depth": 4,
}


# ----------------------------------------------------- steady state

def test_two_stage_chain_bit_exact():
    """THE round-16 criterion: a 2-stage keyed pipeline through the
    chained resident drain equals the host-chained oracle bit-exactly,
    and every step retired through drain dispatches."""
    total = 4096
    env = build_env(1, **RESIDENT_CFG)
    got = run_job(env, total)
    assert got == expected(total)
    m = env.last_job.metrics
    assert m.resident_drains > 0


def test_two_stage_chain_bit_exact_sharded():
    """Same criterion over the sharded (data-parallel) chained drain:
    2 shards, each owning a key-group slice of BOTH stages."""
    total = 4096
    env = build_env(2, **RESIDENT_CFG)
    got = run_job(env, total)
    assert got == expected(total)
    assert env.last_job.metrics.resident_drains > 0


def test_two_stage_chain_default_config():
    """Chained jobs light up the resident drain under pure defaults —
    no silent fallback path exists, so auto must resolve on."""
    total = 2048
    env = build_env(1)
    got = run_job(env, total)
    assert got == expected(total)
    assert env.last_job.metrics.resident_drains > 0


# ------------------------------------------ mid-drain crash, exactly-once

def test_chained_mid_drain_crash_restore_exactly_once(tmp_path):
    """Crash at a drain dispatch with BOTH stages holding window state,
    under prefetch + incremental checkpoints; restore replays the
    un-retired group from the cut — the chained payload
    (aux ``chain_stages``) restores positionally, so neither stage
    loses or double-counts."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{**RESIDENT_CFG,
           "checkpoint.mode": "incremental", "checkpoint.async": True},
    )
    # chained jobs hit the drain seam several times per batch (the
    # flush rounds), so index the crash mid-stream: after the first
    # cut is durable, well before the source drains
    inj = FaultInjector([
        FaultRule("step.drain",
                  exc=RuntimeError("injected mid-drain crash"), at=40),
    ])
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert inj.fired_at("step.drain"), "drain seam never fired"
    assert m.restarts == 1
    assert m.resident_drains > 0
    assert got == expected(total)


def test_chained_checkpoint_cut_across_processes(tmp_path):
    """Chained cut portability: phase 1 checkpoints and stops
    mid-stream; a FRESH env restores the latest cut (both stages'
    states from the aux payload) and finishes. Merged output equals
    the single-run truth."""
    total, half = 8192, 4096
    env1 = build_env(1, tmp_path / "chk", interval=1, **RESIDENT_CFG)
    got1 = run_job(env1, half)
    env2 = build_env(1, **RESIDENT_CFG)
    got2 = run_job(env2, total, restore_from=str(tmp_path / "chk"))
    assert {**got1, **got2} == expected(total)


def test_chained_checkpoint_rejected_by_single_stage_job(tmp_path):
    """A chained checkpoint carries stage state a single-stage job
    cannot hold — restoring it must fail loudly, not drop stage 2."""
    env1 = build_env(1, tmp_path / "chk", interval=1, **RESIDENT_CFG)
    run_job(env1, 4096)
    env2 = build_env(1, **RESIDENT_CFG)
    sink = CollectSink()
    (
        env2.add_source(GeneratorSource(gen, total=4096))
        .key_by(lambda c: c["key"])
        .time_window(W1)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    with pytest.raises(ValueError, match="chained stage state"):
        env2.execute("single", restore_from=str(tmp_path / "chk"))


# --------------------------------------------- setup-time validation

def _chain(env, sink, key_sel=None, extractor=None):
    return (
        env.add_source(GeneratorSource(gen, total=512))
        .key_by(lambda c: c["key"])
        .time_window(W1)
        .sum(lambda c: c["value"])
        .key_by(key_sel or (lambda r: r.key))
        .time_window(W2)
        .sum(extractor or (lambda r: r.value))
        .add_sink(sink)
    )


def test_chain_key_selector_must_preserve_key():
    """The device edge re-keys fires by identity: a selector that keys
    stage 2 by anything else fails at plan time naming the edge."""
    env = build_env(1, **RESIDENT_CFG)
    _chain(env, CollectSink(), key_sel=lambda r: r.value)
    with pytest.raises(StageGraphError,
                       match="does not preserve the upstream key"):
        env.execute("bad-key")


def test_chain_value_extractor_must_forward():
    """The edge carries the fire value verbatim: an extractor reading
    any other slot fails at plan time naming the edge."""
    env = build_env(1, **RESIDENT_CFG)
    _chain(env, CollectSink(), extractor=lambda r: r.key)
    with pytest.raises(StageGraphError,
                       match="value extractor does not pass"):
        env.execute("bad-extract")


def test_chain_depth_capped_by_config():
    """pipeline.stages.max-stages bounds the accepted chain depth —
    deeper chains fail at plan time, before any compile."""
    env = build_env(1, **{**RESIDENT_CFG,
                          "pipeline.stages.max-stages": 2})
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=512))
        .key_by(lambda c: c["key"])
        .time_window(W1)
        .sum(lambda c: c["value"])
        .key_by(lambda r: r.key)
        .time_window(W2)
        .sum(lambda r: r.value)
        .key_by(lambda r: r.key)
        .time_window(2 * W2)
        .sum(lambda r: r.value)
        .add_sink(sink)
    )
    with pytest.raises(StageGraphError, match="max-stages"):
        env.execute("too-deep")


def test_chain_requires_staging_substrate():
    """Without prefetch/staging there is no resident drain, and a
    chained graph has no single-step fallback — loud config error."""
    env = build_env(1, **{"pipeline.prefetch": "off"})
    _chain(env, CollectSink())
    with pytest.raises(StageGraphError, match="resident"):
        env.execute("no-substrate")


def test_chain_rejects_all_to_all_exchange():
    """The chained drain routes ONLY through the mask exchange; the
    all_to_all plan has no inter-stage seam."""
    env = build_env(2, **{**RESIDENT_CFG, "exchange.mode": "all_to_all"})
    _chain(env, CollectSink())
    with pytest.raises(StageGraphError, match="all_to_all"):
        env.execute("bad-exchange")


def test_chain_rejects_trailing_keyed_stage_without_window():
    """A keyBy after a windowed stage must itself end in a window
    aggregation — rolling reduces cannot chain on the device edge."""
    env = build_env(1, **RESIDENT_CFG)
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=512))
        .key_by(lambda c: c["key"])
        .time_window(W1)
        .sum(lambda c: c["value"])
        .key_by(lambda r: r.key)
        .sum(lambda r: r.value)
        .add_sink(sink)
    )
    with pytest.raises(StageGraphError, match="window aggregation"):
        env.execute("rolling-tail")


# --------------------------- stage-aware flight recorder (ISSUE 17)

def test_chained_drain_stats_stage_telemetry_end_to_end():
    """ISSUE 17 acceptance: a 2-stage chained job with drain-stats on
    stays bit-exact vs the host-chained oracle AND surfaces per-stage
    edge-lane utilization, coupled-watermark lag, and kg-heat top-k at
    /jobs/<jid>/pipeline; /jobs/<jid>/doctor serves the ranked-
    findings engine over the same planes; the per-stage Perfetto
    counter tracks and ``drain_stage1_*`` / ``kg_heat_*`` Prometheus
    gauges ride beside the round-14 families."""
    import json as _json
    import urllib.request

    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    def get_json(port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return _json.loads(r.read())

    total = 4096
    env = build_env(2, **{
        **RESIDENT_CFG,
        "observability.tracing": True,
        "observability.drain-stats-every": 1,
    })
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(W1)
        .sum(lambda c: c["value"])
        .key_by(lambda r: r.key)
        .time_window(W2)
        .sum(lambda r: r.value)
        .add_sink(sink)
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    try:
        jid = cluster.submit(env, "chained-obs-job")
        assert cluster.wait(jid, 240) == "FINISHED"
        got = {(r.key, r.window_end_ms): r.value for r in sink.results}
        assert got == expected(total)

        # -- /pipeline: the stage-aware block next to the round-14 view
        rep = get_json(port, f"/jobs/{jid}/pipeline")
        assert rep["available"] is True
        assert rep["drains"] > 0 and rep["payload_fetches"] > 0
        (st,) = rep["stages"]
        assert st["stage"] == 1
        assert st["totals"]["edge_demand"] > 0
        assert st["totals"]["edge_events"] > 0
        assert st["totals"]["dropped_capacity"] == 0
        assert st["totals"]["fire_lanes"] > 0
        assert st["totals"]["panes_advanced"] > 0
        assert st["edge_lane_budget"] > 0
        assert 0.0 < st["edge_utilization"] <= 1.0
        assert st["levels"]["wm_lag_panes"] >= 0
        assert rep["stage_fields"][0] == "edge_demand"
        # kg heat rides the same report (kg-stats defaults to tracing)
        kg = rep["kg_heat"]
        assert kg["available"] and kg["samples"] > 0
        assert kg["top"][0]["heat"] > 0
        assert kg["skew_ratio"] >= 1.0
        assert 0.0 <= kg["cold_tail"]["fraction"] <= 1.0

        # -- /doctor: the rule engine joins the same planes; this
        # healthy run must NOT fire the edge/skew/compile rules, and
        # the payload embeds its snapshot for CLI replay
        doc = get_json(port, f"/jobs/{jid}/doctor")
        assert doc["available"] is True and doc["version"] == 1
        assert set(doc["rules"]) >= {
            "ring-starved", "edge-lane-overflow", "kg-heat-skew",
            "recompile-storm",
        }
        fired = {f["rule"] for f in doc["findings"]}
        assert "edge-lane-overflow" not in fired
        assert "recompile-storm" not in fired
        snap = doc["snapshot"]
        assert snap["pipeline"]["stages"][0]["totals"]["edge_demand"] \
            == st["totals"]["edge_demand"]
        assert "thresholds" in doc and doc["thresholds"]["kg_skew"] > 0

        # -- Prometheus: per-stage + kg-heat gauge families
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        for f in ("edge_events", "fire_lanes", "dropped_capacity",
                  "wm_lag_panes"):
            assert (f'flink_tpu_drain_stage1_{f}'
                    f'{{job="chained-obs-job"}}') in text
        assert 'flink_tpu_kg_heat_max{job="chained-obs-job"}' in text
        assert ('flink_tpu_kg_heat_skew_ratio{job="chained-obs-job"}'
                in text)

        # -- Perfetto: the drain_stage counter track beside the spans
        tr = get_json(port, f"/jobs/{jid}/traces")
        counters = [ev for ev in tr["traceEvents"] if ev["ph"] == "C"]
        st_ev = next(ev for ev in counters
                     if ev["name"] == "drain_stage1")
        assert set(st_ev["args"]) == {
            "edge_lanes", "fire_lanes", "wm_lag_panes",
        }
    finally:
        web.stop()


def test_chained_drain_stats_off_report_unavailable():
    """Default config (no tracing): the chained kernels compile without
    the stage payload and /pipeline stays unavailable — the OFF arity
    contract the frozen op-budget golden pins at the kernel level."""
    env = build_env(1, **RESIDENT_CFG)
    got = run_job(env, 2048)
    assert got == expected(2048)
    rep = env._pipeline_report()
    assert rep["available"] is False and "reason" in rep
