"""Collection-time capability probe for the DCN multi-process tests.

The two-host DCN tests spawn worker PROCESSES that form one global JAX
mesh and route records with cross-process collectives. Some containers'
CPU backend cannot run those at all — every multi-device computation
dies with ``XlaRuntimeError: INVALID_ARGUMENT: Multiprocess computations
aren't implemented on the CPU backend`` during state init, so the whole
ensemble fails identically on every commit. Failing 12 tests forever is
worse than useless: real regressions hide behind "same 12 failures as
the parent". This probe detects the limitation ONCE per session (two
tiny subprocesses doing exactly the operation the runners die on) and
the test modules ``pytest.skip`` with an explicit reason instead.

Overrides: set ``FLINK_TPU_ASSUME_MULTIPROC=1`` to skip the probe and
assume support (e.g. on a backend known-good), ``=0`` to assume the
limitation without paying the probe.
"""

import os
import socket
import subprocess
import sys

_PROBE_CODE = """
import jax
jax.distributed.initialize("{coord}", 2, {pid})
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

mesh = Mesh(np.array(jax.devices()), ("x",))
local = np.zeros(len(jax.local_devices()), np.float32)
arr = jax.make_array_from_process_local_data(
    NamedSharding(mesh, P("x")), local
)
out = jax.jit(lambda a: a + 1)(arr)
jax.block_until_ready(out)
print("MULTIPROC_OK")
"""

_cache = None


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def multiprocess_collectives_supported(timeout_s: float = 90.0) -> bool:
    """True iff this backend can run a 2-process global-mesh computation
    (the minimal operation every DCN runner performs at state init)."""
    global _cache
    if _cache is not None:
        return _cache
    override = os.environ.get("FLINK_TPU_ASSUME_MULTIPROC")
    if override is not None:
        _cache = override.strip() not in ("0", "false", "no")
        return _cache
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        ).strip() + " --xla_force_host_platform_device_count=2"
    ).strip()
    coord = f"127.0.0.1:{_free_port()}"
    procs = [
        subprocess.Popen(
            [sys.executable, "-c",
             _PROBE_CODE.format(coord=coord, pid=p)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for p in range(2)
    ]
    ok = True
    for p in procs:
        try:
            out, _ = p.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            # a hung distributed init cannot run the ensemble tests
            # either — treat as unsupported, loudly
            for q in procs:
                q.kill()
            ok = False
            break
        if p.returncode != 0 or b"MULTIPROC_OK" not in out:
            ok = False
    _cache = ok
    return ok


SKIP_REASON = (
    "this container's CPU backend lacks multi-process collectives "
    "(XLA: \"Multiprocess computations aren't implemented on the CPU "
    "backend\") — the two-process DCN ensemble cannot initialize its "
    "global mesh; pre-existing environment limitation, not a regression "
    "(set FLINK_TPU_ASSUME_MULTIPROC=1 to force-run)"
)
