"""The versioned shared buffer under the CEP NFA (ref flink-cep
SharedBuffer.java:76 page/entry/edge structure, DeweyNumber.java version
gating, SharedBuffer.extractPatterns multi-path extraction).

The buffer is the match store in production position: these tests pin
the properties the reference's structure exists for — prefix sharing,
stale-run invisibility, converged-run dedup — plus an independent
brute-force oracle over randomized streams."""

import pickle
import types

import numpy as np
import pytest

from flink_tpu.cep.nfa import NFA, Partial
from flink_tpu.cep.pattern import Pattern, RELAXED, STRICT


class E:
    def __init__(self, tag, ts):
        self.tag, self.ts = tag, ts

    def __repr__(self):
        return f"E({self.tag}@{self.ts})"


def run(nfa, events):
    partials, out = nfa.initial_state(), []
    for e in events:
        partials, ms = nfa.process(partials, e, e.ts)
        out.extend(ms)
    return partials, out


def tags(match):
    return tuple(ev.tag for ev in match.values())


# ---------------------------------------------------------------- sharing
def test_shared_event_is_one_entry():
    """Two runs taking the same 'b' event share ONE buffer node with one
    back edge per run (the per-(state, event) page of SharedBuffer)."""
    p = (Pattern.begin("a").where(lambda e: e.tag.startswith("a"))
         .followed_by("b").where(lambda e: e.tag == "b")
         .followed_by("c").where(lambda e: e.tag == "c"))
    nfa = NFA(p)
    partials, _ = run(nfa, [E("a1", 0), E("a2", 1), E("b", 2)])
    at_b = [q for q in partials if q.stage_idx == 1]
    assert len(at_b) == 2
    assert at_b[0].ptr is at_b[1].ptr          # one shared Entry object
    assert len(at_b[0].ptr.edges) == 2         # one edge per run
    assert at_b[0].version != at_b[1].version  # distinct run stamps


def test_pickle_preserves_sharing():
    """Checkpointing a key's partials keeps the prefix compression:
    pickle memoizes the shared Entry, so the snapshot stores it once."""
    p = (Pattern.begin("a").where(lambda e: e.tag.startswith("a"))
         .followed_by("b").where(lambda e: e.tag == "b")
         .followed_by("c").where(lambda e: e.tag == "c"))
    partials, _ = run(NFA(p), [E("a1", 0), E("a2", 1), E("b", 2)])
    restored = pickle.loads(pickle.dumps(partials))
    at_b = [q for q in restored if q.stage_idx == 1]
    assert at_b[0].ptr is at_b[1].ptr


def test_entry_count_is_events_not_paths():
    """N runs through one (b, c) suffix store N+2 entries, not 3N event
    slots — the memory claim of the shared design."""
    p = (Pattern.begin("a").where(lambda e: e.tag.startswith("a"))
         .followed_by("b").where(lambda e: e.tag == "b")
         .followed_by("c").where(lambda e: e.tag == "c")
         .followed_by("d").where(lambda e: e.tag == "d"))
    n = 16
    events = [E(f"a{i}", i) for i in range(n)] + [E("b", 50), E("c", 51)]
    partials, _ = run(NFA(p), events)
    seen, stack = set(), [q.ptr for q in partials]
    while stack:
        ent = stack.pop()
        if id(ent) in seen:
            continue
        seen.add(id(ent))
        stack.extend(pr for pr, _v in ent.edges if pr is not None)
    assert len(seen) == n + 2


# ---------------------------------------------------------------- versions
def test_expired_run_edges_invisible_to_live_run():
    """THE version-gating case (DeweyNumber's job): an expired run and a
    live run share the 'b' entry; extraction at 'c' must see only the
    live run's back edge, or the expired (a1, b, c) would wrongly emit."""
    p = (Pattern.begin("a").where(lambda e: e.tag.startswith("a"))
         .followed_by("b").where(lambda e: e.tag == "b")
         .followed_by("c").where(lambda e: e.tag == "c")
         .within(10))
    nfa = NFA(p)
    #  a1@0  a2@6  b@7  c@12: run(a1) is expired at c (12-0 > 10) but its
    #  edge into the shared b entry still exists
    partials, _ = run(nfa, [E("a1", 0), E("a2", 6), E("b", 7)])
    at_b = [q for q in partials if q.stage_idx == 1]
    assert len({id(q.ptr) for q in at_b}) == 1     # genuinely shared
    partials, out = nfa.process(partials, E("c", 12), 12)
    assert [tags(m) for m in out] == [("a2", "b", "c")]


def test_dead_run_number_reuse_is_safe():
    """A new run may reuse a dead run's version number; its chain can
    never reach the dead run's entries, so extraction stays exact."""
    p = (Pattern.begin("a").where(lambda e: e.tag.startswith("a"))
         .next("b").where(lambda e: e.tag == "b"))
    nfa = NFA(p)
    partials, out = run(nfa, [
        E("a1", 0), E("x", 1),      # strict miss kills run 0
        E("a2", 2), E("b", 3),      # new run also numbered 0 completes
    ])
    assert [tags(m) for m in out] == [("a2", "b")]
    assert partials == []


# ---------------------------------------------------------------- dedup
def test_converged_runs_dedupe_then_extract_all_paths():
    """Two branches of one run converge on the same (stage, entry,
    version): ONE computation state remains, and the single completion
    extracts BOTH paths exactly once (SharedBuffer.extractPatterns)."""
    p = (Pattern.begin("a").where(lambda e: e.tag == "a")
         .followed_by("b").where(lambda e: e.tag.startswith("b"))
         .followed_by("c").where(lambda e: e.tag == "c")
         .followed_by("d").where(lambda e: e.tag == "d"))
    nfa = NFA(p)
    partials, out = run(nfa, [
        E("a", 0), E("b1", 1), E("b2", 2), E("c", 3),
    ])
    at_c = [q for q in partials if q.stage_idx == 2]
    assert len(at_c) == 1                          # converged + deduped
    assert len(at_c[0].ptr.edges) == 2             # both paths retained
    partials, out = nfa.process(partials, E("d", 4), 4)
    assert sorted(tags(m) for m in out) == [
        ("a", "b1", "c", "d"), ("a", "b2", "c", "d"),
    ]


def test_sibling_completions_do_not_cross_emit():
    """Two runs completing on the same final event each walk only their
    own just-laid edge — no duplicate or crossed extraction."""
    p = (Pattern.begin("a").where(lambda e: e.tag.startswith("a"))
         .followed_by("b").where(lambda e: e.tag == "b"))
    nfa = NFA(p)
    _, out = run(nfa, [E("a1", 0), E("a2", 1), E("b", 2)])
    assert sorted(tags(m) for m in out) == [("a1", "b"), ("a2", "b")]


# ---------------------------------------------------------------- legacy
def test_legacy_event_tuple_partials_upgrade():
    """Pre-shared-buffer checkpoints stored full event tuples; they
    continue as unshared chains after restore."""
    p = (Pattern.begin("a").where(lambda e: e.tag == "a")
         .followed_by("b").where(lambda e: e.tag == "b"))
    nfa = NFA(p)
    a = E("a", 0)
    legacy = types.SimpleNamespace(stage_idx=0, events=(a,), start_ts=0)
    partials, out = nfa.process([legacy], E("b", 1), 1)
    assert [tags(m) for m in out] == [("a", "b")]


# ---------------------------------------------------------------- oracle
def _oracle(pattern, events):
    """Independent brute force: every index sequence satisfying the
    stage predicates, contiguity (strict = adjacent), and within bound."""
    stages = pattern.stages
    out = []

    def extend(seq, last_idx):
        k = len(seq)
        if k == len(stages):
            out.append(tuple(events[i] for i in seq))
            return
        start = last_idx + 1
        end = last_idx + 2 if (k and stages[k].contiguity == STRICT) \
            else len(events)
        for i in range(start, min(end, len(events))):
            if not stages[k].matches(events[i]):
                continue
            if k and pattern.within_ms is not None and \
                    events[i].ts - events[seq[0]].ts > pattern.within_ms:
                continue
            extend(seq + [i], i)

    extend([], -1)
    return sorted(tuple(e.tag for e in seq) for seq in out)


@pytest.mark.parametrize("seed", range(8))
def test_randomized_equivalence_vs_brute_force(seed):
    rng = np.random.default_rng(seed)
    pats = {
        "fb": (Pattern.begin("s0").where(lambda e: e.tag == "a")
               .followed_by("s1").where(lambda e: e.tag == "b")
               .followed_by("s2").where(lambda e: e.tag == "c")),
        "strict": (Pattern.begin("s0").where(lambda e: e.tag == "a")
                   .next("s1").where(lambda e: e.tag == "b")
                   .followed_by("s2").where(lambda e: e.tag == "c")),
        "within": (Pattern.begin("s0").where(lambda e: e.tag == "a")
                   .followed_by("s1").where(lambda e: e.tag == "b")
                   .followed_by("s2").where(lambda e: e.tag == "c")
                   .within(6)),
    }
    pat = pats[["fb", "strict", "within"][seed % 3]]
    n = int(rng.integers(10, 26))
    events = [
        E(str(rng.choice(["a", "b", "c", "x"])), int(t))
        for t in np.sort(rng.integers(0, 20, n))
    ]
    _, got = run(NFA(pat), events)
    assert sorted(tags(m) for m in got) == _oracle(pat, events)
