"""Session window semantics vs a scalar merging model (the analog of the
reference's session cases in WindowOperatorTest + MergingWindowSet tests)."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.window.assigners import EventTimeSessionWindows
from flink_tpu.runtime.sinks import CollectSink


def scalar_sessions(events, gap):
    """events: (key, ts, v) in arrival order (assumed ts-ordered per test).
    Returns {(key, start, end): sum} with full merging."""
    sessions = {}  # key -> list of [start, last, sum]
    for k, ts, v in events:
        lst = sessions.setdefault(k, [])
        merged = None
        for s in lst:
            if ts <= s[1] + gap and ts + gap >= s[0]:
                s[0] = min(s[0], ts)
                s[1] = max(s[1], ts)
                s[2] += v
                merged = s
                break
        if merged is None:
            lst.append([ts, ts, v])
        else:
            # cascading merges
            changed = True
            while changed:
                changed = False
                for a in lst:
                    for b in lst:
                        if a is not b and a[0] <= b[1] + gap and b[0] <= a[1] + gap:
                            a[0], a[1], a[2] = (
                                min(a[0], b[0]), max(a[1], b[1]), a[2] + b[2]
                            )
                            lst.remove(b)
                            changed = True
                            break
                    if changed:
                        break
    return {
        (k, s[0], s[1] + gap): s[2]
        for k, lst in sessions.items()
        for s in lst
    }


def run(events, gap, batch=16, parallelism=4, oob=0):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(512)
    env.batch_size = batch
    sink = CollectSink()
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    strat = (WatermarkStrategy.for_bounded_out_of_orderness(oob) if oob
             else None)
    ds = env.from_collection(events).assign_timestamps_and_watermarks(
        lambda e: e[1], strat
    )
    (
        ds.key_by(lambda e: e[0])
        .window(EventTimeSessionWindows.with_gap(gap))
        .sum(lambda e: e[2])
        .add_sink(sink)
    )
    env.execute("sessions")
    return {
        (r.key, r.window_start_ms, r.window_end_ms): r.value
        for r in sink.results
    }, env.last_job


def test_basic_sessions_in_order():
    gap = 100
    events = [
        ("a", 0, 1.0), ("a", 50, 2.0),      # session a:[0,150)
        ("b", 20, 5.0),                     # session b:[20,120)
        ("a", 300, 3.0), ("a", 350, 4.0),   # session a:[300,450)
        ("b", 500, 1.0),                    # session b:[500,600)
    ]
    got, job = run(events, gap)
    expect = scalar_sessions(events, gap)
    assert got == expect
    assert job.metrics.dropped_late == 0


def test_sessions_random_stream(rng):
    gap = 50
    t = 0
    events = []
    for _ in range(400):
        t += int(rng.integers(0, 40))  # sometimes > gap -> new sessions
        k = int(rng.integers(0, 6))
        events.append((k, t, 1.0))
    got, job = run(events, gap, batch=32, parallelism=8)
    expect = scalar_sessions(events, gap)
    assert got == expect


def test_session_merge_within_batch_and_across_batches():
    gap = 100
    # one key, events split across batches so the open session carries over
    events = [("k", t, 1.0) for t in range(0, 1000, 60)]  # all one session
    got, job = run(events, gap, batch=4)
    assert got == {("k", 0, 960 + gap): float(len(events))}


def test_session_out_of_order_within_gap(rng):
    gap = 200
    base = [("k", t, 1.0) for t in range(0, 2000, 50)]
    # shuffle lightly within a 100ms horizon (< gap), watermark bound 100
    events = []
    for i, e in enumerate(base):
        events.append(e)
    events[3], events[4] = events[4], events[3]
    events[10], events[12] = events[12], events[10]
    got, job = run(events, gap, batch=8, oob=100)
    expect = scalar_sessions(events, gap)
    assert got == expect


# ------------------------------------------------ checkpoint/restore (r4)
def _session_events(n_keys=6, sessions=3, per=5):
    ev = []
    for u in range(n_keys):
        for s in range(sessions):
            for j in range(per):
                ev.append((u, 5_000 * s + 40 * j, 1.0))
    ev.sort(key=lambda e: e[1])
    return ev


def _session_env(tmpdir, events, sink, extra_cfg=None, batch=16, gap=500):
    from flink_tpu.core.config import Configuration

    cfg = {"restart-strategy": "fixed-delay",
           "restart-strategy.fixed-delay.attempts": 3,
           "restart-strategy.fixed-delay.delay": 0}
    cfg.update(extra_cfg or {})
    env = StreamExecutionEnvironment(Configuration(cfg))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(256)
    env.batch_size = batch
    env.enable_checkpointing(interval_steps=2, directory=str(tmpdir))

    import numpy as np

    def gen(off, n):
        chunk = events[off:off + n]
        return (
            {"key": np.asarray([e[0] for e in chunk], np.int64),
             "value": np.asarray([e[2] for e in chunk], np.float32)},
            np.asarray([e[1] for e in chunk], np.int64),
        )

    from flink_tpu.runtime.sources import GeneratorSource

    (
        env.add_source(GeneratorSource(gen, total=len(events)))
        .key_by(lambda c: c["key"])
        .window(EventTimeSessionWindows.with_gap(gap))
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    return env


def test_session_checkpoint_restart_exactness(tmp_path):
    """Induced sink failure mid-stream: the session job restores from the
    last checkpoint and the final session set is exact (checkpointing for
    session stages — the round-4 removal of the NotImplementedError)."""
    events = _session_events()

    class FailOnce(CollectSink):
        tripped = [False]

        def invoke_batch(self, elements):
            if not self.tripped[0] and len(self.results) >= 4:
                self.tripped[0] = True
                raise RuntimeError("induced session sink failure")
            super().invoke_batch(elements)

        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

    sink = FailOnce()
    env = _session_env(tmp_path, events, sink)
    job = env.execute("session-ckpt")
    assert job.metrics.restarts >= 1
    got = {(r.key, r.window_start_ms, r.window_end_ms): r.value
           for r in sink.results}
    # 6 keys x 3 sessions of 5 events each, exactly once
    assert len(got) == 18, len(got)
    assert all(v == 5.0 for v in got.values())


def test_session_kill_and_resume_from_checkpoint(tmp_path):
    """Run half the stream, 'kill' (abandon the env), resume a FRESH env
    from the checkpoint directory: union of sink outputs is exact."""
    events = _session_events()

    class Boom(CollectSink):
        def invoke_batch(self, elements):
            super().invoke_batch(elements)
            if len(self.results) >= 13:
                raise KeyboardInterrupt("simulated kill")  # not restartable

        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

    s1 = Boom()
    env1 = _session_env(tmp_path, events, s1)
    try:
        env1.execute("session-kill")
        assert False, "expected simulated kill"
    except KeyboardInterrupt:
        pass

    class Plain(CollectSink):
        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

    s2 = Plain()
    env2 = _session_env(tmp_path, events, s2)
    env2.execute("session-resume", restore_from=str(tmp_path))
    got = {(r.key, r.window_start_ms, r.window_end_ms): r.value
           for r in s2.results}
    assert len(got) == 18
    assert all(v == 5.0 for v in got.values())


def test_session_restore_validation_failures(tmp_path):
    """Mismatched configuration fails fast at restore, never corrupts."""
    events = _session_events()

    class Snap(CollectSink):
        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

    env = _session_env(tmp_path, events, Snap())
    env.execute("session-src")          # leaves checkpoints behind

    # different state capacity
    env2 = _session_env(tmp_path, events, Snap())
    env2.set_state_capacity(512)
    with pytest.raises(ValueError, match="capacity"):
        env2.execute("bad-cap", restore_from=str(tmp_path))

    # different gap
    env3 = _session_env(tmp_path, events, Snap(), gap=999)
    with pytest.raises(ValueError, match="gap"):
        env3.execute("bad-gap", restore_from=str(tmp_path))

    # different max-parallelism
    env4 = _session_env(tmp_path, events, Snap())
    env4.set_max_parallelism(16)
    with pytest.raises(ValueError, match="parallelism"):
        env4.execute("bad-maxp", restore_from=str(tmp_path))


def test_round4_session_checkpoint_format_restores(tmp_path):
    """Retained checkpoints from the round-4 inline session format (keys
    session_window/session_state) restore through the unified
    checkpointer's compatibility shim."""
    from flink_tpu.runtime import checkpoint as ckpt

    events = _session_events()

    class Kill(CollectSink):
        def invoke_batch(self, elements):
            super().invoke_batch(elements)
            if len(self.results) >= 13:
                raise KeyboardInterrupt("simulated kill")

        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

    env1 = _session_env(tmp_path, events, Kill())
    try:
        env1.execute("legacy-seed")
        assert False
    except KeyboardInterrupt:
        pass

    # rewrite every retained checkpoint into the ROUND-4 payload shape
    st = ckpt.CheckpointStorage(str(tmp_path), retain=10**9)
    for cid in st.list_checkpoints():
        p = st.read_generic(cid)
        legacy = {
            "session_window": True,
            "session_state": p["stage_state"],
            "gap_ms": p["stage_meta"]["gap_ms"],
            "capacity_per_shard": p["stage_meta"]["capacity_per_shard"],
            "wm_current": p["stage_extra"]["wm_current"],
            "origin_ms": p["stage_extra"]["origin_ms"],
            "offsets": p["offsets"],
            "codec_rev_count": p["codec_rev_count"],
            "sink_states": p["sink_states"],
            "max_parallelism": p["max_parallelism"],
            "n_shards": p["n_shards"],
        }
        st.write_generic(cid, legacy)

    class Plain(CollectSink):
        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

    s2 = Plain()
    env2 = _session_env(tmp_path, events, s2)
    env2.execute("legacy-resume", restore_from=str(tmp_path))
    got = {(r.key, r.window_start_ms, r.window_end_ms): r.value
           for r in s2.results}
    assert len(got) == 18
    assert all(v == 5.0 for v in got.values())


def test_sessions_survive_inter_poll_time_jump():
    """A mid-stream event-time jump far larger than any internal state
    horizon: every pre-jump session closes exactly once and post-jump
    sessions open fresh — the session-path counterpart of the windowed
    inter-poll gap regression (tests/test_time_gap.py)."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    total, n_keys, gap = 30_000, 40, 300
    jump_at, gap_ms = 15_000, 120_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        ts = idx // 5
        ts = np.where(idx >= jump_at, ts + gap_ms, ts)
        return ({"key": idx % n_keys, "value": np.ones(n, np.float32)},
                ts.astype(np.int64))

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(4)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(4096)
    env.batch_size = 4096
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .window(EventTimeSessionWindows.with_gap(gap))
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("session-inter-poll-jump")
    assert job.metrics.dropped_capacity == 0
    assert job.metrics.dropped_late == 0
    # continuous per-key streams split into exactly 2 sessions per key
    assert len(sink.results) == n_keys * 2
    assert sum(float(r.value) for r in sink.results) == float(total)
