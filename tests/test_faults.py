"""Failure containment (ISSUE 4): checkpoint failure budgets, the
watchdog-supervised step loop, DCN peer deadlines/reconnect, and the
deterministic fault-injection harness (flink_tpu/testing/faults.py).

The chaos soak drives one windowed job through a seeded schedule of
filesystem write failures, slow materializer I/O, torn manifest writes,
and prefetch-thread death, and asserts the exactly-once oracle plus
zero hangs; the targeted tests pin each containment mechanism's
acceptance criterion individually."""

import copy
import os
import socket
import threading
import time

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime import dcn
from flink_tpu.runtime.checkpoint import CheckpointStorage
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None, **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, source=None, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("faults-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


def assert_chains_closed(ckpt_dir):
    """No published manifest may reference a checkpoint directory that
    does not exist — aborted checkpoints must never leave a hole a
    retained chain spans."""
    st = CheckpointStorage(str(ckpt_dir))
    present = set(st.list_checkpoints())
    for cid in present:
        m = st.read_manifest(cid)
        if m is not None:
            missing = [c for c in m["chain"] if c not in present]
            assert not missing, (
                f"manifest of chk-{cid} chains over missing {missing}"
            )


# ---------------------------------------------------- injector framework

def test_fault_injector_is_deterministic():
    r1 = FaultInjector(
        [FaultRule("p", prob=0.5, times=10**9,
                   exc=None, action="sleep", delay_s=0.0)],
        seed=7,
    )
    r2 = FaultInjector(
        [FaultRule("p", prob=0.5, times=10**9,
                   exc=None, action="sleep", delay_s=0.0)],
        seed=7,
    )
    for _ in range(64):
        r1.hit("p", {})
        r2.hit("p", {})
    assert [f["hit"] for f in r1.fired] == [f["hit"] for f in r2.fired]
    assert r1.fired  # the coin actually came up at least once in 64


def test_fault_injector_occurrence_index_and_times():
    inj = FaultInjector([
        FaultRule("w", exc=OSError("boom"), at=2),
        FaultRule("e", exc=OSError("boom"), every=3, times=2),
    ])
    with faults.active(inj):
        for i in range(6):
            if i == 2:
                with pytest.raises(OSError):
                    faults.inject("w")
            else:
                faults.inject("w")
        fired = 0
        for i in range(12):
            try:
                faults.inject("e")
            except OSError:
                fired += 1
        assert fired == 2          # every=3 capped by times=2
    faults.inject("w")             # uninstalled: plain no-op


# --------------------------------------------- checkpoint failure budget

def test_write_failure_within_budget_aborts_only_that_checkpoint(tmp_path):
    """THE containment criterion: one transient write failure within
    checkpoint.tolerable-failures aborts only that checkpoint — the job
    keeps running without a restart, the next checkpoint succeeds, and
    recovery from the surviving chain is exactly-once."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=2,
        **{"checkpoint.tolerable-failures": 2, "checkpoint.async": False},
    )
    inj = FaultInjector(
        [FaultRule("ckpt.entries.write", exc=OSError("injected fs blip"),
                   at=1)]
    )
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert inj.fired_at("ckpt.entries.write"), "fault never fired"
    assert m.restarts == 0
    assert m.checkpoints_aborted == 1
    assert got == expected(total)
    stats = m.checkpoint_stats
    aborted = [s for s in stats if s["status"] == "aborted"]
    completed = [s for s in stats if s["status"] == "completed"]
    assert len(aborted) == 1
    assert "injected fs blip" in aborted[0]["failure_reason"]
    # the NEXT checkpoint succeeded (later id than the aborted one)
    assert any(s["id"] > aborted[0]["id"] for s in completed)
    # no staging debris from the abort
    assert not [d for d in os.listdir(tmp_path / "chk")
                if d.endswith(".tmp")]
    # budget state is served live
    assert m.failure_budget.state()["total-failures"] == 1
    # recovery from the surviving chain: a fresh job restores the latest
    # cut and replays a longer stream — merged output is the no-failure
    # truth (exactly-once across the abort)
    got2 = run_job(build_env(2), total * 2,
                   restore_from=str(tmp_path / "chk"))
    assert {**got, **got2} == expected(total * 2)


def test_budget_exhaustion_escalates_to_restart_strategy(tmp_path):
    """Two CONSECUTIVE failures with tolerable-failures=1: the second
    abort exhausts the budget and takes the configured RestartStrategy
    path; recovery still converges to exactly-once."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{"checkpoint.tolerable-failures": 1, "checkpoint.async": False},
    )
    inj = FaultInjector([
        FaultRule("ckpt.entries.write", exc=OSError("injected 1"), at=1),
        FaultRule("ckpt.entries.write", exc=OSError("injected 2"), at=2),
    ])
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert m.checkpoints_aborted == 2
    assert m.restarts == 1
    assert got == expected(total)


def test_async_incremental_abort_rebases_chain(tmp_path):
    """A torn manifest write in incremental mode: the failed delta's
    dirty bits are gone, so the chain must RESET — the next published
    checkpoint is a fresh full base and no retained manifest ever spans
    the hole."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=1,
        **{"checkpoint.mode": "incremental", "checkpoint.async": True,
           "checkpoint.compact-every": 100,
           "checkpoint.tolerable-failures": 3},
    )
    inj = FaultInjector(
        [FaultRule("ckpt.manifest.write", action="torn", at=2)]
    )
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert got == expected(total)
    assert m.restarts == 0
    assert m.checkpoints_aborted >= 1
    aborted_ids = [s["id"] for s in m.checkpoint_stats
                   if s["status"] == "aborted"]
    assert aborted_ids
    assert_chains_closed(tmp_path / "chk")
    # the first checkpoint published after the hole re-based the chain
    st = CheckpointStorage(str(tmp_path / "chk"))
    after = [c for c in st.list_checkpoints() if c > min(aborted_ids)]
    if after:       # (retention may have GC'd it, but normally present)
        m0 = st.read_manifest(min(after))
        assert m0 is None or m0["kind"] == "full"
    # and the chain restores exactly-once
    got2 = run_job(build_env(2), total * 2,
                   restore_from=str(tmp_path / "chk"))
    assert {**got, **got2} == expected(total * 2)


def test_checkpoint_timeout_cancels_wedged_publish(tmp_path):
    """A wedged materialization (injected slow I/O far beyond
    checkpoint.timeout) is declared failed at a later barrier: its
    publish is cancelled, the failure is counted, and the job finishes
    exactly-once with closed chains on disk."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=1,
        **{"checkpoint.mode": "incremental", "checkpoint.async": True,
           "checkpoint.timeout": 0.4,
           "checkpoint.tolerable-failures": 50},
    )
    inj = FaultInjector(
        [FaultRule("materializer.task", action="sleep", delay_s=2.5,
                   at=0)]
    )
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert got == expected(total)
    assert m.checkpoints_aborted >= 1
    reasons = " | ".join(
        s.get("failure_reason", "") for s in m.checkpoint_stats
        if s["status"] == "aborted"
    )
    assert "checkpoint.timeout" in reasons or "wedged" in reasons
    assert_chains_closed(tmp_path / "chk")


def test_min_pause_declines_triggers(tmp_path):
    env = build_env(
        1, tmp_path / "chk", interval=1,
        **{"checkpoint.min-pause": 120.0, "checkpoint.async": False},
    )
    got = run_job(env, 2048)
    m = env.last_job.metrics
    completed = [s for s in (m.checkpoint_stats or [])
                 if s["status"] == "completed"]
    assert len(completed) == 1          # everything after defers
    assert m.checkpoints_declined == 1  # one decline per deferred trigger
    assert got == expected(2048)


def test_policy_unit_accounting():
    from flink_tpu.checkpointing.policy import CheckpointFailurePolicy

    p = CheckpointFailurePolicy(tolerable_failures=2, min_pause_s=0.05)
    assert not p.on_aborted(1, "a")
    assert not p.on_aborted(2, "b")
    assert p.on_aborted(3, "c")            # 3 consecutive > 2
    p.on_completed(4)
    assert not p.on_aborted(5, "d")        # completion reset the run
    s = p.state()
    assert s["total-failures"] == 4 and s["continuous-failures"] == 1
    assert not p.can_trigger()             # 50ms pause after the abort
    time.sleep(0.06)
    assert p.can_trigger()


def test_materializer_slot_wait_timeout():
    from flink_tpu.checkpointing.materializer import (
        Materializer,
        MaterializerStall,
    )

    mat = Materializer(slots=1)
    release = threading.Event()
    mat.submit("wedge", release.wait)
    with pytest.raises(MaterializerStall, match="wedged"):
        mat.wait_for_slot(timeout=0.3)
    release.set()
    mat.close()


# ------------------------------------------------------------- watchdog

def test_watchdog_trips_armed_phase_with_attribution():
    from flink_tpu.runtime.watchdog import Watchdog, WatchdogError

    trips = []
    wd = Watchdog({"fire": 0.3}, interval_s=0.05,
                  on_trip=trips.append).start()
    try:
        with pytest.raises(WatchdogError, match="fire"):
            prev = wd.arm("fire")
            try:
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    time.sleep(0.01)
                pytest.fail("watchdog never tripped")
            finally:
                wd.disarm(prev)
        assert trips and trips[0].phase == "fire"
        assert trips[0].elapsed_s >= 0.3
    finally:
        wd.stop()


def test_watchdog_disarm_restores_nested_phase():
    from flink_tpu.runtime.watchdog import Watchdog

    wd = Watchdog({"outer": 100.0, "inner": 100.0})
    prev = wd.arm("outer")
    t0 = wd._armed[threading.get_ident()][1]
    inner_prev = wd.arm("inner")
    assert wd._armed[threading.get_ident()][0] == "inner"
    wd.disarm(inner_prev)
    phase, t_restored = wd._armed[threading.get_ident()][:2]
    assert phase == "outer" and t_restored == t0   # t0 preserved
    wd.disarm(prev)
    assert threading.get_ident() not in wd._armed


class StalledSource(GeneratorSource):
    """Goes silent forever (short cooperative sleeps) once ``stall_at``
    records have been polled — the distributed-hang stand-in."""

    def __init__(self, fn, total, stall_at):
        super().__init__(fn, total)
        self.stall_at = stall_at

    def poll(self, max_records):
        if self.offset >= self.stall_at:
            while True:
                time.sleep(0.05)
        return super().poll(max_records)


def test_watchdog_converts_source_stall_into_attributed_failure():
    """An injected mid-job stall produces a clean, attributed job
    failure within the watchdog deadline instead of an indefinite
    hang."""
    from flink_tpu.runtime.watchdog import WatchdogError

    env = build_env(
        1,
        **{"pipeline.prefetch": "on",
           "watchdog.source-timeout": 1.5,
           "watchdog.interval": 0.2},
    )
    src = StalledSource(gen, 4096, stall_at=1024)
    t0 = time.monotonic()
    with pytest.raises(WatchdogError, match="source"):
        run_job(env, 4096, source=src)
    assert time.monotonic() - t0 < 30.0
    assert env._live_metrics.watchdog_trips >= 1


# ------------------------------------------------------------- DCN ring

def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _ring_pair(**kw):
    addrs = [f"127.0.0.1:{_free_port()}", f"127.0.0.1:{_free_port()}"]
    rings = [None, None]
    errs = [None, None]

    def build(pid):
        try:
            rings[pid] = dcn._RebalanceRing(pid, 2, addrs, **kw)
        except Exception as e:      # surfaced by the caller's assert
            errs[pid] = e

    ts = [threading.Thread(target=build, args=(p,), daemon=True)
          for p in (0, 1)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=30)
    assert errs == [None, None], errs
    return rings


def _empty_poll(n):
    return (np.zeros(0, np.int64), np.zeros(0, np.int64),
            np.zeros(0, np.float32), False)


def test_dcn_peer_stall_is_attributed_within_deadline():
    """A peer that stops sending mid-job fails ATTRIBUTED (which peer,
    how long) within the recv deadline — not an indefinite hang."""
    rings = _ring_pair(recv_timeout_s=1.0, reconnect_attempts=0)
    try:
        t0 = time.monotonic()
        # peer 1 never serves its side of the round
        with pytest.raises(dcn.DCNPeerStalledError) as ei:
            rings[0].exchange(4, _empty_poll)
        assert time.monotonic() - t0 < 10.0
        assert "peer" in str(ei.value) and "stalled" in str(ei.value)
    finally:
        for r in rings:
            r.close()


def test_dcn_transient_reset_recovers_without_loss():
    """An injected socket reset mid-run: both sides resync their links
    and retry the round; every donated record arrives exactly once
    (the donation cache re-donates, never re-polls)."""
    rings = _ring_pair(recv_timeout_s=5.0, reconnect_attempts=3,
                       reconnect_backoff_s=0.05)
    counters = [iter(range(0, 10**6)), iter(range(1000, 10**6))]

    def poll_for(pid):
        def poll_extra(n):
            ks = np.asarray([next(counters[pid]) for _ in range(3)],
                            np.int64)
            return ks, ks.copy(), ks.astype(np.float32), False
        return poll_extra

    received = [[], []]
    errs = [None, None]

    def run(pid):
        try:
            for _ in range(5):
                rk, _rt, _rv, _dd = rings[pid].exchange(
                    3, poll_for(pid)
                )
                received[pid].append(np.asarray(rk))
        except Exception as e:
            errs[pid] = e

    rule = FaultRule("dcn.send", action="call",
                     fn=lambda ctx: ctx["sock"].close(), at=4)
    try:
        with faults.active(FaultInjector([rule])):
            ts = [threading.Thread(target=run, args=(p,), daemon=True)
                  for p in (0, 1)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert not any(t.is_alive() for t in ts), "ring hung"
        assert errs == [None, None], errs
        # lossless: each side received its peer's records 0..14 /
        # 1000..1014 in order, no gaps, no duplicates
        got0 = np.concatenate(received[0]).tolist()
        got1 = np.concatenate(received[1]).tolist()
        assert got0 == list(range(1000, 1015))
        assert got1 == list(range(0, 15))
    finally:
        for r in rings:
            r.close()


def test_dcn_serve_cache_rededonates_same_round_only():
    """Asymmetric-abort protection: a RE-request for an already-served
    round re-donates the cached records (the originals went into a dead
    socket) WITHOUT re-polling; a new round polls fresh."""
    rings = _ring_pair(recv_timeout_s=2.0)
    try:
        polls = []

        def poll_extra(n):
            polls.append(n)
            ks = np.arange(len(polls) * 10, len(polls) * 10 + 2,
                           dtype=np.int64)
            return ks, ks.copy(), ks.astype(np.float32), False

        r = rings[0]
        first = r._serve_donation(2, 5, poll_extra)
        again = r._serve_donation(2, 5, poll_extra)     # retry of round 5
        assert polls == [2]                             # no second poll
        assert again[0].tolist() == first[0].tolist() == [10, 11]
        fresh = r._serve_donation(2, 6, poll_extra)     # next round
        assert polls == [2, 2]
        assert fresh[0].tolist() == [20, 21]
    finally:
        for ring in rings:
            ring.close()


def test_materializer_recover_bounded_by_timeout():
    """A WEDGED write must not turn recovery into the hang it recovers
    from: flush/recover give up after the timeout (the abandoned task
    keeps running on the daemon thread)."""
    from flink_tpu.checkpointing.materializer import Materializer

    mat = Materializer(slots=1)
    release = threading.Event()
    mat.submit("wedge", release.wait)
    t0 = time.monotonic()
    assert mat.flush(raise_errors=False, timeout=0.4) is False
    mat.recover(timeout=0.4)
    assert time.monotonic() - t0 < 5.0
    release.set()
    mat.close()


def test_gc_sweeps_stale_tmp_dirs(tmp_path):
    """An aborted attempt's chk-<X>.tmp — even under a DIFFERENT id
    than the barrier that counted the abort — is swept by the next
    successful publish's GC."""
    st = CheckpointStorage(str(tmp_path / "chk"), retain=2)
    os.makedirs(st.path(7) + ".tmp")          # orphaned abort debris
    ent = {
        "key_hi": np.zeros(0, np.uint32), "key_lo": np.zeros(0, np.uint32),
        "pane": np.zeros(0, np.int32), "value": np.zeros(0, np.float32),
        "fresh": np.zeros(0, bool),
    }
    scal = {"watermark": 0, "fired_through": 0, "max_pane": 0,
            "min_pane": 0, "dropped_late": 0, "dropped_capacity": 0}
    st.write(9, ent, scal, None, {})
    names = os.listdir(tmp_path / "chk")
    assert not any(n.endswith(".tmp") for n in names), names
    assert st.latest() == 9


def test_dcn_peer_loss_after_reconnect_exhaustion():
    """A peer that dies for good: bounded reconnect gives up with an
    attributed DCNPeerLostError, not an endless redial loop."""
    rings = _ring_pair(recv_timeout_s=1.0, reconnect_attempts=1,
                       reconnect_backoff_s=0.05, resync_window_s=1.0)
    rings[1].close()                # peer gone, server socket included
    try:
        with pytest.raises(dcn.DCNPeerLostError):
            rings[0].exchange(4, _empty_poll)
    finally:
        rings[0].close()


# ----------------------------------------- generic checkpoint paths

def test_generic_stage_write_failure_within_budget(tmp_path):
    """The generic (pickled-payload) checkpoint paths share the failure
    budget: a rolling-reduce stage survives one injected snapshot write
    failure without a restart and keeps the per-record output exact."""
    rng = np.random.default_rng(7)
    events = [(int(rng.integers(0, 5)), float(rng.integers(1, 4)))
              for _ in range(120)]
    acc, expect = {}, []
    for k, v in events:
        acc[k] = acc.get(k, 0.0) + v
        expect.append((k, acc[k]))
    env = StreamExecutionEnvironment(Configuration({
        "checkpoint.tolerable-failures": 2,
    }))
    env.set_parallelism(2).set_max_parallelism(8)
    env.set_state_capacity(256)
    env.batch_size = 8
    env.enable_checkpointing(2, str(tmp_path / "chk"))
    sink = CollectSink()
    (
        env.from_collection(events)
        .key_by(lambda e: e[0])
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    inj = FaultInjector(
        [FaultRule("ckpt.generic.write", exc=OSError("injected"), at=1)]
    )
    with faults.active(inj):
        job = env.execute("rolling-budget")
    assert inj.fired_at("ckpt.generic.write")
    assert job.metrics.restarts == 0
    assert job.metrics.checkpoints_aborted == 1
    assert sink.results == expect


# ----------------------------------------------------- ring-hang satellite

def test_configured_ring_without_headroom_raises(tmp_path):
    """Regression (ADVICE r5): window.ring-panes == panes_per_window + 1
    used to enter a never-advancing grouping loop on the first catch-up
    batch; it must be rejected at setup with a clear error."""
    env = build_env(1, **{"window.ring-panes": 2})   # ppw=1 -> needs >= 4
    with pytest.raises(ValueError, match="window.ring-panes"):
        run_job(env, 512)
    # the minimum accepted configured ring runs to completion
    env = build_env(1, **{"window.ring-panes": 4})
    assert run_job(env, 2048) == expected(2048)


# ------------------------------------------------------------ chaos soak

CHAOS_RULES = [
    # transient filesystem write failures on two non-consecutive
    # checkpoints (within the budget)
    FaultRule("ckpt.entries.write", exc=OSError("chaos fs blip"), at=1),
    FaultRule("ckpt.entries.write", exc=OSError("chaos fs blip"), at=4),
    # torn manifest: partial bytes then failure
    FaultRule("ckpt.manifest.write", action="torn", at=6),
    # slow I/O on the materializer thread
    FaultRule("materializer.task", action="sleep", delay_s=0.05, every=5,
              times=4),
    # prefetch-thread death mid-stream
    FaultRule("ingest.producer", exc=RuntimeError("chaos thread death"),
              at=8),
    # device loss mid-stream (ISSUE 8): one of the 2 mesh shards dies
    # at a step dispatch; the elastic recovery path re-plans the job at
    # parallelism 1 and the stream finishes DEGRADED — exactly-once
    # must hold across the re-slice like every other fault class
    faults.device_loss_rule(shard=1, at=16),
]


def _chaos_run(tmp_path, total):
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{"checkpoint.mode": "incremental", "checkpoint.async": True,
           "checkpoint.compact-every": 100,
           "checkpoint.tolerable-failures": 3,
           "pipeline.prefetch": "on"},
    )
    # deep copy: FaultRule carries a mutable per-run `fired` counter,
    # and the fast + slow soaks share this module-level schedule — a
    # shallow copy would leave the second soak with spent rules that
    # never inject (and failing fired_at assertions)
    inj = FaultInjector(copy.deepcopy(CHAOS_RULES), seed=1234)
    t0 = time.monotonic()
    with faults.active(inj):
        got = run_job(env, total)
    wall = time.monotonic() - t0
    m = env.last_job.metrics
    # exactly-once oracle: the injected faults changed NOTHING about
    # the results
    assert got == expected(total)
    # all fault classes actually fired (device loss rides step.dispatch)
    for point in ("ckpt.entries.write", "ckpt.manifest.write",
                  "materializer.task", "ingest.producer",
                  "step.dispatch"):
        assert inj.fired_at(point), f"{point} never fired"
    assert m.checkpoints_aborted >= 1
    # the device-loss class degraded the job onto the surviving shard
    # and it FINISHED there, exactly-once (asserted above)
    assert env._elasticity_report()["degraded"] is True
    assert env.last_job.ctx.n_shards == 1
    assert_chains_closed(tmp_path / "chk")
    return m, wall


def test_chaos_soak_fast(tmp_path):
    """Tier-1 variant: a windowed job survives a seeded schedule of
    fs write failures, a torn manifest, slow I/O, and prefetch-thread
    death — exactly-once results, zero hangs."""
    m, wall = _chaos_run(tmp_path, total=6144)
    assert wall < 300.0             # "zero hangs", with CPU headroom


@pytest.mark.slow
def test_chaos_soak_full(tmp_path):
    """Full soak: the same fault classes over a longer stream (dozens
    of checkpoints, repeated slow-I/O windows)."""
    m, wall = _chaos_run(tmp_path, total=32768)
    assert wall < 900.0
