"""Multi-process worker runtime: registration, heartbeats, DeathWatch,
kill-the-worker recovery from the last checkpoint, and HA leader
failover (kill-the-leader recovery).

Ref: TaskManager registration + heartbeats (TaskManager.scala:296),
Akka DeathWatch -> ExecutionGraph.restart (ExecutionGraph.java:848),
process-kill recovery ITCases (flink-tests/.../recovery/),
ZooKeeperLeaderElectionService.java:47 + SubmittedJobGraphStore.
"""

import glob
import os
import signal
import subprocess
import sys
import time

import pytest

from flink_tpu.runtime.cluster import control_request
from flink_tpu.runtime.ha import HAJobRegistry, leader_info
from flink_tpu.runtime.process_cluster import ProcessCluster

JOBS = os.path.join(os.path.dirname(__file__), "process_jobs.py")
BUILDER = f"{JOBS}:build_window_job"


def _read_cells(out_dir):
    cells = {}
    dups = 0
    for path in glob.glob(os.path.join(out_dir, "**", "part-0"),
                          recursive=True):
        with open(path) as f:
            for line in f:
                k, wend, v = line.strip().split(",")
                cell = (int(k), int(wend))
                if cell in cells:
                    dups += 1
                cells[cell] = cells.get(cell, 0.0) + float(v)
    return cells, dups


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {what}")


@pytest.fixture
def cluster():
    c = ProcessCluster(heartbeat_timeout_s=10.0, max_restarts=3)
    c.start()
    yield c
    c.shutdown()


def test_happy_path_two_processes(cluster, tmp_path):
    total = 20_000
    out = str(tmp_path / "out")
    wid = cluster.submit(
        BUILDER, "pc-happy", str(tmp_path / "chk"),
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
        },
    )
    assert cluster.wait(wid, timeout_s=180) == "FINISHED"
    kinds = [e["event"] for e in cluster.events]
    assert "registered" in kinds and "status" in kinds
    # heartbeats arrived (worker moved REGISTERED -> RUNNING)
    resp = control_request("127.0.0.1", cluster._port, {"action": "list"})
    assert resp["workers"][0]["status"] == "FINISHED"

    from process_jobs import expected_cells

    cells, dups = _read_cells(out)
    assert dups == 0
    assert cells == expected_cells(total)


def test_kill_worker_recovers_from_checkpoint(cluster, tmp_path):
    total = 32_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")
    wid = cluster.submit(
        BUILDER, "pc-kill", chk,
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
            "FLINK_TPU_TEST_SLEEP_S": "0.05",
        },
    )
    # wait for at least one durable checkpoint, then SIGKILL mid-job
    _wait_for(lambda: glob.glob(os.path.join(chk, "chk-*")), 120,
              "first checkpoint")
    cluster.kill_worker(wid)
    assert cluster.wait(wid, timeout_s=240) == "FINISHED"
    ev = [e["event"] for e in cluster.events]
    assert "death" in ev and "restarted" in ev
    with cluster._lock:
        assert cluster.workers[wid].restarts >= 1

    from process_jobs import expected_cells

    cells, dups = _read_cells(out)
    assert dups == 0, f"{dups} duplicate (key, window) emissions"
    assert cells == expected_cells(total)


def _start_controller(ha_dir, name):
    """Spawn a standalone controller process contending in ha_dir."""
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.process_cluster",
         "--ha-dir", str(ha_dir), "--contender-id", name,
         "--heartbeat-timeout-s", "10"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def test_leader_failover_resumes_jobs(tmp_path):
    """Kill the leader controller: the standby acquires the leader lock,
    recovers the job from the HA registry, and finishes it from its
    latest checkpoint with no lost or duplicated windows.

    The dead leader's worker dies with it (PDEATHSIG task lease), so the
    standby's respawn is the only live attempt — the reference's
    TM-task-cancellation-on-JM-loss + new-leader job recovery semantics.
    """
    ha = tmp_path / "ha"
    total = 32_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")

    ctl_a = _start_controller(ha, "ctl-a")
    ctl_b = None
    try:
        _wait_for(lambda: leader_info(str(ha)) is not None, 30,
                  "first leader published")
        info = leader_info(str(ha))
        assert info["leader_id"] == "ctl-a"

        resp = control_request("127.0.0.1", info["port"], {
            "action": "submit", "builder": BUILDER, "job_name": "ha-job",
            "checkpoint_dir": chk,
            "extra_env": {
                "FLINK_TPU_TEST_OUT": out,
                "FLINK_TPU_TEST_TOTAL": str(total),
                "FLINK_TPU_TEST_SLEEP_S": "0.05",
            },
        })
        assert resp["ok"]
        wid = resp["worker_id"]
        assert HAJobRegistry(str(ha)).get(wid)["status"] == "RUNNING"

        ctl_b = _start_controller(ha, "ctl-b")
        _wait_for(lambda: glob.glob(os.path.join(chk, "chk-*")), 120,
                  "first durable checkpoint")

        ctl_a.kill()        # flock released by the OS -> standby takes over
        ctl_a.wait(10)
        _wait_for(
            lambda: (leader_info(str(ha)) or {}).get("leader_id") == "ctl-b",
            30, "standby takeover",
        )
        new_port = leader_info(str(ha))["port"]

        def finished():
            try:
                reg = HAJobRegistry(str(ha)).get(wid)
                return reg is not None and reg["status"] == "FINISHED"
            except OSError:
                return False

        _wait_for(finished, 240, "job resumed and finished by new leader")
        resp = control_request("127.0.0.1", new_port, {"action": "list"})
        assert resp["workers"][0]["status"] == "FINISHED"

        from process_jobs import expected_cells

        cells, dups = _read_cells(out)
        assert dups == 0, f"{dups} duplicate (key, window) emissions"
        assert cells == expected_cells(total)
    finally:
        for p in (ctl_a, ctl_b):
            if p is not None and p.poll() is None:
                p.kill()


def test_heartbeat_timeout_detects_frozen_worker(cluster, tmp_path):
    """SIGSTOP freezes the process WITHOUT exiting: only the heartbeat
    path can detect it (the DeathWatch-distinct liveness signal)."""
    total = 32_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")
    wid = cluster.submit(
        BUILDER, "pc-freeze", chk,
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
            "FLINK_TPU_TEST_SLEEP_S": "0.05",
        },
    )
    _wait_for(lambda: glob.glob(os.path.join(chk, "chk-*")), 120,
              "first checkpoint")
    with cluster._lock:
        pid = cluster.workers[wid].proc.pid
    os.kill(pid, signal.SIGSTOP)
    _wait_for(
        lambda: any(
            e["event"] == "death" and e["cause"] == "heartbeat-timeout"
            for e in cluster.events
        ),
        60, "heartbeat-timeout death detection",
    )
    assert cluster.wait(wid, timeout_s=240) == "FINISHED"

    from process_jobs import expected_cells

    cells, dups = _read_cells(out)
    assert dups == 0
    assert cells == expected_cells(total)


def test_multihost_registration_over_non_loopback(tmp_path):
    """De-localhosted control plane (VERDICT r2 item 7): the controller
    binds 0.0.0.0 and advertises the machine's real (non-loopback) IP;
    the worker process registers and heartbeats across that interface —
    the same path a TaskManager on another host takes
    (TaskManager.scala:296). Skipped when the environment has no
    non-loopback address."""
    import socket as _socket

    try:
        probe = _socket.socket(_socket.AF_INET, _socket.SOCK_DGRAM)
        probe.connect(("192.0.2.1", 9))   # no traffic sent (UDP)
        ip = probe.getsockname()[0]
        probe.close()
        if ip.startswith("127."):
            raise OSError("loopback only")
    except OSError:
        pytest.skip("no non-loopback interface")

    c = ProcessCluster(heartbeat_timeout_s=10.0, max_restarts=1,
                       advertise_host=ip)
    c.start(host="0.0.0.0")
    try:
        total = 10_000
        out = str(tmp_path / "out")
        wid = c.submit(
            BUILDER, "pc-multihost", str(tmp_path / "chk"),
            extra_env={
                "FLINK_TPU_TEST_OUT": out,
                "FLINK_TPU_TEST_TOTAL": str(total),
            },
        )
        assert c.wait(wid, timeout_s=180) == "FINISHED"
        # the worker really registered via the advertised IP
        resp = control_request(ip, c._port, {"action": "list"})
        assert resp["workers"][0]["status"] == "FINISHED"

        from process_jobs import expected_cells

        cells, dups = _read_cells(out)
        assert dups == 0
        assert cells == expected_cells(total)
    finally:
        c.shutdown()


def test_external_worker_adoption_and_deathwatch(tmp_path):
    """An independently launched worker (bin/taskmanager.sh path, the
    reference's TaskManager-registers-itself flow) is ADOPTED by the
    controller: it appears in the worker list, runs to FINISHED, and a
    killed external worker is flagged DEAD by the DeathWatch."""
    c = ProcessCluster(heartbeat_timeout_s=2.0, max_restarts=1)
    c.start()
    try:
        out = str(tmp_path / "out")
        env = dict(os.environ)
        env.update({
            "JAX_PLATFORMS": "cpu",
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": "8000",
            "PYTHONPATH": os.path.dirname(JOBS) + os.pathsep
            + env.get("PYTHONPATH", ""),
        })
        proc = subprocess.Popen(
            [sys.executable, "-m", "flink_tpu.runtime.worker",
             "--controller", f"127.0.0.1:{c._port}",
             "--worker-id", "EXT1", "--builder", BUILDER,
             "--job-name", "ext-job",
             "--checkpoint-dir", str(tmp_path / "chk")],
            env=env,
        )
        try:
            _wait_for(
                lambda: getattr(
                    c.workers.get("EXT1"), "status", None
                ) == "FINISHED",
                120, "external worker to finish",
            )
        finally:
            proc.wait(timeout=30)
        rec = c.workers["EXT1"]
        assert rec.external and rec.proc is None
        from process_jobs import expected_cells

        cells, dups = _read_cells(out)
        assert dups == 0 and cells == expected_cells(8000)

        # second external worker killed mid-run -> DeathWatch flags DEAD
        env2 = dict(env)
        env2["FLINK_TPU_TEST_OUT"] = str(tmp_path / "out2")
        env2["FLINK_TPU_TEST_TOTAL"] = "4000000"   # long enough to kill
        proc2 = subprocess.Popen(
            [sys.executable, "-m", "flink_tpu.runtime.worker",
             "--controller", f"127.0.0.1:{c._port}",
             "--worker-id", "EXT2", "--builder", BUILDER,
             "--job-name", "ext-kill",
             "--checkpoint-dir", str(tmp_path / "chk2")],
            env=env2,
        )
        _wait_for(
            lambda: "EXT2" in c.workers, 60, "EXT2 registration",
        )
        proc2.kill()
        proc2.wait(timeout=30)
        _wait_for(
            lambda: c.workers["EXT2"].status == "DEAD",
            30, "DeathWatch to flag the killed external worker",
        )
        assert not any(
            e["event"] == "death" and e.get("worker") == "EXT2"
            and not e.get("external")
            for e in c.events
        )
    finally:
        c.shutdown()
