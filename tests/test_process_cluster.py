"""Multi-process worker runtime: registration, heartbeats, DeathWatch,
kill-the-worker recovery from the last checkpoint (VERDICT item 10).

Ref: TaskManager registration + heartbeats (TaskManager.scala:296),
Akka DeathWatch -> ExecutionGraph.restart (ExecutionGraph.java:848),
process-kill recovery ITCases (flink-tests/.../recovery/).
"""

import glob
import os
import signal
import time

import pytest

from flink_tpu.runtime.cluster import control_request
from flink_tpu.runtime.process_cluster import ProcessCluster

JOBS = os.path.join(os.path.dirname(__file__), "process_jobs.py")
BUILDER = f"{JOBS}:build_window_job"


def _read_cells(out_dir):
    cells = {}
    dups = 0
    for path in glob.glob(os.path.join(out_dir, "**", "part-0"),
                          recursive=True):
        with open(path) as f:
            for line in f:
                k, wend, v = line.strip().split(",")
                cell = (int(k), int(wend))
                if cell in cells:
                    dups += 1
                cells[cell] = cells.get(cell, 0.0) + float(v)
    return cells, dups


def _wait_for(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {what}")


@pytest.fixture
def cluster():
    c = ProcessCluster(heartbeat_timeout_s=10.0, max_restarts=3)
    c.start()
    yield c
    c.shutdown()


def test_happy_path_two_processes(cluster, tmp_path):
    total = 20_000
    out = str(tmp_path / "out")
    wid = cluster.submit(
        BUILDER, "pc-happy", str(tmp_path / "chk"),
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
        },
    )
    assert cluster.wait(wid, timeout_s=180) == "FINISHED"
    kinds = [e["event"] for e in cluster.events]
    assert "registered" in kinds and "status" in kinds
    # heartbeats arrived (worker moved REGISTERED -> RUNNING)
    resp = control_request("127.0.0.1", cluster._port, {"action": "list"})
    assert resp["workers"][0]["status"] == "FINISHED"

    from process_jobs import expected_cells

    cells, dups = _read_cells(out)
    assert dups == 0
    assert cells == expected_cells(total)


def test_kill_worker_recovers_from_checkpoint(cluster, tmp_path):
    total = 120_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")
    wid = cluster.submit(
        BUILDER, "pc-kill", chk,
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
            "FLINK_TPU_TEST_SLEEP_S": "0.05",
        },
    )
    # wait for at least one durable checkpoint, then SIGKILL mid-job
    _wait_for(lambda: glob.glob(os.path.join(chk, "chk-*")), 120,
              "first checkpoint")
    cluster.kill_worker(wid)
    assert cluster.wait(wid, timeout_s=240) == "FINISHED"
    ev = [e["event"] for e in cluster.events]
    assert "death" in ev and "restarted" in ev
    with cluster._lock:
        assert cluster.workers[wid].restarts >= 1

    from process_jobs import expected_cells

    cells, dups = _read_cells(out)
    assert dups == 0, f"{dups} duplicate (key, window) emissions"
    assert cells == expected_cells(total)


def test_heartbeat_timeout_detects_frozen_worker(cluster, tmp_path):
    """SIGSTOP freezes the process WITHOUT exiting: only the heartbeat
    path can detect it (the DeathWatch-distinct liveness signal)."""
    total = 200_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")
    wid = cluster.submit(
        BUILDER, "pc-freeze", chk,
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
            "FLINK_TPU_TEST_SLEEP_S": "0.05",
        },
    )
    _wait_for(lambda: glob.glob(os.path.join(chk, "chk-*")), 120,
              "first checkpoint")
    with cluster._lock:
        pid = cluster.workers[wid].proc.pid
    os.kill(pid, signal.SIGSTOP)
    _wait_for(
        lambda: any(
            e["event"] == "death" and e["cause"] == "heartbeat-timeout"
            for e in cluster.events
        ),
        60, "heartbeat-timeout death detection",
    )
    assert cluster.wait(wid, timeout_s=240) == "FINISHED"

    from process_jobs import expected_cells

    cells, dups = _read_cells(out)
    assert dups == 0
    assert cells == expected_cells(total)
