"""RabbitMQ connector: AMQP 0-9-1 wire client vs the in-repo MiniRabbit
broker over real TCP; checkpoint-gated acks; correlation-id
exactly-once across a crash (the reference's RMQSource contract).

Ref flink-streaming-connectors/flink-connector-rabbitmq: RMQSource.java
(MultipleIdsMessageAcknowledgingSourceBase — tags acked on checkpoint
complete, ids dedupe redelivery), RMQSink.java.
"""

import time

import pytest

from flink_tpu.connectors.rabbitmq import (
    AMQPConnection,
    MiniRabbit,
    RMQSink,
    RMQSource,
)


@pytest.fixture
def broker():
    b = MiniRabbit()
    b.start()
    yield b
    b.stop()


def _drain(conn, n, timeout_s=10.0):
    got = []
    deadline = time.time() + timeout_s
    while len(got) < n and time.time() < deadline:
        got.extend(conn.drain_deliveries())
        time.sleep(0.01)
    return got


# ------------------------------------------------------------------ wire
def test_publish_consume_roundtrip(broker):
    pub = AMQPConnection("127.0.0.1", broker.port)
    pub.queue_declare("q1")
    for i in range(20):
        pub.basic_publish("q1", f"msg-{i}".encode(),
                          correlation_id=f"id-{i}")

    sub = AMQPConnection("127.0.0.1", broker.port)
    sub.queue_declare("q1")
    sub.basic_consume("q1")
    got = _drain(sub, 20)
    assert [d["body"].decode() for d in got] == [
        f"msg-{i}" for i in range(20)
    ]
    assert [d["correlation_id"] for d in got] == [
        f"id-{i}" for i in range(20)
    ]
    assert all(not d["redelivered"] for d in got)
    sub.basic_ack(got[-1]["delivery_tag"], multiple=True)
    time.sleep(0.1)
    pub.close()
    sub.close()


def test_unacked_requeue_on_disconnect_with_redelivered_flag(broker):
    pub = AMQPConnection("127.0.0.1", broker.port)
    pub.queue_declare("q2")
    for i in range(10):
        pub.basic_publish("q2", f"m{i}".encode(), correlation_id=f"c{i}")

    sub1 = AMQPConnection("127.0.0.1", broker.port)
    sub1.queue_declare("q2")
    sub1.basic_consume("q2")
    got1 = _drain(sub1, 10)
    assert len(got1) == 10
    # ack only the first 4, then die
    sub1.basic_ack(got1[3]["delivery_tag"], multiple=True)
    time.sleep(0.2)          # let the ack land before the hangup
    sub1.close()
    time.sleep(0.2)          # broker notices + requeues

    sub2 = AMQPConnection("127.0.0.1", broker.port)
    sub2.queue_declare("q2")
    sub2.basic_consume("q2")
    got2 = _drain(sub2, 6)
    assert sorted(d["body"].decode() for d in got2) == [
        f"m{i}" for i in range(4, 10)
    ]
    assert all(d["redelivered"] for d in got2)
    pub.close()
    sub2.close()


def test_large_and_empty_bodies_and_extra_properties(broker):
    """Interop band: a body larger than frame_max crosses as several
    body frames; a zero-length body has NO body frame; a header whose
    flag word carries properties besides correlation-id still parses
    the right id (properties serialize in descending flag-bit order)."""
    from flink_tpu.connectors.rabbitmq import (
        BASIC,
        FRAME_BODY,
        PROP_CORRELATION_ID,
        content_header,
        frame,
        method,
        shortstr,
        struct,
    )

    pub = AMQPConnection("127.0.0.1", broker.port)
    pub.queue_declare("big")
    big = bytes(range(256)) * 1024          # 256 KiB > frame_max 128 KiB
    pub.basic_publish("big", big, correlation_id="big-1")
    pub.basic_publish("big", b"", correlation_id="empty-1")
    # hand-rolled publish with content-type + delivery-mode + priority
    # set IN ADDITION to correlation-id (what pika emits routinely)
    flags = (1 << 15) | (1 << 12) | (1 << 11) | PROP_CORRELATION_ID
    props = (shortstr("text/plain")        # content-type   (bit 15)
             + bytes([2])                  # delivery-mode  (bit 12)
             + bytes([5])                  # priority       (bit 11)
             + shortstr("props-1"))        # correlation-id (bit 10)
    header = frame(
        2, AMQPConnection.CHANNEL_ID,
        struct.pack(">HHQH", BASIC, 0, 5, flags) + props,
    )
    pub._send(
        method(AMQPConnection.CHANNEL_ID, BASIC, 40,
               struct.pack(">H", 0) + shortstr("") + shortstr("big")
               + b"\x00")
        + header + frame(FRAME_BODY, AMQPConnection.CHANNEL_ID, b"hello")
    )

    sub = AMQPConnection("127.0.0.1", broker.port)
    sub.queue_declare("big")
    sub.basic_consume("big")
    got = _drain(sub, 3)
    assert len(got) == 3
    by_cid = {d["correlation_id"]: d["body"] for d in got}
    assert by_cid["big-1"] == big
    assert by_cid["empty-1"] == b""
    assert by_cid["props-1"] == b"hello"
    pub.close()
    sub.close()


# ------------------------------------------------- exactly-once protocol
def test_source_exactly_once_across_crash(broker):
    """Drive the Source checkpoint protocol by hand: snapshot taken,
    crash BEFORE the ack, restore — redeliveries of
    processed-but-unacked records are swallowed; nothing is lost or
    duplicated."""
    pub = AMQPConnection("127.0.0.1", broker.port)
    pub.queue_declare("jobq")
    for i in range(100):
        pub.basic_publish("jobq", f"r{i}".encode(), correlation_id=f"u{i}")

    src_a = RMQSource("127.0.0.1", broker.port, "jobq",
                      uses_correlation_id=True)
    src_a.open()
    emitted_a = []
    deadline = time.time() + 10
    while len(emitted_a) < 100 and time.time() < deadline:
        recs, _ = src_a.poll(1000)
        emitted_a.extend(recs)
    assert len(emitted_a) == 100
    # checkpoint 1 completes: everything so far is acked
    s1 = src_a.snapshot_offsets()
    src_a.notify_checkpoint_complete(1, s1)
    time.sleep(0.2)

    # 50 more records arrive and are emitted
    for i in range(100, 150):
        pub.basic_publish("jobq", f"r{i}".encode(), correlation_id=f"u{i}")
    more = []
    deadline = time.time() + 10
    while len(more) < 50 and time.time() < deadline:
        recs, _ = src_a.poll(1000)
        more.extend(recs)
    assert len(more) == 50
    emitted_a.extend(more)
    # checkpoint 2 is WRITTEN (snapshot) but the job crashes before the
    # ack fires
    s2 = src_a.snapshot_offsets()
    src_a.close()
    time.sleep(0.3)           # broker requeues the 50 unacked

    src_b = RMQSource("127.0.0.1", broker.port, "jobq",
                      uses_correlation_id=True)
    src_b.restore_offsets(s2)
    src_b.open()
    # publish a post-recovery tail
    for i in range(150, 180):
        pub.basic_publish("jobq", f"r{i}".encode(), correlation_id=f"u{i}")
    emitted_b = []
    deadline = time.time() + 10
    while len(emitted_b) < 30 and time.time() < deadline:
        recs, _ = src_b.poll(1000)
        emitted_b.extend(recs)
    # give any late duplicates a chance to show up
    t0 = time.time()
    while time.time() - t0 < 0.5:
        recs, _ = src_b.poll(1000)
        emitted_b.extend(recs)

    # restored state already covers r100..r149 (checkpoint 2 cut): the
    # redeliveries are swallowed; only the fresh tail is emitted
    assert sorted(emitted_b) == [f"r{i}" for i in range(150, 180)]
    total = emitted_a + emitted_b
    assert len(total) == len(set(total)) == 180

    # checkpoint 3 completes on the new incarnation: acks swallow-tags
    # and fresh tags, emptying the broker's unacked ledger
    s3 = src_b.snapshot_offsets()
    assert len(s3["unacked"]) == 80   # 50 swallowed + 30 fresh
    src_b.notify_checkpoint_complete(3, s3)
    time.sleep(0.3)
    src_b.close()
    time.sleep(0.3)
    # a third consumer sees an EMPTY queue: everything was acked
    probe = RMQSource("127.0.0.1", broker.port, "jobq",
                      uses_correlation_id=True, idle_eof_polls=5)
    probe.open()
    leftovers = []
    for _ in range(10):
        recs, eof = probe.poll(1000)
        leftovers.extend(recs)
        if eof:
            break
    assert leftovers == []
    probe.close()
    pub.close()


# -------------------------------------------------------------- pipeline
def test_windowed_pipeline_from_rabbitmq(broker):
    """RMQSink publishes -> RMQSource feeds a keyed windowed job: exact
    per-key totals."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.runtime.sinks import CollectSink

    total, n_keys = 2_400, 8
    sink_side = RMQSink(
        "127.0.0.1", broker.port, "events",
        serializer=lambda e: f"{e[0]},{e[1]}".encode(),
        correlation_id_from=lambda e: f"e{e[2]}",
    )
    sink_side.open()
    sink_side.invoke_batch([
        (i % n_keys, i // 4, i) for i in range(total)
    ])
    sink_side.close()
    # basic.publish is asynchronous (no reply method): wait for the
    # broker's handler thread to drain the socket before asserting
    deadline = time.time() + 10
    while (broker.message_count("events") < total
           and time.time() < deadline):
        time.sleep(0.05)
    assert broker.message_count("events") == total   # no consumer yet

    env = StreamExecutionEnvironment.get_execution_environment()
    # parallelism 2 keeps the exchange compile affordable on 1-core CI
    # hosts while still exercising cross-shard routing; 8-shard routing
    # is covered by tests/test_exchange*.py
    env.set_parallelism(2)
    out = CollectSink()
    (
        env.add_source(RMQSource(
            "127.0.0.1", broker.port, "events",
            deserializer=lambda b: tuple(
                int(x) for x in b.decode().split(",")
            ),
            uses_correlation_id=True,
            idle_eof_polls=25,
        ))
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(lambda e: e[0])
        .time_window(500)
        .sum(lambda e: 1.0)
        .add_sink(out)
    )
    env.execute("rmq-pipeline")
    assert sum(float(r.value) for r in out.results) == float(total)