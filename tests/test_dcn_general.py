"""Generalized cross-host plane (round 5): sliding + session windows over
the DCN global mesh, and the standard env.execute() selecting the plane
via dcn.* config — VERDICT r4 item 4 ("a session-window job spanning two
worker processes with kill-recover exactly-once").

Ref: RecordWriter.java:82 (every-operator fabric), TaskManager.scala:296
(same program on every worker), MergingWindowSet.java (sessions).
"""

import os
import signal
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
import dcn_jobs as J  # noqa: E402
from dcn_probe import (  # noqa: E402
    SKIP_REASON,
    multiprocess_collectives_supported,
)

# collection-time capability gate (see test_dcn.py / dcn_probe.py)
pytestmark = pytest.mark.skipif(
    not multiprocess_collectives_supported(), reason=SKIP_REASON
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NPROC = 2


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _env_for(pid):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _spawn_dcn(pid, coord, out, builder, extra=()):
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.dcn",
         "--coordinator", coord, "--num-processes", str(NPROC),
         "--process-id", str(pid), "--builder",
         os.path.join(REPO, "tests", "dcn_jobs.py") + ":" + builder,
         "--out", out, *extra],
        env=_env_for(pid), stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _spawn_env_job(pid, coord, out, session, shuffle=False,
                   extra_env=None):
    env = _env_for(pid)
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tests", "dcn_env_job.py"),
         "--coordinator", coord, "--num-processes", str(NPROC),
         "--process-id", str(pid), "--out", out,
         *(["--session"] if session else []),
         *(["--shuffle"] if shuffle else [])],
        env=env, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
    )


def _wait_all(procs, timeout=420):
    deadline = time.time() + timeout
    outs = []
    for p in procs:
        remain = max(1, deadline - time.time())
        try:
            out, _ = p.communicate(timeout=remain)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out.decode(errors="replace"))
    return outs


def _merge_sessions(paths):
    got = {}
    by_host = {}
    for host, path in enumerate(paths):
        data = np.load(path)
        for k64, s, e, v in zip(data["key_id"], data["window_start_ms"],
                                data["window_end_ms"], data["value"]):
            key = (int(k64), int(s), int(e))
            assert key not in got, f"duplicate emission {key}"
            got[key] = float(v)
            by_host[key] = host
    return got, by_host


def test_two_host_sessions_exact_and_cross(tmp_path):
    """Session windows spanning two worker processes: exact per-session
    sums, and fires provably cross the process boundary."""
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    procs = [_spawn_dcn(p, coord, outs[p], "two_host_session")
             for p in range(NPROC)]
    logs = _wait_all(procs)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]
    got, by_host = _merge_sessions(outs)
    assert got == J.expected_sessions(NPROC)
    # key k ingested ONLY by host k % NPROC; fires landing elsewhere
    # crossed the DCN hop
    crossed = sum(
        1 for (k, _s, _e), host in by_host.items() if host != k % NPROC
    )
    assert crossed > len(got) // 4, (crossed, len(got))
    assert len(set(by_host.values())) == NPROC


def test_two_host_session_kill_recover(tmp_path):
    """Kill the whole session ensemble mid-run, restart with --restore:
    union of emissions is exactly-once (the session analog of
    test_dcn.py's round trip)."""
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    extra = ["--checkpoint-dir", ckpt, "--ckpt-every", "2"]

    coord = f"127.0.0.1:{_free_port()}"
    procs = [_spawn_dcn(p, coord, outs[p], "two_host_session", extra)
             for p in range(NPROC)]
    deadline = time.time() + 300
    complete = []
    while time.time() < deadline:
        chks = [d for d in os.listdir(ckpt) if d.startswith("chk-")]
        complete = [
            d for d in chks
            if all(os.path.exists(
                os.path.join(ckpt, d, f"proc-{p}.meta.json"))
                for p in range(NPROC))
        ]
        if complete:
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.1)
    alive = [p for p in procs if p.poll() is None]
    assert complete, "no complete checkpoint appeared before the kill"
    assert alive, "workers finished before the kill — raise SESSION_TOTAL"
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
    for p in procs:
        p.wait(timeout=60)

    coord2 = f"127.0.0.1:{_free_port()}"
    procs2 = [
        _spawn_dcn(p, coord2, outs[p], "two_host_session",
                   extra + ["--restore"])
        for p in range(NPROC)
    ]
    logs = _wait_all(procs2)
    for p, log in zip(procs2, logs):
        assert p.returncode == 0, log[-2000:]
    got, _ = _merge_sessions(outs)
    assert got == J.expected_sessions(NPROC)


def test_env_execute_selects_dcn_sliding(tmp_path):
    """The STANDARD env.execute() runs multi-host when dcn.coordinator is
    configured — with SLIDING windows (covers the slide generalization
    and the deployment seam in one ensemble)."""
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    procs = [_spawn_env_job(p, coord, outs[p], session=False)
             for p in range(NPROC)]
    logs = _wait_all(procs)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]
    got = {}
    by_host = {}
    for host, path in enumerate(outs):
        data = np.load(path)
        for k64, e, v in zip(data["key_id"], data["window_end_ms"],
                             data["value"]):
            key = (int(k64), int(e))
            assert key not in got, f"duplicate emission {key}"
            got[key] = float(v)
            by_host[key] = host
    assert got == J.expected_sliding(NPROC)
    crossed = sum(
        1 for (k, _e), host in by_host.items() if host != k % NPROC
    )
    assert crossed > len(got) // 4




def _collect_rows(outs):
    """Merge per-process npz emissions, asserting cross-host dedup."""
    got = {}
    for path in outs:
        data = np.load(path)
        for k64, e, v in zip(data["key_id"], data["window_end_ms"],
                             data["value"]):
            key = (int(k64), int(e))
            assert key not in got, f"duplicate {key}"
            got[key] = float(v)
    return got

def _run_skew(tmp_path, tag, builder, extra_env=None):
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"{tag}-{p}.npz") for p in range(NPROC)]
    procs = []
    for p in range(NPROC):
        env = _env_for(p)
        env.update(extra_env or {})
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "flink_tpu.runtime.dcn",
             "--coordinator", coord, "--num-processes", str(NPROC),
             "--process-id", str(p), "--builder",
             os.path.join(REPO, "tests", "dcn_jobs.py") + ":" + builder,
             "--out", outs[p]],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        ))
    logs = _wait_all(procs)
    import json as _json

    cycles, stats = None, {}
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]
        for line in log.splitlines():
            if line.startswith("{"):
                row = _json.loads(line)
                cycles = row["cycles"]
                stats[row["pid"]] = row
    return _collect_rows(outs), cycles, stats


def test_rebalance_restores_throughput_on_skewed_hosts(tmp_path):
    """90/10 ingest skew: without rebalance the overfull host's lane
    budget bounds the job (~total_0/B cycles); with the host-level
    rebalance ring the underfull host's spare lanes carry the donor's
    backlog and the cycle count drops to ~total/(nproc*B) — throughput
    parity with a balanced assignment. Results exact either way (ref
    RebalancePartitioner.java:30)."""
    got_plain, cyc_plain, _ = _run_skew(
        tmp_path, "plain", "skewed_window_plain")
    addrs = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    got_reb, cyc_reb, _ = _run_skew(
        tmp_path, "reb", "skewed_window_rebalanced",
        {"FLINK_TPU_TEST_REBALANCE_ADDRS": addrs})
    exp = J.expected_skewed()
    assert got_plain == exp
    assert got_reb == exp
    # parity: the rebalanced run needs close to the balanced-ideal cycle
    # count (0.9 -> ~0.5 of the skewed run's cycles; allow slack for
    # flush/fire cycles)
    assert cyc_reb < 0.7 * cyc_plain, (cyc_reb, cyc_plain)


def test_shuffle_partitioner_balances_skewed_hosts(tmp_path):
    """Physical shuffle (ref ShufflePartitioner.java): the targeted ring
    routes every record to a uniformly random host, so even a 90/10
    partition skew leaves BOTH hosts' lanes carrying an equal share of
    the downstream work — the partitioner's decorrelation contract.
    (Shuffle does NOT drain a skewed SOURCE faster: each host still
    polls its own partition at most one budget per cycle; dynamic
    source borrowing is rebalance's job. The reference's shuffle
    likewise balances downstream subtasks, not upstream production.)
    Results stay exact and cycle count doesn't regress."""
    got_plain, cyc_plain, _ = _run_skew(
        tmp_path, "plain-s", "skewed_window_plain")
    addrs = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    got_shuf, cyc_shuf, stats = _run_skew(
        tmp_path, "shuf", "skewed_window_shuffled",
        {"FLINK_TPU_TEST_REBALANCE_ADDRS": addrs})
    exp = J.expected_skewed()
    assert got_plain == exp
    assert got_shuf == exp
    assert cyc_shuf <= cyc_plain + 3, (cyc_shuf, cyc_plain)
    # uniform routing: each host ingested ~total/nproc despite the
    # 90/10 partition assignment (vs 54000/6000 unshuffled)
    ing = [stats[p]["ingested_local"] for p in range(NPROC)]
    assert sum(ing) == J.SKEW_TOTAL
    share = [x / sum(ing) for x in ing]
    assert all(abs(f - 1 / NPROC) < 0.05 for f in share), share


def test_global_partitioner_routes_everything_to_host0(tmp_path):
    """Physical global (ref GlobalPartitioner.java): every record lands
    on host 0\'s lanes; results stay exact and host 1 ingests nothing —
    the single-subtask semantics, with its bottleneck cost visible."""
    addrs = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    got, _cyc, stats = _run_skew(
        tmp_path, "glob", "skewed_window_global",
        {"FLINK_TPU_TEST_REBALANCE_ADDRS": addrs})
    assert got == J.expected_skewed()
    assert stats[0]["ingested_local"] == sum(
        stats[p]["ingested_local"] for p in range(NPROC))
    assert stats[1]["ingested_local"] == 0


def test_env_execute_shuffle_annotation_is_physical(tmp_path):
    """`.shuffle()` before key_by on the STANDARD env.execute() path
    engages the targeted ring over the DCN plane: the 90/10-skewed
    ingest lands near-uniformly on both hosts' lanes, results exact
    (ref ShufflePartitioner.java routed through the API annotation)."""
    coord = f"127.0.0.1:{_free_port()}"
    addrs = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"se-{p}.npz") for p in range(NPROC)]
    procs = [
        _spawn_env_job(p, coord, outs[p], session=False, shuffle=True,
                       extra_env={"FLINK_TPU_TEST_REBALANCE_ADDRS": addrs})
        for p in range(NPROC)
    ]
    logs = _wait_all(procs)
    ing = {}
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]
        for line in log.splitlines():
            if line.startswith("rows="):
                parts = dict(kv.split("=") for kv in line.split())
                ing[int(parts["pid"])] = int(parts["ingested"])
    got = _collect_rows(outs)
    assert got == J.expected_skewed()
    assert sum(ing.values()) == J.SKEW_TOTAL
    share = [ing[p] / J.SKEW_TOTAL for p in range(NPROC)]
    assert all(abs(f - 1 / NPROC) < 0.05 for f in share), share


def test_two_host_rolling_reduce(tmp_path):
    """Rolling keyed reduce spanning two worker processes (round 5,
    the VERDICT r4 'rolling cannot run multi-host' tail): per-record
    updated aggregates emit from owner shards; the final value per key
    is exact, every record produced exactly one emission, and per-key
    running values are the contiguous 1..count sequence (per-key
    channel order survives the exchange)."""
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    procs = [_spawn_dcn(p, coord, outs[p], "two_host_rolling")
             for p in range(NPROC)]
    logs = _wait_all(procs)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]

    finals, counts, by_host = {}, {}, {}
    per_key_vals = {}
    for host, path in enumerate(outs):
        data = np.load(path)
        for k64, v in zip(data["key_id"], data["value"]):
            k = int(np.int64(np.uint64(k64)))
            finals[k] = max(finals.get(k, 0.0), float(v))
            counts[k] = counts.get(k, 0) + 1
            per_key_vals.setdefault(k, []).append(float(v))
            # a key's aggregate lives on ONE owner shard: all its
            # emissions must come from one host
            assert by_host.setdefault(k, host) == host
    exp = J.expected_rolling(NPROC)
    assert finals == exp
    assert counts == {k: int(v) for k, v in exp.items()}
    # per-key emission order is channel order: values are 1..count
    for k, vals in per_key_vals.items():
        assert vals == [float(i) for i in range(1, len(vals) + 1)], k
    # keys ingested on host A emitting on host B prove the DCN crossing
    crossed = sum(1 for k, h in by_host.items() if h != k % NPROC)
    assert crossed > len(by_host) // 4


def test_two_host_rolling_kill_recover(tmp_path):
    """Kill the rolling ensemble mid-run, restart with --restore: the
    union of emissions is exactly-once (final per-key aggregates and
    per-key emission counts both exact)."""
    ckpt = str(tmp_path / "ckpt")
    os.makedirs(ckpt)
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    extra = ["--checkpoint-dir", ckpt, "--ckpt-every", "2"]

    coord = f"127.0.0.1:{_free_port()}"
    procs = [_spawn_dcn(p, coord, outs[p], "two_host_rolling", extra)
             for p in range(NPROC)]
    deadline = time.time() + 300
    complete = []
    while time.time() < deadline:
        chks = [d for d in os.listdir(ckpt) if d.startswith("chk-")]
        complete = [
            d for d in chks
            if all(os.path.exists(
                os.path.join(ckpt, d, f"proc-{p}.meta.json"))
                for p in range(NPROC))
        ]
        if complete:
            break
        if any(p.poll() is not None for p in procs):
            break
        time.sleep(0.1)
    alive = [p for p in procs if p.poll() is None]
    assert complete, "no complete checkpoint appeared before the kill"
    assert alive, "workers finished before the kill — raise ROLL_TOTAL"
    for p in procs:
        if p.poll() is None:
            p.send_signal(signal.SIGKILL)
    for p in procs:
        p.wait(timeout=60)

    coord2 = f"127.0.0.1:{_free_port()}"
    procs2 = [
        _spawn_dcn(p, coord2, outs[p], "two_host_rolling",
                   extra + ["--restore"])
        for p in range(NPROC)
    ]
    logs = _wait_all(procs2)
    for p, log in zip(procs2, logs):
        assert p.returncode == 0, log[-2000:]

    finals, counts = {}, {}
    for path in outs:
        data = np.load(path)
        for k64, v in zip(data["key_id"], data["value"]):
            k = int(np.int64(np.uint64(k64)))
            finals[k] = max(finals.get(k, 0.0), float(v))
            counts[k] = counts.get(k, 0) + 1
    exp = J.expected_rolling(NPROC)
    assert finals == exp
    assert counts == {k: int(v) for k, v in exp.items()}


def test_two_host_cep(tmp_path):
    """CEP pattern matching spanning two worker processes (round 5 —
    the last 'cannot run multi-host' stage kind): per-key match totals
    equal an independent numpy count-NFA oracle, every key's matches
    emit from exactly one owner host, and keys ingested on host A
    matching on host B prove the DCN crossing."""
    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"out-{p}.npz") for p in range(NPROC)]
    procs = [_spawn_dcn(p, coord, outs[p], "two_host_cep")
             for p in range(NPROC)]
    logs = _wait_all(procs)
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]

    totals, by_host = {}, {}
    for host, path in enumerate(outs):
        data = np.load(path)
        assert int(data["dropped_capacity"]) == 0
        for k64, v in zip(data["key_id"], data["value"]):
            k = int(np.int64(np.uint64(k64)))
            totals[k] = totals.get(k, 0.0) + float(v)
            assert by_host.setdefault(k, host) == host
    exp = J.expected_cep(NPROC)
    exp = {k: v for k, v in exp.items() if v > 0}
    assert totals == exp
    crossed = sum(1 for k, h in by_host.items() if h != k % NPROC)
    assert crossed > len(by_host) // 4
    assert len(set(by_host.values())) == NPROC
