"""Exactly-once against a REAL external process (VERDICT r2 item 6).

A ReplayServer (separate OS process — the Kafka-broker role) serves
partitioned offset-addressable records over TCP. The job consumes via
SocketReplayConsumer, checkpoints periodically, is KILLED mid-stream by an
induced failure, restarts from the latest checkpoint, and the final
keyed-window sums must be exactly right — no loss, no duplication —
through offset restore + notify-complete commit.

Ref: FlinkKafkaConsumerBase.java:336 (snapshotState),
:384 (notifyCheckpointComplete).
"""

import json
import os
import subprocess
import sys

import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.connectors.socket_replay import (
    ReplayServer, SocketReplayConsumer, gen_partition_records,
)
from flink_tpu.runtime.sinks import CollectSink

N_PARTS, TOTAL, SEED = 3, 6000, 7


@pytest.fixture
def server_proc(tmp_path):
    commit_file = str(tmp_path / "commits.json")
    proc = subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.connectors.socket_replay",
         "--port", "0", "--partitions", str(N_PARTS),
         "--records", str(TOTAL), "--seed", str(SEED),
         "--commit-file", commit_file],
        stdout=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    line = proc.stdout.readline().strip()
    assert line.startswith("READY "), line
    port = int(line.split()[1])
    yield proc, port, commit_file
    proc.kill()
    proc.wait()


def _collect_sums(results):
    got = {}
    for r in results:
        got[(r.key, r.window_end_ms)] = got.get(
            (r.key, r.window_end_ms), 0
        ) + r.value
    return got


def _expected_sums():
    exp = {}
    for p in range(N_PARTS):
        for k, v, t in gen_partition_records(SEED, p, 0, TOTAL, TOTAL):
            w = (t // 5000 + 1) * 5000
            exp[(k, w)] = exp.get((k, w), 0) + v
    return exp


class FailOnceSink(CollectSink):
    """Dies once after `fail_after` invocations (induced mid-stream kill);
    snapshot/restore carries the collected results for exactly-once."""

    def __init__(self, fail_after: int):
        super().__init__()
        self.fail_after = fail_after
        self.failed = False
        self.invocations = 0

    def invoke_batch(self, elements):
        self.invocations += 1
        if not self.failed and self.invocations > self.fail_after:
            self.failed = True
            raise RuntimeError("injected sink failure")
        super().invoke_batch(elements)

    def snapshot_state(self):
        return list(self.results)

    def restore_state(self, state):
        self.results = list(state)


def test_kill_and_restart_job_exactly_once(server_proc, tmp_path):
    proc, port, commit_file = server_proc

    cfg = Configuration()
    cfg.set("restart-strategy", "fixed-delay")
    cfg.set("restart-strategy.fixed-delay.attempts", 3)
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(256)
    env.batch_size = 256
    env.checkpoint_dir = str(tmp_path / "ck")
    env.checkpoint_interval_steps = 3

    src = SocketReplayConsumer("127.0.0.1", port)
    sink = FailOnceSink(fail_after=2)
    (
        env.add_source(src)
        .assign_timestamps_and_watermarks(lambda e: e[2])
        .key_by(lambda e: e[0])
        .time_window(5000)
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    job = env.execute("exactly-once-external")
    assert sink.failed, "the induced failure never fired"
    assert job.metrics.restarts >= 1

    assert _collect_sums(sink.results) == _expected_sums()

    # offsets were committed to the external broker only at checkpoint
    # completion; the commit file is the broker's durable record
    with open(commit_file) as f:
        committed = json.load(f)
    assert committed["cid"] >= 1
    assert all(0 < o <= TOTAL for o in committed["offsets"].values())
    src.close()


def test_broker_restart_mid_job_reconnects(tmp_path):
    """Kill and restart the SERVER mid-job: deterministic fetch + client
    reconnect resume the stream with exact results."""
    srv = ReplayServer(N_PARTS, TOTAL, SEED, port=0)
    port = srv.start()

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(256)
    env.batch_size = 256

    class RestartingConsumer(SocketReplayConsumer):
        polls = 0

        def poll(self, max_records):
            RestartingConsumer.polls += 1
            if RestartingConsumer.polls == 5:
                # replace the broker between polls: same data (seeded),
                # same port — the client must reconnect transparently
                nonlocal_srv["old"].stop()
                new = ReplayServer(N_PARTS, TOTAL, SEED, port=port)
                new.start()
                nonlocal_srv["old"] = new
            return super().poll(max_records)

    nonlocal_srv = {"old": srv}
    src = RestartingConsumer("127.0.0.1", port)
    sink = CollectSink()
    (
        env.add_source(src)
        .assign_timestamps_and_watermarks(lambda e: e[2])
        .key_by(lambda e: e[0])
        .time_window(5000)
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    env.execute("broker-restart")
    assert _collect_sums(sink.results) == _expected_sums()
    src.close()
    nonlocal_srv["old"].stop()
