"""Round-4 drain/pipeline fast paths stay semantics-preserving:

* the ReducedFires drain (device-reduced fire step, no key/value packing)
  produces the same totals as the full CompactFires drain,
* the prep-half prefetch thread (pipeline.prefetch) changes no results,
* the bounded in-flight step depth (pipeline.max-inflight-steps) changes
  no results.

Mirrors the reference's approach of testing the WindowOperator emission
path against per-record expectations (SURVEY §4; WindowOperatorTest).
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink, CountingSink
from flink_tpu.runtime.sources import GeneratorSource

B, N_KEYS, TOTAL, TS_DIV, WIN = 256, 300, 256 * 40, 64, 40


def _gen(offset, n):
    idx = np.arange(offset, offset + n, dtype=np.int64)
    keys = (idx * 7) % N_KEYS
    return {"key": keys, "value": np.ones(n, np.float32)}, idx // TS_DIV


def _run(sink, **cfg):
    env = StreamExecutionEnvironment(Configuration(cfg))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(N_KEYS)
    env.batch_size = B
    (
        env.add_source(GeneratorSource(_gen, total=TOTAL))
        .key_by(lambda c: c["key"])
        .time_window(WIN)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    return env.execute("fast-drain")


def _expected_windows():
    exp = {}
    for i in range(TOTAL):
        k, w = (i * 7) % N_KEYS, ((i // TS_DIV) // WIN + 1) * WIN
        exp[(k, w)] = exp.get((k, w), 0) + 1.0
    return exp


def test_reduced_drain_matches_full_drain():
    exp = _expected_windows()
    # CountingSink is device_reduce -> ReducedFires drain
    counting = CountingSink()
    job = _run(counting)
    assert counting.count == len(exp)
    assert counting.value_sum == sum(exp.values())
    assert job.metrics.fires == len(exp)
    # CollectSink is not device_reduce -> full CompactFires drain
    collect = CollectSink()
    _run(collect)
    got = {}
    for r in collect.results:
        got[(r.key, r.window_end_ms)] = got.get((r.key, r.window_end_ms),
                                                0) + r.value
    assert got == exp


@pytest.mark.parametrize("cfg", [
    {"pipeline.prefetch": "off"},
    {"pipeline.prefetch": "on"},
    {"pipeline.max-inflight-steps": 1},
])
def test_pipeline_knobs_preserve_results(cfg):
    sink = CountingSink()
    _run(sink, **cfg)
    exp = _expected_windows()
    assert sink.count == len(exp)
    assert sink.value_sum == sum(exp.values())


def test_prefetch_on_with_checkpointing_preserves_results(tmp_path):
    """pipeline.prefetch=on + checkpointing no longer raises (ISSUE 3):
    the epoch-tagged ingest pipeline snapshots the APPLIED-offset cut,
    so running ahead of the source is checkpoint-compatible. Results
    must stay exact with checkpoints being written throughout."""
    env = StreamExecutionEnvironment(
        Configuration({"pipeline.prefetch": "on"})
    )
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(N_KEYS)
    env.batch_size = B
    env.enable_checkpointing(interval_steps=5, directory=str(tmp_path))
    sink = CountingSink()
    (
        env.add_source(GeneratorSource(_gen, total=TOTAL))
        .key_by(lambda c: c["key"])
        .time_window(WIN)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("prefetch-with-ckpt")
    exp = _expected_windows()
    assert sink.count == len(exp)
    assert sink.value_sum == sum(exp.values())
    # checkpoints actually happened while prefetching ran ahead
    assert (env.last_job.metrics.checkpoint_stats or [])
