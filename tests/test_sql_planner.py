"""Rule-driven logical planner (table/planner.py — the
FlinkPlannerImpl.scala:46 seam): plan-diff tests per rewrite rule plus
optimized/unoptimized result equivalence."""

import re

import numpy as np
import pytest

from flink_tpu.table.table import TableEnvironment


def _env():
    tenv = TableEnvironment.create()
    rng = np.random.default_rng(5)
    n = 2000
    tenv.register_table("orders", tenv.from_columns({
        "oid": np.arange(n),
        "cust": rng.integers(0, 50, n),
        "amount": rng.uniform(1.0, 100.0, n).round(2),
        "region": rng.integers(0, 4, n),
        "pad1": np.zeros(n), "pad2": np.zeros(n), "pad3": np.zeros(n),
    }))
    tenv.register_table("customers", tenv.from_columns({
        "cust": np.arange(50),
        "credit": rng.uniform(10.0, 90.0, 50).round(2),
        "tier": rng.integers(1, 4, 50),
        "pad4": np.zeros(50),
    }))
    return tenv


def _rows(t):
    return sorted(map(tuple, t.to_rows()), key=repr)


def _probe_rows(plan_lines):
    for ln in plan_lines:
        m = re.search(r"probe=(\d+) rows", ln)
        if m:
            return int(m.group(1))
    raise AssertionError(f"no HashJoin in {plan_lines}")


def test_filter_pushdown_shrinks_join_probe():
    """A WHERE conjunct on one join side moves below the join: the probe
    input shrinks from 2000 rows to the filtered count, and results are
    identical to the unoptimized plan."""
    tenv = _env()
    q = ("SELECT oid, tier FROM orders JOIN customers "
         "ON orders.cust = customers.cust WHERE amount > 90.0")
    p_opt, p_raw = [], []
    t_opt = tenv.sql_query(q, _plan=p_opt)
    t_raw = tenv.sql_query(q, _plan=p_raw, optimize=False)
    assert _rows(t_opt) == _rows(t_raw)
    assert _probe_rows(p_opt) < _probe_rows(p_raw)
    assert _probe_rows(p_raw) == 2000
    plan = tenv.explain(q)
    assert "FilterPushdown" in plan
    # optimized tree: Filter sits under the Join, above the orders scan
    opt_section = plan.split("== Optimized Logical Plan ==")[1]
    assert opt_section.index("Join(") < opt_section.index("Filter(")


def test_filter_pushdown_splits_conjuncts_both_sides():
    tenv = _env()
    q = ("SELECT oid FROM orders JOIN customers "
         "ON orders.cust = customers.cust "
         "WHERE amount > 50.0 AND tier = 2 AND oid + tier > 0")
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert _rows(t_opt) == _rows(t_raw)
    plan = tenv.explain(q)
    opt = plan.split("== Optimized Logical Plan ==")[1].split("applied")[0]
    # both single-side conjuncts pushed below the join; the cross-side
    # conjunct stays above it
    join_at = opt.index("Join(")
    assert opt.index("Filter(amount > 50.0") > join_at
    assert opt.index("Filter(tier = 2") > join_at
    assert opt.index("Filter(oid + tier > 0") < join_at


def test_outer_join_pushdown_legality():
    """LEFT join: left-side predicates commute with null-extension and
    push; right-side predicates must NOT (they would drop the
    null-extended rows a WHERE keeps visible for filtering)."""
    tenv = TableEnvironment.create()
    tenv.register_table("a", tenv.from_columns({
        "k": [1, 2, 3], "x": [10.0, 20.0, 30.0]}))
    tenv.register_table("b", tenv.from_columns({
        "k": [1, 9], "y": [5.0, 6.0]}))
    q = "SELECT k, x FROM a LEFT JOIN b ON a.k = b.k WHERE x > 15.0"
    assert _rows(tenv.sql_query(q)) == _rows(
        tenv.sql_query(q, optimize=False))
    opt = tenv.explain(q).split("== Optimized Logical Plan ==")[1]
    assert opt.index("Join(") < opt.index("Filter(")   # pushed

    # right-side predicate on a LEFT join: the rule must refuse (plan
    # level — filtering a null-extended column is a separate limitation)
    from flink_tpu.table import planner as pl

    m = tenv._SQL.match(
        "SELECT k, x FROM a LEFT JOIN b ON a.k = b.k WHERE y > 5.5")
    root, rules = pl.optimize(tenv._build_logical(m))
    assert "FilterPushdown" not in rules
    assert isinstance(root, pl.LProject)
    assert isinstance(root.input, pl.LFilter)           # still above
    assert isinstance(root.input.input, pl.LJoin)


def test_constant_filter_true_drops_and_false_empties():
    tenv = _env()
    q = "SELECT oid FROM orders WHERE 1 = 1 AND amount > 95.0"
    plan = tenv.explain(q)
    assert "ConstantFilter" in plan
    opt = plan.split("== Optimized Logical Plan ==")[1]
    assert "1 = 1" not in opt
    assert _rows(tenv.sql_query(q)) == _rows(
        tenv.sql_query(q, optimize=False))

    q2 = ("SELECT oid, tier FROM orders JOIN customers "
          "ON orders.cust = customers.cust WHERE 1 = 0")
    p2 = []
    t2 = tenv.sql_query(q2, _plan=p2)
    assert t2.n == 0
    # both scans under the false filter run emptied: the join is free
    assert any("orders, 0 rows" in ln for ln in p2)
    assert any("customers, 0 rows" in ln for ln in p2)


def test_column_pruning_narrows_scans():
    tenv = _env()
    q = ("SELECT oid, tier FROM orders JOIN customers "
         "ON orders.cust = customers.cust")
    plan = tenv.explain(q)
    assert "ColumnPruning" in plan
    opt = plan.split("== Optimized Logical Plan ==")[1]
    # pad columns never referenced -> not materialized
    assert "pad1" not in opt and "pad4" not in opt
    m = re.search(r"Scan\(orders, cols=\[([^\]]*)\]", opt)
    assert m and set(re.findall(r"'(\w+)'", m.group(1))) == {
        "oid", "cust"}
    assert _rows(tenv.sql_query(q)) == _rows(
        tenv.sql_query(q, optimize=False))


def test_pruning_preserves_clash_naming():
    """Pruning must not un-clash a renamed right column: r_credit keeps
    meaning the RIGHT side's credit even when the left copy is unused."""
    tenv = TableEnvironment.create()
    tenv.register_table("l", tenv.from_columns({
        "k": [1, 2], "credit": [100.0, 200.0], "unused": [0.0, 0.0]}))
    tenv.register_table("r", tenv.from_columns({
        "k": [1, 2], "credit": [7.0, 8.0]}))
    q = "SELECT k, r_credit FROM l JOIN r ON l.k = r.k ORDER BY k"
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert t_opt.to_rows() == t_raw.to_rows() == [(1, 7.0), (2, 8.0)]


def test_aggregate_query_prunes_and_matches():
    tenv = _env()
    q = ("SELECT region, SUM(amount) AS total FROM orders "
         "WHERE amount > 10.0 GROUP BY region ORDER BY region")
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert t_opt.to_rows() == t_raw.to_rows()
    opt = tenv.explain(q).split("== Optimized Logical Plan ==")[1]
    m = re.search(r"Scan\(orders, cols=\[([^\]]*)\]", opt)
    assert m and set(re.findall(r"'(\w+)'", m.group(1))) == {
        "region", "amount"}


def test_select_star_is_never_pruned():
    tenv = _env()
    q = "SELECT * FROM orders WHERE amount > 99.0"
    plan = tenv.explain(q)
    assert "ColumnPruning" not in plan
    t = tenv.sql_query(q)
    assert set(t.schema) == {"oid", "cust", "amount", "region",
                             "pad1", "pad2", "pad3"}


def test_string_literal_with_and_survives_conjunct_split():
    tenv = TableEnvironment.create()
    tenv.register_table("t", tenv.from_columns({
        "name": ["x AND y", "z"], "v": [1.0, 2.0]}))
    t = tenv.sql_query("SELECT v FROM t WHERE name = 'x AND y' AND v > 0.5")
    assert t.to_rows() == [(1.0,)]


def test_planner_benchmark_query_improves():
    """The benchmark query (selective filter + wide join): the optimized
    plan probes an order of magnitude fewer rows AND runs measurably
    faster on a scaled-up input (wall-clock sanity, generous margin)."""
    import time

    tenv = TableEnvironment.create()
    rng = np.random.default_rng(9)
    n = 200_000
    tenv.register_table("facts", tenv.from_columns({
        "k": rng.integers(0, 1000, n),
        "v": rng.uniform(0, 100, n),
        **{f"w{i}": np.zeros(n) for i in range(8)},
    }))
    tenv.register_table("dims", tenv.from_columns({
        "k": np.arange(1000), "label": np.arange(1000) % 7,
        **{f"d{i}": np.zeros(1000) for i in range(4)},
    }))
    q = ("SELECT k, label FROM facts JOIN dims ON facts.k = dims.k "
         "WHERE v > 99.0")
    p_opt, p_raw = [], []
    t0 = time.perf_counter()
    t_opt = tenv.sql_query(q, _plan=p_opt)
    t_o = time.perf_counter() - t0
    t0 = time.perf_counter()
    t_raw = tenv.sql_query(q, _plan=p_raw, optimize=False)
    t_r = time.perf_counter() - t0
    assert _rows(t_opt) == _rows(t_raw)
    assert _probe_rows(p_raw) == n
    assert _probe_rows(p_opt) < n // 50      # ~1% selectivity
    # generous wall-clock check (1.5x slack for loaded CI machines): the
    # deterministic proof is the probe-row assertion above
    assert t_o < t_r * 1.5, (t_o, t_r)


def test_pushdown_rename_spares_string_literals():
    """Regression: pushing a right-side conjunct rewrites r_X column refs
    to X but must NOT touch a string literal that happens to read
    'r_<clash>'."""
    tenv = TableEnvironment.create()
    tenv.register_table("l", tenv.from_columns({
        "k": [1, 2], "credit": [100.0, 200.0]}))
    tenv.register_table("r", tenv.from_columns({
        "k": [1, 2], "credit": [7.0, 8.0],
        "name": ["r_credit", "credit"]}))
    q = ("SELECT k, name FROM l JOIN r ON l.k = r.k "
         "WHERE name = 'r_credit'")
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert t_opt.to_rows() == t_raw.to_rows() == [(1, "r_credit")]


def test_top_level_or_not_severed():
    """Regression: `A OR B AND C` is A OR (B AND C) — a top-level
    un-parenthesized OR means the WHERE is not a conjunction, so the
    planner must keep it whole (severing 'C' changed results)."""
    tenv = TableEnvironment.create()
    tenv.register_table("t", tenv.from_columns({
        "a": [1, 0, 0], "b": [0, 2, 0], "c": [0, 3, 0],
        "v": [1.0, 2.0, 3.0]}))
    q = "SELECT v FROM t WHERE a = 1 OR b = 2 AND c = 3"
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert t_opt.to_rows() == t_raw.to_rows() == [(1.0,), (2.0,)]
    # parenthesized OR operands still split into two conjuncts
    from flink_tpu.table import planner as pl

    assert pl.split_conjuncts("(a = 1 OR b = 2) AND c = 3") == [
        "(a = 1 OR b = 2)", "c = 3"]
    assert pl.split_conjuncts("a = 1 OR b = 2 AND c = 3") == [
        "a = 1 OR b = 2 AND c = 3"]


# ----------------------------------------------------------------- HAVING

def test_having_filters_aggregates():
    tenv = _env()
    q = ("SELECT region, SUM(amount) AS total FROM orders "
         "GROUP BY region HAVING total > 20000.0 ORDER BY region")
    t = tenv.sql_query(q)
    raw = tenv.sql_query(
        "SELECT region, SUM(amount) AS total FROM orders "
        "GROUP BY region ORDER BY region")
    expect = [r for r in map(tuple, raw.to_rows()) if r[1] > 20000.0]
    assert list(map(tuple, t.to_rows())) == expect
    assert t.n > 0


def test_having_on_group_key_pushes_below_aggregate():
    """A HAVING conjunct on the group key selects whole groups: the
    planner moves it below the aggregate, shrinking its input; mixed
    conjuncts split."""
    tenv = _env()
    q = ("SELECT region, SUM(amount) AS total FROM orders "
         "GROUP BY region HAVING region > 1 AND total > 0.0")
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert sorted(map(tuple, t_opt.to_rows())) == sorted(
        map(tuple, t_raw.to_rows()))
    plan = tenv.explain(q)
    assert "HavingPushdown" in plan
    opt = plan.split("== Optimized Logical Plan ==")[1].split("applied")[0]
    agg_at = opt.index("Aggregate(")
    assert opt.index("Filter(region > 1") > agg_at     # below: pushed
    assert opt.index("Filter(total > 0.0") < agg_at    # above: stays


def test_having_requires_group_by_and_aliased_aggregates():
    tenv = _env()
    with pytest.raises(ValueError, match="HAVING requires GROUP BY"):
        tenv.sql_query("SELECT oid FROM orders HAVING oid > 1")
    with pytest.raises(ValueError, match="alias the aggregate"):
        tenv.sql_query(
            "SELECT region FROM orders GROUP BY region "
            "HAVING SUM(amount) > 10.0")


def test_having_alias_shadowing_key_not_pushed():
    """Regression: `SUM(amount) AS region` shadows the group key name —
    HAVING region filters the SUM, so the conjunct must NOT push below
    the aggregate."""
    tenv = _env()
    q = ("SELECT SUM(amount) AS region FROM orders "
         "GROUP BY region HAVING region > 3")
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert sorted(map(tuple, t_opt.to_rows())) == sorted(
        map(tuple, t_raw.to_rows()))
    assert t_opt.n > 0
    assert "HavingPushdown" not in tenv.explain(q)


def test_limit_pushes_below_projection():
    """LIMIT under a scalar projection evaluates expressions for only
    the surviving rows; a global-aggregate projection must see every row
    and is never reordered."""
    tenv = _env()
    q = "SELECT oid, amount * 2.0 AS dbl FROM orders LIMIT 5"
    t_opt = tenv.sql_query(q)
    t_raw = tenv.sql_query(q, optimize=False)
    assert t_opt.to_rows() == t_raw.to_rows() and t_opt.n == 5
    plan = tenv.explain(q)
    assert "LimitPushdown" in plan
    opt = plan.split("== Optimized Logical Plan ==")[1].split("applied")[0]
    assert opt.index("Project(") < opt.index("Limit(")   # limit below

    # global aggregation: LIMIT stays above (one row FROM all inputs)
    q2 = "SELECT SUM(amount) AS total FROM orders LIMIT 5"
    t2 = tenv.sql_query(q2)
    r2 = tenv.sql_query(q2, optimize=False)
    assert t2.to_rows() == r2.to_rows() and t2.n == 1
    assert "LimitPushdown" not in tenv.explain(q2)
