"""Shipped-configuration loading and precedence (ref GlobalConfiguration
+ config.sh: flink-conf.yaml defaults under program/flag overrides).

conf/flink-tpu-conf.yaml loads from $FLINK_TPU_CONF_DIR; the
environment layers it UNDER the program's explicit Configuration, and
the controller/CLI mains read port/HA/security defaults from it."""

import os

import pytest

from flink_tpu.core.config import Configuration, load_global_configuration


@pytest.fixture
def conf_dir(tmp_path, monkeypatch):
    d = tmp_path / "conf"
    d.mkdir()
    (d / "flink-tpu-conf.yaml").write_text(
        "# comment line\n"
        "parallelism.default: 4\n"
        "controller.rpc.port: 7123\n"
        "execution.micro-batch-size: 1024   # trailing comment\n"
        "security.auth.token: sekrit\n"
        "state.backend.strict-capacity: false\n"
    )
    monkeypatch.setenv("FLINK_TPU_CONF_DIR", str(d))
    return d


def test_load_global_configuration_parses_flat_yaml(conf_dir):
    cfg = load_global_configuration()
    assert cfg.get_int("parallelism.default", 0) == 4
    assert cfg.get_int("controller.rpc.port", 0) == 7123
    assert cfg.get_int("execution.micro-batch-size", 0) == 1024
    assert cfg.get_str("security.auth.token") == "sekrit"
    assert cfg.get_bool("state.backend.strict-capacity", True) is False


def test_unset_conf_dir_loads_empty(monkeypatch):
    monkeypatch.delenv("FLINK_TPU_CONF_DIR", raising=False)
    assert load_global_configuration().to_dict() == {}


def test_environment_layers_global_under_explicit(conf_dir):
    from flink_tpu import StreamExecutionEnvironment

    # conf default applies when the program says nothing
    env = StreamExecutionEnvironment.get_execution_environment()
    assert env.parallelism == 4
    assert env.batch_size == 1024
    # the program's explicit Configuration wins over the conf file
    env2 = StreamExecutionEnvironment(
        Configuration({"parallelism.default": 2})
    )
    assert env2.parallelism == 2
    assert env2.batch_size == 1024          # untouched key still from conf


def test_cli_default_port_honors_conf(conf_dir):
    from flink_tpu.cli import _addr

    assert _addr("somehost") == ("somehost", 7123)
    assert _addr("somehost:9999") == ("somehost", 9999)   # explicit wins


def test_controller_main_reads_conf_defaults(conf_dir, monkeypatch):
    """The controller main resolves port/token from the conf file with
    flags still winning — checked at the argparse/constructor seam
    rather than by binding a real port 7123."""
    import flink_tpu.runtime.process_cluster as pc

    captured = {}

    class FakeCluster:
        def __init__(self, **kw):
            captured.update(kw)
            raise SystemExit(0)    # stop before serving

    monkeypatch.setattr(pc, "ProcessCluster", FakeCluster)
    with pytest.raises(SystemExit):
        pc.main([])
    assert captured["auth_token"] == "sekrit"

# -- typed coercion (ADVICE r5: coerce from the DECLARED type, name the
# -- key on parse failure, reject unrecognized boolean strings) ---------

def test_option_with_none_default_still_coerces_by_declared_type():
    from flink_tpu.core.config import ConfigOption

    opt = ConfigOption("some.count", None, type=int)
    assert Configuration({"some.count": "42"}).get(opt) == 42
    assert Configuration().get(opt) is None


def test_parse_failure_names_the_config_key():
    from flink_tpu.core.config import ConfigOption

    opt = ConfigOption("parallelism.default", 1)
    with pytest.raises(ValueError, match="parallelism.default"):
        Configuration({"parallelism.default": "zippy"}).get(opt)
    fopt = ConfigOption("checkpoint.timeout", 600.0)
    with pytest.raises(ValueError, match="checkpoint.timeout"):
        Configuration({"checkpoint.timeout": "soon"}).get(fopt)


def test_unrecognized_boolean_strings_rejected():
    from flink_tpu.core.config import ConfigOption

    opt = ConfigOption("checkpoint.async", False)
    with pytest.raises(ValueError, match="checkpoint.async"):
        Configuration({"checkpoint.async": "maybe"}).get(opt)
    assert Configuration({"checkpoint.async": "on"}).get(opt) is True
    assert Configuration({"checkpoint.async": "OFF"}).get(opt) is False
    assert Configuration({"checkpoint.async": "1"}).get(opt) is True


def test_bool_option_not_coerced_via_int_and_default_kept():
    from flink_tpu.core.config import ConfigOption

    # a bool-typed option must never fall into int("true") territory,
    # and non-string values pass through untouched
    opt = ConfigOption("watchdog.enabled", True)
    assert Configuration({"watchdog.enabled": "false"}).get(opt) is False
    assert Configuration({"watchdog.enabled": False}).get(opt) is False
    assert Configuration().get(opt) is True
