"""Elastic survival (ISSUE 8): shard-loss degraded mode, rescaled
recovery onto surviving devices, and the scale-back-up edge.

The e2e tests drive a real windowed job through an injected
``device_loss`` fault (testing/faults.py) and assert the job RE-PLANS
at reduced parallelism — re-sliced key-group ranges, rebuilt mesh +
compiled step family, rescaled restore from the last durable cut —
with the exactly-once oracle intact across the whole
kill -> degraded run -> scale-back cycle. The library-level property
tests pin the N->M->N rescale round-trip over the state-layout matrix,
and the local-cache tests pin the satellite regressions (cache reads
are parallelism-agnostic; prune follows the chain closure)."""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.checkpointing.local import LocalSnapshotCache
from flink_tpu.core.config import Configuration
from flink_tpu.core.keygroups import (
    assign_to_key_group,
    key_group_range_for_operator,
)
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import route_hash
from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.runtime import checkpoint as ckpt
from flink_tpu.runtime.checkpoint import CheckpointStorage
from flink_tpu.runtime.elastic import (
    DeviceLostError,
    ElasticCapacityError,
    ElasticityController,
    as_device_loss,
    plan_survivors,
)
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.runtime.step import WindowStageSpec
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, device_loss_rule

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


ELASTIC_CFG = {
    "checkpoint.mode": "incremental",
    "checkpoint.async": True,
    "checkpoint.local.enabled": True,
    "pipeline.prefetch": "on",
    "restart-strategy": "exponential-backoff",
    "restart-strategy.exponential-backoff.initial-delay": 0.01,
    "restart-strategy.exponential-backoff.max-delay": 0.05,
}


def build_env(parallelism, ckpt_dir=None, interval=0, **cfg):
    conf = Configuration(cfg)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("elastic-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


# ----------------------------------------------------- classification

def test_classification_and_survivor_planning():
    from flink_tpu.runtime import dcn
    from flink_tpu.runtime.executor import classify_failure

    loss = DeviceLostError("chip 3 gone", lost_shards=(3,))
    assert classify_failure(loss) == "device-loss"
    assert as_device_loss(loss) is loss
    # DCN peer exhaustion IS device loss (the peer's mesh segment died)
    assert classify_failure(dcn.DCNPeerLostError("peer 2")) == \
        "device-loss"
    # plain transients stay transient; unknowns stay state-corrupting
    assert classify_failure(ConnectionError("blip")) == "transient"
    assert classify_failure(RuntimeError("???")) == "state-corrupting"
    # a marker-matched runtime error classifies (probe finds every CPU
    # device healthy, so the casualty list stays empty -> the recovery
    # path falls back to a same-mesh full restore)
    class XlaRuntimeError(RuntimeError):
        pass

    dl = as_device_loss(
        XlaRuntimeError("DEVICE_LOST: core halted"),
        devices=jax.devices()[:2],
    )
    assert dl is not None and dl.lost_devices == ()
    assert as_device_loss(XlaRuntimeError("shape mismatch")) is None
    # survivor planning resolves shard indices against mesh order
    devs = list(jax.devices()[:4])
    surv, lost = plan_survivors(devs, DeviceLostError("x", lost_shards=(1,)))
    assert surv == [devs[0], devs[2], devs[3]] and lost == [devs[1]]
    # duplicate attribution (shard index AND device object) is one loss
    surv, lost = plan_survivors(
        devs, DeviceLostError("x", lost_shards=(1,),
                              lost_devices=(devs[1],)),
    )
    assert len(lost) == 1 and len(surv) == 3


def test_watchdog_trip_with_healthy_devices_is_not_device_loss():
    """A device-wait watchdog trip only classifies as device loss when
    the health probe finds a casualty — on the (healthy) CPU mesh it
    must stay a plain watchdog trip (warm-restartable)."""
    from flink_tpu.runtime.watchdog import WatchdogError

    exc = WatchdogError("fire", 1.0, 0.5)
    assert as_device_loss(exc, devices=jax.devices()[:2]) is None


# ------------------------------------------------- degraded-mode e2e

def test_device_loss_recovers_degraded(tmp_path):
    """Losing 1 of 2 shards mid-stream re-plans the job at parallelism
    1 (re-sliced ranges, rebuilt kernels, rescaled restore) and the
    results stay exactly-once equal to the unfaulted oracle."""
    env = build_env(2, tmp_path / "chk", interval=2, **ELASTIC_CFG)
    inj = FaultInjector([device_loss_rule(shard=1, at=8)])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    assert env.last_job.metrics.restarts == 1
    assert env.last_job.ctx.n_shards == 1      # finished degraded
    rep = env._recovery_report()
    ok = [a for a in rep["attempts"] if a["ok"]]
    assert ok and ok[-1]["classification"] == "device-loss"
    assert ok[-1]["mode"] == "rescale-1of2"
    assert ok[-1]["rescale"] == {"from_shards": 2, "to_shards": 1}
    # the elastic phases are stamped alongside the PR 6 tiers
    for phase in ("reslice", "rescale_restore", "fetch", "stage"):
        assert phase in ok[-1]["phases_ms"], phase
    assert ok[-1]["first_fire_ms"] and ok[-1]["first_fire_ms"] > 0
    assert rep["counts"]["rescales"] == 1
    assert rep["counts"]["degraded_shards"] == 1
    el = env._elasticity_report()
    assert el["degraded"] is True and el["current-shards"] == 1
    assert el["lost-devices"] and el["rescales"][0]["kind"] == "degrade"
    assert el["rescales"][0]["mttr_ms"] > 0


def test_device_loss_under_fused_dispatch(tmp_path):
    """The same loss injected at a K-fused megastep dispatch
    (pipeline.steps-per-dispatch > 1): pending fused groups and lagged
    resident-pipeline fire payloads die with the failed epoch and the
    rescaled replay reproduces them exactly-once."""
    env = build_env(2, tmp_path / "chk", interval=2, **{
        **ELASTIC_CFG, "pipeline.steps-per-dispatch": 4,
    })
    inj = FaultInjector([device_loss_rule(shard=0, at=6)])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    assert env.last_job.ctx.n_shards == 1
    el = env._elasticity_report()
    assert el["degraded"] is True and el["degraded-shards"] == 1


def test_scale_back_up_restores_capacity(tmp_path):
    """The reverse edge: once degraded, an operator scale-up request is
    serviced at a cycle boundary as a savepoint-cut live rescale back
    to full capacity — no restart, exactly-once across the whole
    lose-one -> degraded -> scale-back cycle."""
    env = build_env(2, tmp_path / "chk", interval=2, **ELASTIC_CFG)
    total = 12288

    def scale_up_when_degraded():
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            ctl = getattr(env, "_elastic_controller", None)
            if ctl is not None and ctl.degraded:
                time.sleep(0.3)    # run degraded for a few cycles
                ctl.request_scale_up()
                return
            time.sleep(0.02)

    t = threading.Thread(target=scale_up_when_degraded, daemon=True)
    t.start()
    inj = FaultInjector([device_loss_rule(shard=1, at=8)])
    with faults.active(inj):
        got = run_job(env, total)
    t.join(timeout=5)
    assert got == expected(total)
    el = env._elasticity_report()
    assert el["degraded"] is False and el["current-shards"] == 2
    kinds = [r["kind"] for r in el["rescales"]]
    assert kinds == ["degrade", "scale_up"]
    assert el["rescales"][-1]["mttr_ms"] > 0
    assert env.last_job.ctx.n_shards == 2      # finished at capacity
    # degraded_shards gauge went back to 0
    assert env._recovery_report()["counts"]["degraded_shards"] == 0


def test_min_shards_gate_fails_instead_of_degrading(tmp_path):
    """recovery.min-shards: survivors below the floor FAIL the job
    (ElasticCapacityError) instead of re-planning — and the error is
    not retried (retrying cannot grow the mesh)."""
    env = build_env(2, tmp_path / "chk", interval=2, **{
        **ELASTIC_CFG, "recovery.min-shards": 2,
    })
    inj = FaultInjector([device_loss_rule(shard=1, at=8)])
    with faults.active(inj):
        with pytest.raises(ElasticCapacityError, match="min-shards"):
            run_job(env, 6144)


def test_elastic_disabled_takes_full_restore(tmp_path):
    """recovery.elastic: false — device loss takes the ordinary full
    restore at the ORIGINAL parallelism (on the simulated mesh the
    device still works; on real hardware this is the crash-loop the
    elastic path exists to avoid)."""
    env = build_env(2, tmp_path / "chk", interval=2, **{
        **ELASTIC_CFG, "recovery.elastic": False,
    })
    inj = FaultInjector([device_loss_rule(shard=1, at=8)])
    with faults.active(inj):
        got = run_job(env, 6144)
    assert got == expected(6144)
    rep = env._recovery_report()
    ok = [a for a in rep["attempts"] if a["ok"]]
    assert ok and ok[-1]["mode"] == "full"
    assert rep["counts"]["rescales"] == 0
    assert env.last_job.ctx.n_shards == 2


# ------------------------------------- N->M->N rescale property tests

def _mk_ctx(n):
    return MeshContext.create(n, 128, devices=jax.devices()[:n])


def _mk_spec(layout, packed, overflow=0):
    red = wk.ReduceSpec("sum", jnp.float32)
    win = wk.WindowSpec(size_ticks=1000, slide_ticks=1000, ring=8,
                        fires_per_step=2, overflow=overflow)
    return WindowStageSpec(win=win, red=red, capacity_per_shard=64,
                           layout=layout, packed=packed)


def _mk_entries(rng, layout, n=48):
    """Unique (key, pane) logical entries valid for the layout."""
    if layout == "direct":
        hi = np.zeros(n, np.uint32)
        lo = rng.integers(0, 64, n).astype(np.uint32)
    else:
        hi = rng.integers(0, 2**32, n, dtype=np.int64).astype(np.uint32)
        lo = rng.integers(0, 2**32, n, dtype=np.int64).astype(np.uint32)
    pane = rng.integers(0, 6, n).astype(np.int32)
    comp = (hi.astype(np.uint64) << np.uint64(32)) | lo
    _, first = np.unique(
        np.stack([comp, pane.astype(np.uint64)], 1), axis=0,
        return_index=True,
    )
    sel = np.sort(first)
    return {
        "key_hi": hi[sel], "key_lo": lo[sel], "pane": pane[sel],
        "value": rng.uniform(1, 9, len(sel)).astype(np.float32),
        "fresh": rng.integers(0, 2, len(sel)).astype(bool),
    }


def _canon(entries):
    comp = (
        entries["key_hi"].astype(np.uint64) << np.uint64(32)
    ) | entries["key_lo"]
    order = np.lexsort((entries["pane"], comp))
    return {k: np.asarray(v)[order] for k, v in entries.items()}


def _entries_equal(a, b):
    a, b = _canon(a), _canon(b)
    return a.keys() == b.keys() and all(
        np.array_equal(a[k], b[k]) for k in a
    )


@pytest.mark.parametrize("layout", ["hash", "direct"])
@pytest.mark.parametrize("packed", [False, True])
def test_rescale_roundtrip_matrix(rng, layout, packed):
    """N=4 -> M=2 -> N=4 rescale round-trip over the state-layout
    matrix: the logical snapshot is invariant at every parallelism, the
    re-restored device state is BIT-EXACT equal to the never-rescaled
    oracle, and no key changes key group across the re-slice."""
    spec = _mk_spec(layout, packed)
    red, win = spec.red, spec.win
    entries = _mk_entries(rng, layout)
    scalars = {"watermark": 5000, "fired_through": 2, "max_pane": 5,
               "min_pane": 0, "dropped_late": 3, "dropped_capacity": 0}
    ctx4, ctx2 = _mk_ctx(4), _mk_ctx(2)

    st4 = ckpt.restore_window_state(entries, scalars, ctx4, spec)
    e4, s4 = ckpt.snapshot_window_state(st4, win, red=red)
    assert _entries_equal(e4, entries)

    st2 = ckpt.restore_window_state(e4, s4, ctx2, spec)
    e2, s2 = ckpt.snapshot_window_state(st2, win, red=red)
    # the logical content is parallelism-invariant
    assert _entries_equal(e2, entries) and s2 == s4

    st4b = ckpt.restore_window_state(e2, s2, ctx4, spec)
    e4b, s4b = ckpt.snapshot_window_state(st4b, win, red=red)
    assert _entries_equal(e4b, entries) and s4b == s4
    # bit-exact device state vs the never-rescaled oracle
    la, ta = jax.tree_util.tree_flatten(st4)
    lb, tb = jax.tree_util.tree_flatten(st4b)
    assert ta == tb
    for x, y in zip(la, lb):
        assert np.array_equal(np.asarray(x), np.asarray(y))

    # no key changes key group across the re-slice, and each stage's
    # contiguous ranges cover the group the key hashes to
    kg = assign_to_key_group(
        route_hash(entries["key_hi"], entries["key_lo"], np), 128, np
    )
    kg_after = assign_to_key_group(
        route_hash(_canon(e4b)["key_hi"], _canon(e4b)["key_lo"], np),
        128, np,
    )
    assert np.array_equal(np.sort(kg), np.sort(kg_after))
    for n_shards in (4, 2):
        ranges = [key_group_range_for_operator(128, n_shards, i)
                  for i in range(n_shards)]
        for g in kg.tolist():
            assert sum(g in r for r in ranges) == 1


@pytest.mark.parametrize("layout,packed", [
    ("hash", False),
    # the direct/packed corner rides the slow tier: the cross-layout
    # restore property it adds is already pinned by the (cheap)
    # in-memory matrix test above
    pytest.param("direct", True, marks=pytest.mark.slow),
])
def test_incremental_chain_rescaled_restore(tmp_path, layout, packed):
    """A full-base + delta manifest chain written at p=2 restores at
    p=1 AND p=4 (replay_chain resolves members, the re-slice
    re-buckets), continuing exactly-once — across the state-layout
    corners (hash/split-planes and direct/packed-planes; snapshots are
    logical, so the chain moves freely between them)."""
    total, half = 8192, 4096
    cut_cfg = {"checkpoint.mode": "incremental",
               "checkpoint.async": True,
               "checkpoint.compact-every": 100,
               "state.backend.layout": layout,
               "state.packed-planes": "on" if packed else "off"}
    env1 = build_env(2, tmp_path / "chk", interval=1, **cut_cfg)
    got1 = run_job(env1, half)
    st = CheckpointStorage(str(tmp_path / "chk"))
    m = st.read_manifest(st.latest())
    assert m is not None and len(m["chain"]) > 1, "no delta chain formed"
    for p in (1, 4):
        env2 = build_env(p)
        got2 = run_job(env2, total, restore_from=str(tmp_path / "chk"))
        merged = {**got1, **got2}
        assert merged == expected(total), f"rescale to p={p} diverged"


# --------------------------------------- local cache under a rescale

def test_local_cache_serves_rescaled_restore(tmp_path):
    """Satellite regression: a CRC-clean cache entry written at N=2
    shards serves a restore at M=1 per chain member — cache blobs are
    logical (parallelism-agnostic) — including a chain member whose
    PRIMARY copy was lost."""
    chk = tmp_path / "chk"
    cfg = {"checkpoint.mode": "incremental", "checkpoint.async": True,
           "checkpoint.local.enabled": True,
           "checkpoint.compact-every": 100}
    env1 = build_env(2, chk, interval=1, **cfg)
    got1 = run_job(env1, 4096)
    st = CheckpointStorage(str(chk))
    latest = st.latest()
    chain = st.read_manifest(latest)["chain"]
    assert len(chain) > 1
    # lose a non-latest chain member's primary copy; the cache keeps it
    import shutil

    victim = chain[0]
    shutil.rmtree(st.path(victim))
    # rescaled restore at p=1 resolves the chain THROUGH the cache
    env2 = build_env(1, chk, interval=2, **cfg)
    got2 = run_job(env2, 8192, restore_from=str(chk))
    assert {**got1, **got2} == expected(8192)
    rep = env2._recovery_report()
    assert rep["local-cache"]["hits"] >= 1
    # every surviving cache entry still verifies after the rescaled
    # run's own publishes + prune cycles
    cache = LocalSnapshotCache(str(chk) + "-local")
    assert cache.list_entries()
    for cid in cache.list_entries():
        cache.verify(cid)


def test_local_cache_prune_follows_chain_closure(tmp_path):
    """prune(live) must not evict blobs still live for the re-sliced
    ranges: retention is chain-closure based, so a delta's base stays
    cached while ANY retained manifest references it, and the whole
    chain drops together once superseded."""
    from flink_tpu.checkpointing import manifest as mf

    cache = LocalSnapshotCache(str(tmp_path / "local"))
    st = CheckpointStorage(str(tmp_path / "chk"), retain=2, local=cache)

    def write(cid, kind, chain):
        entries = {
            "key_hi": np.arange(4, dtype=np.uint32),
            "key_lo": np.arange(4, dtype=np.uint32),
            "pane": np.zeros(4, np.int32),
            "value": np.full(4, float(cid), np.float32),
            "fresh": np.zeros(4, bool),
        }
        scal = {"watermark": cid, "fired_through": 0, "max_pane": 1,
                "min_pane": 0, "dropped_late": 0, "dropped_capacity": 0}
        st.write(cid, entries, scal, source_offsets={"o": cid}, aux={},
                 manifest=mf.build_manifest(cid, kind, chain, "all", 128))

    write(1, "full", [1])
    write(2, "delta", [1, 2])
    write(3, "delta", [1, 2, 3])
    # retain=2 keeps {2, 3}; the closure keeps base 1 alive — in the
    # CACHE too (evicting it would break a rescaled chain restore)
    assert st.list_checkpoints() == cache.list_entries() == [1, 2, 3]
    write(4, "full", [4])
    write(5, "full", [5])
    # the old chain is superseded: both tiers drop it together
    assert st.list_checkpoints() == cache.list_entries() == [4, 5]
    for cid in (4, 5):
        cache.verify(cid)


# ------------------------------------------------- web + metrics surface

def test_elasticity_route_and_gauges(tmp_path):
    """/jobs/<jid>/elasticity serves the degraded-state report and the
    recovery_rescales / degraded_shards gauges ride the Prometheus
    exposition."""
    import urllib.request

    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    env = build_env(2, tmp_path / "chk", interval=2, **ELASTIC_CFG)
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=6144))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    inj = FaultInjector([device_loss_rule(shard=1, at=8)])
    try:
        with faults.active(inj):
            jid = cluster.submit(env, "elastic-web-job")
            assert cluster.wait(jid, 240) == "FINISHED"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/jobs/{jid}/elasticity", timeout=10
        ) as r:
            body = json.loads(r.read())
        assert body["available"] is True
        assert body["degraded"] is True
        assert body["current-shards"] == 1 and body["full-shards"] == 2
        assert body["rescales"][0]["kind"] == "degrade"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        assert 'flink_tpu_recovery_rescales{job="elastic-web-job"} 1' \
            in text
        assert 'flink_tpu_degraded_shards{job="elastic-web-job"} 1' \
            in text
    finally:
        web.stop()


# --------------------------------------------------- controller unit

def test_controller_request_latching():
    ctl = ElasticityController(jax.devices()[:2])
    assert not ctl.take_scale_up_request()
    ctl.request_scale_up()
    ctl.request_scale_up()          # idempotent latch
    assert ctl.take_scale_up_request()
    assert not ctl.take_scale_up_request()
    ctl.record("degrade", 2, 1, cause="test", lost=[jax.devices()[1]])
    assert ctl.degraded and ctl.degraded_shards == 1
    rep = ctl.report()
    assert rep["current-shards"] == 1 and rep["degraded"] is True
    ctl.record("scale_up", 1, 2)
    assert not ctl.degraded and ctl.report()["lost-devices"] == []
