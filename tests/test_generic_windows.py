"""Generic window operator: trigger catalog, evictors, apply/fold,
GlobalWindows, merging session windows — golden semantics mirrored from the
reference's WindowOperatorTest / trigger tests (SURVEY §4 harness tier)."""

import pytest

from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.environment import StreamExecutionEnvironment
from flink_tpu.datastream.window.assigners import (
    EventTimeSessionWindows,
    GlobalWindows,
    TumblingEventTimeWindows,
)
from flink_tpu.datastream.window.evictors import CountEvictor, TimeEvictor
from flink_tpu.datastream.window.triggers import (
    ContinuousEventTimeTrigger,
    CountTrigger,
    DeltaTrigger,
    EventTimeTrigger,
    PurgingTrigger,
    TriggerResult,
)
from flink_tpu.datastream.window.windows import GlobalWindow, TimeWindow
from flink_tpu.runtime import sinks as sk
from flink_tpu.runtime.window_operator import MergingWindowSet


def _env_event_time(batch_size=1):
    env = StreamExecutionEnvironment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = batch_size
    return env


# ---------------------------------------------------------------- triggers
def test_count_trigger_on_global_windows():
    """GlobalWindows + PurgingTrigger(CountTrigger(2)) == countWindow(2)
    built from primitives (ref KeyedStream.countWindow)."""
    env = StreamExecutionEnvironment()
    sink = sk.CollectSink()
    data = [("a", 1.0), ("a", 2.0), ("b", 10.0), ("a", 3.0),
            ("b", 20.0), ("a", 4.0)]
    (
        env.from_collection(data)
        .key_by(0)
        .window(GlobalWindows.create())
        .trigger(PurgingTrigger.of(CountTrigger.of(2)))
        .sum(1)
        .add_sink(sink)
    )
    env.execute("count-trigger")
    got = {(r.key, r.value) for r in sink.results}
    assert ("a", 3.0) in got   # 1+2
    assert ("a", 7.0) in got   # 3+4
    assert ("b", 30.0) in got  # 10+20
    assert len(sink.results) == 3  # trailing incomplete windows never fire


def test_count_trigger_without_purge_keeps_accumulating():
    env = StreamExecutionEnvironment()
    sink = sk.CollectSink()
    data = [("a", 1.0), ("a", 2.0), ("a", 3.0), ("a", 4.0)]
    (
        env.from_collection(data)
        .key_by(0)
        .window(GlobalWindows.create())
        .trigger(CountTrigger.of(2))
        .sum(1)
        .add_sink(sink)
    )
    env.execute("count-nopurge")
    vals = sorted(r.value for r in sink.results)
    assert vals == [3.0, 10.0]  # 1+2, then 1+2+3+4 (no purge)


def test_continuous_event_time_trigger_early_fires():
    """Early fires every 10ms of event time inside a 100ms window."""
    env = _env_event_time()
    sink = sk.CollectSink()
    data = [("k", 1, 1.0), ("k", 5, 1.0), ("k", 12, 1.0), ("k", 25, 1.0),
            ("k", 99, 1.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .window(TumblingEventTimeWindows.of(100))
        .trigger(ContinuousEventTimeTrigger.of(10))
        .sum(2)
        .add_sink(sink)
    )
    env.execute("cont-trigger")
    vals = [r.value for r in sink.results]
    # watermarks trail elements: the timer@10 fires at wm=11, after ts=12
    # was already added -> first early fire sees 3 elements, then 4, then
    # the full window (5) on later interval fires and the final fire at 99
    assert vals[0] == 3.0
    assert 4.0 in vals
    assert vals[-1] == 5.0
    assert len(vals) >= 3


def test_delta_trigger():
    env = StreamExecutionEnvironment()
    sink = sk.CollectSink()
    data = [("k", 1.0), ("k", 2.0), ("k", 6.0), ("k", 7.0), ("k", 20.0)]
    (
        env.from_collection(data)
        .key_by(0)
        .window(GlobalWindows.create())
        .trigger(DeltaTrigger.of(3.0, lambda old, new: new[1] - old[1]))
        .sum(1)
        .add_sink(sink)
    )
    env.execute("delta-trigger")
    vals = [r.value for r in sink.results]
    # fires when 6.0 arrives (6-1>3): sum=9; when 20 arrives (20-6>3): sum=36
    assert vals == [9.0, 36.0]


# ---------------------------------------------------------------- evictors
def test_count_evictor_keeps_last_n():
    env = _env_event_time()
    sink = sk.CollectSink()
    data = [("k", 10, 1.0), ("k", 20, 2.0), ("k", 30, 3.0), ("k", 40, 4.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .window(TumblingEventTimeWindows.of(100))
        .evictor(CountEvictor.of(2))
        .sum(2)
        .add_sink(sink)
    )
    env.execute("count-evictor")
    assert [r.value for r in sink.results] == [7.0]  # last two: 3+4


def test_time_evictor():
    env = _env_event_time()
    sink = sk.CollectSink()
    data = [("k", 10, 1.0), ("k", 20, 2.0), ("k", 80, 4.0), ("k", 90, 8.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .window(TumblingEventTimeWindows.of(100))
        .evictor(TimeEvictor.of(15))
        .sum(2)
        .add_sink(sink)
    )
    env.execute("time-evictor")
    # keep elements with ts >= 90-15=75: values 4+8
    assert [r.value for r in sink.results] == [12.0]


# ---------------------------------------------------------------- apply/fold
def test_window_apply_raw_elements():
    env = _env_event_time(batch_size=4)
    sink = sk.CollectSink()
    data = [("a", 10, 1.0), ("a", 20, 2.0), ("b", 30, 5.0), ("a", 120, 9.0)]

    def wf(key, window, elements):
        yield (key, window.start, window.end, sorted(v for _, _, v in elements))

    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .time_window(100)
        .apply(wf)
        .add_sink(sink)
    )
    env.execute("apply")
    got = sorted(sink.results)
    assert ("a", 0, 100, [1.0, 2.0]) in got
    assert ("b", 0, 100, [5.0]) in got
    assert ("a", 100, 200, [9.0]) in got


def test_window_fold_order_preserved():
    env = _env_event_time(batch_size=4)
    sink = sk.CollectSink()
    data = [("k", 10, "x"), ("k", 20, "y"), ("k", 30, "z")]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .time_window(100)
        .fold("", lambda acc, e: acc + e[2])
        .add_sink(sink)
    )
    env.execute("fold")
    # fold emits the raw folded value (window function output)
    assert sink.results == ["xyz"]


# ---------------------------------------------------------------- lateness
def test_late_data_dropped_beyond_lateness():
    env = _env_event_time()
    sink = sk.CollectSink()
    # watermark reaches 499 after ts=500; window [0,100) closes (no lateness);
    # the late element at ts=50 must be dropped
    data = [("k", 10, 1.0), ("k", 500, 2.0), ("k", 50, 100.0),
            ("k", 600, 3.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .time_window(100)
        .apply(lambda key, w, els: [(key, w.start, sum(v for _, _, v in els))])
        .add_sink(sink)
    )
    job = env.execute("late-drop")
    got = sorted(sink.results)
    assert ("k", 0, 1.0) in got          # late 100.0 not included
    assert job.metrics.dropped_late >= 1


def test_allowed_lateness_refires():
    env = _env_event_time()
    sink = sk.CollectSink()
    # lateness 1000: the late element at ts=50 re-fires window [0,100)
    data = [("k", 10, 1.0), ("k", 500, 2.0), ("k", 50, 100.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .time_window(100)
        .allowed_lateness(1000)
        .trigger(EventTimeTrigger.create())
        .sum(2)
        .add_sink(sink)
    )
    env.execute("late-refire")
    vals = [
        r.value for r in sink.results
        if r.window_end_ms == 100
    ]
    assert vals[0] == 1.0          # on-time fire
    assert vals[-1] == 101.0       # late re-fire includes the late element


# ---------------------------------------------------------------- sessions
def test_session_windows_merge_on_generic_path():
    env = _env_event_time()
    sink = sk.CollectSink()
    # gap 50: (10,30,60) merge into [10,110); 300 starts a new session
    data = [("k", 10, 1.0), ("k", 60, 2.0), ("k", 30, 4.0), ("k", 300, 8.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(50))
        .trigger(EventTimeTrigger.create())  # force the generic path
        .sum(2)
        .add_sink(sink)
    )
    env.execute("session-generic")
    got = {(r.window_start_ms, r.window_end_ms, r.value)
           for r in sink.results}
    assert (10, 110, 7.0) in got
    assert (300, 350, 8.0) in got


def test_session_transitive_merge_keeps_all_contents():
    """Two disjoint sessions bridged by a third element must fire with ALL
    elements (regression: merged-away window contents were lost when state
    views aliased)."""
    env = _env_event_time(batch_size=5)
    sink = sk.CollectSink()
    # gap 30: sessions [0,30) and [60,90) exist disjoint; the out-of-order
    # element at 30 -> [30,60) touches both and merges them transitively
    data = [("k", 0, 1.0), ("k", 60, 2.0), ("k", 30, 4.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(30))
        .trigger(EventTimeTrigger.create())
        .sum(2)
        .add_sink(sink)
    )
    env.execute("session-transitive")
    got = {(r.window_start_ms, r.window_end_ms, r.value)
           for r in sink.results}
    assert (0, 90, 7.0) in got, got


def test_count_window_with_apply_lowers_to_generic():
    """count_window(N).apply() lowers to GlobalWindows+CountTrigger."""
    env = StreamExecutionEnvironment()
    sink = sk.CollectSink()
    data = [("a", 1.0), ("a", 2.0), ("a", 3.0), ("a", 4.0)]

    def wf(key, window, elements):
        yield (key, [v for _, v in elements])

    (
        env.from_collection(data)
        .key_by(0)
        .count_window(2)
        .apply(wf)
        .add_sink(sink)
    )
    env.execute("count-apply")
    assert sink.results == [("a", [1.0, 2.0]), ("a", [3.0, 4.0])]


def test_continuous_processing_trigger_finite_stream_terminates():
    """End-of-stream drain must not cascade re-registered timers
    (regression: 2**62 advance looped ~1e15 times)."""
    from flink_tpu.datastream.window.triggers import (
        ContinuousProcessingTimeTrigger,
    )

    env = StreamExecutionEnvironment()
    sink = sk.CollectSink()
    data = [("a", 1.0), ("a", 2.0)]
    (
        env.from_collection(data)
        .key_by(0)
        .window(GlobalWindows.create())
        .trigger(ContinuousProcessingTimeTrigger.of(1000))
        .sum(1)
        .add_sink(sink)
    )
    env.execute("cont-proc")  # must terminate promptly
    # the end-of-stream drain fires the pending interval timer once
    assert [r.value for r in sink.results] == [3.0]


def test_merging_window_set_transitive_merge():
    class FakeMap:
        def __init__(self):
            self.d = {}

        def items(self):
            return list(self.d.items())

        def get(self, k, default=None):
            return self.d.get(k, default)

        def put(self, k, v):
            self.d[k] = v

        def remove(self, k):
            self.d.pop(k, None)

    ms = MergingWindowSet(FakeMap())
    merges = []

    def cb(merged, merged_windows, keep, drops):
        merges.append((merged, sorted(merged_windows), keep, drops))

    w1 = ms.add_window(TimeWindow(0, 50), cb)
    w2 = ms.add_window(TimeWindow(100, 150), cb)
    assert w1 == TimeWindow(0, 50) and w2 == TimeWindow(100, 150)
    assert merges == []
    # bridges both -> single merged window [0, 150)
    w3 = ms.add_window(TimeWindow(40, 110), cb)
    assert w3 == TimeWindow(0, 150)
    assert len(merges) == 1
    merged, merged_windows, keep, drops = merges[0]
    assert merged == TimeWindow(0, 150)
    assert keep in (TimeWindow(0, 50), TimeWindow(100, 150))
    assert ms.state_window(TimeWindow(0, 150)) == keep


# -------------------------------------------- review-regression coverage
def test_sketch_on_generic_path_distinct_count():
    """Sketch aggregations must work when routed to the generic host
    operator (custom trigger): host_add/host_result mirror the device
    registers."""
    env = StreamExecutionEnvironment()
    sink = sk.CollectSink()
    data = [("k", i % 5) for i in range(9)]  # 5 distinct items
    (
        env.from_collection(data)
        .key_by(0)
        .window(GlobalWindows.create())
        .trigger(PurgingTrigger.of(CountTrigger.of(9)))
        .distinct_count(1, precision=10)
        .add_sink(sink)
    )
    env.execute("sketch-generic")
    assert len(sink.results) == 1
    assert abs(sink.results[0].value - 5) < 1


def test_session_merge_preserves_count_trigger_state():
    """Merging sessions must merge per-window trigger state, not clear it
    (ref Trigger.OnMergeContext.mergePartitionedState): elements at 10 and
    100 form two sessions; 55 bridges them -> merged window has 3 elements
    and CountTrigger(3) fires. One batch, so the watermark stays MIN and
    neither pre-merge session expires first."""
    env = _env_event_time(batch_size=3)
    sink = sk.CollectSink()
    data = [(10, 1.0), (100, 2.0), (55, 4.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: "k")
        .window(EventTimeSessionWindows.with_gap(50))
        .trigger(CountTrigger.of(3))
        .sum(1)
        .add_sink(sink)
    )
    env.execute("session-trigger-merge")
    assert [r.value for r in sink.results] == [7.0]


def test_time_evictor_boundary_exclusive():
    """TimeEvictor evicts ts <= max_ts - window_size (boundary element
    goes), mirroring TimeEvictor.java."""
    env = _env_event_time()
    sink = sk.CollectSink()
    data = [(0, 100.0), (100, 1.0)]
    (
        env.from_collection(data)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: "k")
        .window(TumblingEventTimeWindows.of(1000))
        .evictor(TimeEvictor.of(100))
        .sum(1)
        .add_sink(sink)
    )
    env.execute("time-evictor-boundary")
    assert [r.value for r in sink.results] == [1.0]
