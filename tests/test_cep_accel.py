"""Device CEP in production position: CEP.pattern() routes eligible
patterns (no within(), processing time) through the count-NFA kernel +
lazy host extraction (cep/accel.py), equivalent to the host NFA.

Ref: NFA.java:132 / computeNextStates:229; VERDICT r2 item 3.
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.cep import CEP, NFA, Pattern
from flink_tpu.cep.accel import DeviceCepOperator, batch_gaps
from flink_tpu.runtime.sinks import CollectSink

from test_cep import Event  # noqa: E402 — shared event shape


# ---------------------------------------------------------------- batch_gaps
def _gaps_scalar(inv, hit, trailing_in):
    """Scalar model: per key, a hit lane has a gap iff >=1 non-hit lane of
    the same key occurred since its previous hit lane (or carry-in)."""
    trailing = dict(enumerate(trailing_in))
    gap = np.zeros(len(inv), bool)
    for i in range(len(inv)):
        k = int(inv[i])
        if hit[i]:
            gap[i] = trailing.get(k, False)
            trailing[k] = False
        else:
            trailing[k] = True
    out = np.array([trailing.get(g, False)
                    for g in range(len(trailing_in))])
    return gap, out


@pytest.mark.parametrize("seed", range(6))
def test_batch_gaps_matches_scalar_model(seed):
    rng = np.random.default_rng(seed)
    for _ in range(20):
        B = int(rng.integers(1, 40))
        G = int(rng.integers(1, 6))
        inv = rng.integers(0, G, B)
        hit = rng.random(B) < 0.5
        tin = rng.random(G) < 0.3
        got_gap, got_out = batch_gaps(inv, hit, tin.copy())
        exp_gap, exp_out = _gaps_scalar(inv, hit, tin)
        np.testing.assert_array_equal(got_gap, exp_gap)
        np.testing.assert_array_equal(got_out, exp_out)


def test_batch_gaps_empty():
    g, t = batch_gaps(np.zeros(0, np.int64), np.zeros(0, bool),
                      np.array([True, False]))
    assert len(g) == 0 and list(t) == [True, False]


# ------------------------------------------------------- operator equivalence
def _host_matches(pattern, events_by_key):
    out = []
    for key, evs in events_by_key.items():
        nfa = NFA(pattern)
        partials = nfa.initial_state()
        for e in evs:
            partials, ms = nfa.process(partials, e, e.ts)
            out.extend((key, tuple(sorted(
                (name, ev.value) for name, ev in m.items()
            ))) for m in ms)
    return sorted(out)


def _patterns():
    return {
        "strict": (Pattern.begin("a").where(lambda e: e.name == "a")
                   .next("b").where(lambda e: e.name == "b")),
        "relaxed": (Pattern.begin("a").where(lambda e: e.name == "a")
                    .followed_by("b").where(lambda e: e.name == "b")),
        "three-mixed": (Pattern.begin("a").where(lambda e: e.name == "a")
                        .followed_by("b").where(lambda e: e.name == "b")
                        .next("c").where(lambda e: e.name == "c")),
        "single": Pattern.begin("x").where(lambda e: e.name == "a"),
    }


@pytest.mark.parametrize("pname", list(_patterns()))
@pytest.mark.parametrize("batch", [3, 7, 64])
def test_device_operator_equivalent_to_host_nfa(pname, batch):
    """Random keyed streams straddling batch boundaries: the device
    operator's extracted matches equal per-key host NFA ground truth,
    and its device-side count agrees with extraction."""
    pattern = _patterns()[pname]
    rng = np.random.default_rng(hash(pname) % 2**31)
    n, n_keys = 300, 5
    names = rng.choice(["a", "b", "c", "x"], size=n,
                       p=[0.3, 0.3, 0.2, 0.2])
    keys = rng.integers(0, n_keys, n)
    events = [Event(i, str(names[i]), i) for i in range(n)]

    op = DeviceCepOperator(pattern, capacity=64)
    got = []
    for bi, off in enumerate(range(0, n, batch)):
        chunk = list(range(off, min(off + batch, n)))
        ms = op.process_batch([events[i] for i in chunk],
                              [int(keys[i]) for i in chunk], ts=off)
        got.extend(ms)
        if bi % 3 == 2:   # interleaved pruning must not change results
            assert op.prune_dead_keys() == []

    by_key = {}
    for i, e in enumerate(events):
        by_key.setdefault(int(keys[i]), []).append(e)
    exp = _host_matches(pattern, by_key)

    # got matches lack the key; compare multisets of stage-value tuples
    got_flat = sorted(
        tuple(sorted((name, ev.value) for name, ev in m.items()))
        for m in got
    )
    assert got_flat == sorted(e[1] for e in exp)
    assert op.matches_detected == op.matches_extracted == len(exp)
    assert op.dropped_capacity == 0


# ------------------------------------------------------------ public API path
def test_public_api_rides_device_path():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    env.set_parallelism(1)
    sink = CollectSink()
    events = [Event(0, "a", 1), Event(0, "b", 1), Event(0, "a", 2),
              Event(0, "x", 2), Event(0, "b", 2)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(
        lambda m: (m["a"].value, m["b"].ts)
    ).add_sink(sink)
    job = env.execute("cep-device-api")
    assert job.metrics.cep_device_steps > 0, "host path was taken"
    assert job.metrics.cep_matches_detected == \
        job.metrics.cep_matches_extracted == len(sink.results)
    assert sorted(sink.results) == [(1, 0)]


def test_public_api_flat_select_device():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    sink = CollectSink()
    events = [Event(0, "a", 1), Event(1, "x", 1), Event(2, "b", 1),
              Event(3, "b", 1)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).flat_select(
        lambda m: [m["b"].ts, m["b"].ts]
    ).add_sink(sink)
    job = env.execute("cep-device-flat")
    assert job.metrics.cep_device_steps > 0
    assert sorted(sink.results) == [2, 2, 3, 3]


def test_device_cep_checkpoint_kill_restore_exactness(tmp_path):
    """Induced sink failure mid-stream: the device CEP job restores
    device count-state + host buffers/partials from the last checkpoint
    and the exactly-once file sink holds each match exactly once."""
    import os

    from flink_tpu.connectors.files import BucketingFileSink
    from flink_tpu.core.config import Configuration

    rng = np.random.default_rng(11)
    n, n_keys = 400, 6
    names = rng.choice(["a", "b", "x"], size=n, p=[0.4, 0.3, 0.3])
    keys = rng.integers(0, n_keys, n)
    events = [Event(i, str(names[i]), int(keys[i])) for i in range(n)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )

    class FailOnce:
        tripped = False

    def run(fail_after):
        env = StreamExecutionEnvironment(Configuration({
            "restart-strategy": "fixed-delay",
            "restart-strategy.fixed-delay.attempts": 3,
            "restart-strategy.fixed-delay.delay": 0,
        }))
        env.batch_size = 32
        env.set_parallelism(1)
        env.enable_checkpointing(2, str(tmp_path / "chk"))
        out = str(tmp_path / "out")
        sink = BucketingFileSink(
            out, formatter=lambda r: f"{r[0]},{r[1]},{r[2]}"
        )
        orig = sink.invoke_batch

        def failing_invoke(elements):
            orig(elements)
            import glob as _g
            has_chk = _g.glob(str(tmp_path / "chk" / "chk-*"))
            if not FailOnce.tripped and fail_after is not None and has_chk:
                FailOnce.tripped = True
                raise RuntimeError("induced sink failure")

        sink.invoke_batch = failing_invoke
        stream = env.from_collection(events).key_by(lambda e: e.value)
        CEP.pattern(stream, pattern).select(
            lambda m: (m["a"].value, m["a"].ts, m["b"].ts)
        ).add_sink(sink)
        job = env.execute("cep-device-ckpt")
        return job, out

    job, out = run(fail_after=1)
    assert job.metrics.restarts >= 1
    assert job.metrics.cep_device_steps > 0

    import glob

    lines = []
    for path in glob.glob(os.path.join(out, "**", "part-0"), recursive=True):
        lines += [tuple(map(int, ln.split(",")))
                  for ln in open(path).read().splitlines()]

    by_key = {}
    for e in events:
        by_key.setdefault(e.value, []).append(e)
    exp = []
    for key, evs in by_key.items():
        nfa, partials = NFA(pattern), []
        for e in evs:
            partials, ms = nfa.process(partials, e, e.ts)
            exp.extend((key, m["a"].ts, m["b"].ts) for m in ms)
    assert sorted(lines) == sorted(exp), (len(lines), len(exp))


def test_device_cep_queryable_partials():
    """Live partial matches are queryable on the device path (host-path
    'cep-nfa-state' parity): after an 'a' with no 'b' yet, the key holds
    one partial at stage 0."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    env.set_parallelism(1)
    sink = CollectSink()
    events = [Event(0, "a", 7), Event(1, "x", 7)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(lambda m: 1).add_sink(sink)
    job = env.execute("cep-device-query")
    assert job.metrics.cep_device_steps > 0
    partials = env.query_state("cep-nfa-state", 7)
    assert partials is not None and len(partials) == 1
    assert partials[0].stage_idx == 0
    assert env.query_state("cep-nfa-state", 12345) is None


def test_device_cep_savepoint(tmp_path):
    """A savepoint can be taken from a device CEP job via the cluster
    control path and contains a restorable payload."""
    import time as _time

    from flink_tpu.runtime.checkpoint import CheckpointStorage
    from flink_tpu.runtime.cluster import MiniCluster

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 16
    env.set_parallelism(1)
    sink = CollectSink()

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        _time.sleep(0.005)
        return [Event(int(i), "a" if i % 3 else "b", int(i % 4))
                for i in idx], None

    from flink_tpu.runtime.sources import GeneratorSource

    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    stream = env.add_source(GeneratorSource(gen)).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(lambda m: m["b"].ts).add_sink(sink)
    cluster = MiniCluster()
    jid = cluster.submit(env, "cep-device-sp")
    try:
        sp_dir = str(tmp_path / "sp")
        deadline = _time.time() + 60
        path = None
        while _time.time() < deadline:
            try:
                path = cluster.trigger_savepoint(jid, sp_dir)
                break
            except Exception:
                _time.sleep(0.3)
        assert path is not None
        st = CheckpointStorage(sp_dir)
        payload = st.read_generic(st.latest())
        assert payload["cep_device"] and "op" in payload
    finally:
        cluster.cancel(jid)
        cluster.wait(jid, 30)


def test_prune_dead_keys_frees_strict_killed_buffers():
    """STRICT pattern over 'a x a x ...' streams: every 'a'-partial is
    killed by the following 'x', so after pruning the host holds no
    buffered events for those keys (the unbounded-growth regression)."""
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b")
    )
    op = DeviceCepOperator(pattern, capacity=64)
    for r in range(20):
        evs = [Event(r * 8 + i, "a" if i % 2 == 0 else "x", i % 4)
               for i in range(8)]
        op.process_batch(evs, [e.value for e in evs], ts=r)
    assert sum(len(b) for b in op.buffers.values()) >= 20  # grew
    assert op.prune_dead_keys() == []        # no swallowed completions
    # buffers collapse to true NFA-partials size: keys 0/2 (all-'a'
    # streams) hold exactly the one still-viable latest partial; the
    # all-'x' keys hold nothing
    assert op.buffers == {}
    assert sorted(len(p) for p in op.partials.values()) == [1, 1]
    # correctness after pruning: a fresh a->b still matches
    ms = op.process_batch(
        [Event(900, "a", 1), Event(901, "b", 1)], [1, 1], ts=900
    )
    assert len(ms) == 1


def test_prune_keeps_live_relaxed_partials():
    """RELAXED partials stay alive through non-matching events — pruning
    must NOT free their buffers, and the match still extracts after."""
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    op = DeviceCepOperator(pattern, capacity=64)
    op.process_batch([Event(0, "a", 3), Event(1, "x", 3)], [3, 3], ts=0)
    assert op.prune_dead_keys() == []
    assert op.buffers == {}                   # drained into partials
    assert sum(len(p) for p in op.partials.values()) == 1
    ms = op.process_batch([Event(2, "b", 3)], [3], ts=2)
    assert len(ms) == 1 and ms[0]["a"].ts == 0


def test_within_runs_on_device():
    """Round 4: within() patterns take the device path (pane-bucketed
    partial expiry); the engine that ran is surfaced in metrics."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    env.set_parallelism(1)
    sink = CollectSink()
    events = [Event(0, "a", 1), Event(1, "b", 1)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b").within(10)
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(lambda m: 1).add_sink(sink)
    job = env.execute("cep-within-device")
    assert job.metrics.cep_device_steps > 0
    assert job.metrics.cep_engine == "device"
    assert sink.results == [1]


def test_device_engine_can_be_disabled():
    from flink_tpu.core.config import Configuration

    env = StreamExecutionEnvironment(
        Configuration({"cep.device.enabled": False})
    )
    env.batch_size = 8
    env.set_parallelism(1)
    sink = CollectSink()
    events = [Event(0, "a", 1), Event(1, "b", 1)]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b").within(10)
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(lambda m: 1).add_sink(sink)
    job = env.execute("cep-within-host-forced")
    assert job.metrics.cep_device_steps == 0
    assert job.metrics.cep_engine == "host"
    assert sink.results == [1]


def test_match_completing_on_prune_step_is_emitted():
    """Regression: the 64-step prune pass in the device CEP batch loop
    used to overwrite the batch's own matches with prune_dead_keys()'s
    return value, silently dropping every match that completed on a
    step divisible by 64."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 1          # one step per event -> step count is exact
    env.set_parallelism(1)
    sink = CollectSink()
    # 62 filler non-matching events, then two 'a','b' pairs (same key);
    # the first completion lands exactly on step 64 (event index 63)
    events = [Event(0, "x", 1) for _ in range(62)] + [
        Event(100, "a", 1), Event(101, "b", 1),
        Event(200, "a", 1), Event(201, "b", 1),
    ]
    pattern = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(
        lambda m: (m["a"].ts, m["b"].ts)
    ).add_sink(sink)
    job = env.execute("cep-prune-step-match")
    assert job.metrics.cep_device_steps >= 64
    # followed_by is RELAXED: the a@100 partial also pairs with b@201
    assert sorted(sink.results) == [(100, 101), (100, 201), (200, 201)]
    assert job.metrics.cep_matches_detected == \
        job.metrics.cep_matches_extracted == 3


# --------------------------------------------------- multi-shard device CEP
def _rand_events(n=400, keys=24, seed=5):
    rng = __import__("numpy").random.default_rng(seed)
    names = ["a", "b", "x"]
    return [
        Event(i, names[int(rng.integers(0, 3))], int(rng.integers(0, keys)))
        for i in range(n)
    ]


def _run_cep_job(events, parallelism, pattern=None):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 64
    env.set_parallelism(parallelism)
    env.set_max_parallelism(64)
    sink = CollectSink()
    pattern = pattern or (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    stream = env.from_collection(events).key_by(lambda e: e.value)
    CEP.pattern(stream, pattern).select(
        lambda m: (m["a"].value, m["a"].ts, m["b"].ts)
    ).add_sink(sink)
    job = env.execute(f"cep-p{parallelism}")
    assert job.metrics.cep_engine == "device"
    assert job.metrics.cep_device_steps > 0
    assert (job.metrics.cep_matches_detected
            == job.metrics.cep_matches_extracted)
    return sorted(sink.results)


def test_multi_shard_matches_single_shard():
    """8 key-group shards over the virtual mesh produce exactly the
    single-shard match set (VERDICT r3 item 6: multi-shard device CEP)."""
    events = _rand_events()
    assert _run_cep_job(events, 8) == _run_cep_job(events, 1)


def test_multi_shard_with_within():
    """within() under sharding, DETERMINISTIC timestamps: the job path
    stamps batches with wall-clock processing time (so match counts
    legitimately vary with execution speed), so this drives the operator
    directly with explicit batch timestamps and compares 8 shards vs 1."""
    from flink_tpu.cep.accel import DeviceCepOperator

    events = _rand_events(n=300, keys=10, seed=9)
    pat = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .next("b").where(lambda e: e.name == "b").within(50)
    )

    def run(n_shards):
        op = DeviceCepOperator(pat, capacity=64, n_shards=n_shards,
                               max_parallelism=64)
        got = []
        for off in range(0, len(events), 48):
            chunk = events[off:off + 48]
            got.extend(op.process_batch(
                chunk, [e.value for e in chunk], ts=chunk[0].ts
            ))
        assert op.matches_detected == op.matches_extracted
        return sorted(
            (m["a"].value, m["a"].ts, m["b"].ts) for m in got
        ), op.matches_detected

    r8, n8 = run(8)
    r1, n1 = run(1)
    assert r8 == r1 and n8 == n1 and n8 > 0


def test_shard_count_restore_mismatch_rejected():
    from flink_tpu.cep.accel import DeviceCepOperator

    pat = Pattern.begin("a").where(lambda e: e.name == "a")
    op1 = DeviceCepOperator(pat, capacity=64, n_shards=1)
    op8 = DeviceCepOperator(pat, capacity=64, n_shards=8)
    with pytest.raises(ValueError, match="shard-count"):
        op8.restore(op1.snapshot())


# -------------------------------------------------- event-time device mode

def _run_et_job(events, pattern, device: bool, batch=16,
                tmpdir=None, fail_trip=None):
    """Event-time CEP pipeline; device flag toggles cep.device.enabled.
    Returns (sorted results, cep_engine, restarts)."""
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic

    cfg = {"cep.device.enabled": device}
    if tmpdir:
        cfg.update({"restart-strategy": "fixed-delay",
                    "restart-strategy.fixed-delay.attempts": 3,
                    "restart-strategy.fixed-delay.delay": 0})
    env = StreamExecutionEnvironment(Configuration(cfg))
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = batch
    if tmpdir:
        env.enable_checkpointing(2, str(tmpdir))

    class Sink(CollectSink):
        def snapshot_state(self):
            return list(self.results)

        def restore_state(self, state):
            self.results[:] = state

        def invoke_batch(self, elements):
            if (fail_trip is not None and not fail_trip["tripped"]
                    and len(self.results) >= fail_trip["at"]):
                fail_trip["tripped"] = True
                raise RuntimeError("induced failure")
            super().invoke_batch(elements)

    sink = Sink()
    stream = (
        env.from_collection(events)
        .assign_timestamps_and_watermarks(lambda e: e.ts)
        .key_by(lambda e: e.value)
    )
    CEP.pattern(stream, pattern).select(
        lambda m: (m["a"].value, m["a"].ts, m["b"].ts)
    ).add_sink(sink)
    job = env.execute("cep-et")
    return (sorted(sink.results), job.metrics.cep_engine,
            job.metrics.restarts)


def _shuffled_et_events(seed, n=300, n_keys=5, ooo=0):
    """Timestamped a/b/x events, arrival order locally shuffled within
    +-ooo of timestamp order (bounded out-of-orderness)."""
    rng = np.random.default_rng(seed)
    names = rng.choice(["a", "b", "x"], size=n, p=[0.35, 0.3, 0.35])
    keys = rng.integers(0, n_keys, n)
    events = [Event(i, str(names[i]), int(keys[i])) for i in range(n)]
    if ooo:
        arrival = np.argsort(np.arange(n) + rng.uniform(0, ooo, n))
        events = [events[i] for i in arrival]
    return events


@pytest.mark.parametrize("strict", [True, False])
def test_event_time_device_equals_host(strict):
    """Out-of-order event-time CEP: the device path (reorder buffer +
    count NFA) emits exactly the host NFA's matches, and actually runs
    on the device engine."""
    p = Pattern.begin("a").where(lambda e: e.name == "a")
    p = (p.next("b") if strict else p.followed_by("b")).where(
        lambda e: e.name == "b")
    for seed in range(3):
        events = _shuffled_et_events(seed, ooo=6)
        got_d, eng_d, _ = _run_et_job(events, p, device=True)
        got_h, eng_h, _ = _run_et_job(events, p, device=False)
        assert eng_d == "device" and eng_h == "host"
        assert got_d == got_h, (seed, len(got_d), len(got_h))


def test_event_time_device_checkpoint_restart(tmp_path):
    """Mid-stream failure with a half-full reorder buffer: restore
    brings back the et heap + device state and results stay exact."""
    p = (
        Pattern.begin("a").where(lambda e: e.name == "a")
        .followed_by("b").where(lambda e: e.name == "b")
    )
    events = _shuffled_et_events(7, n=400, ooo=8)
    trip = {"tripped": False, "at": 8}
    got, engine, restarts = _run_et_job(
        events, p, device=True, tmpdir=tmp_path / "chk", fail_trip=trip)
    assert engine == "device" and restarts >= 1
    ref, _, _ = _run_et_job(events, p, device=False)
    assert got == ref
