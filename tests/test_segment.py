"""Segmented pre-aggregation kernels vs numpy reference."""

import jax.numpy as jnp
import numpy as np

from flink_tpu.ops.segment import preaggregate, scatter_combine


def test_preaggregate_sum_matches_numpy(rng):
    B = 512
    ids = rng.integers(0, 40, B).astype(np.int32)
    vals = rng.normal(size=B).astype(np.float32)
    valid = rng.random(B) < 0.9

    rep_ids, rep_mask, reduced = preaggregate(
        jnp.asarray(ids), jnp.asarray(vals), jnp.asarray(valid),
        combine=lambda a, b: a + b, neutral=np.float32(0),
    )
    rep_ids, rep_mask, reduced = map(np.asarray, (rep_ids, rep_mask, reduced))

    expect = {}
    for i, v, ok in zip(ids, vals, valid):
        if ok:
            expect[i] = expect.get(i, np.float32(0)) + v
    got = {int(i): float(r) for i, r in zip(rep_ids[rep_mask], reduced[rep_mask])}
    assert set(got) == set(int(k) for k in expect)
    for k, v in expect.items():
        assert abs(got[int(k)] - float(v)) < 1e-3


def test_preaggregate_noncommutative_associative(rng):
    # max-with-argmax packed as (val, tag): associative, not commutative-trivial
    B = 128
    ids = rng.integers(0, 10, B).astype(np.int32)
    vals = rng.normal(size=B).astype(np.float32)
    tags = np.arange(B, dtype=np.float32)
    valid = np.ones(B, bool)

    def combine(a, b):
        take_b = b[..., 0] > a[..., 0]
        return jnp.where(take_b[..., None], b, a)

    packed = jnp.stack([jnp.asarray(vals), jnp.asarray(tags)], axis=-1)
    rep_ids, rep_mask, reduced = preaggregate(
        jnp.asarray(ids), packed, jnp.asarray(valid),
        combine=combine, neutral=np.float32(-np.inf),
    )
    rep_ids, rep_mask, reduced = map(np.asarray, (rep_ids, rep_mask, reduced))
    got = {int(i): r for i, r in zip(rep_ids[rep_mask], reduced[rep_mask])}
    for seg in np.unique(ids):
        mask = ids == seg
        j = np.argmax(vals[mask])
        assert got[int(seg)][0] == vals[mask][j]


def test_scatter_combine_kinds():
    target = jnp.zeros(8, jnp.float32)
    idx = jnp.asarray([1, 1, 3, 9], jnp.int32)  # 9 out of range
    ups = jnp.asarray([2.0, 3.0, 4.0, 100.0], jnp.float32)
    mask = jnp.asarray([True, True, True, True])
    out = np.asarray(scatter_combine(target, idx, ups, mask, "add"))
    assert out[1] == 5.0 and out[3] == 4.0 and out.sum() == 9.0

    tmin = jnp.full(8, jnp.inf, jnp.float32)
    out = np.asarray(scatter_combine(tmin, idx, ups, mask, "min"))
    assert out[1] == 2.0 and out[3] == 4.0

    masked = jnp.asarray([True, False, True, False])
    out = np.asarray(scatter_combine(target, idx, ups, masked, "add"))
    assert out[1] == 2.0 and out[3] == 4.0
