"""Inter-poll event-time jumps must not evict unfired pane state.

Round-5 regression: the catch-up time-slicing bounds the pane span
WITHIN one poll, but a time gap BETWEEN polls (a quiet source resuming
after an event-time gap; a processing-time job resuming after a
compile/GC pause) rotated the pane ring past still-unfired panes — one
pane of ACCUMULATED per-key state vanished, with only the state-entry
count surfacing in dropped_capacity. The executor now pre-fires due
panes before applying a group that jumps the ring
(executor.py poll_cycle; ref WindowOperator.java:222's
processElement-then-timer ordering, where pending window state can
never be destroyed by later elements).
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource


def _run(win_ms, slide_ms, gen, total, batch=8192, ooo_ms=None):
    env = StreamExecutionEnvironment.get_execution_environment()
    # parallelism 4: gap semantics don't depend on shard count, and the
    # 8-shard exchange compile is covered by tests/test_exchange*.py
    env.set_parallelism(4)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(4096)
    env.batch_size = batch
    sink = CollectSink()
    stream = env.add_source(GeneratorSource(gen, total=total))
    if ooo_ms is not None:
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        stream = stream.assign_timestamps_and_watermarks(
            lambda c: c["ts"],
            WatermarkStrategy.for_bounded_out_of_orderness(ooo_ms),
        )
    stream = stream.key_by(lambda c: c["key"])
    if slide_ms == win_ms:
        w = stream.time_window(win_ms)
    else:
        w = stream.time_window(win_ms, slide_ms)
    w.sum(lambda c: c["value"]).add_sink(sink)
    job = env.execute("time-gap")
    return sink.results, job.metrics


@pytest.mark.parametrize("gap_ms", [30_000, 300_000])
def test_tumbling_survives_inter_poll_gap(gap_ms):
    """A mid-stream gap far larger than the pane ring: every record
    before AND after the gap must be emitted exactly once."""
    total, n_keys, win = 60_000, 50, 1000
    jump_at = 30_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        ts = idx // 20
        ts = np.where(idx >= jump_at, ts + gap_ms, ts)
        return ({"key": idx % n_keys, "value": np.ones(n, np.float32)},
                ts.astype(np.int64))

    results, metrics = _run(win, win, gen, total)
    assert metrics.dropped_capacity == 0
    assert metrics.dropped_late == 0
    assert sum(float(r.value) for r in results) == float(total)
    # exact per-cell totals
    cells = {}
    for r in results:
        cells[(int(r.key), int(r.window_end_ms))] = (
            cells.get((int(r.key), int(r.window_end_ms)), 0.0)
            + float(r.value)
        )
    exp = {}
    for i in range(total):
        t = i // 20 + (gap_ms if i >= jump_at else 0)
        cell = (i % n_keys, (t // win + 1) * win)
        exp[cell] = exp.get(cell, 0.0) + 1.0
    assert cells == exp


def test_sliding_windows_all_fire_across_gap():
    """Sliding windows: each pre-gap pane participates in size/slide
    windows; all of them must fire before the jump rotates the ring."""
    total, n_keys = 40_000, 20
    win, slide = 2000, 500
    jump_at, gap_ms = 20_000, 60_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        ts = idx // 10
        ts = np.where(idx >= jump_at, ts + gap_ms, ts)
        return ({"key": idx % n_keys, "value": np.ones(n, np.float32)},
                ts.astype(np.int64))

    results, metrics = _run(win, slide, gen, total)
    assert metrics.dropped_capacity == 0
    # each record belongs to size/slide = 4 windows
    assert sum(float(r.value) for r in results) == float(total) * 4
    cells = {}
    for r in results:
        cells[(int(r.key), int(r.window_end_ms))] = (
            cells.get((int(r.key), int(r.window_end_ms)), 0.0)
            + float(r.value)
        )
    exp = {}
    for i in range(total):
        t = i // 10 + (gap_ms if i >= jump_at else 0)
        pane = t // slide
        for w in range(4):   # windows ending at (pane+1+w)*slide
            cell = (i % n_keys, (pane + 1 + w) * slide)
            exp[cell] = exp.get(cell, 0.0) + 1.0
    assert cells == exp


def test_mid_size_gap_inside_ring_span_with_out_of_orderness():
    """The review-flagged band: a jump LARGER than the unfired horizon
    but SMALLER than the ring span. With 1s windows and 10s
    out-of-orderness the ring is ~14 panes; a 6-pane jump rotated
    unfired panes out under the original span_limit-sized threshold
    while never triggering the pre-fire. The >=2-pane threshold fires
    first."""
    total, n_keys, win = 40_000, 25, 1000
    jump_at, gap_ms = 20_000, 6_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        ts = idx // 20
        ts = np.where(idx >= jump_at, ts + gap_ms, ts)
        return ({"key": idx % n_keys, "value": np.ones(n, np.float32),
                 "ts": ts.astype(np.int64)},
                ts.astype(np.int64))

    results, metrics = _run(win, win, gen, total, ooo_ms=10_000)
    assert metrics.dropped_capacity == 0
    assert metrics.dropped_late == 0
    assert sum(float(r.value) for r in results) == float(total)


def test_sliding_mid_size_gap():
    """Sliding windows, jump of ~6 panes: below the old span_limit
    threshold (2*size/slide + 2 = 10) but beyond the safe horizon
    (size/slide + 1 = 5)."""
    total, n_keys = 30_000, 15
    win, slide = 2000, 500
    jump_at, gap_ms = 15_000, 3_000   # 6 panes of 500ms

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        ts = idx // 10
        ts = np.where(idx >= jump_at, ts + gap_ms, ts)
        return ({"key": idx % n_keys, "value": np.ones(n, np.float32)},
                ts.astype(np.int64))

    results, metrics = _run(win, slide, gen, total)
    assert metrics.dropped_capacity == 0
    assert sum(float(r.value) for r in results) == float(total) * 4


def test_repeated_gaps():
    """Several successive jumps, each larger than the ring."""
    total, n_keys, win = 50_000, 25, 1000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        # a 20s jump every 10k records
        ts = idx // 20 + (idx // 10_000) * 20_000
        return ({"key": idx % n_keys, "value": np.ones(n, np.float32)},
                ts.astype(np.int64))

    results, metrics = _run(win, win, gen, total)
    assert metrics.dropped_capacity == 0
    assert metrics.dropped_late == 0
    assert sum(float(r.value) for r in results) == float(total)
