"""End-to-end jobs through the DataStream API — the analog of the
reference's example ITCases (SocketWindowWordCountITCase etc., SURVEY §4)."""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource


def test_windowed_word_count_event_time():
    """SocketWindowWordCount shape (ref config #1): lines -> words ->
    (word,1) -> keyBy(word) -> 5s tumbling window -> sum."""
    lines = [
        (0, "to be or not to be"),
        (1000, "that is the question"),
        (6000, "to be to be"),
        (7000, "be"),
    ]
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(4).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 16

    sink = CollectSink()
    (
        env.from_collection(lines)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .flat_map(lambda e: [(e[0], w) for w in e[1].split()])
        .key_by(lambda e: e[1])
        .time_window(5000)
        .sum(lambda e: 1.0)
        .add_sink(sink)
    )
    env.execute("wordcount")

    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    expect = {
        ("to", 5000): 2.0, ("be", 5000): 2.0, ("or", 5000): 1.0,
        ("not", 5000): 1.0, ("that", 5000): 1.0, ("is", 5000): 1.0,
        ("the", 5000): 1.0, ("question", 5000): 1.0,
        ("to", 10000): 2.0, ("be", 10000): 3.0,
    }
    assert got == expect
    assert env.last_job.metrics.dropped_late == 0


def test_columnar_generator_tumbling_sum():
    """1M-key-shaped columnar fast path (ref config #2), small scale."""
    n_keys = 1000
    per_batch = 512

    def gen(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            "key": (idx * 2654435761) % n_keys,
            "value": np.ones(n, np.float32),
        }
        ts = (idx // 100) * 1000  # 100 events per second of event time
        return cols, ts

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(4).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(4096)
    env.batch_size = per_batch

    total = 4096
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda cols: cols["key"])
        .time_window(10_000)
        .sum(lambda cols: cols["value"])
        .add_sink(sink)
    )
    env.execute("gen-sum")

    # every event lands in exactly one window; sums must total `total`
    assert sum(r.value for r in sink.results) == total
    # per-key totals match a numpy model
    idx = np.arange(total)
    keys = (idx * 2654435761) % n_keys
    ts = (idx // 100) * 1000
    expect = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // 10_000 + 1) * 10_000
        expect[(k, we)] = expect.get((k, we), 0) + 1
    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    assert got == {k: float(v) for k, v in expect.items()}


def test_stateless_pipeline():
    env = StreamExecutionEnvironment.get_execution_environment()
    sink = CollectSink()
    (
        env.from_collection(range(10))
        .map(lambda x: x * 2)
        .filter(lambda x: x % 4 == 0)
        .add_sink(sink)
    )
    env.execute("stateless")
    assert sink.results == [0, 4, 8, 12, 16]


def test_sliding_window_mean():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(256)
    env.batch_size = 32
    events = [(t * 1000, "k", float(t)) for t in range(10)]
    sink = CollectSink()
    (
        env.from_collection(events)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(4000, 2000)
        .mean(lambda e: e[2])
        .add_sink(sink)
    )
    env.execute("sliding-mean")
    got = {r.window_end_ms: r.value for r in sink.results}
    # window [0,4000) ends 4000: mean(0,1,2,3) = 1.5
    assert got[4000] == 1.5
    # window [2000,6000): mean(2,3,4,5) = 3.5
    assert got[6000] == 3.5
    # trailing partial window [8000,12000): mean(8,9)=8.5
    assert got[12000] == 8.5
