"""ExecutionGraph: per-vertex attempt machine + job state machine (ref
ExecutionGraph.java / ExecutionVertex.java / ExecutionState.java), and
its live wiring through MiniCluster + the executor restart loop."""

import time

import numpy as np
import pytest

from flink_tpu.runtime.execution_graph import (
    ExecutionAttempt,
    ExecutionGraph,
    IllegalTransition,
)


def test_attempt_state_machine_legality():
    a = ExecutionAttempt(1)
    a.transition("SCHEDULED")
    a.transition("DEPLOYING")
    a.transition("RUNNING")
    with pytest.raises(IllegalTransition):
        a.transition("SCHEDULED")      # no going back
    a.transition("FINISHED")
    with pytest.raises(IllegalTransition):
        a.transition("FAILED")         # terminal is terminal
    # failure records its cause with the transition
    b = ExecutionAttempt(1)
    b.transition("SCHEDULED")
    b.transition("FAILED", cause="boom")
    assert b.failure_cause == "boom"
    assert "FAILED" in b.state_times


def test_restart_creates_new_attempts_preserving_history():
    eg = ExecutionGraph("j1", "job")
    from flink_tpu.runtime.execution_graph import ExecutionJobVertex

    eg.job_vertices[1] = ExecutionJobVertex("src", "Source", 2)
    eg.deploy_all()
    assert eg.state == "RUNNING"
    eg.fail_all("induced", will_restart=True)
    assert eg.state == "RUNNING" and eg.restarts == 1
    v = eg.job_vertices[1].vertices[0]
    assert v.current.attempt == 2 and v.current.state == "RUNNING"
    assert v.attempts[0].state == "FAILED"
    assert v.attempts[0].failure_cause == "induced"
    eg.finish_all()
    assert eg.state == "FINISHED"
    with pytest.raises(IllegalTransition):
        eg.transition_job("RUNNING")


def test_minicluster_attaches_and_drives_execution_graph():
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.sinks import CollectSink

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(1)
    env.batch_size = 8
    env.from_collection(list(range(32))).map(lambda x: x + 1) \
        .add_sink(CollectSink())
    cluster = MiniCluster()
    jid = cluster.submit(env, "eg-job")
    assert cluster.wait(jid, 30) == "FINISHED"
    eg = cluster.jobs[jid].execution_graph
    assert eg.state == "FINISHED"
    kinds = {v["type"] for v in eg.vertices_summary()}
    assert "Source" in kinds and "Sink" in kinds
    assert all(v["status"] == "FINISHED" for v in eg.vertices_summary())


def test_restart_notification_increments_attempts(tmp_path):
    """An induced failure under a restart strategy creates attempt 2 on
    every vertex (the executor's restart loop notifies the graph)."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.sinks import Sink
    from flink_tpu.runtime.sources import GeneratorSource

    class FailOnceSink(Sink):
        columnar = True
        tripped = [False]

        def invoke_columnar(self, cols):
            if not self.tripped[0]:
                self.tripped[0] = True
                raise RuntimeError("induced sink failure")

        def invoke_batch(self, elements):
            self.invoke_columnar({})

    env = StreamExecutionEnvironment(Configuration({
        "restart-strategy": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": 0,
    }))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(128)
    env.batch_size = 32
    env.enable_checkpointing(1, str(tmp_path / "chk"))

    def gen(off, n):
        idx = np.arange(off, off + n, dtype=np.int64)
        return {"key": idx % 16, "value": np.ones(n, np.float32)}, idx // 4

    (
        env.add_source(GeneratorSource(gen, total=256))
        .key_by(lambda c: c["key"])
        .time_window(16)
        .sum(lambda c: c["value"])
        .add_sink(FailOnceSink())
    )
    cluster = MiniCluster()
    jid = cluster.submit(env, "restart-job")
    assert cluster.wait(jid, 60) == "FINISHED"
    eg = cluster.jobs[jid].execution_graph
    assert eg.restarts >= 1
    assert eg.state == "FINISHED"
    v = next(iter(eg.job_vertices.values())).vertices[0]
    assert v.current.attempt >= 2
    assert v.attempts[0].state == "FAILED"
    # the REAL exception is the recorded failure cause
    assert "induced sink failure" in v.attempts[0].failure_cause
