"""Worker program for the env.execute()-over-DCN test: every process
runs THIS SAME program (the reference's same-jar-on-every-TaskManager
deployment, TaskManager.scala:296); the dcn.* config keys route the
standard pipeline through the cross-host plane.

Usage: python tests/dcn_env_job.py --coordinator H:P --num-processes N
           --process-id K --out OUT.npz [--session]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import numpy as np  # noqa: E402

import dcn_jobs as J  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--coordinator", required=True)
    ap.add_argument("--num-processes", type=int, required=True)
    ap.add_argument("--process-id", type=int, required=True)
    ap.add_argument("--out", required=True)
    ap.add_argument("--session", action="store_true")
    ap.add_argument("--shuffle", action="store_true",
                    help="apply .shuffle() before key_by over the "
                         "skewed source (physical ingest shuffle)")
    a = ap.parse_args()

    import jax

    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        jax.config.update("jax_platforms", plat)

    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.datastream.window.assigners import (
        EventTimeSessionWindows, SlidingEventTimeWindows,
    )
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    conf = {
        "dcn.coordinator": a.coordinator,
        "dcn.num-processes": a.num_processes,
        "dcn.process-id": a.process_id,
    }
    if a.shuffle:
        conf["dcn.rebalance-addrs"] = \
            os.environ["FLINK_TPU_TEST_REBALANCE_ADDRS"]
    env = StreamExecutionEnvironment(Configuration(conf))
    env.set_max_parallelism(64)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(2048)
    env.batch_size = 2048 if not a.session else 1024

    # THIS process's partition: the dcn_jobs source sliced by process id
    # (the raw deterministic fetch fn, so offset replay stays exact)
    src_fn = (J._session_source if a.session
              else J._skewed_source if a.shuffle else J._source)
    part = src_fn(a.process_id, a.num_processes)

    def gen(offset, n):
        keys, ts, vals = part.fn(offset, n)
        return (
            {"key": np.asarray(keys, np.int64),
             "value": np.asarray(vals, np.float32)},
            np.asarray(ts, np.int64),
        )

    total = (J.SESSION_TOTAL if a.session
             else part.total if a.shuffle else J.TOTAL_PER_HOST)
    sink = CollectSink()
    assigner = (
        EventTimeSessionWindows.with_gap(J.GAP_MS) if a.session
        else SlidingEventTimeWindows.of(
            J.WIN_MS, J.WIN_MS if a.shuffle else J.SLIDE_MS)
    )
    stream = env.add_source(GeneratorSource(gen, total=total))
    if a.shuffle:
        stream = stream.shuffle()      # the API annotation under test
    (
        stream
        .key_by(lambda c: c["key"])
        .window(assigner)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("dcn-env-job")

    if a.session:
        key = np.asarray([r.key for r in sink.results], np.int64)
        start = np.asarray(
            [r.window_start_ms for r in sink.results], np.int64
        )
        end = np.asarray([r.window_end_ms for r in sink.results], np.int64)
    else:
        key = np.asarray([r.key for r in sink.results], np.int64)
        start = np.zeros(len(key), np.int64)
        end = np.asarray([r.window_end_ms for r in sink.results], np.int64)
    val = np.asarray([r.value for r in sink.results], np.float32)
    tmp = a.out + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, key_id=key, window_start_ms=start, window_end_ms=end,
                 value=val)
    os.replace(tmp, a.out)
    print(f"rows={len(key)} pid={a.process_id} "
          f"ingested={job.metrics.dcn_ingested_local}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
