"""YARN deployment glue, end-to-end against the in-repo spec RM.

The full reference loop (flink-yarn/): descriptor creates a YARN
application and submits the AM container -> the AM starts the controller
runtime and registers (AbstractYarnClusterDescriptor.java,
YarnApplicationMasterRunner.java) -> jobs submitted through the session
client run in worker containers requested from the RM
(YarnFlinkResourceManager.java) -> a dead container is re-requested and
the job resumes from its checkpoint -> killing the application tears
down every process (YarnClusterClient.java shutdownCluster).

MiniYarnRM launches AM/worker commands as REAL OS processes, so these
are process-lifecycle tests, not protocol fakes (the MiniKafkaBroker
pattern).
"""

import os
import signal
import time

import pytest

from flink_tpu.deploy.yarn import (
    MiniYarnRM,
    YarnClusterDescriptor,
    YarnError,
    YarnRestClient,
)

JOBS = os.path.join(os.path.dirname(__file__), "process_jobs.py")
BUILDER = f"{JOBS}:build_window_job"


@pytest.fixture
def rm(tmp_path):
    m = MiniYarnRM(str(tmp_path / "yarn"))
    m.start()
    yield m
    m.stop()


def _wait(pred, timeout_s, what):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.1)
    raise TimeoutError(f"timed out waiting for {what}")


# -------------------------------------------------------------- protocol
def test_rest_protocol_surface(rm):
    rest = YarnRestClient(rm.url)
    info = rest.cluster_info()
    assert info["state"] == "STARTED"

    app = rest.new_application()
    app_id = app["application-id"]
    assert app_id.startswith("application_")
    assert app["maximum-resource-capability"]["memory"] >= 1024

    # unknown application -> 404 RemoteException
    with pytest.raises(YarnError, match="404"):
        rest.app_report("application_0_9999")

    # submit a trivial AM that registers and sleeps
    rest.submit_application({
        "application-id": app_id,
        "application-name": "proto-test",
        "am-container-spec": {
            "commands": {"command": (
                "python -c \"import os,time,json,urllib.request;"
                "u=os.environ['RM']+'/ws/v1/cluster/apps/'"
                "+os.environ['APP']+'/master';"
                "r=urllib.request.Request(u,"
                "json.dumps({'trackingUrl':'127.0.0.1:1'}).encode(),"
                "{'Content-Type':'application/json'});"
                "urllib.request.urlopen(r); time.sleep(600)\""
            )},
            "environment": {"entry": [
                {"key": "RM", "value": rm.url},
                {"key": "APP", "value": app_id},
            ]},
        },
        "resource": {"memory": 256, "vCores": 1},
    })
    _wait(lambda: rest.app_report(app_id)["state"] == "RUNNING",
          30, "AM registration")
    report = rest.app_report(app_id)
    assert report["trackingUrl"] == "127.0.0.1:1"
    assert report["name"] == "proto-test"

    # double submission is rejected
    with pytest.raises(YarnError, match="already"):
        rest.submit_application({
            "application-id": app_id,
            "am-container-spec": {"commands": {"command": "true"}},
        })

    # only KILLED is a legal target state
    with pytest.raises(YarnError, match="KILLED"):
        rest._call("PUT", f"/ws/v1/cluster/apps/{app_id}/state",
                   {"state": "RUNNING"})

    rest.kill(app_id)
    _wait(lambda: rest.app_report(app_id)["state"] == "KILLED",
          10, "kill")
    am = rm.apps[app_id].am
    _wait(lambda: am.proc.poll() is not None, 10,
          "AM process death after kill")


def test_failed_am_command_fails_application(rm):
    rest = YarnRestClient(rm.url)
    app_id = rest.new_application()["application-id"]
    rest.submit_application({
        "application-id": app_id,
        "am-container-spec": {"commands": {"command": "exit 3"}},
    })
    _wait(lambda: rest.app_report(app_id)["state"] == "FAILED",
          30, "AM exit to fail the app")
    assert rest.app_report(app_id)["finalStatus"] == "FAILED"


# ------------------------------------------------------------ end-to-end
def test_session_deploy_job_and_teardown(rm, tmp_path):
    desc = YarnClusterDescriptor(rm.url)
    client = desc.deploy_session_cluster("e2e-session")
    assert client.app_report()["state"] == "RUNNING"

    total = 20_000
    out = str(tmp_path / "out")
    wid = client.submit_job(
        BUILDER, "yarn-job", str(tmp_path / "chk"),
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
        },
    )
    assert client.wait_job(wid, timeout_s=180) == "FINISHED"

    # the worker genuinely ran in a YARN container (its terminal status
    # message races slightly ahead of the process exit, so poll)
    containers = client.rest.list_containers(client.app_id)
    assert len(containers) == 1
    _wait(
        lambda: client.rest.list_containers(client.app_id)[0]["state"]
        == "COMPLETE",
        15, "worker container exit",
    )
    assert client.rest.list_containers(client.app_id)[0]["exitStatus"] == 0

    import sys
    sys.path.insert(0, os.path.dirname(JOBS))
    from process_jobs import expected_cells

    cells = {}
    import glob
    for path in glob.glob(os.path.join(out, "**", "part-0"),
                          recursive=True):
        with open(path) as f:
            for line in f:
                k, wend, v = line.strip().split(",")
                cells[(int(k), int(wend))] = (
                    cells.get((int(k), int(wend)), 0.0) + float(v)
                )
    assert cells == expected_cells(total)

    report = client.shutdown_cluster()
    assert report["state"] == "KILLED"
    am = rm.apps[client.app_id].am
    _wait(lambda: am.proc.poll() is not None, 10, "AM teardown")


def test_container_death_rerequests_and_job_recovers(rm, tmp_path):
    desc = YarnClusterDescriptor(rm.url)
    client = desc.deploy_session_cluster("recovery-session")
    total = 32_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")
    wid = client.submit_job(
        BUILDER, "recover-job", chk,
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
            "FLINK_TPU_TEST_SLEEP_S": "0.05",   # keep it alive to kill
        },
    )
    # wait for a durable checkpoint, then kill the container PROCESS out
    # from under the AM (node failure, not a graceful stop)
    import glob as _glob
    _wait(lambda: _glob.glob(os.path.join(chk, "chk-*")), 120,
          "first checkpoint")
    first = client.rest.list_containers(client.app_id)[0]["id"]
    app = rm.apps[client.app_id]
    app.containers[first].proc.kill()

    assert client.wait_job(wid, timeout_s=240) == "FINISHED"
    containers = client.rest.list_containers(client.app_id)
    assert len(containers) >= 2, (
        "a replacement container must have been requested"
    )

    import sys
    sys.path.insert(0, os.path.dirname(JOBS))
    from process_jobs import expected_cells

    cells, dups = {}, 0
    for path in _glob.glob(os.path.join(out, "**", "part-0"),
                           recursive=True):
        with open(path) as f:
            for line in f:
                k, wend, v = line.strip().split(",")
                cell = (int(k), int(wend))
                if cell in cells:
                    dups += 1
                cells[cell] = cells.get(cell, 0.0) + float(v)
    assert dups == 0, f"{dups} duplicate (key, window) emissions"
    assert cells == expected_cells(total)
    client.shutdown_cluster()


def test_shell_submits_to_yarn_session(rm, tmp_path):
    """The interactive shell targets a YARN-deployed session's AM
    controller like any other cluster: a REPL-defined builder ships and
    runs in a YARN worker container (scala-shell + yarn-session
    composition, the reference's shell -> yarn attach flow)."""
    from flink_tpu.deploy.yarn import YarnClusterDescriptor
    from flink_tpu.shell import FlinkShell

    desc = YarnClusterDescriptor(rm.url)
    client = desc.deploy_session_cluster("shell-session")
    sh = FlinkShell(
        controller=f"{client.controller[0]}:{client.controller[1]}",
        job_dir=str(tmp_path / "jobs"),
    )
    out = str(tmp_path / "out")
    sh.run_source(
        "import os\n"
        "import numpy as np\n"
        "def build_job():\n"
        "    from flink_tpu import StreamExecutionEnvironment\n"
        "    from flink_tpu.core.time import TimeCharacteristic\n"
        "    from flink_tpu.connectors.files import BucketingFileSink\n"
        "    from flink_tpu.runtime.sources import GeneratorSource\n"
        "    e = StreamExecutionEnvironment.get_execution_environment()\n"
        "    e.set_parallelism(1)\n"
        "    e.set_max_parallelism(8)\n"
        "    e.set_stream_time_characteristic("
        "TimeCharacteristic.EventTime)\n"
        "    def gen(offset, n):\n"
        "        idx = np.arange(offset, offset + n, dtype=np.int64)\n"
        "        return ({'key': idx % 8,\n"
        "                 'value': np.ones(n, np.float32)},\n"
        "                (idx * 4000) // 10000)\n"
        "    sink = BucketingFileSink(\n"
        f"        {out!r},\n"
        "        formatter=lambda r:"
        " f'{r.key},{r.window_end_ms},{r.value:.0f}')\n"
        "    (e.add_source(GeneratorSource(gen, total=10000))\n"
        "       .key_by(lambda c: c['key'])\n"
        "       .time_window(1000).sum(lambda c: c['value'])\n"
        "       .add_sink(sink))\n"
        "    return e\n"
    )
    wid = sh.submit(sh.namespace["build_job"], job_name="shell-yarn-job")
    assert sh.wait(wid, timeout_s=180) == "FINISHED"
    # it genuinely ran in a YARN container
    containers = client.rest.list_containers(client.app_id)
    assert len(containers) == 1
    import glob as _glob
    total = 0.0
    for path in _glob.glob(os.path.join(out, "**", "part-0"),
                           recursive=True):
        with open(path) as f:
            total += sum(float(l.strip().split(",")[2]) for l in f)
    assert total == 10000.0
    client.shutdown_cluster()


def test_am_restart_recovers_jobs_exactly_once(rm, tmp_path):
    """Kill the ApplicationMaster mid-job with max-app-attempts=2: the
    RM kills the dead attempt's worker containers (no keep-containers),
    relaunches the AM, the new attempt recovers the job from the HA
    registry and resumes it from its checkpoint in a FRESH container,
    and the client re-resolves the moved controller — output exact with
    zero duplicates (YarnApplicationMasterRunner re-attempt + the
    reference's yarn.application-attempts/HA pairing)."""
    import glob as _glob

    desc = YarnClusterDescriptor(
        rm.url, max_app_attempts=2, am_ha_dir=str(tmp_path / "ha"),
    )
    client = desc.deploy_session_cluster("ha-session")
    total = 32_000
    out = str(tmp_path / "out")
    chk = str(tmp_path / "chk")
    wid = client.submit_job(
        BUILDER, "ha-job", chk,
        extra_env={
            "FLINK_TPU_TEST_OUT": out,
            "FLINK_TPU_TEST_TOTAL": str(total),
            "FLINK_TPU_TEST_SLEEP_S": "0.05",
        },
    )
    _wait(lambda: _glob.glob(os.path.join(chk, "chk-*")), 120,
          "first checkpoint")
    app = rm.apps[client.app_id]
    first_url = app.tracking_url
    app.am.proc.kill()                      # AM dies hard

    # polling the report is what detects the death and relaunches
    _wait(
        lambda: client.app_report()["currentAppAttemptId"] == 2
        and client.app_report()["state"] == "RUNNING",
        60, "AM re-attempt registration",
    )
    report = client.app_report()
    assert report["trackingUrl"] and report["trackingUrl"] != first_url

    # the client's next control call re-resolves the moved controller
    assert client.wait_job(wid, timeout_s=240) == "FINISHED"

    # the first attempt's worker container was killed by the RM; the
    # job finished in a container requested by attempt 2
    states = [(c["id"], c["state"], c["exitStatus"])
              for c in client.rest.list_containers(client.app_id)]
    assert len(states) >= 2
    assert states[0][1] == "COMPLETE" and states[0][2] == -137

    import sys
    sys.path.insert(0, os.path.dirname(JOBS))
    from process_jobs import expected_cells

    cells, dups = {}, 0
    for path in _glob.glob(os.path.join(out, "**", "part-0"),
                           recursive=True):
        with open(path) as f:
            for line in f:
                k, wend, v = line.strip().split(",")
                cell = (int(k), int(wend))
                if cell in cells:
                    dups += 1
                cells[cell] = cells.get(cell, 0.0) + float(v)
    assert dups == 0, f"{dups} duplicate (key, window) emissions"
    assert cells == expected_cells(total)
    client.shutdown_cluster()
