"""Native layer: C++ ring buffer, record codec, spill store (SURVEY §2.10
equivalents of the reference's Unsafe/Netty/RocksDB surfaces)."""

import os
import threading

import numpy as np
import pytest

from flink_tpu.native import RECORD_BYTES, RingBuffer, SpillStore


def test_ring_roundtrip_columnar():
    rb = RingBuffer(1 << 16)
    keys = np.arange(100, dtype=np.uint64)
    ts = (np.arange(100) * 10).astype(np.int64)
    vals = np.linspace(0, 1, 100).astype(np.float32)
    assert rb.write_records(keys, ts, vals)
    out = rb.read_batch()
    assert out is not None
    k, t, v = out
    np.testing.assert_array_equal(k, keys)
    np.testing.assert_array_equal(t, ts)
    np.testing.assert_allclose(v, vals)
    assert rb.read_batch() is None
    rb.close()


def test_ring_backpressure_and_wraparound():
    rb = RingBuffer(4096)
    batch = (
        np.arange(100, dtype=np.uint64),
        np.zeros(100, np.int64),
        np.ones(100, np.float32),
    )
    writes = 0
    while rb.write_records(*batch):   # fill until backpressure
        writes += 1
    assert writes == 4096 // (100 * RECORD_BYTES + 4)
    # drain one, write one: wraparound path
    for _ in range(50):
        assert rb.read_batch() is not None
        rb.write_records(*batch)
    # drain everything
    drained = 0
    while rb.read_batch() is not None:
        drained += 1
    assert drained > 0
    rb.close()


def test_ring_threaded_producer_consumer():
    rb = RingBuffer(1 << 20)
    N, B = 200, 256
    total = np.zeros(1)

    def produce():
        for i in range(N):
            keys = np.full(B, i, np.uint64)
            ts = np.zeros(B, np.int64)
            vals = np.ones(B, np.float32)
            while not rb.write_records(keys, ts, vals):
                pass

    t = threading.Thread(target=produce)
    t.start()
    got = 0
    while got < N * B:
        out = rb.read_batch()
        if out is None:
            continue
        got += len(out[0])
        total[0] += float(out[2].sum())
    t.join()
    assert got == N * B
    assert total[0] == N * B
    rb.close()


def test_shared_memory_ring_cross_handle():
    name = f"/flink-tpu-test-{os.getpid()}"
    producer = RingBuffer(1 << 14, name=name, create=True)
    consumer = RingBuffer(1 << 14, name=name, create=False)
    keys = np.array([7, 8], np.uint64)
    producer.write_records(keys, np.zeros(2, np.int64),
                           np.ones(2, np.float32))
    out = consumer.read_batch()
    np.testing.assert_array_equal(out[0], keys)
    consumer.close()
    producer.close()


def test_spill_store_put_get_delete():
    s = SpillStore(width=2, initial_capacity=16)
    keys = np.arange(1, 1001, dtype=np.uint64)
    vals = np.stack([keys.astype(np.float32), keys.astype(np.float32) * 2],
                    axis=1)
    s.put(keys, vals)
    assert len(s) == 1000
    got, found = s.get(np.array([1, 500, 9999], np.uint64))
    assert found.tolist() == [True, True, False]
    assert got[1].tolist() == [500.0, 1000.0]
    assert s.delete(np.array([500, 500, 777], np.uint64)) == 2
    _, found = s.get(np.array([500, 777, 1], np.uint64))
    assert found.tolist() == [False, False, True]
    assert len(s) == 998
    s.close()


def test_spill_store_grow_preserves_entries():
    s = SpillStore(width=1, initial_capacity=16)
    for chunk in range(10):
        keys = np.arange(chunk * 100, chunk * 100 + 100, dtype=np.uint64) + 1
        s.put(keys, keys.astype(np.float32))
    got, found = s.get(np.arange(1, 1001, dtype=np.uint64))
    assert found.all()
    np.testing.assert_allclose(got[:, 0], np.arange(1, 1001))
    s.close()


def test_spill_store_save_load(tmp_path):
    s = SpillStore(width=3, initial_capacity=16)
    keys = np.array([10, 20, 30], np.uint64)
    vals = np.arange(9, dtype=np.float32).reshape(3, 3)
    s.put(keys, vals)
    path = str(tmp_path / "spill.bin")
    s.save(path)
    s.close()
    s2 = SpillStore.load(path)
    assert s2.width == 3
    assert len(s2) == 3
    got, found = s2.get(np.array([20], np.uint64))
    assert found[0]
    assert got[0].tolist() == [3.0, 4.0, 5.0]
    dk, dv = s2.dump()
    assert sorted(dk.tolist()) == [10, 20, 30]
    s2.close()


def test_ring_source_end_to_end_window_job():
    """Producer thread -> native ring -> columnar window sum on device."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import RingBufferSource

    src = RingBufferSource(capacity=1 << 20)
    n_batches, B = 20, 512

    def produce():
        for i in range(n_batches):
            idx = np.arange(i * B, (i + 1) * B)
            src.ring.write_records(
                (idx % 100).astype(np.uint64),
                (idx * 2).astype(np.int64),
                np.ones(B, np.float32),
            )
        src.end_of_stream()

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 1024
    env.set_state_capacity(2048)
    sink = CollectSink()
    (
        env.add_source(src)
        .key_by(lambda c: c["key_id"])
        .time_window(5000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    t = threading.Thread(target=produce)
    t.start()
    env.execute("ring-ingest")
    t.join()
    assert sum(r.value for r in sink.results) == n_batches * B
