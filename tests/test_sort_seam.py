"""tools/check_segment_sort_seam.py wired as a tier-1 test (ISSUE 7
satellite): a ``jnp.sort``/``argsort``/``lax.sort`` call site added to
``flink_tpu/ops`` outside ``segment.py`` fails the suite — the one-sort
pre-combine seam (segment_sort feeding the acc scatter, fire
eligibility, kg_dirty, and kg_fill) must stay auditable in one file."""

import os
import subprocess
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

from check_segment_sort_seam import (  # noqa: E402
    check_source,
    check_tree,
    main,
    ops_files,
)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_repo_ops_tree_is_clean():
    violations = check_tree(ROOT)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_checker_scans_the_real_ops_tree():
    rels = {rel.replace(os.sep, "/") for _p, rel in ops_files(ROOT)}
    assert "flink_tpu/ops/window_kernels.py" in rels
    assert "flink_tpu/ops/segment.py" in rels
    assert "flink_tpu/ops/rolling.py" in rels
    assert len(rels) > 5


def test_checker_flags_every_sort_spelling():
    src = (
        "import jax\n"
        "import jax.numpy as jnp\n"
        "from jax import lax\n"
        "def kernel(x, k, v):\n"
        "    a = jnp.sort(x)\n"
        "    b = jnp.argsort(x)\n"
        "    c = jax.lax.sort(x)\n"
        "    d = lax.sort_key_val(k, v)\n"
        "    e = jnp.lexsort((x,))\n"
        "    return a, b, c, d, e\n"
    )
    vs = check_source(src, "flink_tpu/ops/fake.py")
    assert [v.line for v in vs] == [5, 6, 7, 8, 9]
    assert {v.what for v in vs} == {
        "jnp.sort", "jnp.argsort", "jax.lax.sort",
        "lax.sort_key_val", "jnp.lexsort",
    }


def test_checker_allows_segment_py_itself():
    src = "import jax.numpy as jnp\ndef s(x):\n    return jnp.argsort(x)\n"
    assert check_source(src, "flink_tpu/ops/segment.py") == []
    # ...but the same code anywhere else in ops/ is a violation
    assert len(check_source(src, "flink_tpu/ops/other.py")) == 1


def test_checker_ignores_non_sort_calls_and_prose():
    src = (
        "import jax.numpy as jnp\n"
        "def kernel(x, xs):\n"
        "    '''prose about jnp.sort( and argsort'''\n"
        "    # jnp.argsort(x) in a comment\n"
        "    xs.sort()\n"            # list.sort: bare receiver, not a module
        "    return jnp.where(x > 0, x, 0)\n"
    )
    assert check_source(src, "flink_tpu/ops/fake.py") == []


def test_reintroduced_per_plane_sort_is_caught():
    """The regression this tool exists for: someone re-deriving a
    per-plane order inside window_kernels instead of reusing the shared
    segment_sort permutation."""
    path = os.path.join(ROOT, "flink_tpu", "ops", "window_kernels.py")
    with open(path) as f:
        src = f.read()
    assert check_source(src, "flink_tpu/ops/window_kernels.py") == []
    patched = src + "\n\ndef rogue(x):\n    import jax.numpy as jnp\n" \
        "    return jnp.argsort(x)\n"
    vs = check_source(patched, "flink_tpu/ops/window_kernels.py")
    assert len(vs) == 1 and vs[0].func == "rogue"


def test_cli_entrypoint():
    assert main(["--root", ROOT]) == 0
    rc = subprocess.run(
        [sys.executable,
         os.path.join(ROOT, "tools", "check_segment_sort_seam.py")],
        capture_output=True,
    )
    assert rc.returncode == 0, rc.stderr.decode()
