"""Step-loop span tracing + device-resident telemetry (ISSUE 2).

The classic per-record observability of the reference (LatencyMarker
sampling, stack-trace back-pressure probes) is structurally impossible
over whole-key-group XLA kernels — visibility comes from the step loop
(span tracer, metrics/tracing.py) and from device-side scalars (key-group
skew, watermark lag). These tests pin: the tracer mechanics (bounding,
sampling, Chrome-trace validity), the executor wiring (every step-loop
phase appears as a span), the web surface (/traces, /keygroups,
/metrics), and the JSON-404 guards on job-scoped endpoints.
"""

import json
import urllib.error
import urllib.request

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.metrics.tracing import CompileEvents, SpanTracer
from flink_tpu.runtime.sinks import CountingSink
from flink_tpu.runtime.sources import GeneratorSource


# ---------------------------------------------------------- tracer unit

def test_span_tracer_ring_and_sampling():
    tr = SpanTracer(stage="s", sample_every=3, max_spans=16)
    # sampling: cycle 0 records, 1-2 don't, 3 records again
    assert tr.begin_cycle() is True
    assert tr.begin_cycle() is False
    assert tr.begin_cycle() is False
    assert tr.begin_cycle() is True
    # ring bound: 40 spans into a 16-slot ring keeps the NEWEST 16
    for i in range(40):
        tr.rec(f"span{i}", 0.0, 1.0)
    assert len(tr) == 16
    names = [s[0] for s in tr.snapshot()]
    assert names[0] == "span24" and names[-1] == "span39"
    assert tr.dropped == 40 - 16


def test_span_tracer_chrome_trace_shape(tmp_path):
    tr = SpanTracer(stage="job-x")
    tr.begin_cycle()
    tr.rec("source", 10.0, 10.5, records=7)
    tr.rec("dispatch", 10.5, 10.6)
    ct = tr.to_chrome_trace()
    # the export must round-trip through json (the endpoint contract)
    parsed = json.loads(json.dumps(ct))
    evs = parsed["traceEvents"]
    assert len(evs) == 2
    for ev in evs:
        assert ev["ph"] == "X"
        assert isinstance(ev["ts"], (int, float))
        assert isinstance(ev["dur"], (int, float))
        assert ev["dur"] >= 0
    assert evs[0]["name"] == "source"
    assert evs[0]["args"] == {"records": 7}
    assert evs[1]["ts"] >= evs[0]["ts"]
    # file dump is the same JSON
    p = tr.dump(str(tmp_path / "trace.json"))
    on_disk = json.load(open(p))
    assert on_disk["traceEvents"] == parsed["traceEvents"]


def test_span_tracer_counter_tracks():
    """Round 14: `rec_counter` samples export as Perfetto counter events
    ("ph": "C") alongside the phase spans — one stacked lane per track,
    the kwargs as the stack components. Counters live in their own ring
    so a chatty fill series can never evict spans."""
    tr = SpanTracer(stage="job-c", max_spans=16)
    tr.begin_cycle()
    tr.rec("dispatch", 10.0, 10.5)
    tr.rec_counter("drain/shard0", 10.1, fill=3, duty_pct=75.0)
    tr.rec_counter("drain/shard1", 10.2, fill=0, duty_pct=12.5)
    tr.rec_counter("empty_is_dropped", 10.3)   # no values: no event
    ct = json.loads(json.dumps(tr.to_chrome_trace()))
    cs = [ev for ev in ct["traceEvents"] if ev["ph"] == "C"]
    assert len(cs) == 2
    assert cs[0]["name"] == "drain/shard0"
    assert cs[0]["cat"] == "counter"
    assert cs[0]["args"] == {"fill": 3.0, "duty_pct": 75.0}
    assert cs[1]["args"]["fill"] == 0.0
    # counters ride the same pid so they stack above the span lanes
    assert all(ev["pid"] == 1 for ev in ct["traceEvents"])
    # spans survive a counter flood (independent rings)
    for i in range(100):
        tr.rec_counter("noisy", 11.0 + i, fill=i)
    assert [s[0] for s in tr.snapshot()] == ["dispatch"]


def test_span_context_manager_respects_active():
    tr = SpanTracer(sample_every=2)
    tr.begin_cycle()            # active
    with tr.span("a"):
        pass
    tr.begin_cycle()            # inactive
    with tr.span("b"):
        pass
    assert [s[0] for s in tr.snapshot()] == ["a"]


# ------------------------------------------------- executor wiring (e2e)

def _windowed_env(extra_cfg=None, total=20_000):
    env = StreamExecutionEnvironment(Configuration({
        "observability.tracing": True,
        "observability.kg-stats-interval-ms": 0,
        **(extra_cfg or {}),
    }))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1 << 12)
    env.batch_size = 1024

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return {"key": idx % 100, "value": np.ones(n, np.float32)}, idx // 10

    sink = CountingSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(500)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    return env, sink


def test_windowed_job_records_step_phase_spans():
    env, sink = _windowed_env()
    env.execute("traced-job")
    assert sink.value_sum == 20_000
    tr = env._span_tracer
    assert tr is not None and len(tr) > 0
    names = {s[0] for s in tr.snapshot()}
    # every hot phase of the loop must appear (checkpoint_sync needs a
    # checkpointing job — covered below)
    assert {"source", "route", "dispatch", "fire", "barrier_fetch",
            "emit"} <= names
    ct = tr.to_chrome_trace()
    assert ct["traceEvents"], "trace export must be non-empty"
    # skew + lag telemetry landed in the registry
    snap = env.metric_registry.snapshot("jobs.traced-job.")
    assert snap["jobs.traced-job.kg_occupied_groups"] > 0
    assert snap["jobs.traced-job.kg_occupancy_max"] >= 1
    assert snap["jobs.traced-job.kg_skew_ratio"] >= 1.0
    assert snap["jobs.traced-job.kg_fill_max"] > 0
    assert snap["jobs.traced-job.watermark_ms"] > 0
    assert snap["jobs.traced-job.event_time_lag_ms"] >= 0
    assert snap["jobs.traced-job.watermark_lag_ms"] is not None
    # compile visibility: the warmup compiles were counted + attributed
    assert snap["jobs.traced-job.xla_compile_count"] > 0
    rep = env._compile_report()
    assert any(k.startswith("window-update") for k in rep["by_stage"])
    # hot-group report serves top-k
    top = env._kg_report(3)
    assert 1 <= len(top["occupancy_top"]) <= 3
    assert top["occupancy_top"][0]["count"] >= 1


def test_tracing_off_by_default_and_sampling():
    env, _ = _windowed_env({"observability.tracing": False})
    env.execute("untraced")
    assert env._span_tracer is None

    env2, _ = _windowed_env({"observability.trace-sample-every": 1000})
    env2.execute("sampled")
    # cycle 0 is sampled, later cycles are not: far fewer spans than steps
    spans = len(env2._span_tracer)
    steps = env2.last_job.metrics.steps
    assert 0 < spans < steps + 10


def test_kg_stats_gating():
    """The occupancy kernel is gated by observability.kg-stats, which
    defaults to the tracing flag: the shipping default pays nothing; the
    explicit flag lights up skew telemetry without span tracing."""
    env, _ = _windowed_env({
        "observability.tracing": False,
        "observability.kg-stats": True,
    }, total=8192)
    env.execute("kg-only")
    assert env._span_tracer is None
    snap = env.metric_registry.snapshot("jobs.kg-only.")
    assert snap["jobs.kg-only.kg_occupied_groups"] > 0

    env2, _ = _windowed_env({"observability.tracing": False}, total=8192)
    env2.execute("default-job")
    # default: no occupancy kernel ran (cache stays empty)
    snap2 = env2.metric_registry.snapshot("jobs.default-job.")
    assert snap2["jobs.default-job.kg_occupied_groups"] == 0


def test_drain_stats_gating():
    """The drain flight recorder is gated by observability.drain-stats
    (defaulting to the tracing flag, same discipline as kg-stats): off
    means the drain kernels compile WITHOUT the telemetry payload (the
    trace-tier ledger test pins byte-identity) and the /pipeline report
    stays unavailable; on lights up the per-shard aggregation without
    span tracing."""
    resident = {
        "observability.tracing": False,
        "pipeline.prefetch": "on",
        "pipeline.device-staging": "on",
        "pipeline.resident-loop": "on",
        "pipeline.ring-depth": 4,
    }
    env, _ = _windowed_env({
        **resident,
        "observability.drain-stats": True,
        # fetch the payload on every drain: short jobs drain only a
        # handful of times, far fewer than the default sampling stride
        "observability.drain-stats-every": 1,
    }, total=8192)
    env.execute("drain-only")
    assert env._span_tracer is None
    rep = env._pipeline_report()
    assert rep["available"] is True
    assert rep["n_shards"] == 1 and rep["ring_depth"] == 4
    assert rep["drains"] > 0 and rep["payload_fetches"] > 0
    assert rep["shards"][0]["totals"]["events"] > 0
    assert rep["shards"][0]["occupancy"]

    # default (tracing off): the recorder never instantiates
    env2, _ = _windowed_env(resident, total=4096)
    env2.execute("drain-default")
    rep2 = env2._pipeline_report()
    assert rep2["available"] is False and "reason" in rep2


def test_checkpoint_sync_span_and_trace_dump(tmp_path):
    dump = tmp_path / "trace.json"
    env, _ = _windowed_env({
        "observability.trace-dump": str(dump),
    })
    env.enable_checkpointing(4, str(tmp_path / "ck"))
    env.execute("ck-traced")
    names = {s[0] for s in env._span_tracer.snapshot()}
    assert "checkpoint_sync" in names
    # the end-of-job dump wrote loadable Chrome-trace JSON
    on_disk = json.load(open(dump))
    assert on_disk["traceEvents"]


# --------------------------------------------------------- web endpoints

def _get_json(port, path):
    with urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    ) as r:
        return json.loads(r.read())


def test_web_traces_keygroups_and_prometheus():
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _windowed_env()
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "obs-web-job")
    try:
        assert cluster.wait(jid, 120) == "FINISHED"
        # acceptance: /traces returns valid Chrome-trace JSON with the
        # step-phase spans
        tr = _get_json(port, f"/jobs/{jid}/traces")
        assert tr["enabled"] is True
        assert tr["traceEvents"], "non-empty traceEvents required"
        names = {ev["name"] for ev in tr["traceEvents"]}
        assert {"source", "dispatch", "barrier_fetch", "emit"} <= names
        # skew telemetry over the web API
        kg = _get_json(port, f"/jobs/{jid}/keygroups?k=5")
        assert kg["available"] is True
        assert kg["occupancy_top"] and kg["fill_top"]
        assert len(kg["occupancy_top"]) <= 5
        # gauges visible via the job metric snapshot...
        snap = _get_json(port, f"/jobs/{jid}/metrics")
        assert snap["jobs.obs-web-job.kg_skew_ratio"] >= 1.0
        assert "jobs.obs-web-job.watermark_lag_ms" in snap
        # ...and via the Prometheus endpoint (text exposition, one port)
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            text = r.read().decode()
        assert '# TYPE flink_tpu_kg_skew_ratio gauge' in text
        assert 'flink_tpu_kg_skew_ratio{job="obs-web-job"}' in text
        assert 'flink_tpu_watermark_lag_ms{job="obs-web-job"}' in text
        assert 'flink_tpu_records_in{job="obs-web-job"} 20000' in text
    finally:
        web.stop()


def test_web_job_scoped_endpoints_404_unknown_job():
    """Unknown/finished job ids on job-scoped endpoints return a JSON 404
    body, never a raised 500 (satellite: guard the web surface)."""
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    try:
        for path in (
            "/jobs/nope", "/jobs/nope/traces", "/jobs/nope/keygroups",
            "/jobs/nope/backpressure", "/jobs/nope/checkpoints",
            "/jobs/nope/metrics", "/jobs/nope/checkpoints/config",
            "/jobs/nope/plan", "/jobs/nope/exceptions",
            "/jobs/nope/recovery", "/jobs/nope/elasticity",
            "/jobs/nope/pipeline", "/jobs/nope/doctor",
            "/jobs/nope/controller",
        ):
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get_json(port, path)
            assert ei.value.code == 404, path
            body = json.loads(ei.value.read())
            assert "error" in body, path
    finally:
        web.stop()


def test_web_traces_job_without_tracing():
    """A known job that never enabled tracing gets a 200 with an explicit
    enabled:false payload — distinguishable from an unknown job's 404."""
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    env, _ = _windowed_env({"observability.tracing": False}, total=2048)
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    jid = cluster.submit(env, "untraced-web")
    try:
        assert cluster.wait(jid, 120) == "FINISHED"
        tr = _get_json(port, f"/jobs/{jid}/traces")
        assert tr["enabled"] is False and tr["traceEvents"] == []
    finally:
        web.stop()


# ------------------------------------------------------ compile tracking

def test_compile_events_counts_and_stage_attribution():
    import jax
    import jax.numpy as jnp

    CompileEvents.install()
    mark = CompileEvents.mark()

    @jax.jit
    def f(x):
        return x * 3 + 1

    with CompileEvents.stage("test-stage"):
        f(jnp.arange(7)).block_until_ready()
    count, secs = CompileEvents.since(mark)
    assert count >= 1 and secs > 0
    rep = CompileEvents.report()
    assert rep["by_stage"]["test-stage"]["count"] >= 1
