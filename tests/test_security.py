"""Control-plane auth (runtime/security.py — SecurityContext.java:53
analog): token-protected controllers reject unauthenticated requests
before dispatch; spawned workers inherit the secret and register."""

import json
import socket

import pytest

from flink_tpu.runtime import security
from flink_tpu.runtime.process_cluster import ProcessCluster


def _raw_request(port, req):
    """Bypass control_request's auto-attach: send exactly `req`."""
    with socket.create_connection(("127.0.0.1", port), timeout=10) as s:
        s.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = s.recv(1 << 16)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf)


def test_token_resolution(tmp_path, monkeypatch):
    monkeypatch.delenv(security.ENV_TOKEN, raising=False)
    monkeypatch.delenv(security.ENV_TOKEN_FILE, raising=False)
    assert security.get_token() is None
    monkeypatch.setenv(security.ENV_TOKEN, "s3cret")
    assert security.get_token() == "s3cret"
    monkeypatch.delenv(security.ENV_TOKEN)
    p = tmp_path / "tok"
    p.write_text("filetok\n")
    monkeypatch.setenv(security.ENV_TOKEN_FILE, str(p))
    assert security.get_token() == "filetok"
    # explicit config beats environment
    from flink_tpu.core.config import Configuration

    assert security.get_token(
        Configuration({"security.auth.token": "cfg"})
    ) == "cfg"


def test_check_rejects_bad_or_missing_token():
    security.check(None, {})                       # auth off: open
    security.check("t", {"auth": "t"})
    with pytest.raises(PermissionError):
        security.check("t", {})
    with pytest.raises(PermissionError):
        security.check("t", {"auth": "wrong"})
    with pytest.raises(PermissionError):
        security.check("t", {"auth": 42})


def test_protected_controller_rejects_unauthenticated(monkeypatch):
    monkeypatch.setenv(security.ENV_TOKEN, "hunter2")
    cluster = ProcessCluster(heartbeat_timeout_s=5.0)
    port = cluster.start()
    try:
        # raw request without the token: rejected before dispatch
        resp = _raw_request(port, {"action": "list"})
        assert not resp["ok"] and "auth" in resp["error"]
        # wrong token: rejected
        resp = _raw_request(port, {"action": "list", "auth": "nope"})
        assert not resp["ok"]
        # the authenticated client path (control_request attaches the
        # inherited env token) works
        from flink_tpu.runtime.cluster import control_request

        resp = control_request("127.0.0.1", port, {"action": "list"})
        assert resp["ok"] and resp["workers"] == []
    finally:
        cluster.shutdown()


def test_worker_inherits_token_and_registers(tmp_path, monkeypatch):
    monkeypatch.setenv(security.ENV_TOKEN, "wkr-secret")
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    cluster = ProcessCluster(heartbeat_timeout_s=10.0)
    port = cluster.start()
    try:
        wid = cluster.submit(
            "tests/process_jobs.py:build_window_job", "auth-job",
            str(tmp_path / "ckpt"),
            extra_env={
                "FLINK_TPU_TEST_OUT": str(tmp_path / "out"),
                "FLINK_TPU_TEST_TOTAL": "2048",
            },
        )
        assert cluster.wait(wid, timeout_s=120.0) == "FINISHED"
        # the worker's register/heartbeat/status all authenticated
        kinds = {e["event"] for e in cluster.events}
        assert "registered" in kinds
    finally:
        cluster.shutdown()


# ---------------------------------------------------------- HTTP plane

def _http_get(port, path, headers=None):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_web_monitor_requires_token_when_configured():
    """The HTTP plane (web monitor + queryable state reads) 401s without
    the shared secret — state values are exactly the data worth
    protecting (ref KvStateServerHandler)."""
    from flink_tpu.core.config import Configuration
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    cluster = MiniCluster()
    web = WebMonitor(cluster, config=Configuration(
        {"security.auth.token": "webtok"}))
    port = web.start()
    try:
        # missing + wrong tokens rejected on every route, /web included
        for path in ("/overview", "/jobs", "/jobs/x/state/s?key=1", "/web"):
            code, body = _http_get(port, path)
            assert code == 401, (path, code)
            assert body["error"] == "unauthorized"
        code, _ = _http_get(
            port, "/jobs", headers={"Authorization": "Bearer nope"})
        assert code == 401
        code, _ = _http_get(port, "/jobs?token=wrong")
        assert code == 401
        # correct token accepted via header AND query param
        code, body = _http_get(
            port, "/jobs", headers={"Authorization": "Bearer webtok"})
        assert code == 200 and body == {"jobs": []}
        code, body = _http_get(port, "/overview?token=webtok")
        assert code == 200 and "flink-tpu-version" in body
    finally:
        web.stop()


def test_web_monitor_open_without_token(monkeypatch):
    monkeypatch.delenv(security.ENV_TOKEN, raising=False)
    monkeypatch.delenv(security.ENV_TOKEN_FILE, raising=False)
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    try:
        code, body = _http_get(port, "/jobs")
        assert code == 200 and body == {"jobs": []}
    finally:
        web.stop()


def test_queryable_client_attaches_token(monkeypatch):
    """QueryableStateClient sends the Bearer token: with it, requests
    reach routing (404 for an unknown job); without it, 401."""
    import urllib.error

    monkeypatch.delenv(security.ENV_TOKEN, raising=False)
    monkeypatch.delenv(security.ENV_TOKEN_FILE, raising=False)
    from flink_tpu.core.config import Configuration
    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.queryable import QueryableStateClient
    from flink_tpu.runtime.web import WebMonitor

    cluster = MiniCluster()
    web = WebMonitor(cluster, config=Configuration(
        {"security.auth.token": "qtok"}))
    port = web.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            QueryableStateClient("127.0.0.1", port).get_kv_state(
                "nojob", "s", 1)
        assert ei.value.code == 401
        # with the token the request clears auth and reaches routing:
        # an unknown job is a 404, not a 401
        with pytest.raises(urllib.error.HTTPError) as ei:
            QueryableStateClient("127.0.0.1", port,
                                 token="qtok").get_kv_state("nojob", "s", 1)
        assert ei.value.code == 404
        # env-var resolution path (the deployment default)
        monkeypatch.setenv(security.ENV_TOKEN, "qtok")
        with pytest.raises(urllib.error.HTTPError) as ei:
            QueryableStateClient("127.0.0.1", port).get_kv_state(
                "nojob", "s", 1)
        assert ei.value.code == 404
    finally:
        web.stop()
