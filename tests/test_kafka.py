"""Kafka wire-protocol connector: the client speaks the public binary
protocol (Metadata/Produce/Fetch/ListOffsets v0, MessageSet v0 with
CRC32) against a real TCP broker (MiniKafkaBroker — in-repo, same public
spec; no Kafka server exists in this image). Covers byte-level framing,
CRC validation, producer/consumer round trips through real jobs, and
the checkpoint-offset replay contract."""

import struct
import zlib

import numpy as np
import pytest

from flink_tpu.connectors.kafka import (
    KafkaConsumer,
    KafkaProducerSink,
    KafkaWireClient,
    MiniKafkaBroker,
    decode_message_set,
    encode_message_set,
)


@pytest.fixture()
def broker():
    b = MiniKafkaBroker(topics={"events": 2})
    yield b
    b.shutdown()


def test_message_set_round_trip_and_crc():
    ms = encode_message_set([(b"k1", b"v1"), (None, b"v2")], base_offset=5)
    out = decode_message_set(ms)
    assert out == [(5, b"k1", b"v1"), (6, None, b"v2")]
    # flip one payload byte: CRC must catch it
    bad = bytearray(ms)
    bad[-1] ^= 0xFF
    with pytest.raises(IOError, match="CRC"):
        decode_message_set(bytes(bad))
    # partial trailing message is dropped, not an error (spec behavior)
    assert decode_message_set(ms[:-3]) == [(5, b"k1", b"v1")]


def test_wire_client_apis(broker):
    c = KafkaWireClient(broker.host, broker.port)
    assert c.metadata(["events"]) == {"events": [0, 1]}
    with pytest.raises(IOError, match="nope"):
        c.metadata(["nope"])        # errored topics raise, never vanish
    base = c.produce("events", 0, [(None, b"a"), (b"key", b"b")])
    assert base == 0
    assert c.produce("events", 0, [(None, b"c")]) == 2
    msgs, hw = c.fetch("events", 0, 0)
    assert hw == 3
    assert [(o, v) for o, _k, v in msgs] == [(0, b"a"), (1, b"b"), (2, b"c")]
    # offset-addressed re-fetch (the replay primitive)
    msgs2, _ = c.fetch("events", 0, 1)
    assert [v for _o, _k, v in msgs2] == [b"b", b"c"]
    assert c.list_offsets("events", 0, -2) == 0      # earliest
    assert c.list_offsets("events", 0, -1) == 3      # latest
    c.close()


def test_consumer_through_streaming_job(broker):
    """Broker -> KafkaConsumer -> keyed window -> sink, exact counts."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.runtime.sinks import CollectSink

    for i in range(120):
        broker.append("events", i % 2, None, f"w{i % 6}".encode())
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(1)
    env.batch_size = 16
    sink = CollectSink()
    src = KafkaConsumer(broker.host, broker.port, "events")
    (
        env.add_source(src)
        .key_by(lambda w: w)
        .reduce(lambda a, b: a + b, extractor=lambda w: 1.0)
        .add_sink(sink)
    )
    env.execute("kafka-wordcount")
    finals = {}
    for key, value in sink.results:
        finals[key] = max(finals.get(key, 0), value)
    assert finals == {f"w{j}": 20.0 for j in range(6)}
    src.close()


def test_offset_snapshot_replay_exactly_once(broker):
    """Consume some, snapshot offsets, resume a FRESH consumer from the
    snapshot: union is exactly the log, no loss, no duplicates (ref
    FlinkKafkaConsumerBase.snapshotState/restoreState)."""
    for i in range(40):
        broker.append("events", i % 2, None, str(i).encode())

    a = KafkaConsumer(broker.host, broker.port, "events")
    a.open()
    seen = []
    got, _end = a.poll(10)
    seen.extend(got)
    offs = a.snapshot_offsets()
    a.close()

    b = KafkaConsumer(broker.host, broker.port, "events")
    b.restore_offsets(offs)
    b.open()
    end = False
    while not end:
        got, end = b.poll(16)
        seen.extend(got)
    b.close()
    assert sorted(int(v) for v in seen) == list(range(40))


def test_producer_sink_and_broker_restart(broker):
    """Producer sink writes over the wire; the client reconnects through
    a broker restart on the same port (reference reconnect behavior)."""
    sink = KafkaProducerSink(broker.host, broker.port, "events",
                             partition=1)
    sink.invoke_batch(["alpha", "beta"])
    assert [v for _k, v in broker.logs[("events", 1)]] == [b"alpha",
                                                           b"beta"]
    # restart the broker on the SAME port; topic state is fresh
    port = broker.port
    broker.shutdown()
    b2 = MiniKafkaBroker(port=port, topics={"events": 2})
    try:
        sink.invoke_batch(["gamma"])
        assert [v for _k, v in b2.logs[("events", 1)]] == [b"gamma"]
        assert sink.records_written == 3
    finally:
        b2.shutdown()
        sink.close()
