"""Batch DataSet API semantics — mirrors the reference's batch example
ITCases (WordCount, joins, iterations; SURVEY §2.6/§2.9)."""

import numpy as np
import pytest

from flink_tpu.dataset import ExecutionEnvironment


def _env():
    return ExecutionEnvironment.get_execution_environment()


def test_word_count():
    text = ["to be or not to be", "that is the question"]
    env = _env()
    counts = (
        env.from_collection(text)
        .flat_map(lambda line: line.split())
        .map(lambda w: (w, 1))
        .group_by(0)
        .sum(1)
        .collect()
    )
    d = dict(counts)
    assert d["to"] == 2.0 and d["be"] == 2.0 and d["question"] == 1.0


def test_grouped_aggregates_device_path():
    env = _env()
    data = [(f"k{i % 3}", float(i)) for i in range(30)]
    ds = env.from_collection(data).group_by(0)
    assert dict(ds.max(1).collect())["k0"] == 27.0
    assert dict(ds.min(1).collect())["k1"] == 1.0
    assert dict(ds.count().collect())["k2"] == 10.0
    assert dict(ds.mean(1).collect())["k0"] == pytest.approx(13.5)


def test_grouped_reduce_and_group_reduce():
    env = _env()
    ds = env.from_collection([("a", 2), ("a", 3), ("b", 5)])
    out = ds.group_by(0).reduce(lambda x, y: (x[0], x[1] * y[1])).collect()
    assert sorted(out) == [("a", 6), ("b", 5)]
    out = (
        env.from_collection([("a", 3), ("a", 1), ("b", 2)])
        .group_by(0).sort_group(1).reduce_group(
            lambda g: [tuple(v for _, v in g)]
        ).collect()
    )
    assert sorted(out) == [(1, 3), (2,)]


def test_joins():
    env = _env()
    users = env.from_collection([(1, "alice"), (2, "bob"), (3, "carol")])
    orders = env.from_collection([(1, "x"), (1, "y"), (3, "z"), (9, "w")])
    inner = users.join(orders).where(0).equal_to(0).apply(
        lambda u, o: (u[1], o[1])
    ).collect()
    assert sorted(inner) == [("alice", "x"), ("alice", "y"), ("carol", "z")]

    left = users.left_outer_join(orders).where(0).equal_to(0).apply(
        lambda u, o: (u[1], o[1] if o else None)
    ).collect()
    assert ("bob", None) in left

    full = users.full_outer_join(orders).where(0).equal_to(0).apply(
        lambda u, o: ((u or o)[0], u is not None, o is not None)
    ).collect()
    assert (9, False, True) in full

    cg = users.co_group(orders).where(0).equal_to(0).apply(
        lambda us, os_: [(len(us), len(os_))]
    ).collect()
    assert sorted(cg) == [(0, 1), (1, 0), (1, 1), (1, 2)]


def test_cross_distinct_first_sort_union():
    env = _env()
    a = env.from_elements(1, 2)
    b = env.from_elements("x", "y")
    assert sorted(a.cross(b).collect()) == [
        (1, "x"), (1, "y"), (2, "x"), (2, "y")
    ]
    assert sorted(
        env.from_collection([3, 1, 3, 2, 1]).distinct().collect()
    ) == [1, 2, 3]
    assert env.from_collection([5, 3, 1]).sort_partition(
        ascending=True
    ).first(2).collect() == [1, 3]
    assert sorted(a.union(env.from_elements(7)).collect()) == [1, 2, 7]
    assert env.generate_sequence(1, 5).zip_with_index().collect()[2] == (2, 3)


def test_full_reduce_and_aggregates():
    env = _env()
    assert env.generate_sequence(1, 10).reduce(
        lambda a, b: a + b
    ).collect() == [55]
    assert env.from_collection([(1, 9), (2, 3)]).min_by(1).collect() == [(2, 3)]
    assert env.from_collection([1.5, 2.5]).sum().collect() == [4.0]


def test_bulk_iteration_pi_style():
    """KMeans-flavored bulk iteration: 1-D centroid refinement."""
    env = _env()
    points = [float(x) for x in [1, 2, 3, 20, 21, 22]]

    def step(centroids):
        cs = centroids.collect()

        def nearest(p):
            return min(range(len(cs)), key=lambda i: abs(p - cs[i]))

        assign = env.from_collection(points).map(lambda p: (nearest(p), p))
        return assign.group_by(0).mean(1).map(lambda kv: kv[1])

    out = sorted(
        env.from_collection([0.0, 10.0]).iterate(10, step).collect()
    )
    assert out == [2.0, 21.0]


def test_delta_iteration_connected_components():
    """The reference's canonical delta-iteration example (ref
    ConnectedComponents): propagate min component id along edges."""
    env = _env()
    vertices = [(i, i) for i in range(1, 8)]          # (vid, component)
    edges = [(1, 2), (2, 3), (3, 4), (5, 6), (6, 7)]
    undirected = edges + [(b, a) for a, b in edges]

    def step(solution, workset):
        # candidate components propagated to neighbors
        cand = (
            workset.join(env.from_collection(undirected))
            .where(0).equal_to(0)
            .apply(lambda w, e: (e[1], w[1]))
            .group_by(0).min(1)
            .map(lambda kv: (kv[0], int(kv[1])))
        )
        cur = {v: c for v, c in solution.collect()}
        delta = cand.filter(lambda vc: vc[1] < cur[vc[0]])
        return delta, delta

    out = dict(
        env.from_collection(vertices)
        .delta_iterate(env.from_collection(vertices), 0, 10, step)
        .collect()
    )
    assert out == {1: 1, 2: 1, 3: 1, 4: 1, 5: 5, 6: 5, 7: 5}


def test_lazy_memoized_evaluation():
    env = _env()
    calls = []

    def trace(x):
        calls.append(x)
        return x

    base = env.from_collection([1, 2, 3]).map(trace)
    a = base.map(lambda x: x + 1)
    b = base.map(lambda x: x * 10)
    assert not calls                       # lazy until an action
    a.collect()
    b.collect()
    assert calls == [1, 2, 3]              # shared upstream ran once


def test_csv_and_text_sources(tmp_path):
    p = tmp_path / "data.csv"
    p.write_text("1,alice\n2,bob\n")
    env = _env()
    rows = env.read_csv_file(str(p), types=[int, str]).collect()
    assert rows == [(1, "alice"), (2, "bob")]
    t = tmp_path / "t.txt"
    t.write_text("x\ny\n")
    assert env.read_text_file(str(t)).collect() == ["x", "y"]


# -- round 5: ship/local strategy planner (ref Optimizer.java:396) --------
def _pairs(n, keymod, seed):
    import numpy as _np

    rng = _np.random.default_rng(seed)
    return [(int(k), i) for i, k in
            enumerate(rng.integers(0, keymod, n))]


def test_plan_assigns_ship_and_local_strategies_without_executing():
    env = ExecutionEnvironment.get_execution_environment()
    small = env.from_collection([(k, k) for k in range(50)])
    big = env.from_collection(_pairs(5000, 100, 0))
    j = big.join(small).where(0).equal_to(0).apply(lambda l, r: (l, r))
    plan = j.plan()
    # small side broadcast (50 <= threshold, 5000 >= 4*50), built over
    assert "ship=broadcast-hash-second" in plan
    assert "local=hash build-right" in plan
    assert j._cache is None                    # nothing executed


def test_plan_repartition_for_comparable_sides():
    env = ExecutionEnvironment.get_execution_environment()
    a = env.from_collection(_pairs(4000, 100, 1))
    b = env.from_collection(_pairs(5000, 100, 2))
    plan = a.join(b).where(0).equal_to(0).apply(
        lambda l, r: (l, r)).plan()
    assert "ship=repartition-hash" in plan
    assert "local=hash build-left" in plan


def test_plan_sort_merge_when_hash_exceeds_budget():
    env = ExecutionEnvironment.get_execution_environment()
    env.hash_max_build_rows = 100          # shrink the build budget
    a = env.from_collection(_pairs(4000, 50, 3))
    b = env.from_collection(_pairs(5000, 50, 4))
    j = a.join(b).where(0).equal_to(0).apply(lambda l, r: (l, r))
    assert "local=sort-merge" in j.plan()
    # the run-time decision matches and the merge is exact
    got = sorted(j.collect())
    exp = sorted(
        (l, r) for l in a.collect() for r in b.collect() if l[0] == r[0]
    )
    assert got == exp
    assert "sort-merge" in j.strategy


@pytest.mark.parametrize("kind,method", [
    ("inner", "join"), ("left", "left_outer_join"),
    ("right", "right_outer_join"), ("full", "full_outer_join"),
])
def test_sort_merge_equals_hash_all_kinds(kind, method):
    env_h = ExecutionEnvironment.get_execution_environment()
    env_m = ExecutionEnvironment.get_execution_environment()
    env_m.hash_max_build_rows = 0          # force sort-merge
    outs = []
    for env in (env_h, env_m):
        a = env.from_collection(_pairs(300, 40, 5))
        b = env.from_collection(_pairs(260, 40, 6))
        j = getattr(a, method)(b).where(0).equal_to(0).apply(
            lambda l, r: (l, r))
        outs.append(sorted(j.collect(), key=repr))
    assert outs[0] == outs[1]


def test_sort_merge_unsortable_keys_fall_back_to_hash():
    env = ExecutionEnvironment.get_execution_environment()
    env.hash_max_build_rows = 0
    a = env.from_collection([(1, "a"), ("x", "b")])   # mixed key types
    b = env.from_collection([(1, "c"), ("x", "d")])
    j = a.join(b).where(0).equal_to(0).apply(lambda l, r: (l[1], r[1]))
    assert sorted(j.collect()) == [("a", "c"), ("b", "d")]
    assert "keys unsortable" in j.strategy


def test_device_broadcast_ship_for_int_keyed_inner_join():
    """The physical broadcast ship: unique-int-key build side replicated
    over the device mesh, probe positions joined host-side — results
    identical to the host hash path (parallel/broadcast.py)."""
    env = ExecutionEnvironment.get_execution_environment()
    dim = env.from_collection([(k, f"name-{k}") for k in range(64)])
    facts = env.from_collection(_pairs(4000, 64, 7))
    j = facts.join(dim).where(0).equal_to(0).apply(
        lambda l, r: (l[0], l[1], r[1]))
    got = sorted(j.collect())
    assert j.strategy and "device mesh" in j.strategy, j.strategy
    exp = sorted(
        (l[0], l[1], f"name-{l[0]}") for l in facts.collect()
    )
    assert got == exp


def test_join_hint_forces_build_side_in_plan_and_run():
    env = ExecutionEnvironment.get_execution_environment()
    big = env.from_collection(_pairs(5000, 100, 8))
    small = env.from_collection([(k, k) for k in range(50)])
    j = big.join(small).where(0).equal_to(0).with_hint(
        "build-left").apply(lambda l, r: (l, r))
    assert "local=hash build-left (hinted)" in j.plan()
    j.collect()
    assert "build-left (hinted)" in j.strategy
