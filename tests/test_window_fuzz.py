"""Randomized equivalence fuzz of the windowed hot path against a scalar
model — sliding/tumbling sizes, out-of-orderness, batch sizes, and both
drain variants (CollectSink = packed CompactFires, CountingSink =
device-reduced), with the round-4 pipelining (prefetch + bounded
in-flight + lagged reads) active. The scalar model is the reference
WindowOperator semantics: every window containing a record's pane gets
its value; late records (all containing windows past the watermark)
drop."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink, CountingSink
from flink_tpu.runtime.sources import GeneratorSource


def scalar_model(keys, ts, size, slide, ooo, batch):
    """Batch-faithful scalar model."""
    exp = {}
    wm = None
    n = len(keys)
    for off in range(0, n, batch):
        bk = keys[off:off + batch]
        bt = ts[off:off + batch]
        for k, t in zip(bk, bt):
            # windows containing pane floor(t/slide): ends at
            # (p+1)*slide .. (p + size//slide)*slide
            p = t // slide
            last_end = (p + size // slide) * slide
            if wm is not None and last_end - 1 <= wm:
                continue                        # late: drop
            for j in range(size // slide):
                end = (p + 1 + j) * slide
                exp[(k, end)] = exp.get((k, end), 0) + 1.0
        new_wm = max(bt) - ooo - 1
        wm = new_wm if wm is None else max(wm, new_wm)
    return exp


CASES = [
    # (size, slide, ooo, batch, n_keys, n_events, seed)
    (40, 40, 0, 64, 37, 4000, 0),
    (60, 20, 0, 128, 11, 6000, 1),
    (100, 25, 50, 96, 53, 5000, 2),
    (32, 16, 16, 33, 8, 3000, 3),       # odd batch size
    (200, 50, 120, 256, 97, 8000, 4),
]


def _gen(seed, n_keys, n_events, ooo):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, n_events).astype(np.int64)
    base = np.arange(n_events, dtype=np.int64) // 4
    jitter = rng.integers(0, max(1, ooo + 1), n_events)
    ts = np.maximum(base - jitter, 0)
    return keys, ts


def _gen_gaps(seed, n_keys, n_events, ooo):
    """Like _gen, plus 3-6 random TIME JUMPS far larger than any pane
    ring — the inter-poll gap regression class (quiet source resuming,
    compile pauses): unfired panes must fire, not be evicted."""
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, n_events).astype(np.int64)
    base = np.arange(n_events, dtype=np.int64) // 4
    n_jumps = int(rng.integers(3, 7))
    points = np.sort(rng.integers(1, n_events, n_jumps))
    gaps = rng.integers(500, 20_000, n_jumps)
    add = np.zeros(n_events, np.int64)
    for p, g in zip(points, gaps):
        add[p:] += g
    jitter = rng.integers(0, max(1, ooo + 1), n_events)
    ts = np.maximum(base + add - jitter, 0)
    return keys, ts


def _run_case(size, slide, ooo, batch, n_keys, n_events, seed, keys, ts):
    exp = scalar_model(keys.tolist(), ts.tolist(), size, slide, ooo, batch)

    def gen(off, n):
        return (
            {"key": keys[off:off + n], "ts": ts[off:off + n],
             "value": np.ones(min(n, n_events - off), np.float32)},
            ts[off:off + n],
        )

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(max(128, n_keys))
    env.batch_size = batch
    sink = CollectSink()
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    stream = env.add_source(GeneratorSource(gen, total=n_events))
    if ooo:
        stream = stream.assign_timestamps_and_watermarks(
            lambda c: c["ts"],
            WatermarkStrategy.for_bounded_out_of_orderness(ooo),
        )
    (
        stream.key_by(lambda c: c["key"])
        .time_window(size, slide if slide != size else None)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute(f"fuzz-{seed}")

    got = {}
    for r in sink.results:
        got[(int(r.key), int(r.window_end_ms))] = (
            got.get((int(r.key), int(r.window_end_ms)), 0) + r.value
        )
    assert got == exp, (
        f"case {(size, slide, ooo, batch, n_keys, n_events, seed)}: "
        f"{len(got)} vs {len(exp)} windows; "
        f"dropped_late={job.metrics.dropped_late} "
        f"dropped_capacity={job.metrics.dropped_capacity}"
    )


GAP_CASES = [
    # (size, slide, ooo, batch, n_keys, n_events, seed)
    (40, 40, 0, 64, 23, 4000, 10),
    (80, 20, 30, 96, 17, 5000, 11),
    (50, 50, 0, 57, 31, 4000, 12),      # odd batch size
    (100, 25, 60, 128, 41, 6000, 13),
]


@pytest.mark.parametrize("case", GAP_CASES)
def test_windowed_path_with_time_jumps_matches_scalar_model(case):
    size, slide, ooo, batch, n_keys, n_events, seed = case
    keys, ts = _gen_gaps(seed, n_keys, n_events, ooo)
    _run_case(size, slide, ooo, batch, n_keys, n_events, seed, keys, ts)


@pytest.mark.parametrize("case", CASES)
def test_windowed_path_matches_scalar_model(case):
    size, slide, ooo, batch, n_keys, n_events, seed = case
    keys, ts = _gen(seed, n_keys, n_events, ooo)
    exp = scalar_model(keys.tolist(), ts.tolist(), size, slide, ooo, batch)

    def gen(off, n):
        return (
            {"key": keys[off:off + n], "ts": ts[off:off + n],
             "value": np.ones(min(n, n_events - off), np.float32)},
            ts[off:off + n],
        )

    env = StreamExecutionEnvironment(Configuration())
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(max(128, n_keys))
    env.batch_size = batch
    sink = CollectSink()
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    stream = env.add_source(GeneratorSource(gen, total=n_events))
    if ooo:
        # columnar sources carry timestamps; the strategy sets the
        # out-of-orderness budget the watermark trails by
        stream = stream.assign_timestamps_and_watermarks(
            lambda c: c["ts"],
            WatermarkStrategy.for_bounded_out_of_orderness(ooo),
        )
    (
        stream.key_by(lambda c: c["key"])
        .time_window(size, slide if slide != size else None)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute(f"fuzz-{seed}")

    got = {}
    for r in sink.results:
        got[(int(r.key), int(r.window_end_ms))] = (
            got.get((int(r.key), int(r.window_end_ms)), 0) + r.value
        )
    assert got == exp, (
        f"case {case}: {len(got)} vs {len(exp)} windows; "
        f"dropped_late={job.metrics.dropped_late}"
    )


@pytest.mark.parametrize("seed", [7, 8])
def test_device_reduce_drain_totals_match(seed):
    """CountingSink (ReducedFires drain) totals equal the packed drain's."""
    size, slide, ooo, batch, n_keys, n_events = 50, 50, 20, 80, 29, 4000
    keys, ts = _gen(seed, n_keys, n_events, ooo)
    exp = scalar_model(keys.tolist(), ts.tolist(), size, slide, ooo, batch)

    def run(sink):
        def gen(off, n):
            return (
                {"key": keys[off:off + n], "ts": ts[off:off + n],
                 "value": np.ones(min(n, n_events - off), np.float32)},
                ts[off:off + n],
            )

        env = StreamExecutionEnvironment(Configuration())
        env.set_parallelism(1)
        env.set_max_parallelism(8)
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        env.set_state_capacity(max(128, n_keys))
        env.batch_size = batch
        from flink_tpu.runtime.watermarks import WatermarkStrategy

        (
            env.add_source(GeneratorSource(gen, total=n_events))
            .assign_timestamps_and_watermarks(
                lambda c: c["ts"],
                WatermarkStrategy.for_bounded_out_of_orderness(ooo),
            )
            .key_by(lambda c: c["key"])
            .time_window(size)
            .sum(lambda c: c["value"])
            .add_sink(sink)
        )
        env.execute(f"fuzz-reduce-{seed}")
        return sink

    counting = run(CountingSink())
    assert counting.count == len(exp)
    assert counting.value_sum == sum(exp.values())
