"""Device-resident steady-state loop (ISSUE 12, runtime/ingest.py
DeviceBatchRing + runtime/executor.py resident drain):

* steady-state correctness with ``pipeline.resident-loop=on`` — exact
  windows, drains actually dispatched (one host round trip per ring
  drain, not per megastep),
* exactly-once across a MID-DRAIN crash (the ``step.drain`` fault seam
  fires inside the drain dispatch path) with prefetch + incremental
  checkpoints + packed state planes — the ring-drain boundary is the
  cut, so the un-retired group replays without loss or double count,
* the device-drain watchdog phase: per-slot deadline scaled by the slot
  count the drain consumes (``Watchdog.arm(scale=)``),
* DeviceBatchRing units: wraparound reuse of slots across many cursor
  laps, restore ``clear()`` discard, and a threaded producer/consumer
  cursor-race property test over the SPSC publish/release seam.
"""

import queue
import threading
import time

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime import ingest as ingest_mod
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None, **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, source=None, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("resident-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


RESIDENT_CFG = {
    "pipeline.prefetch": "on",
    "pipeline.device-staging": "on",
    "pipeline.resident-loop": "on",
    "pipeline.ring-depth": 4,
}


# ----------------------------------------------------- steady state

def test_resident_loop_exact_and_drains_dispatched():
    """Windows are exact with the resident loop on, and the steady
    state really ran through ring drains: every step retired by a drain
    dispatch, strictly fewer dispatches than steps."""
    total = 4096
    env = build_env(1, **RESIDENT_CFG)
    got = run_job(env, total)
    assert got == expected(total)
    m = env.last_job.metrics
    assert m.resident_drains > 0
    assert m.resident_drains < m.steps


def test_resident_loop_on_requires_staging_substrate():
    """``on`` without the prefetch+staging substrate is a config error,
    never a silent downgrade to the per-megastep dispatch path."""
    env = build_env(1, **{"pipeline.prefetch": "off",
                          "pipeline.resident-loop": "on"})
    with pytest.raises(ValueError, match="resident-loop"):
        run_job(env, 512)


# ------------------------------------------ mid-drain crash, exactly-once

def test_resident_mid_drain_crash_restore_exactly_once(tmp_path):
    """THE round-12 exactly-once criterion: crash at a drain dispatch
    (the ``step.drain`` seam fires with staged slots accumulated but the
    drain not yet retired) under prefetch + incremental checkpoints +
    packed state planes; restore replays the un-retired group from the
    applied-offset cut — no skipped and no double-counted records."""
    total = 4096
    env = build_env(
        2, tmp_path / "chk", interval=2, restart=3,
        **{**RESIDENT_CFG,
           "checkpoint.mode": "incremental", "checkpoint.async": True,
           "state.packed-planes": "on"},
    )
    inj = FaultInjector([
        FaultRule("step.drain",
                  exc=RuntimeError("injected mid-drain crash"), at=1),
    ])
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert inj.fired_at("step.drain"), "drain seam never fired"
    assert m.restarts == 1
    assert m.resident_drains > 0
    assert got == expected(total)


def test_resident_checkpoint_cut_across_processes(tmp_path):
    """Ring-drain cut portability: phase 1 checkpoints at drain
    boundaries and stops mid-stream; a FRESH env restores the latest cut
    and finishes. Merged output equals the single-run truth — a cut
    inside a drain group (or at the live source position) would lose or
    duplicate the ring-resident batches."""
    total, half = 8192, 4096
    env1 = build_env(1, tmp_path / "chk", interval=1, **RESIDENT_CFG)
    got1 = run_job(env1, half)
    assert env1.last_job.metrics.resident_drains > 0
    env2 = build_env(1, **RESIDENT_CFG)
    got2 = run_job(env2, total, restore_from=str(tmp_path / "chk"))
    assert {**got1, **got2} == expected(total)


# --------------------------------------------------- watchdog drain phase

def test_watchdog_arm_scale_multiplies_deadline():
    """The drain arms ``device-drain`` scaled by the slot count it
    dispatched: deadline = per-slot config x slots; scale below 1 clamps
    so a tiny drain never shrinks the configured floor."""
    from flink_tpu.runtime.watchdog import Watchdog

    wd = Watchdog({"device-drain": 0.2}, interval_s=0.05)
    tid = threading.get_ident()
    prev = wd.arm("device-drain", scale=16)
    assert wd._armed[tid][2] == pytest.approx(3.2)
    wd.disarm(prev)
    prev = wd.arm("device-drain", scale=0.25)
    assert wd._armed[tid][2] == pytest.approx(0.2)
    wd.disarm(prev)


def test_watchdog_device_drain_trip_attributed():
    """A wedged drain trips the SCALED deadline with the phase name and
    the slot-count detail in the attribution."""
    from flink_tpu.runtime.watchdog import Watchdog, WatchdogError

    trips = []
    wd = Watchdog({"device-drain": 0.15}, interval_s=0.05,
                  on_trip=trips.append).start()
    try:
        with pytest.raises(WatchdogError, match="device-drain"):
            prev = wd.arm("device-drain", detail="slots=3", scale=2)
            try:
                deadline = time.monotonic() + 10
                while time.monotonic() < deadline:
                    time.sleep(0.01)
                pytest.fail("watchdog never tripped")
            finally:
                wd.disarm(prev)
        assert trips and trips[0].phase == "device-drain"
        assert trips[0].elapsed_s >= 0.3       # the SCALED deadline held
        assert trips[0].detail == "slots=3"
    finally:
        wd.stop()


def test_watchdog_from_config_carries_drain_deadline():
    from flink_tpu.runtime.watchdog import watchdog_from_config

    wd = watchdog_from_config(
        Configuration({"watchdog.drain-timeout": 7.5})
    )
    assert wd.deadlines["device-drain"] == 7.5


# ------------------------------------------------- DeviceBatchRing units

def _mk_plan(B=8, depth=4):
    from flink_tpu.parallel.mesh import MeshContext

    ctx = MeshContext.create(1, 128)
    mask_sh, split_sh = ingest_mod.IngestPlan.shardings_for(ctx.mesh)
    return ingest_mod.IngestPlan(
        td=None, slide_ticks=1000, span_limit=8, B=B, B_step=B,
        n_shards=1, max_parallelism=128,
        kg_ends=np.array([128], np.int32), exchange_cap=0,
        routes=("mask",), staging=True,
        mask_sharding=mask_sh, split_sharding=split_sh,
        ring_depth=depth,
    )


def _batch(j, n, B):
    assert n <= B
    return (np.full(n, j, np.uint32), np.arange(n, dtype=np.uint32),
            np.zeros(n, np.int32), np.ones(n, np.float32))


def test_device_ring_wraparound_and_release():
    """Slots recycle across cursor laps: publish/release several times
    the ring depth, verifying full-ring refusal, monotone seqs, payload
    integrity after wraparound, and release accounting."""
    depth, B = 3, 8
    plan = _mk_plan(B=B, depth=depth)
    ring = ingest_mod.DeviceBatchRing(plan, depth)
    assert ring.occupancy() == 0

    seq_next = 0
    for lap in range(4):                       # 4 laps = 12 slots through
        pubs = []
        for j in range(depth):                 # fill to the brim
            hi, lo, ticks, vals = _batch(seq_next, 5, B)
            pub = ring.try_publish(plan, hi, lo, ticks, vals, 5,
                                   "mask", epoch=0)
            assert pub is not None
            seq, staged = pub
            assert seq == seq_next
            seq_next += 1
            pubs.append((seq, staged))
        assert ring.occupancy() == depth
        # full ring refuses deterministically (fallback-to-plain seam)
        hi, lo, ticks, vals = _batch(999, 2, B)
        assert ring.try_publish(plan, hi, lo, ticks, vals, 2,
                                "mask", epoch=0) is None
        # payload integrity after the slot was recycled from prior laps
        for seq, staged in pubs:
            got_hi = np.asarray(staged[0])
            assert (got_hi[:5] == seq).all()
            valid = np.asarray(staged[4])
            assert valid[:5].all() and not valid[5:].any()
        # one release covering the whole drain group
        assert ring.release_through(pubs[-1][0]) == depth
        assert ring.occupancy() == 0
    # already-released / out-of-window seqs are a no-op
    assert ring.release_through(0) == 0
    assert ring.release_through(seq_next + 100) == 0


def test_device_ring_clear_discards_inflight():
    """Restore path: ``clear()`` retires every in-flight slot; later
    publishes keep the monotone seq space (no slot aliasing with the
    discarded epoch's batches)."""
    depth, B = 4, 8
    plan = _mk_plan(B=B, depth=depth)
    ring = ingest_mod.DeviceBatchRing(plan, depth)
    for j in range(3):
        hi, lo, ticks, vals = _batch(j, 4, B)
        assert ring.try_publish(plan, hi, lo, ticks, vals, 4,
                                "mask", epoch=0) is not None
    assert ring.clear() == 3
    assert ring.occupancy() == 0
    hi, lo, ticks, vals = _batch(7, 4, B)
    seq, _staged = ring.try_publish(plan, hi, lo, ticks, vals, 4,
                                    "mask", epoch=1)
    assert seq == 3                    # seq space continues past clear
    assert ring.release_through(seq) == 1


def test_device_ring_cursor_race_property():
    """SPSC cursor race: a producer thread publishes (spinning on full)
    while the consumer releases concurrently. Every batch arrives
    exactly once, in order, with its staged payload intact — the write
    cursor can never expose a half-published slot, and release can never
    free a slot the producer still owns."""
    depth, B, M = 3, 8, 150
    plan = _mk_plan(B=B, depth=depth)
    ring = ingest_mod.DeviceBatchRing(plan, depth)
    out_q: "queue.Queue" = queue.Queue()
    errs = []

    def producer():
        try:
            rng = np.random.default_rng(3)
            for j in range(M):
                n = int(rng.integers(1, B + 1))
                hi, lo, ticks, vals = _batch(j, n, B)
                while True:
                    pub = ring.try_publish(plan, hi, lo, ticks, vals,
                                           n, "mask", epoch=0)
                    if pub is not None:
                        break
                    time.sleep(0.0002)     # ring full: drain is behind
                out_q.put((j, n, pub[0], pub[1]))
        except Exception as e:  # noqa: BLE001 — surfaced by the assert
            errs.append(e)
        finally:
            out_q.put(None)

    t = threading.Thread(target=producer, daemon=True)
    t.start()
    rng = np.random.default_rng(11)
    seen, held = 0, []
    while True:
        item = out_q.get(timeout=60)
        if item is None:
            break
        j, n, seq, staged = item
        assert seq == seen                 # in order, exactly once
        seen += 1
        assert 0 < ring.occupancy() <= depth
        got_hi = np.asarray(staged[0])
        assert (got_hi[:n] == j).all()
        valid = np.asarray(staged[4])
        assert valid[:n].all() and not valid[n:].any()
        # release in variable-size groups like the executor's drains
        held.append(seq)
        if len(held) >= int(rng.integers(1, depth + 1)):
            ring.release_through(held[-1])
            held = []
    if held:
        ring.release_through(held[-1])
    t.join(timeout=10)
    assert not errs, errs
    assert seen == M
    assert ring.occupancy() == 0
