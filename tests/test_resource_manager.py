"""ResourceManager (ref FlinkResourceManager.java:95): slot accounting,
spread placement, elastic scale-up through the launcher seam, and
admission control over a real ProcessCluster."""

import threading
import time

import pytest

from flink_tpu.runtime.resource_manager import (
    ProcessClusterResourceManager,
    ResourceManager,
    SlotRequest,
    TaskManagerPool,
)


def test_pool_spread_placement_and_release():
    pool = TaskManagerPool()
    pool.register("tm-a", 2)
    pool.register("tm-b", 3)
    # spread: the first grant lands on the TM with most free slots, and
    # repeated grants alternate so free counts stay balanced
    assert pool.allocate() == "tm-b"
    pool.allocate()
    pool.allocate()
    ov = {t["id"]: t for t in pool.overview()}
    assert ov["tm-a"]["free"] == 1 and ov["tm-b"]["free"] == 1
    assert pool.total_free == 2
    pool.release("tm-b")
    assert pool.total_free == 3
    assert pool.allocate(3) is None        # no single TM has 3 free
    assert ov["tm-b"]["slots"] == 3


def test_request_blocks_until_release():
    rm = ResourceManager()
    rm.notify_registered("tm-1", 1)
    g1 = rm.request_slots(SlotRequest("r1", "job1"))
    assert g1.tm_id == "tm-1"
    got = {}

    def waiter():
        got["g"] = rm.request_slots(SlotRequest("r2", "job2"),
                                    timeout_s=20.0)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.2)
    assert "g" not in got                 # r2 waits while r1 holds the slot
    rm.release("r1")
    t.join(timeout=20)
    assert got["g"].tm_id == "tm-1"


def test_scale_up_through_launcher():
    """An unsatisfiable request triggers the cluster-framework seam; the
    new worker's registration satisfies the waiter (ref
    FlinkResourceManager.requestNewWorkers)."""
    rm = ResourceManager(launcher=None)

    def launcher(n):
        # "start a container" -> it registers shortly after
        def come_up():
            time.sleep(0.1)
            rm.notify_registered("tm-elastic", n)

        threading.Thread(target=come_up, daemon=True).start()

    rm.launcher = launcher
    g = rm.request_slots(SlotRequest("r1", "job"), timeout_s=20.0)
    assert g.tm_id == "tm-elastic"
    assert any(e["event"] == "scale-up" for e in rm.events)


def test_request_timeout_and_dead_tm_reclaim():
    rm = ResourceManager()
    with pytest.raises(TimeoutError, match="no TaskManager"):
        rm.request_slots(SlotRequest("r0", "job"), timeout_s=0.2)
    rm.notify_registered("tm-1", 2)
    rm.request_slots(SlotRequest("r1", "job"))
    rm.notify_dead("tm-1")
    assert rm.pool.total_free == 0        # the TM is gone, not just freed


def test_admission_control_over_process_cluster(tmp_path, monkeypatch):
    """capacity=1: two concurrent submits serialize — the second job only
    spawns after the first worker reaches a terminal state."""
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    from flink_tpu.runtime.process_cluster import ProcessCluster

    cluster = ProcessCluster(heartbeat_timeout_s=10.0)
    cluster.start()
    prm = ProcessClusterResourceManager(cluster, capacity=1)
    try:
        common = dict(extra_env={
            "FLINK_TPU_TEST_OUT": str(tmp_path / "out"),
            "FLINK_TPU_TEST_TOTAL": "1024",
        })
        w1 = prm.submit_with_lease(
            "tests/process_jobs.py:build_window_job", "rm-job-1",
            str(tmp_path / "c1"), timeout_s=60.0, **common,
        )
        t0 = time.time()
        w2 = prm.submit_with_lease(
            "tests/process_jobs.py:build_window_job", "rm-job-2",
            str(tmp_path / "c2"), timeout_s=120.0, **common,
        )
        # the second lease waited for the first job to finish
        assert cluster.wait(w1, timeout_s=1.0) == "FINISHED"
        assert cluster.wait(w2, timeout_s=120.0) == "FINISHED"
        granted = [e for e in prm.rm.events if e["event"] == "granted"]
        released = [e for e in prm.rm.events if e["event"] == "released"]
        assert len(granted) == 2 and len(released) >= 1
    finally:
        prm.stop()
        cluster.shutdown()
