# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# GOOD twin: one sort, matching the fixture ledger's budget for this
# family (sort: 1) — the shared-sort discipline holding.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        return jnp.sort(x) * 2.0

    return [{
        "name": "fixture.sortk",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
    }]
