# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# GOOD twin: the family traces at exactly the signature the fixture
# ledger records (f32[8]) — one signature, one compile, no storm.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        return x * 2.0

    return [{
        "name": "fixture.sig",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
    }]
