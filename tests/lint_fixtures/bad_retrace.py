# virtual-path: flink_tpu/runtime/executor.py
# Red-team fixture: the PR 3 bug class — a fresh np.ones mask allocated
# per dispatch inside the hot section, plus a compile inside a loop.
import jax
import numpy as np

update_step = jax.jit(lambda s, m: s)


def run_update(state, n):
    mask = np.ones(8192, bool)         # fresh per-dispatch allocation
    mask[n:] = False
    return update_step(state, mask)


def warm_all(bodies):
    compiled = []
    for body in bodies:
        compiled.append(jax.jit(body))  # retrace storm: compile per item
    return compiled
