# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# GOOD twin: same computation with the boundary cast done RIGHT —
# everything stays f32 even when the fixture traces under x64, because
# every scalar enters the graph already narrowed. This is the
# discipline the state planes rely on.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        acc = x * jnp.float32(2.0)
        return acc.sum()

    return [{
        "name": "fixture.f32_clean",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
        "x64": True,
    }]
