# virtual-path: flink_tpu/runtime/executor.py
# Good twin: the sanctioned idiom — the donating call REBINDS the name,
# so every later read sees the new buffer.
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def loop(state, batches):
    state = step(state, batches[0])
    return state, state.sum()
