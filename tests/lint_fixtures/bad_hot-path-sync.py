# virtual-path: flink_tpu/ops/fake_kernel.py
# Red-team fixture: host syncs in a kernel module — every construct the
# hot-path-sync rule exists to catch.
import numpy as np


def kernel(x):
    x.block_until_ready()          # serializes the dispatch pipeline
    n = x.ovf_n.item()             # device->host scalar fetch
    a = np.asarray(x.acc)          # device->host array fetch
    return n, a
