# virtual-path: flink_tpu/runtime/executor.py
# Red-team fixture: state is read AFTER being passed in the donated
# position — the buffer was invalidated by donate_argnums.
from functools import partial

import jax


@partial(jax.jit, donate_argnums=(0,))
def step(state, batch):
    return state + batch


def loop(state, batches):
    out = step(state, batches[0])
    total = state.sum()            # use-after-donate: stale buffer read
    return out, total
