# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# BAD: an explicit float64 cast in the kernel body. Under x64 (which
# this fixture enables for its trace, mimicking a host environment
# where some dependency flipped the flag) the whole downstream plane
# widens — double the HBM traffic, >10x ALU cost on TPU. With x64 off,
# JAX silently demotes and unit tests never see it; the trace tier does.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        acc = x.astype(jnp.float64) * 2.0
        return acc.sum()

    return [{
        "name": "fixture.f64_leak",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
        "x64": True,
    }]
