# virtual-path: flink_tpu/runtime/executor.py
# Good twin: the template is hoisted to setup (frozen mask template) and
# the hot section only slices it; compiles happen once, outside loops.
import jax
import numpy as np

update_step = jax.jit(lambda s, m: s)
_MASK_TMPL = np.ones(8192, bool)       # allocated once at import/setup


def run_update(state, n):
    mask = _MASK_TMPL[:n]              # view slice, no allocation
    return update_step(state, mask)
