# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# BAD: a jax.debug.print left over from a debugging session inside the
# scan body. It lowers to a debug_callback primitive — a device->host
# round trip on EVERY scan iteration, invisible to source-level
# scanning once it hides in a helper, and exactly the serialization the
# megastep exists to avoid.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        def body(carry, _):
            jax.debug.print("carry sum {s}", s=carry.sum())
            return carry + 1.0, None

        y, _ = jax.lax.scan(body, x, None, length=4)
        return y

    return [{
        "name": "fixture.scan_with_debug_print",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
    }]
