# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# BAD: the kernel requests donation of its state arg but returns a
# SLICED view — the output shape no longer matches the donated input,
# so XLA cannot alias and silently copies. The donated-but-copied bug
# class the donation-effective rule exists to catch: the program stays
# correct, the step just pays a full extra state write every dispatch.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(state, x):
        # shrinks the state: unusable donation, XLA copies
        return state[:4] + x[:4]

    args = (
        jax.ShapeDtypeStruct((8,), jnp.float32),
        jax.ShapeDtypeStruct((8,), jnp.float32),
    )
    return [{
        "name": "fixture.donated_but_copied",
        "fn": kernel,
        "args": args,
        "donate": (0,),
    }]
