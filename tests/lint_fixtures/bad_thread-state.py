# virtual-path: flink_tpu/runtime/ingest.py
# Red-team fixture: the producer thread mutates shared attributes with
# no covering lock and no registry entry — the PR 3 bug shape.
import threading


class Producer:
    def __init__(self):
        self.count = 0
        self.batches = []
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._main, daemon=True)

    def _main(self):
        while True:
            self.count += 1              # unlocked cross-thread write
            self.batches.append(object())   # unlocked mutator call
