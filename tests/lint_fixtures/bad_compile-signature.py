# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# BAD: the family's abstract input signature no longer matches the
# recorded one (f32[8] in the fixture ledger, f32[16] here) — the
# recompile-storm shape: some call path resized/re-dtyped an operand,
# and the "same" step now compiles twice and flips between executables.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        return x * 2.0

    return [{
        "name": "fixture.sig",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((16,), jnp.float32),),
    }]
