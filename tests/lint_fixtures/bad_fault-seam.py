# virtual-path: flink_tpu/checkpointing/fake_store.py
# Red-team fixture: raw checkpoint IO with NO faults.inject seam — the
# chaos soak cannot schedule this failure mode.
import os


def publish(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(payload)
    os.replace(tmp, path)
