# virtual-path: flink_tpu/ops/segment.py
# Good twin: the identical sorts are LEGAL in segment.py — the one file
# the seam designates as the sort home.
import jax.numpy as jnp


def segment_sort(x):
    return jnp.argsort(x)
