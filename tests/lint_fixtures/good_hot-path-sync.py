# virtual-path: flink_tpu/ops/fake_kernel.py
# Good twin: host-side work lives in host-named helpers (naming
# convention) or behind a reasoned inline marker.
import numpy as np


def decode_host(x):
    return np.asarray(x)           # host helper by naming contract


def kernel(x):
    return x + 1                   # stays on device


def barrier(x):
    return np.asarray(x)  # host-sync-ok: documented step-boundary barrier
