# virtual-path: flink_tpu/runtime/ingest.py
# Good twin: every producer-thread mutation sits inside `with
# self._lock:` (auto-detected — the lock attr is assigned
# threading.Lock in this module), and the queue is a sanctioned
# sync primitive.
import queue
import threading


class Producer:
    def __init__(self):
        self.count = 0
        self._q = queue.Queue()
        self._lock = threading.Lock()
        self._thread = threading.Thread(target=self._main, daemon=True)

    def _main(self):
        while True:
            with self._lock:
                self.count += 1
            self._q.put_nowait(object())
