# virtual-path: flink_tpu/ops/rogue_kernel.py
# Red-team fixture: a second sort added to a kernel outside segment.py —
# exactly the per-plane re-sort the shared-sort seam exists to prevent.
import jax
import jax.numpy as jnp
from jax import lax


def rogue(x, k, v):
    a = jnp.argsort(x)
    b = jax.lax.sort(x)
    c = lax.sort_key_val(k, v)
    return a, b, c
