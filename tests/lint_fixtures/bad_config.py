# virtual-path: flink_tpu/runtime/demo_reader.py
# Red-team fixture: a typed-getter read of a key NO ConfigOption
# declares — it bypasses strict coercion and can typo silently —
# plus a fallback contradicting the declared default.


def setup(config):
    a = config.get_int("demo.bogus", 1)       # undeclared key
    b = config.get_int("demo.knob", 99)       # drifted fallback (4 declared)
    return a, b
