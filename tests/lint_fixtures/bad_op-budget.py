# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# BAD: the kernel pays a SECOND sort the ledger doesn't budget for —
# the exact regression shape of losing the one-sort precombine seam
# (PR 7): numerically identical output, structurally twice the cost.
# The fixture ledger (AUX in tests/test_lint.py) budgets sort: 1 for
# this family.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        y = jnp.sort(x)
        # ledger-busting extra sort: already sorted, sorted again
        return jnp.sort(y * 2.0)

    return [{
        "name": "fixture.sortk",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
    }]
