# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# GOOD twin: the updated state keeps the donated input's shape and
# dtype, so the lowering aliases the buffer (tf.aliasing_output on the
# param) and the compiled executable keeps the alias — an in-place
# update, the contract every step builder relies on.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(state, x):
        return state + x

    args = (
        jax.ShapeDtypeStruct((8,), jnp.float32),
        jax.ShapeDtypeStruct((8,), jnp.float32),
    )
    return [{
        "name": "fixture.donated_inplace",
        "fn": kernel,
        "args": args,
        "donate": (0,),
    }]
