# virtual-path: flink_tpu/audit_fixture.py
# lint-kernel-fixture
#
# GOOD twin: the same scan with the debug print removed — the kernel
# stays entirely on device; anything worth observing rides the lagged
# monitoring outputs instead of a callback.


def lint_kernel_families():
    import jax
    import jax.numpy as jnp

    def kernel(x):
        def body(carry, _):
            return carry + 1.0, None

        y, _ = jax.lax.scan(body, x, None, length=4)
        return y

    return [{
        "name": "fixture.scan_clean",
        "fn": kernel,
        "args": (jax.ShapeDtypeStruct((8,), jnp.float32),),
    }]
