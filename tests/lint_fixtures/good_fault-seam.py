# virtual-path: flink_tpu/checkpointing/fake_store.py
# Good twin: the same IO behind a named injection point (covering the
# whole function), plus a helper whose every caller carries the seam.
import os

from flink_tpu.testing import faults


def publish(path, payload):
    faults.inject("ckpt.fake.publish", path=path)
    tmp = path + ".tmp"
    _write(tmp, payload)
    os.replace(tmp, path)


def _write(tmp, payload):
    # no inject here — covered because every intra-module caller
    # (publish) carries one
    with open(tmp, "w") as f:
        f.write(payload)
