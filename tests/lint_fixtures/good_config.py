# virtual-path: flink_tpu/runtime/demo_reader.py
# Good twin: the read resolves to a declared option and the fallback
# agrees with the declared default.


def setup(config):
    return config.get_int("demo.knob", 4)
