"""flink-core API analogs added in round 3: accumulators, CSV batch
formats, and the scheme-dispatched FileSystem SPI.

Ref: api/common/accumulators/*, api/common/io/CsvInputFormat+
CsvOutputFormat, core/fs/FileSystem.
"""

import pytest

from flink_tpu.core.accumulators import (
    AccumulatorRegistry, AverageAccumulator, DoubleCounter, Histogram,
    IntCounter,
)
from flink_tpu.core.filesystem import get_filesystem
from flink_tpu.dataset import ExecutionEnvironment


def test_accumulator_types_and_merge():
    a, b = IntCounter(), IntCounter()
    a.add(3)
    b.add(4)
    a.merge(b)
    assert a.get_local_value() == 7

    avg = AverageAccumulator()
    for v in (1, 2, 3):
        avg.add(v)
    assert avg.get_local_value() == 2.0

    h = Histogram()
    for v in (1, 1, 2):
        h.add(v)
    h2 = Histogram()
    h2.add(2)
    h.merge(h2)
    assert h.get_local_value() == {1: 2, 2: 2}

    reg = AccumulatorRegistry()
    reg.add("lines", a)
    assert reg.results() == {"lines": 7}
    with pytest.raises(ValueError):
        reg.add("lines", IntCounter())


def test_rich_function_accumulators_through_job():
    """A RichProcessFunction counts records via getIntCounter; the job
    handle exposes merged results (ref JobExecutionResult)."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.datastream.functions import ProcessFunction
    from flink_tpu.runtime.sinks import CollectSink

    class Counting(ProcessFunction):
        def open(self, ctx):
            self.ctr = ctx.get_int_counter("records-seen")

        def process_element(self, value, ctx, out):
            self.ctr.add(1)
            out.collect(value * 10)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    env.set_parallelism(1)
    sink = CollectSink()
    (
        env.from_collection(list(range(20)))
        .key_by(lambda e: e % 2)
        .process(Counting())
        .add_sink(sink)
    )
    job = env.execute("acc-job")
    assert sorted(sink.results) == [i * 10 for i in range(20)]
    assert job.accumulator_result("records-seen") == 20


def test_csv_roundtrip(tmp_path):
    env = ExecutionEnvironment.get_execution_environment()
    ds = env.from_collection([(1, "a", 2.5), (2, "b", 3.5)])
    p = str(tmp_path / "out.csv")
    ds.write_as_csv(p)
    back = env.read_csv_file(p, types=(int, str, float)).collect()
    assert back == [(1, "a", 2.5), (2, "b", 3.5)]


def test_memory_filesystem_roundtrip():
    fs, p = get_filesystem("mem://bucket/data.txt")
    with fs.open(p, "w") as f:
        f.write("hello\nworld\n")
    assert fs.exists(p)
    with fs.open(p, "r") as f:
        assert f.read() == "hello\nworld\n"
    assert fs.size(p) == 12
    assert "data.txt" in fs.list_dir("bucket")
    fs.rename(p, "bucket/moved.txt")
    assert not fs.exists(p) and fs.exists("bucket/moved.txt")
    fs.delete("bucket", recursive=True)
    assert not fs.exists("bucket/moved.txt")


def test_dataset_io_over_memory_scheme():
    """The batch formats dispatch on the path scheme (FileSystem SPI)."""
    env = ExecutionEnvironment.get_execution_environment()
    env.from_collection(["x", "y"]).write_as_text("mem://t/out.txt")
    assert env.read_text_file("mem://t/out.txt").collect() == ["x", "y"]

    env.from_collection([(7, "q")]).write_as_csv("mem://t/out.csv")
    assert env.read_csv_file(
        "mem://t/out.csv", types=(int, str)
    ).collect() == [(7, "q")]


def test_unknown_scheme_rejected():
    with pytest.raises(ValueError, match="no filesystem registered"):
        get_filesystem("s3://bucket/x")


def test_accumulators_roll_back_on_restart(tmp_path):
    """Regression: recovery used to replay records into live counters
    without rolling them back to the checkpoint cut, double-counting
    everything between the cut and the failure."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.functions import ProcessFunction
    from flink_tpu.runtime.sinks import CollectSink

    total = 256

    class Counting(ProcessFunction):
        fail_armed = [True]

        def open(self, ctx):
            self.ctr = ctx.get_int_counter("seen")

        def process_element(self, value, ctx, out):
            self.ctr.add(1)
            if value == 180 and Counting.fail_armed[0]:
                Counting.fail_armed[0] = False
                raise RuntimeError("injected failure")
            out.collect(value)

    cfg = Configuration()
    cfg.set("restart-strategy", "fixed-delay")
    cfg.set("restart-strategy.fixed-delay.attempts", 2)
    env = StreamExecutionEnvironment(cfg)
    env.batch_size = 32
    env.set_parallelism(1)
    env.checkpoint_dir = str(tmp_path / "ck")
    env.checkpoint_interval_steps = 2
    sink = CollectSink()
    (
        env.from_collection(list(range(total)))
        .key_by(lambda e: e % 4)
        .process(Counting())
        .add_sink(sink)
    )
    job = env.execute("acc-restart")
    assert job.metrics.restarts >= 1
    assert job.accumulator_result("seen") == total


def test_operator_state_checkpoint_restore(tmp_path):
    """Non-keyed operator ListState (OperatorStateStore analog): survives
    an induced failure via checkpoint snapshot + in-place restore, so the
    operator's buffer reflects exactly the records up to the cut plus the
    replay."""
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.config import Configuration
    from flink_tpu.datastream.functions import ProcessFunction
    from flink_tpu.runtime.sinks import CollectSink

    total = 192

    class Buffering(ProcessFunction):
        armed = [True]

        def open(self, ctx):
            self.buf = ctx.get_operator_list_state("seen-values")

        def process_element(self, value, ctx, out):
            self.buf.add(value)
            if value == 130 and Buffering.armed[0]:
                Buffering.armed[0] = False
                raise RuntimeError("injected failure")
            out.collect((value, len(self.buf)))

    cfg = Configuration()
    cfg.set("restart-strategy", "fixed-delay")
    cfg.set("restart-strategy.fixed-delay.attempts", 2)
    env = StreamExecutionEnvironment(cfg)
    env.batch_size = 16
    env.set_parallelism(1)
    env.checkpoint_dir = str(tmp_path / "ck")
    env.checkpoint_interval_steps = 2
    sink = CollectSink()
    fn = Buffering()
    (
        env.from_collection(list(range(total)))
        .key_by(lambda e: e % 4)
        .process(fn)
        .add_sink(sink)
    )
    job = env.execute("opstate-restart")
    assert job.metrics.restarts >= 1
    # exactly-once: every record buffered once despite the replay
    assert sorted(fn.buf.get()) == list(range(total))
    assert len(sink.results) == total


# ---------------------------------------------------- rescale repartitioning
def test_operator_state_round_robin_repartition():
    """SPLIT_DISTRIBUTE rescale (ref RoundRobinOperatorStateRepartitioner):
    3 old subtasks -> 2 new: every item placed exactly once, fair spread
    (counts differ by at most 1 per name)."""
    from flink_tpu.state.operator_state import (
        OperatorStateStore,
        repartition_round_robin,
    )

    olds = []
    for p in range(3):
        st = OperatorStateStore()
        ls = st.get_list_state("offsets")
        for i in range(4):
            ls.add(("part", p, i))
        st.get_list_state("buffers").add(f"buf-{p}")
        olds.append(st.snapshot())

    news = repartition_round_robin(olds, 2)
    assert len(news) == 2
    all_offsets = [it for s in news for it in s["offsets"]]
    assert sorted(all_offsets) == sorted(
        [("part", p, i) for p in range(3) for i in range(4)]
    )
    # fairness: 12 items -> 6/6; 3 buffers -> 2/1
    assert {len(s["offsets"]) for s in news} == {6}
    assert sorted(len(s["buffers"]) for s in news) == [1, 2]

    # restore into fresh stores: disjoint, complete
    stores = [OperatorStateStore() for _ in range(2)]
    for st, snap in zip(stores, news):
        st.restore(snap)
    merged = [it for st in stores for it in st.get_list_state("offsets").get()]
    assert sorted(merged) == sorted(all_offsets)


def test_operator_state_union_repartition():
    from flink_tpu.state.operator_state import repartition_union

    olds = [{"offs": [1, 2]}, {"offs": [3]}]
    news = repartition_union(olds, 3)
    assert len(news) == 3
    for s in news:
        assert s["offs"] == [1, 2, 3]
    # deep copies: mutating one subtask's view must not leak
    news[0]["offs"].append(99)
    assert news[1]["offs"] == [1, 2, 3]


def test_rescale_down_to_one_collapses_to_union_of_items():
    from flink_tpu.state.operator_state import repartition_round_robin

    olds = [{"s": [1]}, {"s": [2]}, {"s": [3, 4]}]
    (one,) = repartition_round_robin(olds, 1)
    assert sorted(one["s"]) == [1, 2, 3, 4]
