"""Mesh-resident data parallelism (ISSUE 13, pipeline.data-parallel):
per-chip key-group slices feeding shard-local device rings and a
shard_map'd drain loop.

* kernel-level property test: per-shard routed batches reconstruct the
  single-chip oracle BIT-EXACTLY (logical state snapshot AND in-scan
  fire payloads) across {hash, direct} x {packed planes on/off} x
  n_shards in {1, 2, 4}, with zero overflow pinned on both sides (the
  per-shard tables spread load, so an overflowing oracle would diverge
  for capacity reasons, not routing bugs),
* per-shard count gating: shards drain INDEPENDENT fill levels in one
  dispatch (zero collectives in the keyed body is what makes divergent
  counts safe),
* the executor end to end: exact windows with ``pipeline.data-parallel
  =on`` on a 4-shard mesh, steps actually retired through the sharded
  drain, and the config ladder (dp without the resident loop is a
  config error; skewed batches fall back without loss),
* exactly-once across a mid-drain crash (``step.drain`` seam) on a
  4-shard mesh with the per-shard applied cut, and across a PR 8
  elastic lose-one -> degraded -> scale-back cycle with sharded rings,
* the PR 12 loose end: ``pipeline.resident-loop=on`` under the DCN
  lockstep plane is an explicit config error; ``auto`` resolves to off
  with a startup log line,
* ``ring_publish_refusals`` backpressure observability in the
  Prometheus exposition (total + per-shard series), and
  ShardedDeviceBatchRing unit behavior (per-shard cursors, refusal
  accounting, independent release).
"""

import json
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.keygroups import assign_to_key_group
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import hash64_host, route_hash
from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.runtime import checkpoint as ckpt
from flink_tpu.runtime import ingest as ingest_mod
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource
from flink_tpu.runtime.step import (
    WindowStageSpec,
    build_window_resident_drain,
    build_window_sharded_drain,
    init_sharded_state,
)
from flink_tpu.testing import faults
from flink_tpu.testing.faults import FaultInjector, FaultRule, \
    device_loss_rule

MAXP = 8
D = 3          # ring depth of the kernel-level drains
B = 48         # records per slot
N_KEYS = 200
WINDOW = 10_000


# ------------------------------------------------ kernel-level property

def _split(keys):
    h = hash64_host(np.asarray(keys, dtype=np.int64))
    return ((h >> np.uint64(32)).astype(np.uint32),
            (h & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _spec(layout, packed):
    return WindowStageSpec(
        win=wk.WindowSpec(10, 10, ring=8, fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=256, probe_len=8, layout=layout,
        packed=packed,
    )


def _batches(rng, layout):
    """D slot batches; slot i's timestamps sit in pane i and its
    watermark crosses pane boundaries so fires happen IN-SCAN (the
    last slot's watermark flushes everything that remains)."""
    out = []
    wms = [5, 15, 10**6]
    for i in range(D):
        if layout == "direct":
            hi = np.zeros(B, np.uint32)
            lo = rng.integers(0, 64, B).astype(np.uint32)
        else:
            hi, lo = _split(rng.integers(0, 32, B).astype(np.int64))
        ts = rng.integers(10 * i, 10 * i + 10, B).astype(np.int32)
        vals = rng.integers(1, 9, B).astype(np.float32)
        out.append((hi, lo, ts, vals, np.ones(B, bool),
                    np.int32(wms[i])))
    return out


def _partition(ctx, batch, cap):
    """Route one slot batch to owning shards: the SAME searchsorted-
    over-inclusive-ends math the ingest planner uses."""
    hi, lo, ts, vals, valid, wm = batch
    kg = assign_to_key_group(route_hash(hi, lo, np), MAXP, np)
    shard = np.searchsorted(np.asarray(ctx.kg_bounds()[1]), kg)
    n = ctx.n_shards
    p_hi = np.zeros((n, cap), np.uint32)
    p_lo = np.zeros((n, cap), np.uint32)
    p_ts = np.zeros((n, cap), np.int32)
    p_vl = np.zeros((n, cap), np.float32)
    p_ok = np.zeros((n, cap), bool)
    for s in range(n):
        m = shard == s
        c = int(m.sum())
        assert c <= cap, "test geometry must never skew past cap"
        p_hi[s, :c] = hi[m]
        p_lo[s, :c] = lo[m]
        p_ts[s, :c] = ts[m]
        p_vl[s, :c] = vals[m]
        p_ok[s, :c] = True
    return p_hi, p_lo, p_ts, p_vl, p_ok


def _decode_fires(fires):
    """Stacked [n_shards, D, ...] CompactFires -> {(end, key64): value},
    asserting each (window, key) fired exactly once."""
    counts = np.asarray(fires.counts)
    lanes = np.asarray(fires.lane_valid)
    ends = np.asarray(fires.window_end_ticks)
    khi = np.asarray(fires.key_hi)
    klo = np.asarray(fires.key_lo)
    vals = np.asarray(fires.values)
    out = {}
    for sh in range(counts.shape[0]):
        for d in range(counts.shape[1]):
            for f in np.nonzero(lanes[sh, d])[0]:
                for j in range(int(counts[sh, d, f])):
                    kid = (int(khi[sh, d, f, j]) << 32) | int(
                        klo[sh, d, f, j]
                    )
                    key = (int(ends[sh, d, f]), kid)
                    assert key not in out, f"duplicate fire {key}"
                    out[key] = float(vals[sh, d, f, j])
    return out


def _canon(entries):
    comp = (
        entries["key_hi"].astype(np.uint64) << np.uint64(32)
    ) | entries["key_lo"]
    order = np.lexsort((entries["pane"], comp))
    return {k: np.asarray(v)[order] for k, v in entries.items()}


def _entries_equal(a, b):
    a, b = _canon(a), _canon(b)
    return a.keys() == b.keys() and all(
        np.array_equal(a[k], b[k]) for k in a
    )


# compiled-kernel memo: the per-shard-counts test reuses the property
# test's (hash, unpacked) builds — count/counts are TRACED operands, so
# one compile per (layout, packed, n_shards) serves every fill level
_KERNELS = {}


def _oracle_drain(spec, key):
    k = ("oracle",) + key
    if k not in _KERNELS:
        ctx1 = MeshContext.create(1, MAXP, devices=jax.devices()[:1])
        _KERNELS[k] = (ctx1, build_window_resident_drain(ctx1, spec, D))
    return _KERNELS[k]


def _sharded_drain(spec, key, n):
    k = ("sharded", n) + key
    if k not in _KERNELS:
        ctx = MeshContext.create(n, MAXP, devices=jax.devices()[:n])
        _KERNELS[k] = (ctx, build_window_sharded_drain(ctx, spec, D))
    return _KERNELS[k]


@pytest.mark.parametrize("layout", ["hash", "direct"])
@pytest.mark.parametrize("packed", [False, True])
def test_sharded_drain_bitexact_vs_single_chip_oracle(
    rng, layout, packed
):
    """THE round-13 property: per-shard routed batches reconstruct the
    single-chip oracle bit-exactly — in-scan fire payloads AND the
    logical state snapshot — at n_shards 1, 2 and 4, with overflow
    pinned to zero on both sides (per-shard tables spread hash load, so
    an overflowing oracle diverges for capacity reasons; the pin keeps
    the property self-checking)."""
    spec = _spec(layout, packed)
    batches = _batches(rng, layout)
    key = (layout, packed)

    ctx1, oracle = _oracle_drain(spec, key)
    s1 = init_sharded_state(ctx1, spec)
    flat1 = [a for b in batches for a in b[:5]]
    wmv1 = np.stack([np.full(1, b[5], np.int32) for b in batches], 1)
    s1, (ovf1, _, _), fires1 = oracle(s1, *flat1, wmv1, np.int32(D))
    assert np.asarray(ovf1).sum() == 0, "oracle overflowed: re-dim test"
    want_fires = _decode_fires(
        jax.tree_util.tree_map(lambda x: np.asarray(x), fires1)
    )
    assert want_fires, "test must actually fire in-scan"
    want_entries, want_scalars = ckpt.snapshot_window_state(
        s1, spec.win, red=spec.red
    )

    for n in (1, 2, 4):
        ctx, drain = _sharded_drain(spec, key, n)
        cap = B                       # worst case: every record one shard
        sn = init_sharded_state(ctx, spec)
        flat = [a for b in batches for a in _partition(ctx, b, cap)]
        wmv = np.stack(
            [np.full(n, b[5], np.int32) for b in batches], 1
        )
        counts = np.full(n, D, np.int32)
        sn, (ovfn, _, _), firesn = drain(sn, *flat, wmv, counts)
        assert np.asarray(ovfn).sum() == 0, f"n={n} overflowed"
        got_fires = _decode_fires(
            jax.tree_util.tree_map(lambda x: np.asarray(x), firesn)
        )
        assert got_fires == want_fires, f"fires diverged at n={n}"
        got_entries, got_scalars = ckpt.snapshot_window_state(
            sn, spec.win, red=spec.red
        )
        assert _entries_equal(got_entries, want_entries), (
            f"logical state diverged at n={n}"
        )
        assert got_scalars == want_scalars


def test_sharded_drain_per_shard_counts_gate_independently(rng):
    """Divergent per-shard fill levels drain in ONE dispatch: shard s
    consumes exactly its own ``counts[s]`` slots. Oracle: the single-
    chip drain fed only the records whose owning shard's cursor covers
    their slot. (Zero collectives in the keyed body is the invariant
    that makes divergent counts deadlock-free; the lint grid pins it.)"""
    spec = _spec("hash", False)
    batches = _batches(rng, "hash")
    # no fires: count-gating is a pure state property here
    batches = [b[:5] + (np.int32(-(2**31) + 1),) for b in batches]
    n = 4
    ctx, drain = _sharded_drain(spec, ("hash", False), n)
    counts = np.array([3, 1, 2, 0], np.int32)
    cap = B
    sn = init_sharded_state(ctx, spec)
    flat = [a for b in batches for a in _partition(ctx, b, cap)]
    wmv = np.stack([np.full(n, b[5], np.int32) for b in batches], 1)
    sn, (ovfn, _, _), _ = drain(sn, *flat, wmv, counts)
    assert np.asarray(ovfn).sum() == 0

    # oracle keeps record (slot d, lane) iff counts[owning shard] > d
    kg_ends = np.asarray(ctx.kg_bounds()[1])
    ctx1, oracle = _oracle_drain(spec, ("hash", False))
    s1 = init_sharded_state(ctx1, spec)
    flat1 = []
    for d, (hi, lo, ts, vals, valid, _) in enumerate(batches):
        kg = assign_to_key_group(route_hash(hi, lo, np), MAXP, np)
        shard = np.searchsorted(kg_ends, kg)
        keep = counts[shard] > d
        flat1.extend((hi, lo, ts, vals, valid & keep))
    wmv1 = np.stack([np.full(1, b[5], np.int32) for b in batches], 1)
    s1, (ovf1, _, _), _ = oracle(s1, *flat1, wmv1, np.int32(D))
    assert np.asarray(ovf1).sum() == 0
    e_got, _ = ckpt.snapshot_window_state(sn, spec.win, red=spec.red)
    e_want, _ = ckpt.snapshot_window_state(s1, spec.win, red=spec.red)
    assert _entries_equal(e_got, e_want)
    assert len(e_got["key_hi"]) > 0    # the gated drain did real work


# ------------------------------------------------------ executor e2e

def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    return cols, (idx // 50) * 1000


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None,
              **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("dp-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


DP_CFG = {
    "pipeline.prefetch": "on",
    "pipeline.device-staging": "on",
    "pipeline.resident-loop": "on",
    "pipeline.ring-depth": 4,
    "pipeline.data-parallel": "on",
}


def test_dp_job_exact_and_sharded_drains_dispatched():
    """Exact windows on a 4-shard mesh with dp on, and the steady state
    really ran shard-locally: steps retired through the sharded drain,
    strictly fewer drain dispatches than steps."""
    total = 4096
    env = build_env(4, **DP_CFG)
    got = run_job(env, total)
    assert got == expected(total)
    m = env.last_job.metrics
    assert m.steps_sharded > 0
    assert m.resident_drains > 0
    assert m.resident_drains < m.steps


def test_dp_on_requires_resident_loop():
    """dp=on without the resident-loop substrate is a config error,
    never a silent downgrade."""
    env = build_env(4, **{"pipeline.data-parallel": "on"})
    with pytest.raises(ValueError, match="data-parallel"):
        run_job(env, 512)


def test_skewed_batch_falls_back_without_loss():
    """A batch whose per-shard slice overflows ``shard_cap`` takes the
    replicated route for that batch only — the adaptive ladder is never
    lossy. Planner unit: all-one-key skew refuses the sharded route."""
    ctx = MeshContext.create(4, MAXP, devices=jax.devices()[:4])
    mask_sh, split_sh = ingest_mod.IngestPlan.shardings_for(ctx.mesh)
    plan = ingest_mod.IngestPlan(
        td=None, slide_ticks=1000, span_limit=8, B=64, B_step=64,
        n_shards=4, max_parallelism=MAXP,
        kg_ends=np.asarray(ctx.kg_bounds()[1]), exchange_cap=0,
        routes=("mask", "sharded"), staging=True,
        mask_sharding=mask_sh, split_sharding=split_sh,
        ring_depth=4, shard_cap=32,
    )
    rng = np.random.default_rng(7)
    hi = rng.integers(0, 2**32, 64, dtype=np.uint32)
    lo = rng.integers(0, 2**32, 64, dtype=np.uint32)
    route, shard = ingest_mod.plan_route_and_shards(plan, hi, lo)
    assert route == "sharded" and shard is not None
    # the planner's shard assignment matches the mesh ownership ranges
    kg = assign_to_key_group(route_hash(hi, lo, np), MAXP, np)
    starts, ends = (np.asarray(a) for a in ctx.kg_bounds())
    assert ((kg >= starts[shard]) & (kg <= ends[shard])).all()
    skew_hi = np.zeros(64, np.uint32)
    skew_lo = np.zeros(64, np.uint32)
    route, shard = ingest_mod.plan_route_and_shards(plan, skew_hi,
                                                    skew_lo)
    assert route == "mask" and shard is None


# ------------------------------------- exactly-once: crash + elastic

def test_dp_mid_drain_crash_restore_exactly_once(tmp_path):
    """THE round-13 exactly-once criterion: crash at a sharded drain
    dispatch (``step.drain`` seam, staged slots in per-shard rings, the
    drain not yet retired) on a 4-shard mesh; restore replays the
    un-retired group from the per-shard applied cut — nothing skipped,
    nothing double-counted."""
    total = 4096
    env = build_env(
        4, tmp_path / "chk", interval=2, restart=3,
        **{**DP_CFG, "checkpoint.mode": "incremental",
           "checkpoint.async": True},
    )
    inj = FaultInjector([
        FaultRule("step.drain",
                  exc=RuntimeError("injected mid-drain crash"), at=1),
    ])
    with faults.active(inj):
        got = run_job(env, total)
    m = env.last_job.metrics
    assert inj.fired_at("step.drain"), "drain seam never fired"
    assert m.restarts == 1
    assert m.steps_sharded > 0
    assert got == expected(total)


def test_dp_elastic_lose_one_then_scale_back(tmp_path):
    """PR 8 elastic cycle with sharded rings in play: lose 1 of 4
    shards (device loss) -> degraded 3-shard re-plan re-slices the
    key-group ranges AND the per-shard rings and drops the sharded
    drain caches -> operator scale-up back to 4 — exactly-once across
    the whole cycle."""
    env = build_env(4, tmp_path / "chk", interval=2, **{
        **DP_CFG,
        "checkpoint.mode": "incremental",
        "checkpoint.async": True,
        "checkpoint.local.enabled": True,
        "restart-strategy": "exponential-backoff",
        "restart-strategy.exponential-backoff.initial-delay": 0.01,
        "restart-strategy.exponential-backoff.max-delay": 0.05,
    })
    total = 8192

    def scale_up_when_degraded():
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            ctl = getattr(env, "_elastic_controller", None)
            if ctl is not None and ctl.degraded:
                time.sleep(0.3)
                ctl.request_scale_up()
                return
            time.sleep(0.02)

    t = threading.Thread(target=scale_up_when_degraded, daemon=True)
    t.start()
    inj = FaultInjector([device_loss_rule(shard=1, at=8)])
    with faults.active(inj):
        got = run_job(env, total)
    t.join(timeout=5)
    assert got == expected(total)
    assert env.last_job.metrics.steps_sharded > 0
    el = env._elasticity_report()
    kinds = [r["kind"] for r in el["rescales"]]
    assert kinds == ["degrade", "scale_up"]
    assert el["degraded"] is False and el["current-shards"] == 4


# --------------------------------------------- DCN lockstep loose end

def test_dcn_resident_loop_on_no_longer_config_gated():
    """Round 20 replaces the round-13 refusal: ``on`` (or ``while``)
    under the DCN plane selects the PER-HOST resident mode
    (``DCNJobSpec.resident``, docs/DCN_INGESTION.md) instead of raising.
    Submission must proceed past config validation into the plane's
    distributed init — here that init fails (pytest's process already
    ran JAX computations, and port 1 is unbindable anyway), but the
    round-13 ValueError must NOT resurface as a config gate."""
    env = build_env(1, **{
        "dcn.coordinator": "127.0.0.1:1",
        "pipeline.resident-loop": "on",
    })
    with pytest.raises(Exception) as ei:
        run_job(env, 256)
    assert not isinstance(ei.value, ValueError), ei.value


def test_dcn_data_parallel_on_is_config_error():
    env = build_env(1, **{
        "dcn.coordinator": "127.0.0.1:1",
        "pipeline.data-parallel": "on",
    })
    with pytest.raises(ValueError, match="data-parallel.*lockstep"):
        run_job(env, 256)


def test_dcn_resident_loop_auto_resolves_off_with_log(capsys):
    """``auto`` resolves to off on the lockstep plane, loudly: a
    startup stderr line says so before anything executes. (The probe
    pipeline is stateless, so the plane raises NotImplementedError
    right after the resolution — the log must already be out.)"""
    env = build_env(1, **{
        "dcn.coordinator": "127.0.0.1:1",
        "pipeline.resident-loop": "auto",
    })
    sink = CollectSink()
    env.add_source(GeneratorSource(gen, total=256)).add_sink(sink)
    with pytest.raises(NotImplementedError):
        env.execute("dcn-auto-probe")
    err = capsys.readouterr().err
    assert "resident-loop auto resolves to OFF" in err


# ------------------------------------------- refusal observability

def test_ring_publish_refusals_in_prometheus_exposition(tmp_path):
    """Backpressure from a stalled shard is OBSERVABLE: the total
    ``ring_publish_refusals`` gauge and the per-shard series ride the
    Prometheus text exposition for a dp job."""
    import urllib.request

    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    env = build_env(4, **DP_CFG)
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=2048))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    try:
        jid = cluster.submit(env, "dp-web-job")
        assert cluster.wait(jid, 240) == "FINISHED"
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        assert 'flink_tpu_ring_publish_refusals{job="dp-web-job"}' \
            in text
        for s in range(4):
            assert (
                f'flink_tpu_ring_publish_refusals_shard_{s}'
                f'{{job="dp-web-job"}}'
            ) in text
        assert 'flink_tpu_steps_sharded{job="dp-web-job"}' in text
    finally:
        web.stop()


def test_drain_flight_recorder_pipeline_endpoint_and_gauges(tmp_path):
    """Round 14 acceptance: a sharded resident job with
    ``observability.drain-stats`` on serves per-shard ring occupancy,
    drain duty-cycle, and fire-latency percentiles at
    /jobs/<jid>/pipeline; the per-shard gauge families ride the
    Prometheus exposition; and the Perfetto export carries the drain
    counter tracks next to the phase spans."""
    import urllib.request

    from flink_tpu.runtime.cluster import MiniCluster
    from flink_tpu.runtime.web import WebMonitor

    def get_json(port, path):
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10
        ) as r:
            return json.loads(r.read())

    env = build_env(4, **{
        **DP_CFG,
        "observability.tracing": True,
        "observability.drain-stats-every": 1,
    })
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=4096))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    cluster = MiniCluster()
    web = WebMonitor(cluster)
    port = web.start()
    try:
        jid = cluster.submit(env, "dp-pipe-job")
        assert cluster.wait(jid, 240) == "FINISHED"
        got = {(r.key, r.window_end_ms): r.value for r in sink.results}
        assert got == expected(4096)

        # -- /jobs/<jid>/pipeline: the consolidated drain view
        rep = get_json(port, f"/jobs/{jid}/pipeline")
        assert rep["available"] is True
        assert rep["n_shards"] == 4
        assert rep["drains"] > 0 and rep["payload_fetches"] > 0
        assert rep["fields"][0] == "events"
        assert len(rep["shards"]) == 4
        for row in rep["shards"]:
            assert 0.0 <= row["duty_cycle"] <= 1.0
            assert 0.0 <= row["ring_starved"] <= 1.0
            assert "publish_refusals" in row
            # occupancy points carry (t, fill, publish|drain) triples
            assert all(src in ("publish", "drain")
                       for _t, _f, src in row["occupancy"])
        assert sum(r["totals"]["events"] for r in rep["shards"]) > 0
        assert any(r["occupancy"] for r in rep["shards"])
        lat = rep["latency_ms"]
        assert lat["publish_to_consume"]["samples"] > 0
        assert lat["publish_to_consume"]["p99"] is not None
        assert lat["event_to_fire"]["samples"] > 0
        assert rep["drain_stats_every"] == 1
        assert rep["classification"] in (
            "ok", "source-starved", "host-bound", "device-bound",
            "sink-bound", "device-saturated", "ring-starved",
        )

        # -- Prometheus: per-shard gauge families + latency summaries
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10
        ) as r:
            text = r.read().decode()
        for s in range(4):
            assert (f'flink_tpu_drain_slot_fill_shard_{s}'
                    f'{{job="dp-pipe-job"}}') in text
            assert (f'flink_tpu_drain_duty_cycle_shard_{s}'
                    f'{{job="dp-pipe-job"}}') in text
        for q in (50, 95, 99):
            assert f'flink_tpu_drain_fire_latency_p{q}_ms' in text
            assert f'flink_tpu_drain_consume_latency_p{q}_ms' in text

        # -- Perfetto: counter tracks ("ph": "C") next to the spans
        tr = get_json(port, f"/jobs/{jid}/traces")
        counters = [ev for ev in tr["traceEvents"] if ev["ph"] == "C"]
        tracks = {ev["name"] for ev in counters}
        assert any(t.startswith("drain/shard") for t in tracks)
        assert any(t.startswith("drain_retired/shard") for t in tracks)
        fill_ev = next(ev for ev in counters
                       if ev["name"].startswith("drain/shard"))
        assert set(fill_ev["args"]) == {"fill", "duty_pct"}
    finally:
        web.stop()


# --------------------------------------- ShardedDeviceBatchRing units

def _dp_plan(n=4, B_=32, cap=16, depth=4):
    ctx = MeshContext.create(n, MAXP, devices=jax.devices()[:n])
    mask_sh, split_sh = ingest_mod.IngestPlan.shardings_for(ctx.mesh)
    return ctx, ingest_mod.IngestPlan(
        td=None, slide_ticks=1000, span_limit=8, B=B_, B_step=B_,
        n_shards=n, max_parallelism=MAXP,
        kg_ends=np.asarray(ctx.kg_bounds()[1]), exchange_cap=0,
        routes=("mask", "sharded"), staging=True,
        mask_sharding=mask_sh, split_sharding=split_sh,
        ring_depth=depth, shard_cap=cap,
    )


def test_sharded_ring_per_shard_cursors_and_release():
    """Per-shard write cursors and per-shard release: one shard's
    retirement never frees (or blocks) another's lanes, refusals count
    PER SHARD, and a refused lane still publishes (fresh buffer) so the
    global staged array always carries every shard's row."""
    ctx, plan = _dp_plan()
    ring = ingest_mod.ShardedDeviceBatchRing(plan, 2)
    rng = np.random.default_rng(3)
    hi = rng.integers(0, 2**32, 32, dtype=np.uint32)
    lo = rng.integers(0, 2**32, 32, dtype=np.uint32)
    kg = assign_to_key_group(route_hash(hi, lo, np), MAXP, np)
    shard = np.searchsorted(np.asarray(ctx.kg_bounds()[1]), kg)
    ticks = np.zeros(32, np.int32)
    vals = np.ones(32, np.float32)

    seqs0, staged = ring.publish_batch(plan, hi, lo, ticks, vals,
                                       shard, 32, 0)
    assert seqs0 == [0, 0, 0, 0]
    assert all(a.shape == (4, 16) for a in staged)
    # staged rows reconstruct the partition exactly
    shi = np.asarray(staged[0])
    sok = np.asarray(staged[4])
    for s in range(4):
        assert sorted(hi[shard == s].tolist()) == \
            sorted(shi[s][sok[s]].tolist())
    seqs1, _ = ring.publish_batch(plan, hi, lo, ticks, vals, shard,
                                  32, 0)
    assert seqs1 == [1, 1, 1, 1] and ring.occupancy() == 2
    # full ring: every shard refuses its lane but the publish still
    # returns a complete staged array (fresh buffers, seq None)
    seqs2, staged2 = ring.publish_batch(plan, hi, lo, ticks, vals,
                                        shard, 32, 0)
    assert seqs2 == [None] * 4
    assert all(a.shape == (4, 16) for a in staged2)
    assert ring.refusals() == [1, 1, 1, 1]
    # release shard 2 only: ITS lane frees, others stay occupied
    assert ring.release_shards([None, None, 0, None]) == 1
    seqs3, _ = ring.publish_batch(plan, hi, lo, ticks, vals, shard,
                                  32, 0)
    assert seqs3 == [None, None, 2, None]
    assert ring.refusals() == [2, 2, 1, 2]
    assert ring.clear() > 0 and ring.occupancy() == 0


def test_sharded_ring_epoch_and_clear_discard():
    """A restore-path ``clear()`` empties every shard's lane ring so
    the replay epoch starts from empty cursors."""
    ctx, plan = _dp_plan(n=2)
    ring = ingest_mod.ShardedDeviceBatchRing(plan, 3)
    hi = np.arange(8, dtype=np.uint32)
    shard = np.array([0, 0, 0, 0, 1, 1, 1, 1])
    for _ in range(2):
        ring.publish_batch(plan, hi, hi, np.zeros(8, np.int32),
                           np.ones(8, np.float32), shard, 8, 0)
    assert ring.occupancy() == 2
    assert ring.clear() == 4          # 2 slots x 2 shards
    assert ring.occupancy() == 0
    seqs, _ = ring.publish_batch(plan, hi, hi, np.zeros(8, np.int32),
                                 np.ones(8, np.float32), shard, 8, 1)
    assert seqs == [2, 2]             # cursors continue monotonically
