"""allowedLateness semantics: late-but-allowed records re-fire their window
with the corrected value; beyond-lateness records drop (ref WindowOperator
lateness logic + cleanup timers)."""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink


def run(batches, window=10_000, lateness=5_000, batch=8):
    """batches: list of event lists [(ts, key, v), ...]; batch_size makes
    each list one micro-batch. The watermark is monotonous on the max seen
    ts, so later batches make earlier timestamps late."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(2).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(256)
    env.batch_size = batch
    flat = [e for batch_ in batches for e in batch_]
    sink = CollectSink()
    (
        env.from_collection(flat)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(window)
        .allowed_lateness(lateness)
        .sum(lambda e: e[2])
        .add_sink(sink)
    )
    env.execute("lateness")
    return sink.results, env.last_job


def test_late_refire_within_lateness():
    # batch sizing: batch=2 -> each pair is one micro-batch; watermark
    # advances to 14999 after the second batch, firing window [0,10000).
    # The late record at ts=5000 (within 5s lateness) must RE-FIRE the
    # window with the corrected sum.
    batches = [
        [(0, "k", 1.0), (9_000, "k", 2.0)],        # window [0,10k): sum 3
        [(12_000, "k", 10.0), (12_500, "k", 1.0)],  # wm -> 12499, fires [0,10k)
        # late for [0,10k) but within lateness (cleanup at 9999+5000=14999)
        [(5_000, "k", 5.0), (13_000, "k", 1.0)],
    ]
    results, job = run(batches, batch=2)
    w1 = [r for r in results if r.window_end_ms == 10_000]
    assert [r.value for r in w1] == [3.0, 8.0], w1  # on-time fire + re-fire
    assert job.metrics.dropped_late == 0
    # the second window [10k,20k) contains 10+1+1 = 12
    w2 = [r for r in results if r.window_end_ms == 20_000]
    assert [r.value for r in w2] == [12.0]


def test_beyond_lateness_drops():
    batches = [
        [(0, "k", 1.0), (9_000, "k", 2.0)],
        [(30_000, "k", 1.0), (30_500, "k", 1.0)],  # wm -> 30499 >> 10k+5k
        [(5_000, "k", 100.0), (31_000, "k", 1.0)],  # beyond lateness
    ]
    results, job = run(batches, batch=2)
    w1 = [r for r in results if r.window_end_ms == 10_000]
    assert [r.value for r in w1] == [3.0]  # no re-fire
    assert job.metrics.dropped_late == 1


def test_multiple_late_refires_accumulate():
    batches = [
        [(0, "a", 1.0), (0, "b", 1.0)],
        [(12_000, "a", 0.5), (12_500, "b", 0.5)],  # fires [0,10k) a=1, b=1
        [(1_000, "a", 1.0), (13_000, "x", 0.0)],   # late a -> refire a=2
        [(2_000, "a", 1.0), (2_500, "b", 1.0)],    # late both -> a=3, b=2
    ]
    results, job = run(batches, batch=2)
    a = [r.value for r in results if r.key == "a" and r.window_end_ms == 10_000]
    b = [r.value for r in results if r.key == "b" and r.window_end_ms == 10_000]
    assert a == [1.0, 2.0, 3.0]
    assert b == [1.0, 2.0]
    # re-fires are per-updated-key: 'b' did not re-emit on a-only updates
