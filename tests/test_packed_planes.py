"""Packed state planes (ISSUE 7): acc + touched in one wider array.

The packed layout must be observationally identical to split planes —
same logical accumulator bits, same touched set, same fires, same
snapshot format (checkpoints move freely between layouts) — while the
kernels issue one scatter/sweep where split issues two. CPU tier-1
forces packing explicitly (the runtime's auto gate keeps CPU on split
planes), so the layout is covered wherever it can run.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import hash64_host

B = 256


def _split_keys(keys):
    h = hash64_host(np.asarray(keys, dtype=np.int64))
    return ((h >> np.uint64(32)).astype(np.uint32),
            (h & np.uint64(0xFFFFFFFF)).astype(np.uint32))


def _batches(rng, n=5):
    out = []
    for i in range(n):
        hi, lo = _split_keys(rng.integers(0, 80, B).astype(np.int64))
        ts = rng.integers(0, 50, B).astype(np.int32)
        vals = rng.integers(1, 6, B).astype(np.float32)
        out.append((hi, lo, ts, vals, np.int32(i * 13 - 4)))
    return out


def _run_seq(win, red, packed, batches, kind_vals=True):
    st = wk.init_state(256, 8, win, red, n_key_groups=64, packed=packed)
    for (hi, lo, ts, vals, wm) in batches:
        st, _act, _kgf = wk.update(
            st, win, red, jnp.asarray(hi), jnp.asarray(lo),
            jnp.asarray(ts), jnp.asarray(vals),
            jnp.asarray(np.ones(B, bool)), kg_fill=64,
        )
        st, fr = wk.advance_and_fire(st, win, red, wm)
    return st, fr


@pytest.mark.parametrize("kind", ["sum", "count", "min", "max"])
def test_packed_logical_parity_with_split(rng, kind):
    """Same update/fire sequence on packed vs split planes: logical acc
    view, touched view, fires, counters — all identical."""
    win = wk.WindowSpec(10, 10, ring=8, fires_per_step=4)
    red = wk.ReduceSpec(kind, jnp.float32)
    batches = _batches(rng)
    s_split, fr_split = _run_seq(win, red, False, batches)
    s_pack, fr_pack = _run_seq(win, red, True, batches)

    assert s_pack.packed == 0 and s_split.packed == -1
    assert s_pack.touched.shape == (0,)
    np.testing.assert_array_equal(
        np.asarray(wk.acc_view(s_split, red)),
        np.asarray(wk.acc_view(s_pack, red)),
    )
    np.testing.assert_array_equal(
        np.asarray(wk.touched_view(s_split, red)),
        np.asarray(wk.touched_view(s_pack, red)),
    )
    for name in ("mask", "values", "window_end_ticks", "n_fires",
                 "lane_valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fr_split, name)),
            np.asarray(getattr(fr_pack, name)), err_msg=name,
        )
    for name in ("pane_ids", "max_pane", "fired_through", "purged_through",
                 "dropped_late", "dropped_capacity", "kg_dirty"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_split, name)),
            np.asarray(getattr(s_pack, name)), err_msg=name,
        )


def test_packed_precombine_parity(rng):
    """Packed + precombine: the rep scatter carries the touch column
    through the shared sort; results equal the split/plain path."""
    win = wk.WindowSpec(20, 10, ring=8, fires_per_step=4)
    red = wk.ReduceSpec("sum", jnp.float32)
    # duplicate-heavy: 90% of lanes on 8 hot keys
    batches = []
    for i in range(4):
        keys = np.concatenate([
            rng.integers(0, 8, (9 * B) // 10),
            rng.integers(100, 200, B - (9 * B) // 10),
        ]).astype(np.int64)
        rng.shuffle(keys)
        hi, lo = _split_keys(keys)
        ts = np.full(B, i * 10 + 5, np.int32)
        vals = rng.integers(1, 4, B).astype(np.float32)
        batches.append((hi, lo, ts, vals, np.int32(i * 10 - 1)))

    def run(packed, pre):
        st = wk.init_state(256, 8, win, red, n_key_groups=64,
                           packed=packed)
        kgfs = []
        for (hi, lo, ts, vals, wm) in batches:
            st, _a, kgf = wk.update(
                st, win, red, jnp.asarray(hi), jnp.asarray(lo),
                jnp.asarray(ts), jnp.asarray(vals),
                jnp.asarray(np.ones(B, bool)), precombine=pre, kg_fill=64,
            )
            kgfs.append(np.asarray(kgf))
            st, _ = wk.advance_and_fire(st, win, red, wm)
        return st, np.stack(kgfs)

    base, kgf0 = run(False, False)
    for packed, pre in ((True, False), (True, True), (False, True)):
        st, kgf = run(packed, pre)
        np.testing.assert_array_equal(
            np.asarray(wk.acc_view(base, red)),
            np.asarray(wk.acc_view(st, red)),
            err_msg=f"packed={packed} pre={pre}",
        )
        np.testing.assert_array_equal(
            np.asarray(wk.touched_view(base, red)),
            np.asarray(wk.touched_view(st, red)),
        )
        np.testing.assert_array_equal(np.asarray(base.kg_dirty),
                                      np.asarray(st.kg_dirty))
        np.testing.assert_array_equal(kgf0, kgf)


def test_packed_snapshot_roundtrips_across_layouts(rng):
    """Checkpoint format is LOGICAL: a snapshot of packed state restores
    into a split stage (and back) with identical logical contents."""
    from flink_tpu.parallel.mesh import MeshContext
    from flink_tpu.runtime import checkpoint as ckpt
    from flink_tpu.runtime.step import (
        WindowStageSpec, build_window_update_step, init_sharded_state,
    )

    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    win = wk.WindowSpec(10, 10, ring=8, fires_per_step=4)
    red = wk.ReduceSpec("sum", jnp.float32)
    spec_p = WindowStageSpec(win=win, red=red, capacity_per_shard=256,
                             probe_len=8, packed=True)
    spec_s = dataclasses.replace(spec_p, packed=False)

    step = build_window_update_step(ctx, spec_p)
    state = init_sharded_state(ctx, spec_p)
    hi, lo = _split_keys(rng.integers(0, 300, B).astype(np.int64))
    ts = rng.integers(0, 30, B).astype(np.int32)
    state, _ = step(state, hi, lo, ts, np.ones(B, np.float32),
                    np.ones(B, bool), np.full(8, np.int32(-1)))

    entries, scalars = ckpt.snapshot_window_state(state, win, red=red)
    assert len(entries["key_hi"]) > 0
    # packed -> split
    restored_s = ckpt.restore_window_state(entries, scalars, ctx, spec_s)
    # packed -> packed
    restored_p = ckpt.restore_window_state(entries, scalars, ctx, spec_p)
    assert restored_s.packed == -1 and restored_p.packed == 0

    # both restores rebuild from the same logical entries, so their
    # planes must agree position-for-position across the layouts
    np.testing.assert_array_equal(np.asarray(restored_s.table.keys),
                                  np.asarray(restored_p.table.keys))
    np.testing.assert_array_equal(
        np.asarray(wk.acc_view(restored_s, red)),
        np.asarray(wk.acc_view(restored_p, red)),
    )
    np.testing.assert_array_equal(
        np.asarray(wk.touched_view(restored_s, red)),
        np.asarray(wk.touched_view(restored_p, red)),
    )

    # ...and re-snapshotting the PACKED restore reproduces the original
    # logical entry set exactly (a restore reshuffles slots, so the
    # entry multiset — not the plane layout — is the format contract)
    def entry_set(e):
        return {
            (int(h), int(l), int(p), float(v))
            for h, l, p, v in zip(e["key_hi"], e["key_lo"], e["pane"],
                                  e["value"])
        }

    entries2, _ = ckpt.snapshot_window_state(restored_p, win, red=red)
    assert entry_set(entries2) == entry_set(entries)

    # staging packed state without the reduce spec must fail loudly
    with pytest.raises(ValueError, match="ReduceSpec"):
        ckpt.stage_window_state(state)


def test_packed_compact_table_and_occupancy_parity(rng):
    """compact_table remaps the packed plane in one pass; kg_occupancy
    derives the touched view — both must match split planes."""
    win = wk.WindowSpec(10, 10, ring=8, fires_per_step=4)
    red = wk.ReduceSpec("sum", jnp.float32)
    batches = _batches(rng, n=3)
    s_split, _ = _run_seq(win, red, False, batches)
    s_pack, _ = _run_seq(win, red, True, batches)
    occ_s = np.asarray(wk.kg_occupancy(s_split, 64, red=red, win=win))
    occ_p = np.asarray(wk.kg_occupancy(s_pack, 64, red=red, win=win))
    np.testing.assert_array_equal(occ_s, occ_p)

    c_split = wk.compact_table(s_split, win, red)
    c_pack = wk.compact_table(s_pack, win, red)
    # same live population lands in the (deterministically rebuilt) table
    np.testing.assert_array_equal(
        np.asarray(c_split.table.keys), np.asarray(c_pack.table.keys)
    )
    np.testing.assert_array_equal(
        np.asarray(wk.acc_view(c_split, red)),
        np.asarray(wk.acc_view(c_pack, red)),
    )
    np.testing.assert_array_equal(
        np.asarray(wk.touched_view(c_split, red)),
        np.asarray(wk.touched_view(c_pack, red)),
    )


@pytest.mark.parametrize("packed", [False, True])
def test_slot_major_layout_parity(rng, packed):
    """acc_layout="slot" (slot-major storage, the bench-swept variant)
    must be observationally identical to the default pane-major order:
    same logical views, same fires, same counters — only the memory walk
    differs."""
    win_p = wk.WindowSpec(20, 10, ring=8, fires_per_step=4)
    win_s = dataclasses.replace(win_p, acc_layout="slot")
    red = wk.ReduceSpec("sum", jnp.float32)
    batches = _batches(rng, n=4)

    def run(win):
        st = wk.init_state(256, 8, win, red, n_key_groups=64,
                           packed=packed)
        for (hi, lo, ts, vals, wm) in batches:
            st, _a, _k = wk.update(
                st, win, red, jnp.asarray(hi), jnp.asarray(lo),
                jnp.asarray(ts), jnp.asarray(vals),
                jnp.asarray(np.ones(B, bool)), kg_fill=64,
            )
            st, fr = wk.advance_and_fire(st, win, red, wm)
        return st, fr

    s_p, fr_p = run(win_p)
    s_s, fr_s = run(win_s)
    # fires are layout-independent (per-lane dense planes)
    for name in ("mask", "values", "window_end_ticks", "n_fires",
                 "lane_valid"):
        np.testing.assert_array_equal(
            np.asarray(getattr(fr_p, name)),
            np.asarray(getattr(fr_s, name)), err_msg=name,
        )
    # logical plane content matches after undoing the storage order
    C, R = 256, win_p.ring
    a_p = np.asarray(wk.acc_view(s_p, red)).reshape(R, C)
    a_s = np.asarray(wk.acc_view(s_s, red)).reshape(C, R).T
    np.testing.assert_array_equal(a_p, a_s)
    t_p = np.asarray(wk.touched_view(s_p, red)).reshape(R, C)
    t_s = np.asarray(wk.touched_view(s_s, red)).reshape(C, R).T
    np.testing.assert_array_equal(t_p, t_s)
    for name in ("pane_ids", "fired_through", "purged_through",
                 "dropped_late", "dropped_capacity", "kg_dirty"):
        np.testing.assert_array_equal(
            np.asarray(getattr(s_p, name)), np.asarray(getattr(s_s, name)),
            err_msg=name,
        )


def test_packed_eligibility_gates():
    win = wk.WindowSpec(10, 10, ring=8)
    generic = wk.ReduceSpec("generic", jnp.float32,
                            combine=lambda a, b: a + b, neutral=0.0)
    assert not wk.packed_eligible(generic)
    with pytest.raises(ValueError, match="packed"):
        wk.init_state(64, 8, win, generic, packed=True)
    # explicit user neutral could collide with the touch marker
    assert not wk.packed_eligible(
        wk.ReduceSpec("min", jnp.float32, neutral=0.0)
    )
    assert wk.packed_eligible(wk.ReduceSpec("min", jnp.float32))
    assert wk.packed_eligible(wk.ReduceSpec("count", jnp.int32))


# ------------------------------------------------------------- end to end

N_KEYS = 150
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    return ({"key": (idx * 48271) % N_KEYS,
             "value": np.ones(n, np.float32)}, (idx // 1000) * 1000)


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 1000) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def _env(tmp=None, interval=0, restart=None, **cfg):
    conf = Configuration(cfg)
    if restart:
        conf.set("restart-strategy", "fixed-delay")
        conf.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(conf)
    env.set_parallelism(2).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = B
    if tmp:
        env.enable_checkpointing(interval, str(tmp))
    return env


def _run(env, total, source=None):
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("packed-job")
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


def test_packed_job_end_to_end_exact():
    total = 8192
    env = _env(**{"state.packed-planes": "on"})
    got = _run(env, total)
    assert got == expected(total)
    assert env.last_job.metrics.state_packed_planes is True


def test_packed_job_with_fused_fire_and_crash_restore(tmp_path):
    """The whole round in one scenario: packed planes + K-fused resident
    pipeline + incremental async checkpoints + prefetch, with a
    mid-stream crash — exactly-once across a packed-state restore."""
    import threading

    from flink_tpu.runtime.sources import GeneratorSource

    class FailingSource(GeneratorSource):
        def __init__(self, fn, total, fail_at):
            super().__init__(fn, total)
            self.fail_at = fail_at
            self.failed = False

        def poll(self, max_records):
            out = super().poll(max_records)
            if not self.failed and self.offset >= self.fail_at:
                self.failed = True
                raise RuntimeError("injected failure")
            return out

    total = 12288
    env = _env(
        tmp_path / "chk", interval=2, restart=3,
        **{"state.packed-planes": "on", "pipeline.steps-per-dispatch": 4,
           "pipeline.prefetch": "on", "checkpoint.mode": "incremental",
           "checkpoint.async": True},
    )
    got = _run(env, total, source=FailingSource(gen, total, total // 2))
    m = env.last_job.metrics
    assert m.restarts == 1
    assert m.state_packed_planes is True
    assert m.fused_fire_dispatches > 0
    assert got == expected(total)


def test_packed_on_rejected_for_ineligible_reduce():
    env = _env(**{"state.packed-planes": "on"})
    total = 1024

    def gen2(offset, n):
        idx = np.arange(offset, offset + n)
        return ({"key": idx % 10, "value": np.ones(n, np.float32)},
                (idx // 100) * 1000)

    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import GeneratorSource

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen2, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .reduce(lambda a, b: a + b, extractor=lambda c: c["value"],
                neutral=0.0)
        .add_sink(sink)
    )
    with pytest.raises(ValueError, match="packed-planes"):
        env.execute("packed-generic")
