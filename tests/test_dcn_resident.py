"""Per-host DCN-resident mode (ISSUE 20 tentpole (b), runtime/dcn.py
``_run_resident`` + runtime/step.py ``build_window_dcn_resident_drain``):

* single-process (one host, real 8-device collectives): resident drains
  are bit-exact vs the analytic oracle and retire the stream in strictly
  fewer lockstep rounds than single-step dispatch — each round stacks up
  to ring-depth locally-polled chunks into ONE drain dispatch,
* two-process ensemble (capability-gated like test_dcn.py): merged
  emissions bit-exact vs the single-host oracle, records still cross the
  process boundary through the in-kernel all_to_all, and the cycle count
  beats the lockstep ensemble,
* resident + rebalance side channel: peer exchange runs only at drain
  boundaries with the frame deadline scaled by the previous drain's slot
  count — results stay exact,
* drain-boundary peer-stall units: ``_frame_deadline_s`` scales the base
  recv timeout by ``deadline_scale`` (never below the base, 1.0 in
  lockstep mode ⇒ byte-identical), and a stalled peer still raises an
  attributed :class:`DCNPeerStalledError` under the SCALED deadline —
  the semantics ISSUE 20 requires the resident mode to preserve.
"""

import os
import socket
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(__file__))
from dcn_jobs import (  # noqa: E402
    RESIDENT_DEPTH,
    expected,
    expected_skewed,
)
from dcn_probe import (  # noqa: E402
    SKIP_REASON,
    multiprocess_collectives_supported,
)

from flink_tpu.runtime.dcn import (  # noqa: E402
    DCNPeerStalledError,
    _RebalanceRing,
    runner_for_spec,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
NPROC = 2

ensemble = pytest.mark.skipif(
    not multiprocess_collectives_supported(), reason=SKIP_REASON
)


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rows(out):
    got = {}
    for k64, w, v in zip(out["key_id"], out["window_end_ms"],
                         out["value"]):
        key = (int(k64), int(w))
        assert key not in got, f"duplicate emission {key}"
        got[key] = float(v)
    return got


# ------------------------------------------- single process, real drains

def test_resident_single_host_exact_and_fewer_cycles():
    """One host over the full local mesh: the resident drain kernel's
    collectives (pmax fill agreement, pmin done/watermark, all_to_all
    routing) run for real across the local shards. Exact results, and
    the cycle count (= drain dispatches) is strictly below the lockstep
    runner's single-step rounds on the same stream."""
    from dcn_jobs import two_host_window, two_host_window_resident

    out = runner_for_spec(two_host_window_resident(), 0, 1).run()
    assert _rows(out) == expected(1)
    assert out["cycles"] > 0

    base = runner_for_spec(two_host_window(), 0, 1).run()
    assert _rows(base) == expected(1)
    assert out["cycles"] < base["cycles"], (out["cycles"], base["cycles"])


def test_resident_requires_time_window_job():
    """``resident=True`` on a runner family without a resident drain
    kernel (session/rolling/cep) is a config error, never a silent
    fallback to lockstep."""
    from dcn_jobs import two_host_session

    spec = two_host_session()
    spec.resident = True
    spec.resident_ring_depth = RESIDENT_DEPTH
    with pytest.raises(ValueError, match="resident"):
        runner_for_spec(spec, 0, 1)


# --------------------------------------------- two-process ensemble (gated)

def _spawn(pid, coord, builder, out, extra_env=None):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "flink_tpu.runtime.dcn",
         "--coordinator", coord, "--num-processes", str(NPROC),
         "--process-id", str(pid), "--builder",
         os.path.join(REPO, "tests", "dcn_jobs.py") + ":" + builder,
         "--out", out],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
    )


def _run_ensemble(tmp_path, tag, builder, extra_env=None):
    import json

    coord = f"127.0.0.1:{_free_port()}"
    outs = [str(tmp_path / f"{tag}-{p}.npz") for p in range(NPROC)]
    procs = [_spawn(p, coord, builder, outs[p], extra_env)
             for p in range(NPROC)]
    deadline = time.time() + 420
    logs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=max(1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        logs.append(out.decode(errors="replace"))
    cycles = None
    for p, log in zip(procs, logs):
        assert p.returncode == 0, log[-2000:]
        for line in log.splitlines():
            if line.startswith("{"):
                cycles = json.loads(line)["cycles"]
    got, by_host = {}, {}
    for host, path in enumerate(outs):
        data = np.load(path)
        for k64, w, v in zip(data["key_id"], data["window_end_ms"],
                             data["value"]):
            key = (int(k64), int(w))
            assert key not in got, f"duplicate emission {key}"
            got[key] = float(v)
            by_host[key] = host
    return got, by_host, cycles


@ensemble
def test_resident_two_process_bit_exact_vs_oracle(tmp_path):
    """The round-20 cross-host criterion: the two-process resident
    ensemble's merged emissions equal the single-host oracle exactly,
    records provably cross the DCN hop inside the resident drain, and
    the drain-grouped rounds beat the lockstep ensemble's cycles."""
    got, by_host, cycles = _run_ensemble(
        tmp_path, "res", "two_host_window_resident")
    assert got == expected(NPROC)
    crossed = sum(
        1 for (k, _w), host in by_host.items() if host != k % NPROC
    )
    assert crossed > len(got) // 4, (crossed, len(got))
    assert len(set(by_host.values())) == NPROC

    _got_l, _bh, cyc_lock = _run_ensemble(
        tmp_path, "lock", "two_host_window")
    assert cycles < cyc_lock, (cycles, cyc_lock)


@ensemble
def test_resident_with_rebalance_exchanges_at_drain_boundaries(tmp_path):
    """Resident drains + the host-level rebalance ring: the peer
    exchange happens only at drain boundaries (one frame per up-to-depth
    chunks) under the drain-scaled frame deadline, and the 90/10 skewed
    stream still sums exactly."""
    addrs = f"127.0.0.1:{_free_port()},127.0.0.1:{_free_port()}"
    got, _by_host, cycles = _run_ensemble(
        tmp_path, "resreb", "skewed_window_rebalanced_resident",
        {"FLINK_TPU_TEST_REBALANCE_ADDRS": addrs})
    assert got == expected_skewed()
    assert cycles > 0


# -------------------------------------- drain-boundary stall units (local)

def _mk_ring_shell(recv_timeout_s, scale):
    """A _RebalanceRing with just the fields the deadline/stall paths
    touch — no sockets dialed, no peers needed."""
    import struct as struct_mod

    ch = _RebalanceRing.__new__(_RebalanceRing)
    ch.struct = struct_mod
    ch.socket = socket
    ch.pid = 0
    ch.recv_timeout_s = float(recv_timeout_s)
    ch.deadline_scale = float(scale)
    return ch


def test_frame_deadline_scales_with_drained_slots():
    """deadline = base x max(1, scale): lockstep (scale 1.0) is
    byte-identical to the pre-resident contract, a deep drain multiplies
    the budget, and a sub-1 scale NEVER shrinks below the base."""
    ch = _mk_ring_shell(2.0, 1.0)
    assert ch._frame_deadline_s() == 2.0
    ch.deadline_scale = 4.0
    assert ch._frame_deadline_s() == 8.0
    ch.deadline_scale = 0.25          # drained 0 slots: clamp to base
    assert ch._frame_deadline_s() == 2.0


def test_stalled_peer_attributed_under_scaled_deadline():
    """A peer that sends nothing still raises DCNPeerStalledError — the
    resident mode scales the deadline, it never disables attribution.
    The error names the peer, and the wait really honors the scaled
    budget (scale 3 waits ~3x the base before attributing)."""
    a, b = socket.socketpair()
    try:
        a.settimeout(0.05)
        ch = _mk_ring_shell(0.2, 1.0)
        t0 = time.monotonic()
        with pytest.raises(DCNPeerStalledError, match="next stalled"):
            ch._recv_exact(a, 8, peer="next")
        base_wait = time.monotonic() - t0

        ch.deadline_scale = 3.0
        t0 = time.monotonic()
        with pytest.raises(DCNPeerStalledError, match="next stalled"):
            ch._recv_exact(a, 8, peer="next")
        scaled_wait = time.monotonic() - t0
        assert scaled_wait >= 0.55        # ~0.6s budget vs ~0.2s base
        assert scaled_wait > base_wait
    finally:
        a.close()
        b.close()
