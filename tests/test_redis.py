"""Redis connector: RESP2 wire client vs the in-repo MiniRedis server
over real TCP, the RedisSink command catalog, and pipeline integration.

Ref flink-streaming-connectors/flink-connector-redis: RedisSink.java
(invoke -> container dispatch), RedisCommand.java (the 8-command
catalog), RedisCommandDescription.java (additional-key validation).
"""

import numpy as np
import pytest

from flink_tpu.connectors.redis import (
    MiniRedis,
    RedisConnection,
    RedisError,
    RedisMapper,
    RedisSink,
)


@pytest.fixture
def server():
    s = MiniRedis()
    s.start()
    yield s
    s.stop()


# ------------------------------------------------------------------ wire
def test_resp_roundtrip_all_reply_types(server):
    c = RedisConnection("127.0.0.1", server.port)
    assert c.execute("PING") == "PONG"
    assert c.execute("ECHO", "hello\r\nworld") == "hello\r\nworld"
    assert c.execute("SET", "k", "v") == "OK"
    assert c.execute("GET", "k") == "v"            # bulk
    assert c.execute("GET", "absent") is None      # null bulk
    assert c.execute("LPUSH", "l", "a") == 1       # integer
    assert c.execute("LPUSH", "l", "b") == 2
    assert c.execute("LRANGE", "l", "0", "-1") == ["b", "a"]  # array
    with pytest.raises(RedisError, match="unknown command"):
        c.execute("NOPE")
    c.close()


def test_mapper_validates_catalog():
    with pytest.raises(ValueError, match="unknown redis command"):
        RedisMapper("GETSET", str, str)
    with pytest.raises(ValueError, match="additional_key"):
        RedisMapper("HSET", str, str)          # hash name missing
    with pytest.raises(ValueError, match="additional_key"):
        RedisMapper("ZADD", str, str)
    RedisMapper("ZADD", str, str, additional_key="board")   # ok


def test_sink_commands_land_per_data_type(server):
    recs = [("a", "1"), ("b", "2"), ("a", "3")]

    def run(mapper):
        sink = RedisSink("127.0.0.1", server.port, mapper)
        sink.open()
        sink.invoke_batch(recs)
        sink.close()

    run(RedisMapper("SET", lambda r: r[0], lambda r: r[1]))
    assert server.strings == {"a": "3", "b": "2"}   # last write wins

    run(RedisMapper("HSET", lambda r: r[0], lambda r: r[1],
                    additional_key="h"))
    assert server.hashes["h"] == {"a": "3", "b": "2"}

    run(RedisMapper("ZADD", lambda r: r[0], lambda r: r[1],
                    additional_key="z"))
    assert server.zsets["z"] == {"a": 3.0, "b": 2.0}

    run(RedisMapper("SADD", lambda r: r[0], lambda r: r[1]))
    assert server.sets == {"a": {"1", "3"}, "b": {"2"}}
    server.sets.clear()

    run(RedisMapper("RPUSH", lambda r: r[0], lambda r: r[1]))
    assert server.lists["a"] == ["1", "3"]

    run(RedisMapper("PUBLISH", lambda r: "chan", lambda r: r[1]))
    assert server.published["chan"] == ["1", "2", "3"]


def test_idempotent_commands_absorb_replay(server):
    """The reference's exactly-once-by-idempotence story: replaying a
    batch after a failure leaves SET/HSET/ZADD/SADD state identical."""
    recs = [("k1", "10"), ("k2", "20")]
    sink = RedisSink(
        "127.0.0.1", server.port,
        RedisMapper("HSET", lambda r: r[0], lambda r: r[1],
                    additional_key="agg"),
    )
    sink.open()
    sink.invoke_batch(recs)
    sink.invoke_batch(recs)          # replay
    sink.close()
    assert server.hashes["agg"] == {"k1": "10", "k2": "20"}


# -------------------------------------------------------------- pipeline
def test_windowed_aggregation_into_redis(server):
    """source -> keyBy -> tumbling sum -> RedisSink(HSET): per-key
    totals land in a Redis hash, exact."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.runtime.sources import GeneratorSource

    total, n_keys = 50_000, 500

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return (
            {"key": (idx * 2654435761) % n_keys,
             "value": np.ones(n, np.float32)},
            (idx // 20).astype(np.int64),    # ~5 windows over the run
        )

    from flink_tpu.core.time import TimeCharacteristic

    env = StreamExecutionEnvironment.get_execution_environment()
    # parallelism 2: same keyed routing paths, a quarter of the shard
    # compile cost (8-shard coverage lives in tests/test_exchange*.py)
    env.set_parallelism(2)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = RedisSink(
        "127.0.0.1", server.port,
        RedisMapper(
            "HSET",
            key_from=lambda r: f"{r.key}:{r.window_end_ms}",
            value_from=lambda r: f"{r.value:.0f}",
            additional_key="window-sums",
        ),
    )
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("redis-sink-job")

    landed = server.hashes["window-sums"]
    assert sum(float(v) for v in landed.values()) == float(total)
    # exact per-cell check against the scalar model
    exp = {}
    for i in range(total):
        cell = (f"{(i * 2654435761) % n_keys}:"
                f"{((i // 20) // 1000 + 1) * 1000}")
        exp[cell] = exp.get(cell, 0) + 1
    assert {k: int(float(v)) for k, v in landed.items()} == exp
