"""countWindow(N) semantics vs a scalar model: exact-N windows per key,
multiple fires within one batch, partial windows carried across batches."""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.runtime.sinks import CollectSink


def scalar_model(events, n):
    acc, cnt, widx = {}, {}, {}
    fires = []
    for k, v in events:
        acc[k] = acc.get(k, 0.0) + v
        cnt[k] = cnt.get(k, 0) + 1
        if cnt[k] == n:
            fires.append((k, widx.get(k, 0), acc[k]))
            widx[k] = widx.get(k, 0) + 1
            acc[k], cnt[k] = 0.0, 0
    return fires


def run(events, n, batch=32, parallelism=4):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_state_capacity(512)
    env.batch_size = batch
    sink = CollectSink()
    (
        env.from_collection(events)
        .key_by(lambda e: e[0])
        .count_window(n)
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    env.execute("count-window")
    return [(r.key, r.window_end_ms, r.value) for r in sink.results]


def test_count_window_matches_model(rng):
    events = [(int(rng.integers(0, 7)), float(rng.integers(1, 4)))
              for _ in range(600)]
    got = run(events, n=5)
    expect = scalar_model(events, 5)
    assert sorted(got) == sorted(expect)


def test_count_window_many_fires_single_batch(rng):
    # N=2 with batch 64: several windows per key per batch
    events = [(int(rng.integers(0, 3)), 1.0) for _ in range(128)]
    got = run(events, n=2, batch=64)
    expect = scalar_model(events, 2)
    assert sorted(got) == sorted(expect)
    assert all(v == 2.0 for _, _, v in got)


def test_count_window_partial_carry():
    # 7 elements, window of 3 -> two fires, one element carried (never fired)
    events = [("x", float(i)) for i in range(1, 8)]
    got = run(events, n=3, batch=2)
    assert got == [("x", 0, 6.0), ("x", 1, 15.0)]
