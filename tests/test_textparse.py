"""Native text tokenizer + columnar socket word source
(native/src/textparse.cpp; ref SocketWindowWordCount.java:76-79 — the
split/parse done once per batch in C++ instead of per line in Python).
"""

import socket
import threading

import numpy as np
import pytest

from flink_tpu.native import parse_ts_words


def _pyref(data: bytes):
    """Independent Python reference of the parser contract."""
    out = []
    consumed = 0
    pos = 0
    while True:
        eol = data.find(b"\n", pos)
        if eol < 0:
            break
        line = data[pos:eol]
        pos = eol + 1
        consumed = pos
        parts = line.split()
        if not parts:
            continue
        try:
            ts = int(parts[0])
        except ValueError:
            continue
        for w in parts[1:]:
            out.append((ts, w.decode()))
    return out, consumed


def test_parse_matches_python_reference_on_random_text():
    rng = np.random.default_rng(5)
    words = ["alpha", "beta", "gamma", "x", "longer-token", "Zz9"]
    lines = []
    for i in range(2000):
        n = int(rng.integers(0, 6))
        ws = [words[int(rng.integers(0, len(words)))] for _ in range(n)]
        sep = "  " if i % 7 == 0 else " "       # multi-space runs
        lines.append(f"{i * 3}{sep}" + sep.join(ws) + "\n")
    lines.insert(100, "\n")                     # empty line
    lines.insert(200, "notanumber word\n")      # malformed ts: skipped
    lines.insert(300, "-50 negative ts\n")
    data = "".join(lines).encode() + b"17 partial-tail"

    ts, ids, offs, lens, consumed = parse_ts_words(data)
    ref, ref_consumed = _pyref(data)
    assert consumed == ref_consumed
    got = [
        (int(t), data[int(o):int(o) + int(l)].decode())
        for t, o, l in zip(ts, offs, lens)
    ]
    assert got == ref
    # ids are stable hashes: equal tokens <=> equal ids (no collision
    # among this vocabulary)
    by_id = {}
    for (t, w), i in zip(got, ids.tolist()):
        assert by_id.setdefault(i, w) == w
    assert len(set(by_id.values())) == len(by_id)


def test_parse_respects_line_atomicity_and_tail():
    # tail without newline is not consumed
    ts, ids, offs, lens, consumed = parse_ts_words(b"1 a b\n2 c")
    assert ts.tolist() == [1, 1]
    assert consumed == len(b"1 a b\n")
    # empty input
    assert parse_ts_words(b"")[4] == 0


def test_socket_words_source_end_to_end():
    """source -> keyBy(token id) -> 5s windows -> counts equal the
    scalar model; word_of() maps ids back to strings."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from flink_tpu import StreamExecutionEnvironment
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CollectSink
    from flink_tpu.runtime.sources import SocketWordsSource
    from flink_tpu.runtime.watermarks import WatermarkStrategy

    words = ["to", "be", "or", "not", "that", "is", "the", "question"]
    rng = np.random.default_rng(11)
    n_lines, per_line = 3000, 6
    widx = rng.integers(0, len(words), n_lines * per_line)
    lines = []
    for i in range(n_lines):
        ws = widx[i * per_line:(i + 1) * per_line]
        lines.append(
            (f"{i * 4} " + " ".join(words[j] for j in ws) + "\n").encode()
        )
    payload = b"".join(lines)

    exp = {}
    for i in range(n_lines):
        pane_end = ((i * 4) // 1000 + 1) * 1000
        for j in widx[i * per_line:(i + 1) * per_line]:
            k = (words[j], pane_end)
            exp[k] = exp.get(k, 0) + 1

    srv = socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def feed():
        conn, _ = srv.accept()
        with conn:
            conn.sendall(payload)

    threading.Thread(target=feed, daemon=True).start()

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(4)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(4096)
    env.batch_size = 4096
    sink = CollectSink()
    src = SocketWordsSource("127.0.0.1", port)
    (
        env.add_source(src)
        .assign_timestamps_and_watermarks(
            lambda c: c["ts"],
            WatermarkStrategy.for_monotonous_timestamps(),
        )
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("socket-words")
    srv.close()

    got = {}
    for r in sink.results:
        w = src.word_of(int(r.key))
        assert w is not None
        got[(w, int(r.window_end_ms))] = (
            got.get((w, int(r.window_end_ms)), 0) + int(float(r.value))
        )
    assert got == exp

def test_parse_cap_is_line_atomic_and_reofferable():
    data = b"1 a b c\n2 d e\n3 f\n"
    # cap 4: first line (3 tokens) fits, second (2) would overflow
    ts, ids, offs, lens, consumed = parse_ts_words(data, cap=4)
    assert ts.tolist() == [1, 1, 1]
    assert consumed == len(b"1 a b c\n")
    # re-offer the remainder
    rest = data[consumed:]
    ts2, *_rest2, consumed2 = parse_ts_words(rest, cap=4)
    assert ts2.tolist() == [2, 2, 3]
    assert consumed2 == len(rest)
    # a single line wider than cap still returns whole (no wedge)
    wide = b"9 " + b" ".join(b"t%d" % i for i in range(50)) + b"\n"
    ts3, ids3, *_r, consumed3 = parse_ts_words(wide, cap=4)
    assert len(ts3) == 50 and consumed3 == len(wide)


def test_socket_words_source_respects_poll_cap():
    """poll(max_records) never returns more than one line's overshoot —
    the non-chunking keyed paths pad to exactly B lanes."""
    import socket as _socket
    import threading as _threading

    from flink_tpu.runtime.sources import SocketWordsSource

    payload = b"".join(
        (f"{i} " + " ".join(f"w{j}" for j in range(8)) + "\n").encode()
        for i in range(2000)
    )
    srv = _socket.create_server(("127.0.0.1", 0))
    port = srv.getsockname()[1]

    def feed():
        conn, _ = srv.accept()
        with conn:
            conn.sendall(payload)

    _threading.Thread(target=feed, daemon=True).start()
    src = SocketWordsSource("127.0.0.1", port)
    src.open()
    total = 0
    import time as _time
    deadline = _time.time() + 20
    while _time.time() < deadline:
        (cols, ts), done = src.poll(64)
        n = len(cols.get("key", ()))
        assert n <= 64, n           # cap holds (8-token lines divide 64)
        total += n
        if done:
            break
    src.close()
    srv.close()
    assert total == 2000 * 8


def test_socket_words_poll_cap_holds_for_oversized_line():
    """ADVICE r5: a single line wider than max_records tokens must
    split across polls (carried offset state) so the poll contract
    (<= max_records records per poll) actually holds."""
    from flink_tpu.runtime.sources import SocketWordsSource

    src = SocketWordsSource("unused", 0)
    words = [f"w{i}" for i in range(50)]
    src._buf = ("7 " + " ".join(words) + "\n").encode()
    src._eof = True

    chunks, done, polls = [], False, 0
    while not done:
        (cols, ts), done = src.poll(16)
        polls += 1
        assert len(cols["key"]) <= 16, "poll cap violated"
        chunks.append(cols)
        assert polls < 20
    ids = np.concatenate([c["key"] for c in chunks])
    assert len(ids) == 50
    # order, words, and timestamps all survive the split
    assert [src.word_of(int(i)) for i in ids] == words
    assert all((np.asarray(c["ts"]) == 7).all() for c in chunks)
