"""Elasticsearch REST connector vs the in-repo spec server (the Kafka
MiniBroker pattern): real HTTP bulk protocol, buffering, retry,
flush-on-checkpoint, deterministic-id idempotent replay.

Ref: flink-streaming-connectors/flink-connector-elasticsearch2/
ElasticsearchSink.java (BulkProcessor wrapping, flushOnCheckpoint)."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.elasticsearch import (
    ElasticsearchSink, MiniElasticsearch,
)


@pytest.fixture
def es():
    server = MiniElasticsearch()
    server.start()
    yield server
    server.stop()


def _sink(es, **kw):
    return ElasticsearchSink(
        "127.0.0.1", es.port,
        emitter=lambda e: {"index": "events", "id": e[0],
                           "source": {"k": e[0], "v": e[1]}},
        **kw,
    )


def test_bulk_indexing_and_search(es):
    sink = _sink(es, flush_max_actions=4)
    sink.open()
    sink.invoke_batch([(i, float(i)) for i in range(10)])
    sink.close()
    assert es.doc_count("events") == 10
    # the wire subset: doc get + search term query through real HTTP
    got = sink._request("GET", "/events/_doc/7")
    assert got["_source"] == {"k": 7, "v": 7.0}
    hits = sink._request("POST", "/events/_search",
                         b'{"query": {"term": {"k": 3}}}')
    assert hits["hits"]["total"] == 1
    assert hits["hits"]["hits"][0]["_source"]["v"] == 3.0


def test_buffering_flushes_at_max_actions(es):
    sink = _sink(es, flush_max_actions=5)
    sink.open()
    sink.invoke_batch([(i, 1.0) for i in range(4)])
    assert es.bulk_requests == 0          # buffered below the threshold
    sink.invoke_batch([(4, 1.0)])
    assert es.bulk_requests == 1 and es.doc_count("events") == 5
    sink.close()


def test_retry_on_429_backoff(es):
    sink = _sink(es, flush_max_actions=2, max_retries=4)
    sink.open()
    es.throttle(2)                         # next two bulks rejected
    sink.invoke_batch([(1, 1.0), (2, 2.0)])
    assert sink.stats["retries"] == 2
    assert es.doc_count("events") == 2     # delivered after backoff


def test_retry_exhaustion_raises(es):
    sink = _sink(es, flush_max_actions=1, max_retries=2)
    sink.open()
    es.throttle(10)
    with pytest.raises(ConnectionError, match="429"):
        sink.invoke_batch([(1, 1.0)])


def test_per_item_failure_goes_to_handler(es):
    failures = []
    sink = _sink(es, flush_max_actions=1,
                 failure_handler=lambda a, st, item: failures.append(
                     (a["id"], st)))
    sink.open()
    es.fail_ids([2])
    sink.invoke_batch([(1, 1.0)])
    sink.invoke_batch([(2, 2.0)])
    assert failures == [(2, 400)]
    assert es.doc_count("events") == 1

    # default handler raises
    strict = _sink(es, flush_max_actions=1)
    with pytest.raises(RuntimeError, match="status 400"):
        strict.invoke_batch([(2, 5.0)])


def test_flush_on_checkpoint(es):
    sink = _sink(es, flush_max_actions=1000)
    sink.open()
    sink.invoke_batch([(i, 1.0) for i in range(7)])
    assert es.doc_count("events") == 0     # still buffered
    sink.snapshot_state()                  # the checkpoint cut flushes
    assert es.doc_count("events") == 7


def test_deterministic_ids_make_replay_idempotent(es):
    """The reference's exactly-once recipe: deterministic _id means a
    replayed action overwrites instead of duplicating."""
    sink = _sink(es, flush_max_actions=1)
    sink.open()
    sink.invoke_batch([(1, 1.0), (2, 2.0)])
    sink.invoke_batch([(1, 10.0), (2, 2.0)])   # replay + update
    assert es.doc_count("events") == 2
    assert sink._request("GET", "/events/_doc/1")["_source"]["v"] == 10.0


def test_open_rejects_non_es_endpoint():
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
    import threading

    class NotES(BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):
            self.send_response(200)
            self.send_header("Content-Length", "2")
            self.end_headers()
            self.wfile.write(b"{}")

    srv = ThreadingHTTPServer(("127.0.0.1", 0), NotES)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        sink = ElasticsearchSink("127.0.0.1", srv.server_address[1],
                                 emitter=lambda e: [])
        with pytest.raises(ConnectionError, match="not an Elasticsearch"):
            sink.open()
    finally:
        srv.shutdown()
        srv.server_close()


def test_pipeline_end_to_end(es):
    """Streaming job -> windowed sums -> Elasticsearch, queried back over
    the wire."""
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sources import GeneratorSource

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_parallelism(2).set_max_parallelism(32)
    env.set_state_capacity(256)
    env.batch_size = 64

    def gen(off, n):
        idx = np.arange(off, off + n)
        return ({"key": idx % 5, "value": np.ones(n, np.float32)},
                (idx * 10).astype(np.int64))

    sink = ElasticsearchSink(
        "127.0.0.1", es.port,
        emitter=lambda r: {
            "index": "windows",
            "id": f"{r.key}-{r.window_end_ms}",   # deterministic id
            "source": {"key": int(r.key),
                       "window_end": int(r.window_end_ms),
                       "total": float(r.value)},
        },
        flush_max_actions=16,
    )
    (
        env.add_source(GeneratorSource(gen, total=1000))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("to-es")
    # 1000 records, ts = idx*10 -> 10 windows x 5 keys
    assert es.doc_count("windows") == 50
    hits = sink._request(
        "POST", "/windows/_search", b'{"query": {"term": {"key": 3}}}'
    )["hits"]["hits"]
    assert len(hits) == 10
    assert sum(h["_source"]["total"] for h in hits) == 200.0


def test_per_item_429_retried_not_failed(es):
    """HTTP 200 bulk responses can carry item-level 429s (a loaded real
    cluster): those items must be resent with backoff, not routed to the
    failure handler."""
    failures = []
    sink = _sink(es, flush_max_actions=3, max_retries=4,
                 failure_handler=lambda a, st, item: failures.append(a))
    sink.open()
    es.throttle_ids([2], times=2)
    sink.invoke_batch([(1, 1.0), (2, 2.0), (3, 3.0)])
    assert failures == []
    assert es.doc_count("events") == 3      # delivered after item retries
    assert sink.stats["retries"] == 2


def test_transport_failure_keeps_buffer(es):
    """A failed flush must NOT lose the buffered actions: they stay in
    the buffer for the next flush (at-least-once)."""
    sink = _sink(es, flush_max_actions=100, max_retries=0)
    sink.open()
    sink.invoke_batch([(1, 1.0), (2, 2.0)])
    es.throttle(1)
    with pytest.raises(ConnectionError):
        sink.flush()
    assert len(sink._buf) == 2              # restored, not dropped
    sink.flush()                             # throttle expired: delivers
    assert es.doc_count("events") == 2


def test_oversized_element_batch_splits_bulks(es):
    """One invoke_batch far beyond flush_max_actions must produce several
    bounded bulk requests, not one oversized body."""
    sink = _sink(es, flush_max_actions=10)
    sink.open()
    sink.invoke_batch([(i, 1.0) for i in range(35)])
    assert es.bulk_requests == 3            # 3 full bulks, 5 buffered
    assert len(sink._buf) == 5
    sink.close()
    assert es.doc_count("events") == 35


def test_poison_item_keeps_throttled_batchmates(es):
    """A response mixing a permanent failure (default handler raises)
    with per-item 429s must re-buffer the THROTTLED items — the poison
    item cannot drop its batch-mates."""
    sink = _sink(es, flush_max_actions=3, max_retries=3)
    sink.open()
    es.fail_ids([2])            # permanent 400 for id 2
    es.throttle_ids([3], times=10)   # transient 429 for id 3
    with pytest.raises(RuntimeError, match="status 400"):
        sink.invoke_batch([(1, 1.0), (2, 2.0), (3, 3.0)])
    # id 1 delivered; id 3 (throttled) back in the buffer, id 2 not
    assert es.doc_count("events") == 1
    assert [a["id"] for a in sink._buf] == [3]
    es.fail_ids([])
    es.throttle_ids([], times=0)
    sink.flush()
    assert es.doc_count("events") == 2      # id 3 delivered on retry


def test_truncated_bulk_response_rebuffers_everything(es, monkeypatch):
    """A response with fewer items than actions (broken proxy) must not
    silently drop the unmatched tail: the whole round re-buffers."""
    from flink_tpu.connectors.elasticsearch import BulkTransportError

    sink = _sink(es, flush_max_actions=100, max_retries=0)
    sink.open()
    real = sink._request_raw

    def truncating(method, path, body=b"", ctype=""):
        status, resp = real(method, path, body, ctype)
        if path == "/_bulk":
            import json as _json
            payload = _json.loads(resp)
            payload["errors"] = True
            payload["items"] = payload["items"][:1]
            resp = _json.dumps(payload).encode()
        return status, resp

    monkeypatch.setattr(sink, "_request_raw", truncating)
    sink.invoke_batch([(1, 1.0), (2, 2.0), (3, 3.0)])
    with pytest.raises(BulkTransportError, match="item count"):
        sink.flush()
    assert len(sink._buf) == 3              # nothing silently lost
