"""Sharded SPMD window step over the 8-device virtual mesh: every record
is owned by exactly one shard and global results match the scalar model."""

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.ops import window_kernels as wk
from flink_tpu.ops.hashing import hash64_host
from flink_tpu.parallel.mesh import MeshContext
from flink_tpu.runtime.step import (
    WindowStageSpec,
    build_window_step,
    init_sharded_state,
    watermark_vector,
)


def _split(keys):
    h = hash64_host(np.asarray(keys, dtype=np.int64))
    return (
        (h >> np.uint64(32)).astype(np.uint32),
        (h & np.uint64(0xFFFFFFFF)).astype(np.uint32),
    )


def test_eight_shard_window_sum_matches_model(rng):
    assert len(jax.devices()) >= 8
    ctx = MeshContext.create(n_shards=8, max_parallelism=128)
    spec = WindowStageSpec(
        win=wk.WindowSpec(10, 10, ring=8, fires_per_step=4),
        red=wk.ReduceSpec("sum", jnp.float32),
        capacity_per_shard=512,
    )
    step = build_window_step(ctx, spec)
    state = init_sharded_state(ctx, spec)

    expect = {}  # (window_end, key) -> sum
    fires = {}
    keymap = {}
    t = 0
    B = 256
    for s in range(8):
        keys = rng.integers(0, 100, B).astype(np.int64)
        ts = (t + rng.integers(0, 10, B)).astype(np.int32)
        vals = rng.integers(1, 5, B).astype(np.float32)
        for k, tt, v in zip(keys.tolist(), ts.tolist(), vals.tolist()):
            we = (tt // 10 + 1) * 10
            expect[(we, k)] = expect.get((we, k), 0.0) + v
        hi, lo = _split(keys)
        for k, h, l in zip(keys.tolist(), hi, lo):
            keymap[(int(h) << 32) | int(l)] = k
        t += 10
        wm = watermark_vector(ctx, t - 1 if s < 7 else 10**6)
        state, fr = step(
            state, jnp.asarray(hi), jnp.asarray(lo), jnp.asarray(ts),
            jnp.asarray(vals), jnp.ones(B, dtype=bool), wm,
        )
        mask = np.asarray(fr.mask)       # [S, F, C]
        values = np.asarray(fr.values)   # [S, F, C]
        ends = np.asarray(fr.window_end_ticks)  # [S, F]
        tkeys = np.asarray(state.table.keys)    # [S, C, 2]
        lanes = np.asarray(fr.lane_valid)
        for sh in range(mask.shape[0]):
            for f in np.nonzero(lanes[sh])[0]:
                for c in np.nonzero(mask[sh, f])[0]:
                    kid = (int(tkeys[sh, c, 0]) << 32) | int(tkeys[sh, c, 1])
                    key = keymap[kid]
                    entry = (int(ends[sh, f]), key)
                    assert entry not in fires, "duplicate fire across shards"
                    fires[entry] = float(values[sh, f, c])

    assert int(np.asarray(state.dropped_late).sum()) == 0
    assert int(np.asarray(state.dropped_capacity).sum()) == 0
    assert set(fires) == set(expect)
    for k in expect:
        assert abs(fires[k] - expect[k]) < 1e-3

    # state really is laid out over 8 devices
    assert len(state.acc.sharding.device_set) == 8
