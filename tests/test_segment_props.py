"""Property tests for the shared-sort seam (ISSUE 7 satellite).

``segment_sort`` / ``reduce_sorted`` / ``segmented_reduce_sorted`` now
feed FOUR consumers in the update kernel (the accumulator scatter, fire
eligibility, kg_dirty, kg_fill — window_kernels.update), so their
contract gets direct coverage against a NumPy oracle: dtypes (f32/i32),
all-invalid batches, segments with no valid lanes, and single-segment
batches — the shapes a streaming batch actually takes.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from flink_tpu.ops.segment import (
    argsort_ids,
    invert_permutation,
    reduce_sorted,
    segment_sort,
    segmented_reduce_sorted,
    sort_values,
)

BIG = 2**31 - 1


def _oracle(ids, vals, valid, combine, neutral):
    """Per-segment reduction the slow way."""
    out = {}
    for i, v, ok in zip(ids.tolist(), vals.tolist(), valid.tolist()):
        if not ok:
            continue
        out[i] = combine(out[i], v) if i in out else v
    return out


def _reduced_by_segment(ids, valid, values, combine, neutral):
    """Run the shared sort + segmented reduce; return {seg_id: value}
    from the representative lanes."""
    order, ids_s, valid_s, seg_start, rep_mask = segment_sort(
        jnp.asarray(ids), jnp.asarray(valid)
    )
    red = reduce_sorted(order, valid_s, seg_start, jnp.asarray(values),
                        combine, neutral)
    ids_s, rep_mask, red = map(np.asarray, (ids_s, rep_mask, red))
    return {
        int(i): r for i, r in zip(ids_s[rep_mask], red[rep_mask])
    }


CASES = [
    # (dtype, combine, neutral, value sampler)
    (np.float32, lambda a, b: a + b, np.float32(0),
     lambda rng, n: rng.normal(size=n).astype(np.float32)),
    (np.int32, lambda a, b: a + b, np.int32(0),
     lambda rng, n: rng.integers(-50, 50, n).astype(np.int32)),
    (np.float32, np.minimum, np.float32(np.finfo(np.float32).max),
     lambda rng, n: rng.normal(size=n).astype(np.float32)),
    (np.int32, np.maximum, np.int32(np.iinfo(np.int32).min),
     lambda rng, n: rng.integers(-1000, 1000, n).astype(np.int32)),
]


@pytest.mark.parametrize("case", range(len(CASES)))
def test_segment_sort_reduce_matches_numpy(rng, case):
    dtype, combine, neutral, sample = CASES[case]
    B = 384
    ids = rng.integers(0, 37, B).astype(np.int32)
    vals = sample(rng, B)
    valid = rng.random(B) < 0.8

    got = _reduced_by_segment(ids, valid, vals, combine, neutral)
    expect = _oracle(ids, vals, valid, combine, neutral)
    assert set(got) == set(int(k) for k in expect)
    for k, v in expect.items():
        if dtype == np.float32:
            assert abs(got[int(k)] - float(v)) < 1e-3 * max(1, abs(v))
        else:
            assert got[int(k)] == v   # integer adds/extremes are exact


def test_segment_sort_invariants(rng):
    """order is a permutation; invalid lanes sort to the end with the
    INT32_MAX sentinel; exactly one representative per valid segment."""
    B = 256
    ids = rng.integers(0, 20, B).astype(np.int32)
    valid = rng.random(B) < 0.7
    order, ids_s, valid_s, seg_start, rep_mask = map(
        np.asarray,
        segment_sort(jnp.asarray(ids), jnp.asarray(valid)),
    )
    assert sorted(order.tolist()) == list(range(B))
    assert (np.diff(ids_s) >= 0).all()           # sorted ascending
    n_valid = int(valid.sum())
    assert (ids_s[:n_valid] != BIG).all() or n_valid == 0
    assert (ids_s[n_valid:] == BIG).all()
    assert not rep_mask[ids_s == BIG].any()      # sentinels never represent
    assert int(rep_mask.sum()) == len(set(ids[valid].tolist()))


def test_all_invalid_batch_has_no_representatives(rng):
    B = 64
    ids = rng.integers(0, 8, B).astype(np.int32)
    valid = np.zeros(B, bool)
    _o, ids_s, valid_s, _s, rep_mask = map(
        np.asarray, segment_sort(jnp.asarray(ids), jnp.asarray(valid))
    )
    assert (ids_s == BIG).all()
    assert not valid_s.any()
    assert not rep_mask.any()
    got = _reduced_by_segment(ids, valid, np.ones(B, np.float32),
                              lambda a, b: a + b, np.float32(0))
    assert got == {}


def test_single_segment_batch_reduces_to_one_rep(rng):
    B = 128
    ids = np.full(B, 7, np.int32)
    vals = rng.integers(1, 5, B).astype(np.int32)
    valid = np.ones(B, bool)
    got = _reduced_by_segment(ids, valid, vals, lambda a, b: a + b,
                              np.int32(0))
    assert got == {7: int(vals.sum())}


def test_segment_with_no_valid_lanes_is_absent(rng):
    """A segment id present only on invalid lanes must not produce a
    representative (its neutral-substituted lanes sort to the end)."""
    ids = np.array([1, 1, 2, 2, 3], np.int32)
    valid = np.array([True, True, False, False, True])
    vals = np.array([10, 20, 99, 99, 5], np.float32)
    got = _reduced_by_segment(ids, valid, vals, lambda a, b: a + b,
                              np.float32(0))
    assert got == {1: 30.0, 3: 5.0}


def test_segmented_reduce_sorted_prefix_semantics():
    """The last lane of each run holds the full reduction; earlier lanes
    hold prefixes (the flagged-scan contract reduce_sorted builds on)."""
    vals = jnp.asarray(np.array([1, 2, 3, 10, 20], np.float32))
    seg_start = jnp.asarray(np.array([True, False, False, True, False]))
    out = np.asarray(
        segmented_reduce_sorted(vals, seg_start, lambda a, b: a + b)
    )
    assert out.tolist() == [1.0, 3.0, 6.0, 10.0, 30.0]


def test_reduce_sorted_int32_counts_are_exact(rng):
    """The kg_fill consumer reduces int32 ones — per-segment lane counts
    must be exact for any batch."""
    B = 300
    ids = rng.integers(0, 11, B).astype(np.int32)
    valid = rng.random(B) < 0.6
    got = _reduced_by_segment(ids, valid, np.ones(B, np.int32),
                              lambda a, b: a + b, np.int32(0))
    expect = {}
    for i, ok in zip(ids.tolist(), valid.tolist()):
        if ok:
            expect[i] = expect.get(i, 0) + 1
    assert got == expect


def test_sort_wrappers(rng):
    """The segment.py sort wrappers every other ops/ kernel must use
    (tools/check_segment_sort_seam.py)."""
    x = rng.integers(0, 100, 64).astype(np.int32)
    assert np.asarray(sort_values(jnp.asarray(x))).tolist() == \
        sorted(x.tolist())
    order = np.asarray(argsort_ids(jnp.asarray(x)))
    assert (x[order] == np.sort(x)).all()
    inv = np.asarray(invert_permutation(jnp.asarray(order)))
    assert (inv[order] == np.arange(64)).all()
    assert (np.asarray(argsort_ids(jnp.asarray(x), stable=True))
            == np.argsort(x, kind="stable")).all()
