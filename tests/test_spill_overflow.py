"""Spill-tier overflow: state beyond the device table's capacity degrades
to the host SpillStore instead of failing the job (VERDICT item 7; ref
structural sibling RocksDBKeyedStateBackend.java:82).

Mechanism under test (ops/window_kernels.py overflow ring +
runtime/executor.py pane stores + compaction):
  * records whose key finds no table slot append to the device overflow
    ring; the host drains the ring into per-pane native SpillStores at
    fire boundaries and compacts the table;
  * window emissions merge spill contributions (split keys combine);
  * checkpoints fold spill contents into the logical snapshot entries.
"""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource


def _run_window_sum(n_keys, capacity, events_per_key=4, window_ms=1000,
                    batch=256, checkpoint_dir=None, ovf_ring=None):
    """keyed tumbling-window count-sum with keys >> capacity."""
    opts = {"keys.reverse-map": True}
    if ovf_ring is not None:       # None = auto-sized ring
        opts["state.backend.overflow-ring"] = ovf_ring
    cfg = Configuration(opts)
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(capacity)
    env.batch_size = batch
    if checkpoint_dir:
        env.enable_checkpointing(4, checkpoint_dir)

    total = n_keys * events_per_key

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = idx % n_keys
        # all events of one window pane first, then the next window
        ts = (idx * 2 * window_ms) // total
        return {"key": keys, "value": np.ones(n, np.float32)}, ts

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(window_ms)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("spill-overflow")
    return job, sink


def _expected(n_keys, events_per_key, window_ms):
    """Scalar model of the generator stream."""
    total = n_keys * events_per_key
    state = {}
    for i in range(total):
        k = i % n_keys
        pane = ((i * 2 * window_ms) // total) // window_ms
        sk = (k, pane)
        state[sk] = state.get(sk, 0.0) + 1.0
    return state


def test_2x_capacity_keys_stream_correctly():
    # 512 distinct keys through a 256-slot table: >=half the keys overflow
    n_keys, capacity = 512, 256
    job, sink = _run_window_sum(n_keys, capacity)
    assert job.metrics.dropped_capacity == 0
    assert job.metrics.dropped_late == 0
    got = {}
    for r in sink.results:
        pane = (r.window_end_ms // 1000) - 1
        got[(r.key, pane)] = got.get((r.key, pane), 0.0) + r.value
    exp = _expected(n_keys, 4, 1000)
    assert got == exp


def test_4x_capacity_keys_stream_correctly():
    n_keys, capacity = 1024, 256
    job, sink = _run_window_sum(n_keys, capacity, events_per_key=3)
    assert job.metrics.dropped_capacity == 0
    total_emitted = sum(r.value for r in sink.results)
    assert total_emitted == n_keys * 3


def test_overflow_ring_exhaustion_is_counted_not_silent():
    # a tiny ring that cannot absorb the overflow between boundaries:
    # records are genuinely lost and the job must SAY so
    n_keys, capacity = 2048, 64
    cfg_dir = None
    job = None
    with pytest.raises(RuntimeError, match="over capacity"):
        job, sink = _run_window_sum(
            n_keys, capacity, events_per_key=2, ovf_ring=16
        )


def test_key_churn_compaction_reuses_slots():
    # sequential windows each with a DISTINCT key population of exactly
    # table capacity: compaction at boundaries must recycle dead slots so
    # each window's keys fit (with room in the ring for stragglers)
    capacity = 256
    windows = 4
    per_window = capacity  # fills the table every window
    total = windows * per_window

    cfg = Configuration({"keys.reverse-map": True})
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(capacity)
    env.batch_size = 128

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        w = idx // per_window
        keys = w * per_window + (idx % per_window)   # unique per window
        ts = w * 1000 + (idx % per_window) % 999
        return {"key": keys, "value": np.ones(n, np.float32)}, ts

    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("churn-compaction")
    assert job.metrics.dropped_capacity == 0
    assert sum(r.value for r in sink.results) == total
    assert len(sink.results) == total  # every (key, window) exactly once


def test_checkpoint_restore_with_active_spill(tmp_path):
    """Kill-and-restore mid-stream while spill holds state: the snapshot
    folds spill contents into logical entries; restore rebuilds both
    tiers and exactly-once sums survive."""
    from flink_tpu.runtime.sources import GeneratorSource

    n_keys, capacity = 512, 256
    window_ms = 1000
    events_per_key = 4
    total = n_keys * events_per_key

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = idx % n_keys
        ts = (idx * 2 * window_ms) // total
        return {"key": keys, "value": np.ones(n, np.float32)}, ts

    class FailingSink(CollectSink):
        def __init__(self, fail_after):
            super().__init__()
            self.fail_after = fail_after
            self.tripped = False

        def invoke_batch(self, elements):
            super().invoke_batch(elements)
            if not self.tripped and len(self.results) >= self.fail_after:
                self.tripped = True
                raise RuntimeError("induced sink failure")

    cfg = Configuration({
        "keys.reverse-map": True,
        "restart-strategy": "fixed-delay",
        "restart-strategy.fixed-delay.attempts": 3,
        "restart-strategy.fixed-delay.delay": 0,
    })
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(capacity)
    env.batch_size = 256
    env.enable_checkpointing(2, str(tmp_path / "chk"))

    sink = FailingSink(fail_after=n_keys // 2)
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(window_ms)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("spill-ckpt-restore")
    assert job.metrics.restarts >= 1
    got = {}
    for r in sink.results:
        pane = (r.window_end_ms // window_ms) - 1
        # restart may re-emit a window fired between checkpoint and crash;
        # last write wins (the values must match the scalar model)
        got[(r.key, pane)] = r.value
    exp = _expected(n_keys, events_per_key, window_ms)
    assert got == exp


# ------------------------------------- checksummed spill dumps (ISSUE 18)

def _filled_store(width=3, n=64):
    from flink_tpu.native import SpillStore

    st = SpillStore(width=width, initial_capacity=16)
    for i in range(n):
        st.put(i * 2654435761 % (1 << 63),
               np.arange(width, dtype=np.float32) + i)
    return st


def test_spill_dump_round_trips_and_detects_corruption(tmp_path):
    """save() writes a checksummed dump; load() of a byte-flipped or
    truncated file raises OSError instead of rebuilding bad state —
    the caller falls back to replay, never restores silently-wrong
    accumulators."""
    from flink_tpu.native import SpillStore

    st = _filled_store()
    path = str(tmp_path / "spill.bin")
    st.save(path)
    keys, vals = st.dump()
    back = SpillStore.load(path)
    bk, bv = back.dump()
    assert sorted(bk.tolist()) == sorted(keys.tolist())
    assert np.isclose(sorted(bv.sum(axis=1)), sorted(vals.sum(axis=1))
                      ).all()

    raw = bytearray(open(path, "rb").read())
    # flip a byte inside the value payload: crc mismatch
    flipped = bytearray(raw)
    flipped[len(flipped) - 5] ^= 0x40
    (tmp_path / "flip.bin").write_bytes(bytes(flipped))
    with pytest.raises(OSError, match="checksum|corrupt"):
        SpillStore.load(str(tmp_path / "flip.bin"))
    # torn write: truncated payload
    (tmp_path / "torn.bin").write_bytes(bytes(raw[:len(raw) // 2]))
    with pytest.raises(OSError):
        SpillStore.load(str(tmp_path / "torn.bin"))
    # wrong magic (pre-checksum format / foreign file)
    other = bytearray(raw)
    other[:4] = b"XXXX"
    (tmp_path / "magic.bin").write_bytes(bytes(other))
    with pytest.raises(OSError):
        SpillStore.load(str(tmp_path / "magic.bin"))


def test_spill_read_fault_point_surfaces_to_caller(tmp_path):
    """The ``ckpt.spill.read`` seam fires before the dump is read: an
    injected I/O failure surfaces as the real OSError the fallback
    branch under test would see in production."""
    from flink_tpu.native import SpillStore
    from flink_tpu.testing import faults
    from flink_tpu.testing.faults import FaultInjector, FaultRule

    st = _filled_store(n=8)
    path = str(tmp_path / "spill.bin")
    st.save(path)
    inj = FaultInjector([
        FaultRule("ckpt.spill.read", exc=OSError("injected read")),
    ])
    with faults.active(inj):
        with pytest.raises(OSError, match="injected read"):
            SpillStore.load(path)
    assert inj.fired_at("ckpt.spill.read")
    # uninstalled: the same dump loads clean (the hook is free)
    assert SpillStore.load(path).dump()[0].size == 8
