"""Checkpoint/restore/rescale + failure recovery — the analogs of the
reference's EventTimeWindowCheckpointingITCase, RescalingITCase and
StateCheckpointedITCase (SURVEY §4), plus the async-incremental
subsystem (flink_tpu/checkpointing): manifest chains, retention GC, and
materializer fault injection."""

import json
import os

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    ts = (idx // 50) * 1000
    return cols, ts


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None,
              mode=None, async_=None, compact_every=None):
    cfg = Configuration()
    if restart:
        cfg.set("restart-strategy", "fixed-delay")
        cfg.set("restart-strategy.fixed-delay.attempts", restart)
    if mode is not None:
        cfg.set("checkpoint.mode", mode)
    if async_ is not None:
        cfg.set("checkpoint.async", async_)
    if compact_every is not None:
        cfg.set("checkpoint.compact-every", compact_every)
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, source=None, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("ckpt-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


class FailingSource(GeneratorSource):
    """Throws once when crossing `fail_at` (ref failing-map ITCase pattern)."""

    def __init__(self, fn, total, fail_at):
        super().__init__(fn, total)
        self.fail_at = fail_at
        self.failed = False

    def poll(self, max_records):
        out = super().poll(max_records)
        if not self.failed and self.offset >= self.fail_at:
            self.failed = True
            raise RuntimeError("injected failure")
        return out


@pytest.mark.parametrize("mode,async_", [
    ("full", False),            # the classic sync-full path
    ("full", True),             # full snapshots, background write
    ("incremental", True),      # changelog deltas + manifest chain
])
def test_failure_recovery_exactly_once_state(tmp_path, mode, async_):
    total = 4096
    env = build_env(4, tmp_path / "chk", interval=2, restart=3,
                    mode=mode, async_=async_)
    src = FailingSource(gen, total, fail_at=total // 2)
    got = run_job(env, total, source=src)
    assert env.last_job.metrics.restarts == 1
    assert got == expected(total)


def test_failure_without_checkpoint_raises(tmp_path):
    total = 2048
    env = build_env(2)  # no checkpointing, no restart strategy
    src = FailingSource(gen, total, fail_at=512)
    with pytest.raises(RuntimeError, match="injected failure"):
        run_job(env, total, source=src)


def test_rescale_up_and_down(tmp_path):
    """savepoint at p=2, resume at p=4 and p=1 (RescalingITCase analog).

    Windows fired before the checkpoint live in phase 1's output; the
    restored job re-fires everything after the checkpoint cut (including a
    corrected version of the final window phase 1 flushed early). The merged
    view (phase 2 overriding phase 1) must equal the single-run truth.
    """
    total, half = 8192, 4096
    # phase 1: consume first half at p=2, checkpointing every cycle
    env1 = build_env(2, tmp_path / "chk", interval=1)
    got1 = run_job(env1, half)
    # phase 2: restore at different parallelism, consume the rest
    for p in (4, 1):
        env2 = build_env(p)
        got2 = run_job(
            env2, total, restore_from=str(tmp_path / "chk"),
        )
        merged = {**got1, **got2}
        assert merged == expected(total), f"rescale to p={p} diverged"
        # the restored run must carry real state across the cut: at least
        # one window overlapping the cut point must come out corrected
        assert any(got1.get(k) != v for k, v in got2.items())


def test_restore_preserves_string_keys(tmp_path):
    """codec reverse map survives the checkpoint (keys decode after restore)."""
    events = [(t * 1000, f"key-{t % 5}") for t in range(40)]
    env = build_env(2, tmp_path / "chk", interval=1)
    sink = CollectSink()
    (
        env.from_collection(events[:20])
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(WINDOW)
        .count()
        .add_sink(sink)
    )
    env.execute("phase1")

    env2 = build_env(2)
    sink2 = CollectSink()
    (
        env2.from_collection(events)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(WINDOW)
        .count()
        .add_sink(sink2)
    )
    env2.execute("phase2", restore_from=str(tmp_path / "chk"))
    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    got.update({(r.key, r.window_end_ms): r.value for r in sink2.results})
    expect = {}
    for t, k in events:
        we = (t // WINDOW + 1) * WINDOW
        expect[(k, we)] = expect.get((k, we), 0) + 1.0
    assert got == expect
    assert all(isinstance(k, str) for k, _ in got)


# ---------------------------------------------------------------------------
# Async-incremental subsystem (flink_tpu/checkpointing)
# ---------------------------------------------------------------------------

def _manifest(ckpt_dir, cid):
    p = os.path.join(str(ckpt_dir), f"chk-{cid}", "manifest.json")
    if not os.path.exists(p):
        return None
    with open(p) as f:
        return json.load(f)


def _latest_cid(ckpt_dir):
    from flink_tpu.runtime.checkpoint import CheckpointStorage

    return CheckpointStorage(str(ckpt_dir)).latest()


@pytest.mark.parametrize("p2", [2, 4, 1])
def test_incremental_chain_restore_equals_full_restore(tmp_path, p2):
    """THE equivalence criterion: restoring a keyed windowed-aggregation
    job from an async-incremental manifest chain (base + >= 2 deltas)
    yields byte-identical sink results to restoring from a sync full
    snapshot at the same cut — including across a rescale (p=2 -> 4/1)."""
    total, half = 8192, 4096

    # phase 1 twice over the identical stream: sync-full vs async-
    # incremental. Checkpoint interval is counted in steps, so the two
    # runs cut at identical offsets.
    env_f = build_env(2, tmp_path / "full", interval=1,
                      mode="full", async_=False)
    got1_f = run_job(env_f, half)
    env_i = build_env(2, tmp_path / "incr", interval=1,
                      mode="incremental", async_=True, compact_every=100)
    got1_i = run_job(env_i, half)
    assert got1_f == got1_i

    # the incremental run must actually have produced a chain with a
    # full base + at least 2 deltas, and exercised the async phase
    cid = _latest_cid(tmp_path / "incr")
    m = _manifest(tmp_path / "incr", cid)
    assert m is not None and m["kind"] == "delta"
    assert len(m["chain"]) >= 3, m
    base = _manifest(tmp_path / "incr", m["chain"][0])
    assert base is not None and base["kind"] == "full"
    stats = env_i.last_job.metrics.checkpoint_stats
    deltas = [s for s in stats if s["kind"] == "delta"]
    assert deltas, "no delta checkpoints recorded"
    # presence-of-async-phase (not a timing threshold: CPU mode)
    assert all(s["async_ms"] > 0 for s in deltas)
    # the sync stall is a strict sub-phase of the whole checkpoint
    # (epsilon covers independent 2-dp rounding of the two fields)
    assert all(s["sync_ms"] <= s["duration_ms"] + 0.05 for s in deltas)
    full_stats = [s for s in env_f.last_job.metrics.checkpoint_stats]
    assert all(s["kind"] == "full" and s["async_ms"] == 0.0
               for s in full_stats)

    # phase 2: restore each at parallelism p2 and finish the stream
    got2_f = run_job(build_env(p2), total,
                     restore_from=str(tmp_path / "full"))
    got2_i = run_job(build_env(p2), total,
                     restore_from=str(tmp_path / "incr"))
    assert got2_i == got2_f, "chain restore diverged from full restore"
    assert {**got1_i, **got2_i} == expected(total)


def test_delta_coverage_is_partial_for_skewed_updates(tmp_path):
    """A delta only covers the key groups that changed: a stream that
    updates ONE key between checkpoints must produce deltas whose
    coverage (and entries) are a small subset of the key space."""
    from flink_tpu.runtime.checkpoint import CheckpointStorage

    def gen_one_key(offset, n):
        idx = np.arange(offset, offset + n)
        cols = {
            # first 512 records spray all keys; later records hit key 7
            "key": np.where(idx < 512, (idx * 48271) % N_KEYS, 7),
            "value": np.ones(n, np.float32),
        }
        return cols, (idx // 50) * 1000

    env = build_env(2, tmp_path / "chk", interval=1,
                    mode="incremental", async_=True, compact_every=100)
    run_job(env, 4096, source=GeneratorSource(gen_one_key, total=4096))
    st = CheckpointStorage(str(tmp_path / "chk"))
    cid = st.latest()
    m = _manifest(tmp_path / "chk", cid)
    assert m["kind"] == "delta"
    assert m["coverage"] != "all"
    assert 1 <= len(m["coverage"]) < 8, m["coverage"]
    # and the delta's entries are only that coverage's keys
    entries, _s, _o, _a = st.read_raw(cid)
    assert 0 < len(entries["key_hi"]) < N_KEYS


def test_chain_compaction_writes_fresh_full_base(tmp_path):
    env = build_env(2, tmp_path / "chk", interval=1,
                    mode="incremental", async_=True, compact_every=3)
    run_job(env, 8192)
    st_dir = tmp_path / "chk"
    cids = sorted(
        int(d[4:]) for d in os.listdir(st_dir) if d.startswith("chk-")
    )
    kinds = {c: _manifest(st_dir, c)["kind"] for c in cids}
    assert "full" in kinds.values() and "delta" in kinds.values()
    # every chain is at most compact-every long
    for c in cids:
        assert len(_manifest(st_dir, c)["chain"]) <= 3


def test_manifest_gc_never_collects_referenced_chain(tmp_path):
    """CheckpointStorage._gc with retain=2: a base/delta referenced by a
    retained manifest survives even when plain retention would drop it;
    once a new full base supersedes the chain, the old one collects."""
    from flink_tpu.checkpointing.manifest import build_manifest
    from flink_tpu.runtime.checkpoint import CheckpointStorage

    st = CheckpointStorage(str(tmp_path / "chk"), retain=2)
    ent = {
        "key_hi": np.zeros(0, np.uint32), "key_lo": np.zeros(0, np.uint32),
        "pane": np.zeros(0, np.int32), "value": np.zeros(0, np.float32),
        "fresh": np.zeros(0, bool),
    }
    scal = {"watermark": 0, "fired_through": 0, "max_pane": 0,
            "min_pane": 0, "dropped_late": 0, "dropped_capacity": 0}

    def write(cid, kind, chain, cov):
        st.write(cid, ent, scal, None, {}, manifest=build_manifest(
            cid, kind, chain, cov, 128))

    write(1, "full", [1], "all")
    write(2, "delta", [1, 2], [3])
    write(3, "delta", [1, 2, 3], [4])
    write(4, "delta", [1, 2, 3, 4], [5])
    # retain=2 would keep {3, 4}; the manifest closure keeps 1 and 2 too
    assert st.list_checkpoints() == [1, 2, 3, 4]
    # a fresh full base supersedes the chain: old members now collect
    write(5, "full", [5], "all")
    write(6, "delta", [5, 6], [7])
    assert st.list_checkpoints() == [5, 6]
    # and the retained chain still restores
    entries, scalars, _o, _a = st.read(6)
    assert scalars["watermark"] == 0


def test_crash_during_async_write_leaves_recoverable_checkpoint(tmp_path):
    """Materializer fault injection: a failing async write (simulating a
    crash mid-materialization) must leave the PREVIOUS checkpoint fully
    recoverable, surface the failure at the next barrier, and never
    publish a partial directory."""
    from flink_tpu.checkpointing.materializer import (
        Materializer, MaterializerError,
    )
    from flink_tpu.runtime.checkpoint import CheckpointStorage

    st = CheckpointStorage(str(tmp_path / "chk"), retain=5)
    mat = Materializer(slots=2)
    ent = {
        "key_hi": np.asarray([1], np.uint32),
        "key_lo": np.asarray([2], np.uint32),
        "pane": np.asarray([0], np.int32),
        "value": np.asarray([3.0], np.float32),
        "fresh": np.asarray([False]),
    }
    scal = {"watermark": 5, "fired_through": 0, "max_pane": 0,
            "min_pane": 0, "dropped_late": 0, "dropped_capacity": 0}
    mat.submit("chk-1", lambda: st.write(1, ent, scal, None, {}))

    def crash():
        # partial write then death: only the .tmp staging dir exists
        tmp = st.path(2) + ".tmp"
        os.makedirs(tmp, exist_ok=True)
        with open(os.path.join(tmp, "entries.npz"), "wb") as f:
            f.write(b"partial")
        raise OSError("injected materializer crash")

    mat.submit("chk-2", crash)
    with pytest.raises(MaterializerError, match="chk-2"):
        mat.flush()
    # previous checkpoint untouched and recoverable; no partial publish
    assert st.latest() == 1
    entries, scalars, _o, _a = st.read(1)
    assert scalars["watermark"] == 5 and len(entries["key_hi"]) == 1
    # the queue is poisoned-then-cleared: later submits work again
    mat.submit("chk-3", lambda: st.write(3, ent, scal, None, {}))
    mat.flush()
    assert st.latest() == 3
    mat.close()


def test_async_write_failure_triggers_restart_recovery(tmp_path, monkeypatch):
    """End-to-end fault injection ON THE MATERIALIZER THREAD: one
    checkpoint's directory write raises; the failure surfaces at the next
    barrier, the job restarts from the last durable checkpoint, and the
    final results are still exactly-once."""
    from flink_tpu.runtime import checkpoint as ckpt

    orig = ckpt.CheckpointStorage.write
    fired = {"done": False}

    def flaky(self, cid, *a, **k):
        if cid == 3 and not fired["done"]:
            fired["done"] = True
            raise OSError("injected async write failure")
        return orig(self, cid, *a, **k)

    monkeypatch.setattr(ckpt.CheckpointStorage, "write", flaky)
    total = 4096
    env = build_env(2, tmp_path / "chk", interval=2, restart=3,
                    mode="incremental", async_=True)
    got = run_job(env, total)
    assert fired["done"]
    assert env.last_job.metrics.restarts == 1
    assert got == expected(total)


def test_incremental_rejects_allowed_lateness(tmp_path):
    env = build_env(2, tmp_path / "chk", interval=1, mode="incremental")
    sink = CollectSink()
    (
        env.add_source(GeneratorSource(gen, total=512))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .allowed_lateness(5000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    with pytest.raises(ValueError, match="allowed-lateness"):
        env.execute("lateness-job")
