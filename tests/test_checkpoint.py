"""Checkpoint/restore/rescale + failure recovery — the analogs of the
reference's EventTimeWindowCheckpointingITCase, RescalingITCase and
StateCheckpointedITCase (SURVEY §4)."""

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.runtime.sources import GeneratorSource

N_KEYS = 200
WINDOW = 10_000


def gen(offset, n):
    idx = np.arange(offset, offset + n)
    cols = {
        "key": (idx * 48271) % N_KEYS,
        "value": np.ones(n, np.float32),
    }
    ts = (idx // 50) * 1000
    return cols, ts


def expected(total):
    idx = np.arange(total)
    keys = (idx * 48271) % N_KEYS
    ts = (idx // 50) * 1000
    out = {}
    for k, t in zip(keys.tolist(), ts.tolist()):
        we = (t // WINDOW + 1) * WINDOW
        out[(k, we)] = out.get((k, we), 0) + 1.0
    return out


def build_env(parallelism, ckpt_dir=None, interval=0, restart=None):
    cfg = Configuration()
    if restart:
        cfg.set("restart-strategy", "fixed-delay")
        cfg.set("restart-strategy.fixed-delay.attempts", restart)
    env = StreamExecutionEnvironment(cfg)
    env.set_parallelism(parallelism).set_max_parallelism(128)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1024)
    env.batch_size = 256
    if ckpt_dir:
        env.enable_checkpointing(interval, str(ckpt_dir))
    return env


def run_job(env, total, source=None, restore_from=None):
    sink = CollectSink()
    (
        env.add_source(source or GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(WINDOW)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("ckpt-job", restore_from=restore_from)
    return {(r.key, r.window_end_ms): r.value for r in sink.results}


class FailingSource(GeneratorSource):
    """Throws once when crossing `fail_at` (ref failing-map ITCase pattern)."""

    def __init__(self, fn, total, fail_at):
        super().__init__(fn, total)
        self.fail_at = fail_at
        self.failed = False

    def poll(self, max_records):
        out = super().poll(max_records)
        if not self.failed and self.offset >= self.fail_at:
            self.failed = True
            raise RuntimeError("injected failure")
        return out


def test_failure_recovery_exactly_once_state(tmp_path):
    total = 4096
    env = build_env(4, tmp_path / "chk", interval=2, restart=3)
    src = FailingSource(gen, total, fail_at=total // 2)
    got = run_job(env, total, source=src)
    assert env.last_job.metrics.restarts == 1
    assert got == expected(total)


def test_failure_without_checkpoint_raises(tmp_path):
    total = 2048
    env = build_env(2)  # no checkpointing, no restart strategy
    src = FailingSource(gen, total, fail_at=512)
    with pytest.raises(RuntimeError, match="injected failure"):
        run_job(env, total, source=src)


def test_rescale_up_and_down(tmp_path):
    """savepoint at p=2, resume at p=4 and p=1 (RescalingITCase analog).

    Windows fired before the checkpoint live in phase 1's output; the
    restored job re-fires everything after the checkpoint cut (including a
    corrected version of the final window phase 1 flushed early). The merged
    view (phase 2 overriding phase 1) must equal the single-run truth.
    """
    total, half = 8192, 4096
    # phase 1: consume first half at p=2, checkpointing every cycle
    env1 = build_env(2, tmp_path / "chk", interval=1)
    got1 = run_job(env1, half)
    # phase 2: restore at different parallelism, consume the rest
    for p in (4, 1):
        env2 = build_env(p)
        got2 = run_job(
            env2, total, restore_from=str(tmp_path / "chk"),
        )
        merged = {**got1, **got2}
        assert merged == expected(total), f"rescale to p={p} diverged"
        # the restored run must carry real state across the cut: at least
        # one window overlapping the cut point must come out corrected
        assert any(got1.get(k) != v for k, v in got2.items())


def test_restore_preserves_string_keys(tmp_path):
    """codec reverse map survives the checkpoint (keys decode after restore)."""
    events = [(t * 1000, f"key-{t % 5}") for t in range(40)]
    env = build_env(2, tmp_path / "chk", interval=1)
    sink = CollectSink()
    (
        env.from_collection(events[:20])
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(WINDOW)
        .count()
        .add_sink(sink)
    )
    env.execute("phase1")

    env2 = build_env(2)
    sink2 = CollectSink()
    (
        env2.from_collection(events)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(WINDOW)
        .count()
        .add_sink(sink2)
    )
    env2.execute("phase2", restore_from=str(tmp_path / "chk"))
    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    got.update({(r.key, r.window_end_ms): r.value for r in sink2.results})
    expect = {}
    for t, k in events:
        we = (t // WINDOW + 1) * WINDOW
        expect[(k, we)] = expect.get((k, we), 0) + 1.0
    assert got == expect
    assert all(isinstance(k, str) for k, _ in got)
