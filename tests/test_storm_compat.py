"""Storm compatibility layer: a word-count topology (the flink-storm
canonical example) runs unchanged on the DataStream runtime.

Ref: flink-contrib/flink-storm FlinkTopology/SpoutWrapper/BoltWrapper.
"""

import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.storm import BasicBolt, BasicSpout, FlinkTopology, \
    TopologyBuilder

LINES = [
    "to be or not to be",
    "that is the question",
    "be that as it may",
]


class LineSpout(BasicSpout):
    def open(self, collector):
        self.collector = collector
        self.i = 0

    def next_tuple(self):
        if self.i >= len(LINES):
            return False
        self.collector.emit((LINES[self.i],))
        self.i += 1
        return True


class SplitBolt(BasicBolt):
    def execute(self, tup):
        for w in tup[0].split():
            self.collector.emit((w, 1))


class CountBolt(BasicBolt):
    def prepare(self, collector):
        super().prepare(collector)
        self.counts = {}

    def execute(self, tup):
        w, n = tup
        self.counts[w] = self.counts.get(w, 0) + n
        self.collector.emit((w, self.counts[w]))


def test_storm_word_count_topology():
    builder = TopologyBuilder()
    builder.set_spout("lines", LineSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    results = FlinkTopology(builder).execute(env)

    # the last emission per word is its total count
    final = {}
    for w, n in results:
        final[w] = max(final.get(w, 0), n)
    words = " ".join(LINES).split()
    expected = {w: words.count(w) for w in set(words)}
    assert final == expected


def test_topology_validation():
    b = TopologyBuilder()
    b.set_spout("s", LineSpout())
    b.set_bolt("b1", SplitBolt())          # no grouping declared
    try:
        FlinkTopology(b).execute(None)
    except ValueError as e:
        assert "grouping" in str(e)
    else:
        raise AssertionError("must refuse ungrouped bolts")


# ------------------------------------------------------- DAG topologies (r4)
class _ListSpout(BasicSpout):
    def __init__(self, items):
        self.items = list(items)
        self.i = 0

    def open(self, collector):
        self.collector = collector

    def next_tuple(self):
        if self.i >= len(self.items):
            return False
        self.collector.emit(self.items[self.i])
        self.i += 1
        return True


class _TagBolt(BasicBolt):
    def __init__(self, tag):
        self.tag = tag

    def execute(self, tup):
        self.collector.emit((self.tag,) + tup)


class _CountBolt(BasicBolt):
    def __init__(self):
        self.counts = {}

    def execute(self, tup):
        w = tup[0]
        self.counts[w] = self.counts.get(w, 0) + 1
        self.collector.emit((w, self.counts[w]))


def test_multi_spout_union_into_one_bolt():
    """Two spouts feed one bolt — the createTopology union (ref
    flink-storm-examples multi-input shapes)."""
    b = TopologyBuilder()
    b.set_spout("a", _ListSpout([("x",), ("y",)]))
    b.set_spout("b", _ListSpout([("z",)]))
    b.set_bolt("merge", _TagBolt("m")) \
        .shuffle_grouping("a").shuffle_grouping("b")
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = FlinkTopology(b).execute(env)
    assert sorted(out) == [("m", "x"), ("m", "y"), ("m", "z")]


def test_fan_out_to_multiple_leaves():
    b = TopologyBuilder()
    b.set_spout("src", _ListSpout([("p",), ("q",)]))
    b.set_bolt("left", _TagBolt("L")).shuffle_grouping("src")
    b.set_bolt("right", _TagBolt("R")).shuffle_grouping("src")
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = FlinkTopology(b).execute(env)
    assert set(out) == {"left", "right"}
    assert sorted(out["left"]) == [("L", "p"), ("L", "q")]
    assert sorted(out["right"]) == [("R", "p"), ("R", "q")]


def test_multi_input_keyed_bolt():
    """Two upstream bolts union into a fields-grouped counter."""
    b = TopologyBuilder()
    b.set_spout("s1", _ListSpout([("dog",), ("cat",)]))
    b.set_spout("s2", _ListSpout([("dog",), ("dog",)]))
    b.set_bolt("count", _CountBolt()) \
        .fields_grouping("s1", 0).fields_grouping("s2", 0)
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = FlinkTopology(b).execute(env)
    got = {}
    for w, c in out:
        got[w] = max(got.get(w, 0), c)
    assert got == {"dog": 3, "cat": 1}


class _PosCountBolt(BasicBolt):
    """Counts occurrences of tuple position `pos`, emits (value, n)."""

    def __init__(self, pos):
        self.pos = pos
        self.counts = {}

    def execute(self, tup):
        v = tup[self.pos]
        self.counts[v] = self.counts.get(v, 0) + 1
        self.collector.emit((v, self.counts[v]))


def test_two_keyed_hops_word_count_then_count_histogram():
    """Round 5: TWO fieldsGrouping hops run as chained pipeline stages
    (the one-keyed-stage-per-topology limit is lifted). Stage 1 counts
    words; stage 2 keys the running counts BY COUNT VALUE and tallies
    how many emissions carried each count."""
    b = TopologyBuilder()
    b.set_spout("lines", LineSpout())
    b.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    b.set_bolt("count", CountBolt()).fields_grouping("split", 0)
    # second keyed hop: histogram of running-count values
    b.set_bolt("hist", _PosCountBolt(1)).fields_grouping("count", 1)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    results = FlinkTopology(b).execute(env)

    # scalar model of the same two hops
    counts = {}
    emissions = []
    for line in LINES:
        for w in line.split():
            counts[w] = counts.get(w, 0) + 1
            emissions.append((w, counts[w]))
    hist = {}
    expect = []
    for _w, c in emissions:
        hist[c] = hist.get(c, 0) + 1
        expect.append((c, hist[c]))
    assert sorted(results) == sorted(expect)


def test_multi_input_bolt_below_keyed_runs_staged():
    """A MULTI-INPUT bolt below a fields-grouped one is not expressible
    as one SPMD job (the staged path must carry it): the merge bolt
    unions the keyed output with a side stream."""
    b = TopologyBuilder()
    b.set_spout("s", _ListSpout([("a", 1), ("b", 1), ("a", 1)]))
    b.set_spout("side", _ListSpout([("side", 0)]))
    b.set_bolt("k", _CountBolt()).fields_grouping("s", 0)
    b.set_bolt("merge", _TagBolt("m")).shuffle_grouping("k") \
        .shuffle_grouping("side")

    topo = FlinkTopology(b)
    assert not topo._single_job_ok(topo._topo_order())
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = topo.execute(env)
    expect = [("m", "a", 1), ("m", "b", 1), ("m", "a", 2),
              ("m", "side", 0)]
    assert sorted(out) == sorted(expect)


def test_fan_out_below_keyed_stays_single_job():
    """Fan-out below a keyed bolt IS one SPMD job (trailing stateless
    sink branches): both leaves see every keyed emission."""
    b = TopologyBuilder()
    b.set_spout("s", _ListSpout([("a", 1), ("b", 1), ("a", 1)]))
    b.set_bolt("k", _CountBolt()).fields_grouping("s", 0)
    b.set_bolt("t1", _TagBolt("x")).shuffle_grouping("k")
    b.set_bolt("t2", _TagBolt("y")).shuffle_grouping("k")

    topo = FlinkTopology(b)
    assert topo._single_job_ok(topo._topo_order())
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = topo.execute(env)
    assert set(out) == {"t1", "t2"}
    keyed = [("a", 1), ("b", 1), ("a", 2)]
    assert sorted(out["t1"]) == sorted(("x", k, c) for k, c in keyed)
    assert sorted(out["t2"]) == sorted(("y", k, c) for k, c in keyed)


def test_two_keyed_hops_route_staged():
    """The two-hop topology must actually take the staged path."""
    b = TopologyBuilder()
    b.set_spout("s", _ListSpout([("a",)]))
    b.set_bolt("k1", _CountBolt()).fields_grouping("s", 0)
    b.set_bolt("k2", _PosCountBolt(1)).fields_grouping("k1", 1)
    topo = FlinkTopology(b)
    assert not topo._single_job_ok(topo._topo_order())


def test_cycle_rejected():
    b = TopologyBuilder()
    b.set_spout("s", _ListSpout([("a",)]))
    b.set_bolt("b1", _TagBolt("1")).shuffle_grouping("s") \
        .shuffle_grouping("b2")
    b.set_bolt("b2", _TagBolt("2")).shuffle_grouping("b1")
    with pytest.raises(ValueError, match="cycle"):
        FlinkTopology(b)._topo_order()
