"""Storm compatibility layer: a word-count topology (the flink-storm
canonical example) runs unchanged on the DataStream runtime.

Ref: flink-contrib/flink-storm FlinkTopology/SpoutWrapper/BoltWrapper.
"""

import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.storm import BasicBolt, BasicSpout, FlinkTopology, \
    TopologyBuilder

LINES = [
    "to be or not to be",
    "that is the question",
    "be that as it may",
]


class LineSpout(BasicSpout):
    def open(self, collector):
        self.collector = collector
        self.i = 0

    def next_tuple(self):
        if self.i >= len(LINES):
            return False
        self.collector.emit((LINES[self.i],))
        self.i += 1
        return True


class SplitBolt(BasicBolt):
    def execute(self, tup):
        for w in tup[0].split():
            self.collector.emit((w, 1))


class CountBolt(BasicBolt):
    def prepare(self, collector):
        super().prepare(collector)
        self.counts = {}

    def execute(self, tup):
        w, n = tup
        self.counts[w] = self.counts.get(w, 0) + n
        self.collector.emit((w, self.counts[w]))


def test_storm_word_count_topology():
    builder = TopologyBuilder()
    builder.set_spout("lines", LineSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    results = FlinkTopology(builder).execute(env)

    # the last emission per word is its total count
    final = {}
    for w, n in results:
        final[w] = max(final.get(w, 0), n)
    words = " ".join(LINES).split()
    expected = {w: words.count(w) for w in set(words)}
    assert final == expected


def test_topology_validation():
    b = TopologyBuilder()
    b.set_spout("s", LineSpout())
    b.set_bolt("b1", SplitBolt())          # no grouping declared
    try:
        FlinkTopology(b).execute(None)
    except ValueError as e:
        assert "grouping" in str(e)
    else:
        raise AssertionError("must refuse ungrouped bolts")


# ------------------------------------------------------- DAG topologies (r4)
class _ListSpout(BasicSpout):
    def __init__(self, items):
        self.items = list(items)
        self.i = 0

    def open(self, collector):
        self.collector = collector

    def next_tuple(self):
        if self.i >= len(self.items):
            return False
        self.collector.emit(self.items[self.i])
        self.i += 1
        return True


class _TagBolt(BasicBolt):
    def __init__(self, tag):
        self.tag = tag

    def execute(self, tup):
        self.collector.emit((self.tag,) + tup)


class _CountBolt(BasicBolt):
    def __init__(self):
        self.counts = {}

    def execute(self, tup):
        w = tup[0]
        self.counts[w] = self.counts.get(w, 0) + 1
        self.collector.emit((w, self.counts[w]))


def test_multi_spout_union_into_one_bolt():
    """Two spouts feed one bolt — the createTopology union (ref
    flink-storm-examples multi-input shapes)."""
    b = TopologyBuilder()
    b.set_spout("a", _ListSpout([("x",), ("y",)]))
    b.set_spout("b", _ListSpout([("z",)]))
    b.set_bolt("merge", _TagBolt("m")) \
        .shuffle_grouping("a").shuffle_grouping("b")
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = FlinkTopology(b).execute(env)
    assert sorted(out) == [("m", "x"), ("m", "y"), ("m", "z")]


def test_fan_out_to_multiple_leaves():
    b = TopologyBuilder()
    b.set_spout("src", _ListSpout([("p",), ("q",)]))
    b.set_bolt("left", _TagBolt("L")).shuffle_grouping("src")
    b.set_bolt("right", _TagBolt("R")).shuffle_grouping("src")
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = FlinkTopology(b).execute(env)
    assert set(out) == {"left", "right"}
    assert sorted(out["left"]) == [("L", "p"), ("L", "q")]
    assert sorted(out["right"]) == [("R", "p"), ("R", "q")]


def test_multi_input_keyed_bolt():
    """Two upstream bolts union into a fields-grouped counter."""
    b = TopologyBuilder()
    b.set_spout("s1", _ListSpout([("dog",), ("cat",)]))
    b.set_spout("s2", _ListSpout([("dog",), ("dog",)]))
    b.set_bolt("count", _CountBolt()) \
        .fields_grouping("s1", 0).fields_grouping("s2", 0)
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    out = FlinkTopology(b).execute(env)
    got = {}
    for w, c in out:
        got[w] = max(got.get(w, 0), c)
    assert got == {"dog": 3, "cat": 1}


def test_two_keyed_bolts_rejected():
    b = TopologyBuilder()
    b.set_spout("s", _ListSpout([("a",)]))
    b.set_bolt("k1", _CountBolt()).fields_grouping("s", 0)
    b.set_bolt("k2", _CountBolt()).fields_grouping("k1", 0)
    with pytest.raises(ValueError, match="one fields-grouped"):
        FlinkTopology(b)._topo_order()


def test_cycle_rejected():
    b = TopologyBuilder()
    b.set_spout("s", _ListSpout([("a",)]))
    b.set_bolt("b1", _TagBolt("1")).shuffle_grouping("s") \
        .shuffle_grouping("b2")
    b.set_bolt("b2", _TagBolt("2")).shuffle_grouping("b1")
    with pytest.raises(ValueError, match="cycle"):
        FlinkTopology(b)._topo_order()
