"""Storm compatibility layer: a word-count topology (the flink-storm
canonical example) runs unchanged on the DataStream runtime.

Ref: flink-contrib/flink-storm FlinkTopology/SpoutWrapper/BoltWrapper.
"""

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.storm import BasicBolt, BasicSpout, FlinkTopology, \
    TopologyBuilder

LINES = [
    "to be or not to be",
    "that is the question",
    "be that as it may",
]


class LineSpout(BasicSpout):
    def open(self, collector):
        self.collector = collector
        self.i = 0

    def next_tuple(self):
        if self.i >= len(LINES):
            return False
        self.collector.emit((LINES[self.i],))
        self.i += 1
        return True


class SplitBolt(BasicBolt):
    def execute(self, tup):
        for w in tup[0].split():
            self.collector.emit((w, 1))


class CountBolt(BasicBolt):
    def prepare(self, collector):
        super().prepare(collector)
        self.counts = {}

    def execute(self, tup):
        w, n = tup
        self.counts[w] = self.counts.get(w, 0) + n
        self.collector.emit((w, self.counts[w]))


def test_storm_word_count_topology():
    builder = TopologyBuilder()
    builder.set_spout("lines", LineSpout())
    builder.set_bolt("split", SplitBolt()).shuffle_grouping("lines")
    builder.set_bolt("count", CountBolt()).fields_grouping("split", 0)

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    env.set_parallelism(1)
    results = FlinkTopology(builder).execute(env)

    # the last emission per word is its total count
    final = {}
    for w, n in results:
        final[w] = max(final.get(w, 0), n)
    words = " ".join(LINES).split()
    expected = {w: words.count(w) for w in set(words)}
    assert final == expected


def test_topology_validation():
    b = TopologyBuilder()
    b.set_spout("s", LineSpout())
    b.set_bolt("b1", SplitBolt())          # no grouping declared
    try:
        FlinkTopology(b).execute(None)
    except ValueError as e:
        assert "grouping" in str(e)
    else:
        raise AssertionError("must refuse ungrouped bolts")
