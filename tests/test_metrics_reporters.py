"""Wire-protocol metric reporters (ref flink-metrics-statsd /
flink-metrics-graphite): real sockets, config-driven setup, line formats.
"""

import socket
import threading
import time

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.config import Configuration
from flink_tpu.metrics.core import MetricRegistry
from flink_tpu.metrics.reporters import (
    GraphiteReporter,
    StatsDReporter,
    configure_reporters,
)


def _registry_with_metrics():
    reg = MetricRegistry()
    g = reg.group("jobs", "j1")
    g.counter("records_in").inc(42)
    g.gauge("steps", lambda: 7)
    h = g.histogram("lat")
    for v in (1.0, 2.0, 3.0):
        h.update(v)
    return reg


def test_statsd_lines_over_udp():
    srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv.bind(("127.0.0.1", 0))
    srv.settimeout(5)
    port = srv.getsockname()[1]
    reg = _registry_with_metrics()
    rep = StatsDReporter("127.0.0.1", port)
    reg.add_reporter(rep)
    rep.report()
    got = []
    deadline = time.time() + 5
    while time.time() < deadline and len(got) < 3:
        try:
            data, _ = srv.recvfrom(65536)
            got.append(data.decode())
        except socket.timeout:
            break
    joined = "\n".join(got)
    assert "jobs.j1.records_in:42|g" in joined
    assert "jobs.j1.steps:7|g" in joined
    rep.close()
    srv.close()


def test_graphite_plaintext_over_tcp():
    srv = socket.create_server(("127.0.0.1", 0))
    srv.settimeout(10)
    port = srv.getsockname()[1]
    lines = []

    def accept():
        conn, _ = srv.accept()
        with conn:
            buf = b""
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
            lines.extend(buf.decode().splitlines())

    t = threading.Thread(target=accept, daemon=True)
    t.start()
    reg = _registry_with_metrics()
    rep = GraphiteReporter("127.0.0.1", port, prefix="pfx")
    reg.add_reporter(rep)
    rep.report()
    t.join(timeout=10)
    srv.close()
    by_path = {ln.split()[0]: ln.split() for ln in lines}
    assert by_path["pfx.jobs.j1.records_in"][1] == "42"
    assert by_path["pfx.jobs.j1.steps"][1] == "7"
    # histogram expands to per-statistic paths
    assert any(p.startswith("pfx.jobs.j1.lat.") for p in by_path)
    # plaintext rows are "<path> <value> <epoch>"
    assert all(len(v) == 3 for v in by_path.values())


def test_config_driven_reporters_on_env():
    """A real job with metrics.reporters configured emits its JobMetrics
    gauges over StatsD without any code-level wiring."""
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sinks import CountingSink
    from flink_tpu.runtime.sources import GeneratorSource

    srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv.bind(("127.0.0.1", 0))
    srv.settimeout(5)
    port = srv.getsockname()[1]

    env = StreamExecutionEnvironment(Configuration({
        "metrics.reporters": "stsd",
        "metrics.reporter.stsd.class": "statsd",
        "metrics.reporter.stsd.port": port,
        "metrics.reporter.stsd.interval": 0.1,
    }))
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(64)
    env.batch_size = 64

    def gen(off, n):
        idx = np.arange(off, off + n, dtype=np.int64)
        return {"key": idx % 50, "value": np.ones(n, np.float32)}, idx // 8

    (
        env.add_source(GeneratorSource(gen, total=6400))
        .key_by(lambda c: c["key"])
        .time_window(100)
        .sum(lambda c: c["value"])
        .add_sink(CountingSink())
    )
    env.execute("metrics-job")
    got = []
    deadline = time.time() + 5
    while time.time() < deadline:
        try:
            data, _ = srv.recvfrom(65536)
            got.append(data.decode())
        except socket.timeout:
            break
        if any("records_in" in g for g in got):
            break
    assert any("metrics-job.records_in" in g for g in got), got[:5]
    for t in env._reporter_threads:
        t.stop()
    srv.close()


def test_prometheus_text_exposition():
    """Exposition format 0.0.4: one TYPE header per family, job names as
    labels, histograms as summaries with quantile series + _count/_sum."""
    from flink_tpu.metrics.reporters import prometheus_text

    reg = _registry_with_metrics()
    reg.group("jobs", "j1").meter("throughput").mark_event(5)
    text = prometheus_text(reg)
    lines = text.splitlines()
    assert "# TYPE flink_tpu_records_in counter" in lines
    assert 'flink_tpu_records_in{job="j1"} 42' in lines
    assert "# TYPE flink_tpu_steps gauge" in lines
    assert 'flink_tpu_steps{job="j1"} 7' in lines
    # histogram -> summary family with quantile labels
    assert "# TYPE flink_tpu_lat summary" in lines
    assert 'flink_tpu_lat{job="j1",quantile="0.50"} 2.0' in lines
    assert 'flink_tpu_lat_count{job="j1"} 3' in lines
    assert 'flink_tpu_lat_sum{job="j1"} 6.0' in lines
    # _count/_sum ride the parent family: no separate TYPE header
    assert not any(ln.startswith("# TYPE flink_tpu_lat_count")
                   for ln in lines)
    # meter -> _total counter + _rate gauge
    assert 'flink_tpu_throughput_total{job="j1"} 5' in lines
    assert any(ln.startswith('flink_tpu_throughput_rate{job="j1"} ')
               for ln in lines)
    # exactly one TYPE line per family
    types = [ln for ln in lines if ln.startswith("# TYPE")]
    assert len(types) == len(set(types))


def test_prometheus_name_sanitization_and_merge():
    from flink_tpu.metrics.core import MetricRegistry
    from flink_tpu.metrics.reporters import prometheus_text_from_items

    r1, r2 = MetricRegistry(), MetricRegistry()
    r1.group("jobs", 'my "job"-1').counter("cycle-time.p99").inc(1)
    r2.group("jobs", "other").counter("cycle-time.p99").inc(2)
    text = prometheus_text_from_items(r1.items() + r2.items())
    lines = text.splitlines()
    # metric-name charset enforced; label values escaped, not mangled
    assert 'flink_tpu_cycle_time_p99{job="my \\"job\\"-1"} 1' in lines
    assert 'flink_tpu_cycle_time_p99{job="other"} 2' in lines
    # merged registries still yield ONE header for the shared family
    assert lines.count("# TYPE flink_tpu_cycle_time_p99 counter") == 1


def test_prometheus_reporter_via_configure(tmp_path):
    """configure_reporters instantiates the prometheus kind; the textfile
    path makes report() drop the exposition for file-based scrapers."""
    from flink_tpu.metrics.reporters import PrometheusReporter

    out = tmp_path / "metrics.prom"
    reg = _registry_with_metrics()
    threads = configure_reporters(reg, Configuration({
        "metrics.reporters": "prom",
        "metrics.reporter.prom.class": "prometheus",
        "metrics.reporter.prom.path": str(out),
        "metrics.reporter.prom.interval": 3600,
    }))
    try:
        rep = threads[0].reporter
        assert isinstance(rep, PrometheusReporter)
        assert 'flink_tpu_records_in{job="j1"} 42' in rep.scrape()
        rep.report()
        assert 'flink_tpu_records_in{job="j1"} 42' in out.read_text()
    finally:
        for t in threads:
            t.stop()


def test_unknown_reporter_class_rejected():
    import pytest

    reg = MetricRegistry()
    with pytest.raises(ValueError, match="class"):
        configure_reporters(reg, Configuration({
            "metrics.reporters": "x",
            "metrics.reporter.x.class": "nope",
        }))


def test_ganglia_xdr_over_udp():
    """Decode the gmond v3.1 XDR datagrams RECEIVER-SIDE: metadata
    (id 128) declares type double with matching host/name; the value
    message (id 135) carries the IEEE-754 big-endian double. Ref
    flink-metrics-ganglia via gmetric4j; wire format from the public
    gm_protocol.x spec."""
    import struct

    from flink_tpu.metrics.reporters import GangliaReporter

    srv = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    srv.bind(("127.0.0.1", 0))
    srv.settimeout(5)
    port = srv.getsockname()[1]

    def xdr_int(data, off):
        return int.from_bytes(data[off:off + 4], "big"), off + 4

    def xdr_string(data, off):
        n, off = xdr_int(data, off)
        s = data[off:off + n].decode()
        return s, off + n + ((4 - n % 4) % 4)

    reg = _registry_with_metrics()
    rep = GangliaReporter("127.0.0.1", port, hostname="testhost")
    reg.add_reporter(rep)
    rep.report()

    meta, values = {}, {}
    deadline = time.time() + 5
    while time.time() < deadline and len(values) < 2:
        try:
            data, _ = srv.recvfrom(65536)
        except socket.timeout:
            break
        mid, off = xdr_int(data, 0)
        host, off = xdr_string(data, off)
        name, off = xdr_string(data, off)
        _spoof, off = xdr_int(data, off)
        assert host == "testhost"
        if mid == GangliaReporter.GMETADATA_FULL:
            mtype, off = xdr_string(data, off)
            name2, off = xdr_string(data, off)
            _units, off = xdr_string(data, off)
            slope, off = xdr_int(data, off)
            tmax, off = xdr_int(data, off)
            dmax, off = xdr_int(data, off)
            nextra, off = xdr_int(data, off)
            assert (mtype, name2, slope, tmax, dmax, nextra) == (
                "double", name, 3, 60, 0, 0
            )
            meta[name] = mtype
        elif mid == GangliaReporter.GMETRIC_DOUBLE:
            fmt, off = xdr_string(data, off)
            (v,) = struct.unpack_from(">d", data, off)
            values[name] = v
    assert values.get("jobs.j1.records_in") == 42.0
    assert values.get("jobs.j1.steps") == 7.0
    # every value had its metadata announced first
    assert set(values) <= set(meta)
    rep.close()
    srv.close()
