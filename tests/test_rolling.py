"""Rolling keyed reduce (StreamGroupedReduce semantics): per-record
emission of the updated accumulator, in order, across shards."""

import jax.numpy as jnp
import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.runtime.sinks import CollectSink


def test_rolling_sum_matches_scalar_model(rng):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(4).set_max_parallelism(128)
    env.set_state_capacity(512)
    env.batch_size = 64

    events = [(int(rng.integers(0, 10)), float(rng.integers(1, 5)))
              for _ in range(500)]
    sink = CollectSink()
    (
        env.from_collection(events)
        .key_by(lambda e: e[0])
        .sum(lambda e: e[1])
        .add_sink(sink)
    )
    env.execute("rolling-sum")

    acc = {}
    expect = []
    for k, v in events:
        acc[k] = acc.get(k, 0.0) + v
        expect.append((k, acc[k]))
    assert sink.results == expect


def test_rolling_generic_max():
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(4)
    env.set_state_capacity(256)
    env.batch_size = 8
    events = [("a", 3.0), ("b", 7.0), ("a", 5.0), ("a", 2.0), ("b", 9.0)]
    sink = CollectSink()
    (
        env.from_collection(events)
        .key_by(lambda e: e[0])
        .reduce(jnp.maximum, extractor=lambda e: e[1], neutral=-np.inf)
        .add_sink(sink)
    )
    env.execute("rolling-max")
    assert sink.results == [("a", 3.0), ("b", 7.0), ("a", 5.0),
                            ("a", 5.0), ("b", 9.0)]
