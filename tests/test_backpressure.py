"""Back-pressure cause attribution + latency markers (VERDICT item 9).

Ref: BackPressureStatsTracker.java:64 samples task-thread stacks to
classify network-buffer blockage; LatencyMarker.java rides timestamped
sentinels into per-operator latency histograms. The micro-batch design
MEASURES the decomposition instead: each poll cycle splits exactly into
source / host / dispatch / emit phases, and emissions record
ingest-to-sink latency of their youngest records.
"""

import numpy as np

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.executor import CycleAttribution
from flink_tpu.runtime.sinks import CountingSink
from flink_tpu.runtime.sources import GeneratorSource


def test_classification_rules():
    a = CycleAttribution()
    assert a.classify() == "ok"
    # mostly idle -> source-starved (decaying fraction, alpha=0.05)
    for _ in range(30):
        a.record(idle=True)
    a.record(idle=False, source=1, host=1, dispatch=1, emit=1)
    assert a.classify() == "source-starved"
    # regime change: sustained device saturation must FLIP the verdict
    # even though lifetime idle count still dominates
    for _ in range(60):
        a.record(idle=False, source=1, host=1, dispatch=50, emit=1)
    assert a.classify() == "device-bound"

    b = CycleAttribution(alpha=1.0)
    b.record(idle=False, source=1, host=1, dispatch=30, emit=2)
    assert b.classify() == "device-bound"
    b.record(idle=False, source=1, host=1, dispatch=1, emit=40)
    assert b.classify() == "sink-bound"
    b.record(idle=False, source=2, host=30, dispatch=1, emit=1)
    assert b.classify() == "host-bound"
    # balanced phases -> ok
    c = CycleAttribution(alpha=1.0)
    c.record(idle=False, source=1, host=1.2, dispatch=0.9, emit=1.1)
    assert c.classify() == "ok"


def test_resident_regimes_flip_with_drain_telemetry():
    """Round 14: when a resident drain loop is live, the duty-cycle /
    ring-starved signals from the drain telemetry OUTRANK the phase-EWMA
    rules — a saturated device ring and a starved ring are distinct
    regimes the phase decomposition cannot see (the drain span is one
    opaque interval either way), and the classification must flip as the
    live signal crosses the thresholds."""
    a = CycleAttribution(alpha=1.0)
    # phase rule alone says device-bound
    a.record(idle=False, source=1, host=1, dispatch=30, emit=1)
    assert a.classify() == "device-bound"

    signal = {"duty": 0.95, "starved": 0.0}
    a.resident_fn = lambda: (signal["duty"], signal["starved"])
    assert a.classify() == "device-saturated"
    # regime flip: rings now drain shallow and come up empty — the
    # publish side can't keep the device fed
    signal["duty"] = 0.2
    signal["starved"] = 0.8
    assert a.classify() == "ring-starved"
    # both signals below threshold: fall back to the phase rules
    signal["starved"] = 0.1
    assert a.classify() == "device-bound"
    # starvation wins over saturation (starved checked first: an empty
    # ring explains a high duty EWMA still decaying)
    signal["duty"] = 0.99
    signal["starved"] = 0.9
    assert a.classify() == "ring-starved"

    # the hooked report carries both live signals
    r = a.report()
    assert r["drain-duty-cycle"] == 0.99
    assert r["ring-starved-fraction"] == 0.9
    # unhooked instances never grow the keys (back-compat)
    assert "drain-duty-cycle" not in CycleAttribution().report()


def test_report_shape():
    a = CycleAttribution(alpha=1.0)
    a.record(idle=False, source=5, host=1, dispatch=2, emit=1)
    r = a.report()
    assert r["busy-cycles"] == 1 and r["idle-cycles"] == 0
    assert r["phase-ewma-ms"]["source"] == 5.0


def test_windowed_job_records_attribution_and_latency():
    env = StreamExecutionEnvironment()
    env.set_parallelism(1)
    env.set_max_parallelism(8)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_state_capacity(1 << 12)
    env.batch_size = 1024
    total = 20_000

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        return {"key": idx % 100, "value": np.ones(n, np.float32)}, idx // 10

    sink = CountingSink()
    (
        env.add_source(GeneratorSource(gen, total=total))
        .key_by(lambda c: c["key"])
        .time_window(500)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    job = env.execute("bp-job")
    assert sink.value_sum == total

    report = env._backpressure_report()
    assert report["busy-cycles"] > 0
    assert report["classification"] in (
        "ok", "source-starved", "host-bound", "device-bound", "sink-bound"
    )
    snap = env.metric_registry.snapshot("jobs.bp-job.record_latency_ms")
    hist = next(iter(snap.values()))
    assert hist["count"] > 0 and hist["p99"] > 0
    phases = env.metric_registry.snapshot("jobs.bp-job.phase_")
    assert len(phases) == 4
    assert all(v["count"] > 0 for v in phases.values())
