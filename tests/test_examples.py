"""Examples smoke: the runnable programs under examples/ are part of
the user-facing surface (README enumerates them) — run a fast subset as
real subprocesses so API drift breaks a test, not a reader.

(The socket example needs an external feeder by design, and the
heavier ones — YARN session, multi-host DCN, Kafka pipeline — are
covered by their subsystem test files; this picks fast self-contained
programs across batch, SQL, Storm, and wire-connector surfaces.)
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

FAST_EXAMPLES = [
    "batch_word_count.py",
    "planner_explain.py",
    "streaming_sql.py",
    "storm_word_count.py",
    "message_queues.py",
]


@pytest.mark.parametrize("name", FAST_EXAMPLES)
def test_example_runs(name):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", name)],
        env=env, capture_output=True, timeout=300,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]