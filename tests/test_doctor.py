"""Pipeline doctor + stage-aware flight recorder units (ISSUE 17).

* every doctor rule reproduced by a synthetic-pathology snapshot —
  starved ring, saturated drain, edge-lane near-overflow AND overflow,
  kg heat skew, recompile storm, checkpoint budget burn, ring
  refusals, watchdog trips, tier thrash (ISSUE 18) — each finding
  carrying evidence values and a concrete config remedy;
* ranking (severity class, then score), graceful degradation on
  missing planes, threshold overrides;
* the ``python -m flink_tpu.doctor`` CLI: exit 0 clean / 1 findings /
  2 error, the stable ``--json`` schema, and replaying a served
  payload through its embedded snapshot;
* DrainTelemetry's stage-aware half: per-downstream-stage counter
  totals / levels / peaks, the report() stages block with edge
  utilization, and the key-group heat series (EWMA fold, recency,
  cold tail, skew, live resize);
* the doctor->controller contract lint (ISSUE 19): every remedy key
  a finding emits names a declared ConfigOption, and every machine
  ``action`` names a registered RuntimeController actuator.
"""

import ast
import inspect
import json
import subprocess
import sys

import numpy as np
import pytest

from flink_tpu.metrics.doctor import (
    DEFAULT_THRESHOLDS,
    DOCTOR_SCHEMA_VERSION,
    RULE_NAMES,
    diagnose,
    run_rules,
)
from flink_tpu.metrics.drain_stats import (
    STAGE_STAT_FIELDS,
    DrainTelemetry,
)


# ------------------------------------------------ synthetic pathologies

def _shard(i, **kw):
    row = {"shard": i, "duty_cycle": 0.2, "ring_starved": 0.0,
           "totals": {}, "levels": {}}
    row.update(kw)
    return row


def _one(snapshot, rule):
    found = [f for f in run_rules(snapshot) if f["rule"] == rule]
    assert len(found) == 1, (rule, [f["rule"] for f in
                                    run_rules(snapshot)])
    return found[0]


def test_rule_ring_starved():
    snap = {"pipeline": {"shards": [
        _shard(0, ring_starved=0.85), _shard(1, ring_starved=0.1),
    ]}}
    f = _one(snap, "ring-starved")
    assert f["severity"] == "warning"
    assert f["evidence"]["shards"] == [
        {"shard": 0, "ring_starved": 0.85}
    ]
    assert f["remedy"]["key"] == "pipeline.prefetch-depth"
    # below threshold: no finding
    snap["pipeline"]["shards"][0]["ring_starved"] = 0.3
    assert not [x for x in run_rules(snap) if x["rule"] == "ring-starved"]


def test_rule_device_saturated():
    snap = {"pipeline": {"shards": [
        _shard(0, duty_cycle=0.97), _shard(1, duty_cycle=0.95),
    ]}}
    f = _one(snap, "device-saturated")
    assert f["severity"] == "warning"
    assert len(f["evidence"]["shards"]) == 2
    assert f["score"] == 0.97
    assert f["remedy"]["key"] == "pipeline.ring-depth"


def test_rule_edge_lane_near_overflow_warns_before_dropping():
    snap = {"pipeline": {"stages": [{
        "stage": 1, "edge_lane_budget": 1024, "edge_peak_demand": 900,
        "edge_utilization": 0.8789, "totals": {"dropped_capacity": 0},
        "levels": {},
    }]}}
    f = _one(snap, "edge-lane-overflow")
    assert f["severity"] == "warning"
    assert f["evidence"]["edge_peak_demand"] == 900
    assert f["evidence"]["dropped_capacity"] == 0
    assert f["remedy"]["key"] == "pipeline.stages.exchange-lanes"


def test_rule_edge_lane_overflow_dropped_is_critical():
    snap = {"pipeline": {"stages": [{
        "stage": 2, "edge_lane_budget": 64, "edge_peak_demand": 91,
        "edge_utilization": 1.4219, "totals": {"dropped_capacity": 27},
        "levels": {},
    }]}}
    f = _one(snap, "edge-lane-overflow")
    assert f["severity"] == "critical"
    assert f["evidence"]["dropped_capacity"] == 27
    assert "OVERFLOWED" in f["summary"]


def test_rule_kg_heat_skew():
    snap = {"pipeline": {"kg_heat": {
        "available": True, "skew_ratio": 9.3,
        "top": [{"group": 7, "heat": 93.0, "last_touched_ago": 0}],
        "cold_tail": {"count": 90, "fraction": 0.7},
    }}}
    f = _one(snap, "kg-heat-skew")
    assert f["severity"] == "warning"
    assert f["evidence"]["skew_ratio"] == 9.3
    assert f["evidence"]["hot_groups"][0]["group"] == 7
    assert f["remedy"]["key"] == "pipeline.data-parallel"
    # unavailable heat block never fires the rule
    snap["pipeline"]["kg_heat"] = {"available": False, "samples": 0}
    assert not run_rules(snap)


def test_rule_recompile_storm():
    snap = {"compile": {"compiles": 40, "by_stage": {
        "steady": {"count": 31, "time_ms": 9000.0},
    }}}
    f = _one(snap, "recompile-storm")
    assert f["severity"] == "critical"
    assert f["evidence"]["steady_compiles"] == 31
    assert f["remedy"]["key"] == "pipeline.steps-per-dispatch"
    # the warmup bucket never triggers it
    assert not run_rules({"compile": {
        "by_stage": {"warmup": {"count": 99, "time_ms": 1.0}},
    }})


def test_rule_checkpoint_budget_burn():
    snap = {
        "metrics": {"checkpoints_aborted": 2, "checkpoints_declined": 1},
        "checkpoints": [
            {"id": 3, "status": "completed"},
            {"id": 4, "status": "aborted",
             "failure_reason": "injected fault: publish"},
        ],
    }
    f = _one(snap, "checkpoint-budget-burn")
    assert f["severity"] == "warning"
    assert f["evidence"]["recent_aborts"] == [
        {"id": 4, "failure_reason": "injected fault: publish"}
    ]
    assert f["remedy"]["key"] == "checkpoint.tolerable-failures"


def test_rule_ring_refusals():
    snap = {"pipeline": {"shards": [
        _shard(0, publish_refusals=5), _shard(1, publish_refusals=0),
    ]}}
    f = _one(snap, "ring-refusals")
    assert f["severity"] == "info"
    assert f["evidence"]["total_refusals"] == 5
    assert f["remedy"]["key"] == "pipeline.ring-depth"


def test_rule_watchdog_trips():
    snap = {"metrics": {"watchdog_trips": 1, "restarts": 1}}
    f = _one(snap, "watchdog-trips")
    assert f["severity"] == "warning"
    assert f["evidence"] == {"watchdog_trips": 1, "restarts": 1}
    assert f["remedy"]["key"] == "watchdog.drain-timeout"


def test_rule_tier_thrash_churn_and_miss_arms():
    # churn arm: swaps outpace dispatches
    snap = {
        "pipeline": {"tiers": {
            "demotes": 30, "promotes": 30, "faults": 2,
            "prefetch_hits": 9, "prefetch_misses": 1,
            "budget_per_shard": 2, "resident_groups": 4,
            "cold_groups_pending": 3,
        }},
        "metrics": {"resident_drains": 40},
    }
    f = _one(snap, "tier-thrash")
    assert f["severity"] == "warning"
    assert f["evidence"]["dispatches"] == 40
    assert f["evidence"]["demotes"] == 30
    assert f["remedy"]["key"] == "state.tiers.resident-key-groups"
    assert "thrashing" in f["summary"]
    # miss arm: prefetches mostly never touched (needs >= 4 samples)
    snap["pipeline"]["tiers"].update(
        demotes=1, promotes=1, prefetch_hits=1, prefetch_misses=5)
    f = _one(snap, "tier-thrash")
    assert "mispredicting" in f["summary"]
    assert f["evidence"]["prefetch_misses"] == 5
    # healthy tiering: low churn, good hit rate — no finding
    snap["pipeline"]["tiers"].update(prefetch_hits=50, prefetch_misses=1)
    assert not run_rules(snap)
    # a job without tiers never fires the rule
    assert not run_rules({"pipeline": {}, "metrics": {"steps": 100}})


# ------------------------------------------------ engine behaviour

def test_empty_snapshot_is_clean_and_every_plane_degrades():
    payload = diagnose({})
    assert payload["clean"] is True
    assert payload["findings"] == []
    assert payload["version"] == DOCTOR_SCHEMA_VERSION
    assert set(payload["rules"]) == set(RULE_NAMES)
    assert len(RULE_NAMES) == 9
    # partial planes of the wrong-but-plausible shapes never crash
    assert diagnose({"pipeline": {}, "metrics": {}, "compile": {},
                     "checkpoints": []})["clean"] is True


def test_findings_rank_critical_first_then_score():
    snap = {
        "pipeline": {"shards": [_shard(0, ring_starved=0.9,
                                       publish_refusals=3)]},
        "compile": {"by_stage": {"steady": {"count": 50}}},
        "metrics": {"watchdog_trips": 7},
    }
    findings = run_rules(snap)
    assert [f["rule"] for f in findings] == [
        "recompile-storm",                      # critical
        "watchdog-trips", "ring-starved",       # warnings, score desc
        "ring-refusals",                        # info
    ]
    sev = [f["severity"] for f in findings]
    assert sev == ["critical", "warning", "warning", "info"]


def test_threshold_overrides_and_none_values_ignored():
    snap = {"pipeline": {"shards": [_shard(0, duty_cycle=0.5)]}}
    assert not run_rules(snap)
    hot = run_rules(snap, {"saturated": 0.4, "kg_skew": None})
    assert [f["rule"] for f in hot] == ["device-saturated"]
    assert hot[0]["evidence"]["threshold"] == 0.4
    # a None override keeps the default, not a crash / 0-threshold
    assert DEFAULT_THRESHOLDS["kg_skew"] == 4.0


# ------------------------------------------------ CLI exit codes

def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "flink_tpu.doctor", *args],
        capture_output=True, text=True,
    )


def test_cli_clean_snapshot_exits_zero(tmp_path):
    p = tmp_path / "snap.json"
    p.write_text("{}")
    r = _cli(str(p))
    assert r.returncode == 0, r.stderr
    assert "clean" in r.stdout


def test_cli_findings_exit_one_with_stable_json(tmp_path):
    p = tmp_path / "snap.json"
    p.write_text(json.dumps({
        "metrics": {"watchdog_trips": 3},
        "compile": {"by_stage": {"steady": {"count": 20}}},
    }))
    r = _cli(str(p), "--json")
    assert r.returncode == 1, r.stderr
    payload = json.loads(r.stdout)
    assert payload["version"] == DOCTOR_SCHEMA_VERSION
    assert payload["clean"] is False
    assert [f["rule"] for f in payload["findings"]] == [
        "recompile-storm", "watchdog-trips",
    ]
    for f in payload["findings"]:
        assert f["evidence"] and f["remedy"]["key"]
    # human rendering carries the same remedies
    rt = _cli(str(p))
    assert rt.returncode == 1
    assert "pipeline.steps-per-dispatch" in rt.stdout


def test_cli_replays_a_served_payload_through_embedded_snapshot(
        tmp_path):
    """A saved /jobs/<jid>/doctor payload re-diagnoses identically:
    the embedded snapshot + thresholds are the replay inputs."""
    served = diagnose({"metrics": {"watchdog_trips": 2}})
    served["snapshot"] = {"metrics": {"watchdog_trips": 2}}
    served["thresholds"] = dict(DEFAULT_THRESHOLDS)
    p = tmp_path / "served.json"
    p.write_text(json.dumps(served))
    r = _cli(str(p), "--json")
    assert r.returncode == 1
    replay = json.loads(r.stdout)
    assert replay["findings"] == served["findings"]


def test_cli_errors_exit_two(tmp_path):
    assert _cli("/definitely/not/there.json").returncode == 2
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert _cli(str(bad)).returncode == 2
    # exactly one of <snapshot> / --url
    assert _cli().returncode == 2
    p = tmp_path / "s.json"
    p.write_text("{}")
    assert _cli(str(p), "--url", "http://x/").returncode == 2


# --------------------------------------- stage-aware flight recorder

def _stage_payload(**kw):
    """One [1, n_shards, K] record with named fields on shard 0."""
    fi = {f: i for i, f in enumerate(STAGE_STAT_FIELDS)}
    n_shards = kw.pop("n_shards", 1)
    ss = np.zeros((1, n_shards, len(STAGE_STAT_FIELDS)), np.int32)
    for f, v in kw.items():
        ss[0, 0, fi[f]] = v
    return ss


def test_stage_payload_counters_accumulate_and_levels_track_latest():
    dt = DrainTelemetry(1, 4, n_stages=2, exchange_lanes=100)
    dt.absorb_stage_payload(_stage_payload(
        edge_demand=40, edge_events=40, fire_lanes=3, wm_lag_panes=5,
        panes_advanced=2,
    ))
    dt.absorb_stage_payload(_stage_payload(
        edge_demand=90, edge_events=90, fire_lanes=1, wm_lag_panes=1,
        panes_advanced=1,
    ))
    assert dt.stage_stat(1, "edge_demand") == 130      # counter: sum
    assert dt.stage_stat(1, "fire_lanes") == 4
    assert dt.stage_stat(1, "panes_advanced") == 3
    assert dt.stage_stat(1, "wm_lag_panes") == 1       # level: latest
    # out-of-range stage / unknown field read as 0, never raise
    assert dt.stage_stat(2, "edge_demand") == 0
    assert dt.stage_stat(1, "nope") == 0

    rep = dt.report()
    (st,) = rep["stages"]
    assert st["stage"] == 1
    assert st["totals"]["edge_demand"] == 130
    assert st["levels"]["wm_lag_panes"] == 1
    assert st["edge_lane_budget"] == 100
    assert st["edge_peak_demand"] == 90                # per-drain peak
    assert st["edge_utilization"] == 0.9
    assert rep["stage_fields"] == list(STAGE_STAT_FIELDS)


def test_stage_payload_sums_shards_and_accepts_2d():
    dt = DrainTelemetry(2, 4, n_stages=2, exchange_lanes=0)
    ss = _stage_payload(n_shards=2, edge_demand=10)
    ss[0, 1, 0] = 30                                   # shard 1 demand
    dt.absorb_stage_payload(ss)
    assert dt.stage_stat(1, "edge_demand") == 40       # summed shards
    # a [n_stages-1, K] payload (no shard axis) is promoted
    dt.absorb_stage_payload(
        np.full((1, len(STAGE_STAT_FIELDS)), 2, np.int32))
    assert dt.stage_stat(1, "edge_demand") == 42
    # zero budget: utilization is None, not a ZeroDivisionError
    assert dt.report()["stages"][0]["edge_utilization"] is None


def test_single_stage_report_has_no_stages_block():
    dt = DrainTelemetry(1, 4)
    rep = dt.report()
    assert "stages" not in rep and "kg_heat" not in rep


class _FakeTracer:
    active = True

    def __init__(self):
        self.counters = []

    def rec_counter(self, track, t, **values):
        self.counters.append((track, values))


def test_stage_payload_emits_per_stage_counter_tracks():
    tr = _FakeTracer()
    dt = DrainTelemetry(1, 4, tracer=tr, n_stages=3, exchange_lanes=8)
    ss = np.zeros((2, 1, len(STAGE_STAT_FIELDS)), np.int32)
    ss[:, 0, 1] = (4, 7)                               # edge_events
    dt.absorb_stage_payload(ss)
    tracks = dict(tr.counters)
    assert set(tracks) == {"drain_stage1", "drain_stage2"}
    assert tracks["drain_stage2"]["edge_lanes"] == 7
    assert set(tracks["drain_stage1"]) == {
        "edge_lanes", "fire_lanes", "wm_lag_panes",
    }


# ------------------------------------------------ key-group heat

def test_kg_heat_ewma_recency_and_cold_tail():
    dt = DrainTelemetry(1, 4, key_groups=8, kg_alpha=0.5)
    assert dt.kg_heat_block()["available"] is False
    fill = np.zeros(8, np.int64)
    fill[2] = 100
    dt.absorb_kg_fill(fill)
    dt.absorb_kg_fill(fill)
    # EWMA with alpha .5 over obs 100: 50 then 75
    assert dt.kg_heat_max() == pytest.approx(75.0)
    blk = dt.kg_heat_block(k=3)
    assert blk["available"] and blk["samples"] == 2
    assert blk["top"][0] == {
        "group": 2, "heat": 75.0, "last_touched_ago": 0,
    }
    # only group 2 was ever touched: mean-over-touched == max
    assert blk["skew_ratio"] == 1.0
    assert blk["cold_tail"]["count"] == 7              # untouched tail
    # now a second group goes hot-then-cold: recency ages out
    fill2 = np.zeros(8, np.int64)
    fill2[5] = 10
    dt.absorb_kg_fill(fill2)
    dt.absorb_kg_fill(np.zeros(8, np.int64))
    blk = dt.kg_heat_block(k=8)
    ago = {r["group"]: r["last_touched_ago"] for r in blk["top"]}
    assert ago[5] == 1 and ago[2] == 2
    assert dt.kg_heat_skew() > 1.0                     # 2 dominates 5


def test_kg_heat_normalizes_by_batches_and_resizes():
    dt = DrainTelemetry(1, 4, key_groups=4, kg_alpha=1.0)
    dt.absorb_kg_fill(np.asarray([8, 0, 0, 0], np.int64), n_batches=4)
    assert dt.kg_heat_max() == pytest.approx(2.0)      # per-batch obs
    # a wider fill vector (elastic re-plan) resizes in place,
    # preserving the existing prefix
    dt.absorb_kg_fill(np.zeros(6, np.int64))
    assert dt.kg_heat_block(k=1)["groups"] == 6
    assert dt.kg_heat_max() == pytest.approx(2.0 * 0.0)  # alpha=1 decay


# ------------------------------------------------ controller contract

def _finding_call_sites():
    """AST of every ``_finding(...)`` call in the doctor module."""
    from flink_tpu.metrics import doctor as doctor_mod
    tree = ast.parse(inspect.getsource(doctor_mod))
    return [
        node for node in ast.walk(tree)
        if isinstance(node, ast.Call)
        and getattr(node.func, "id", "") == "_finding"
    ]


def test_doctor_remedy_keys_are_declared_config_options():
    """Every remedy a finding emits must name a key the Configuration
    layer declares — a typo'd remedy would read as actionable advice
    the config system then silently ignores. Linted statically so the
    contract holds for rules no synthetic snapshot happens to fire."""
    from flink_tpu.core.config import ConfigOption, CoreOptions
    declared = {
        v.key for v in vars(CoreOptions).values()
        if isinstance(v, ConfigOption)
    }
    calls = _finding_call_sites()
    assert calls                                   # lint found the rules
    keys = []
    for call in calls:
        assert len(call.args) > 5, ast.dump(call)  # remedy_key positional
        rk = call.args[5]
        assert isinstance(rk, ast.Constant) and isinstance(rk.value, str)
        keys.append(rk.value)
    assert keys and set(keys) <= declared, sorted(set(keys) - declared)


def test_doctor_actions_name_registered_actuators():
    """The machine-actionable ``action`` arm of a remedy must name a
    RuntimeController actuator: the self-tuning loop looks actions up
    by name, and an unknown one is refused at apply time — far from
    the rule that emitted it. Every literal ``{"actuator": ...}`` dict
    in the module is checked, including those bound to locals before
    being passed to ``_finding``."""
    from flink_tpu.metrics import doctor as doctor_mod
    from flink_tpu.runtime.controller import ACTUATOR_NAMES
    tree = ast.parse(inspect.getsource(doctor_mod))
    actions = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Dict):
            continue
        lit = {
            k.value: v for k, v in zip(node.keys, node.values)
            if isinstance(k, ast.Constant)
        }
        if "actuator" not in lit:
            continue
        act = lit["actuator"]
        assert isinstance(act, ast.Constant), ast.dump(node)
        actions.append((node.lineno, act.value, lit.get("direction")))
    assert actions                                  # lint found actions
    names = {a for _, a, _ in actions}
    assert names <= set(ACTUATOR_NAMES), sorted(names)
    for lineno, _, direction in actions:
        if direction is not None:
            assert isinstance(direction, ast.Constant), lineno
            assert direction.value in ("up", "down"), (lineno,
                                                       direction.value)
