"""Multi-stream operators: union, connect/co-ops, windowed join/coGroup,
split/select, multi-sink fan-out, partition annotations.

Mirrors the reference's DataStream multi-input surface (SURVEY §2.5:
ConnectedStreams, JoinedStreams/CoGroupedStreams, SplitStream) and the
union+tag lowering CoGroupedStreams.java uses internally."""

import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.datastream.functions import (
    CoFlatMapFunction, CoMapFunction, CoProcessFunction,
)
from flink_tpu.runtime.sinks import CollectSink
from flink_tpu.state.descriptors import ValueStateDescriptor


def _env(batch=8):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = batch
    return env


def test_union_merges_streams():
    env = _env()
    sink = CollectSink()
    a = env.from_collection([1, 2, 3])
    b = env.from_collection([10, 20])
    c = env.from_collection([100])
    a.union(b, c).map(lambda x: x * 2).add_sink(sink)
    env.execute("union")
    assert sorted(sink.results) == [2, 4, 6, 20, 40, 200]


def test_union_then_keyed_window():
    env = _env()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = CollectSink()
    a = env.from_collection([(0, "x", 1.0), (1000, "y", 2.0)])
    b = env.from_collection([(500, "x", 3.0), (6000, "x", 7.0)])
    (
        a.union(b)
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(5000)
        .sum(lambda e: e[2])
        .add_sink(sink)
    )
    env.execute("union-window")
    got = {(r.key, r.window_end_ms): r.value for r in sink.results}
    assert got == {("x", 5000): 4.0, ("y", 5000): 2.0, ("x", 10000): 7.0}


def test_connect_co_map():
    class MyCoMap(CoMapFunction):
        def map1(self, v):
            return ("int", v)

        def map2(self, v):
            return ("str", v.upper())

    env = _env()
    sink = CollectSink()
    nums = env.from_collection([1, 2])
    words = env.from_collection(["a", "b"])
    nums.connect(words).map(MyCoMap()).add_sink(sink)
    env.execute("co-map")
    assert sorted(sink.results) == [
        ("int", 1), ("int", 2), ("str", "A"), ("str", "B")
    ]


def test_connect_co_flat_map_with_pair_of_callables():
    env = _env()
    sink = CollectSink()
    a = env.from_collection(["x y", "z"])
    b = env.from_collection([3])
    a.connect(b).flat_map(
        (lambda s: s.split(), lambda n: [n] * n)
    ).add_sink(sink)
    env.execute("co-flat-map")
    assert sorted(sink.results, key=str) == [3, 3, 3, "x", "y", "z"]


def test_keyed_co_process_shared_state():
    """Control-stream pattern: stream 2 sets a per-key threshold, stream 1
    emits values exceeding it — shared keyed state across both inputs."""

    class Gate(CoProcessFunction):
        def open(self, ctx):
            self.threshold = ctx.get_state(
                ValueStateDescriptor("threshold", default=0.0)
            )

        def process_element1(self, e, ctx, out):
            if e[1] > self.threshold.value():
                out.collect(e)

        def process_element2(self, e, ctx, out):
            self.threshold.update(e[1])

    env = _env(batch=2)
    sink = CollectSink()
    # round-robin merge polls 1 element per branch per cycle: the control
    # record lands in cycle 1, before ("k", 1.0) arrives in cycle 2
    data = env.from_collection([("z", 0.0), ("k", 1.0), ("k", 5.0), ("j", 4.0)])
    control = env.from_collection([("k", 2.0)])
    data.connect(control).key_by(
        lambda e: e[0], lambda e: e[0]
    ).process(Gate()).add_sink(sink)
    env.execute("co-process")
    assert ("k", 5.0) in sink.results
    assert ("j", 4.0) in sink.results
    assert ("k", 1.0) not in sink.results
    assert ("z", 0.0) not in sink.results


def test_windowed_join():
    env = _env()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = CollectSink()
    orders = env.from_collection(
        [(0, "u1", "order-a"), (1000, "u2", "order-b"), (9000, "u1", "order-c")]
    ).assign_timestamps_and_watermarks(lambda e: e[0])
    pays = env.from_collection(
        [(500, "u1", "pay-a"), (1500, "u2", "pay-b"), (2000, "u1", "pay-x")]
    ).assign_timestamps_and_watermarks(lambda e: e[0])
    (
        orders.join(pays)
        .where(lambda e: e[1])
        .equal_to(lambda e: e[1])
        .time_window(5000)
        .apply(lambda o, p: (o[1], o[2], p[2]))
        .add_sink(sink)
    )
    env.execute("join")
    assert sorted(sink.results) == [
        ("u1", "order-a", "pay-a"),
        ("u1", "order-a", "pay-x"),
        ("u2", "order-b", "pay-b"),
    ]


def test_windowed_co_group_sees_unmatched():
    env = _env()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = CollectSink()
    a = env.from_collection([(0, "x", 1), (100, "y", 2)]) \
        .assign_timestamps_and_watermarks(lambda e: e[0])
    b = env.from_collection([(50, "x", 10)]) \
        .assign_timestamps_and_watermarks(lambda e: e[0])
    (
        a.co_group(b)
        .where(lambda e: e[1])
        .equal_to(lambda e: e[1])
        .time_window(5000)
        .apply(lambda lefts, rights: [(len(lefts), len(rights))])
        .add_sink(sink)
    )
    env.execute("cogroup")
    # x: 1 left 1 right; y: 1 left 0 rights (outer-join visibility)
    assert sorted(sink.results) == [(1, 0), (1, 1)]


def test_split_select():
    env = _env()
    evens, odds = CollectSink(), CollectSink()
    split = env.from_collection(list(range(6))).split(
        lambda e: ["even"] if e % 2 == 0 else ["odd"]
    )
    split.select("even").add_sink(evens)
    split.select("odd").map(lambda x: -x).add_sink(odds)
    env.execute("split")
    assert sorted(evens.results) == [0, 2, 4]
    assert sorted(odds.results) == [-5, -3, -1]


def test_multi_sink_fan_out_after_window():
    env = _env()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    raw, doubled = CollectSink(), CollectSink()
    win = (
        env.from_collection([(0, "a", 1.0), (1000, "a", 2.0)])
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .key_by(lambda e: e[1])
        .time_window(5000)
        .sum(lambda e: e[2])
    )
    win.add_sink(raw)
    win.map(lambda r: r.value * 2).add_sink(doubled)
    env.execute("fan-out")
    assert [r.value for r in raw.results] == [3.0]
    assert doubled.results == [6.0]


def test_partition_annotations_are_noops():
    env = _env()
    sink = CollectSink()
    (
        env.from_collection([1, 2, 3])
        .rebalance()
        .map(lambda x: x + 1)
        .shuffle()
        .broadcast()
        .add_sink(sink)
    )
    env.execute("partitions")
    assert sorted(sink.results) == [2, 3, 4]


def test_join_map_after_timestamp_assignment():
    """Ops after assign_timestamps on a joined input must not feed the
    transformed element back into the timestamp_fn; outputs inherit the
    input element's timestamp (ref TimestampedCollector)."""
    env = _env()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = CollectSink()
    orders = (
        env.from_collection([(0, "u1", "order-a"), (1000, "u2", "order-b")])
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .map(lambda e: (e[1], e[2]))          # drops the ts field
    )
    pays = (
        env.from_collection([(500, "u1", "pay-a"), (1500, "u2", "pay-b")])
        .assign_timestamps_and_watermarks(lambda e: e[0])
        .map(lambda e: (e[1], e[2]))
    )
    (
        orders.join(pays)
        .where(lambda e: e[0]).equal_to(lambda e: e[0])
        .time_window(5000)
        .apply(lambda o, p: (o[0], o[1], p[1]))
        .add_sink(sink)
    )
    env.execute("join-ts-then-map")
    assert sorted(sink.results) == [
        ("u1", "order-a", "pay-a"), ("u2", "order-b", "pay-b")
    ]


def test_skewed_inputs_use_min_watermark():
    """A fast input must not advance the merged watermark past the slow
    input's elements (ref StreamTwoInputProcessor min-across-inputs)."""
    env = _env(batch=4)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    sink = CollectSink()
    a = env.from_collection([(100000, "z", 0)]) \
        .assign_timestamps_and_watermarks(lambda e: e[0])
    b = env.from_collection([(t, "z", t) for t in range(6)]) \
        .assign_timestamps_and_watermarks(lambda e: e[0])
    (
        a.co_group(b)
        .where(lambda e: e[1]).equal_to(lambda e: e[1])
        .time_window(5000)
        .apply(lambda lefts, rights: [(len(lefts), len(rights))])
        .add_sink(sink)
    )
    env.execute("skewed-cogroup")
    assert env.last_job.metrics.dropped_late == 0
    assert sorted(sink.results) == [(0, 6), (1, 0)]


def test_iteration_feedback_loop():
    """Streaming iteration (ref IterativeStream / IterateExample): decrement
    until zero; non-zero values loop back through the body."""
    env = _env(batch=4)
    sink = CollectSink()
    it = env.from_collection([3, 1, 4]).iterate()
    body = it.map(lambda x: x - 1)
    it.close_with(body.filter(lambda x: x > 0))
    body.filter(lambda x: x <= 0).add_sink(sink)
    env.execute("iterate")
    assert sink.results == [0, 0, 0]
    # 3+1+4 = 8 trips through the body in total
    assert env.last_job.metrics.records_in == 8


def test_union_type_mismatch_divergent_spine_rejected():
    env = _env()
    s1, s2 = CollectSink(), CollectSink()
    a = env.from_collection([1]).key_by(lambda e: e).sum()
    a.add_sink(s1)
    env.from_collection([2]).key_by(lambda e: e).sum().add_sink(s2)
    with pytest.raises(NotImplementedError):
        env.execute("divergent")
