"""Graph (Gelly analog) + ML library semantics (ref flink-gelly library
algorithm tests + flink-ml pipeline ITCases, SURVEY §2.7)."""

import numpy as np
import pytest

from flink_tpu.gelly import Graph
from flink_tpu.ml import (
    KNN,
    SVM,
    KMeans,
    MinMaxScaler,
    MultipleLinearRegression,
    Pipeline,
    PolynomialFeatures,
    StandardScaler,
)


# ---------------------------------------------------------------- graph
def _two_components():
    return Graph.from_edge_list(
        [("a", "b"), ("b", "c"), ("d", "e")], undirected=True
    )


def test_connected_components():
    cc = _two_components().connected_components()
    assert cc["a"] == cc["b"] == cc["c"]
    assert cc["d"] == cc["e"]
    assert cc["a"] != cc["d"]


def test_degrees_and_transforms():
    g = Graph.from_edge_list([(0, 1), (0, 2), (1, 2)])
    assert g.out_degrees() == {0: 2, 1: 1, 2: 0}
    assert g.in_degrees() == {0: 0, 1: 1, 2: 2}
    assert g.reverse().out_degrees() == {0: 0, 1: 1, 2: 2}
    g2 = g.map_edges(lambda ev: ev * 3.0)
    assert float(np.asarray(g2.edge_values).sum()) == 9.0


def test_sssp():
    g = Graph.from_edge_list(
        [("s", "a"), ("a", "b"), ("s", "b"), ("b", "c")],
        edge_values=[1.0, 1.0, 5.0, 2.0],
    )
    d = g.single_source_shortest_paths("s")
    assert d["s"] == 0.0
    assert d["a"] == 1.0
    assert d["b"] == 2.0   # s->a->b beats s->b
    assert d["c"] == 4.0


def test_page_rank_sums_to_one_and_ranks_hub():
    # star: everyone links to 'hub'
    edges = [(f"u{i}", "hub") for i in range(5)]
    # give sources an incoming edge so they're reachable
    edges += [("hub", f"u{i}") for i in range(5)]
    g = Graph.from_edge_list(edges)
    pr = g.page_rank(num_iterations=50)
    assert sum(pr.values()) == pytest.approx(1.0, abs=1e-3)
    assert pr["hub"] > max(v for k, v in pr.items() if k != "hub")


def test_triangle_count():
    g = Graph.from_edge_list([(0, 1), (1, 2), (2, 0), (2, 3)])
    assert g.triangle_count() == 1


# ------------------------------------------------------------------ ml
def test_linear_regression_recovers_coefficients():
    rng = np.random.default_rng(0)
    X = rng.normal(size=(400, 3)).astype(np.float32)
    y = X @ np.array([2.0, -1.0, 0.5]) + 3.0
    mlr = MultipleLinearRegression(iterations=500, stepsize=0.2).fit(X, y)
    w = np.asarray(mlr.weights)
    assert np.allclose(w[:3], [2.0, -1.0, 0.5], atol=0.05)
    assert abs(w[3] - 3.0) < 0.05
    assert mlr.squared_residual_sum(X, y) < 1.0


def test_svm_separates():
    rng = np.random.default_rng(1)
    X0 = rng.normal(loc=-2, size=(100, 2))
    X1 = rng.normal(loc=+2, size=(100, 2))
    X = np.vstack([X0, X1]).astype(np.float32)
    y = np.array([-1.0] * 100 + [1.0] * 100, np.float32)
    svm = SVM(iterations=500).fit(X, y)
    pred = np.asarray(svm.predict(X))
    assert (pred == y).mean() > 0.97


def test_kmeans_finds_clusters():
    rng = np.random.default_rng(2)
    X = np.vstack([
        rng.normal(loc=(0, 0), scale=0.3, size=(50, 2)),
        rng.normal(loc=(5, 5), scale=0.3, size=(50, 2)),
        rng.normal(loc=(0, 5), scale=0.3, size=(50, 2)),
    ]).astype(np.float32)
    km = KMeans(k=3, iterations=30).fit(X)
    centers = sorted(np.asarray(km.centers).round(0).tolist())
    assert centers == [[0.0, 0.0], [0.0, 5.0], [5.0, 5.0]]
    labels = np.asarray(km.predict(X))
    assert len(set(labels[:50])) == 1


def test_knn_regression():
    X = np.arange(20, dtype=np.float32)
    y = X * 2.0
    knn = KNN(k=3).fit(X, y)
    pred = float(np.asarray(knn.predict(np.array([10.0])))[0])
    assert pred == pytest.approx(20.0)


def test_pipeline_chaining():
    rng = np.random.default_rng(3)
    X = rng.normal(loc=100.0, scale=50.0, size=(300, 2)).astype(np.float32)
    y = (0.01 * X[:, 0] - 0.02 * X[:, 1] + 1.0).astype(np.float32)
    pipe = Pipeline([
        StandardScaler(),
        MultipleLinearRegression(iterations=400, stepsize=0.3),
    ]).fit(X, y)
    pred = np.asarray(pipe.predict(X))
    assert np.abs(pred - y).max() < 0.05


def test_scalers_and_poly():
    X = np.array([[1.0], [2.0], [3.0]], np.float32)
    z = np.asarray(StandardScaler().fit_transform(X))
    assert z.mean() == pytest.approx(0.0, abs=1e-6)
    mm = np.asarray(MinMaxScaler().fit_transform(X))
    assert mm.min() == 0.0 and mm.max() == 1.0
    p = np.asarray(PolynomialFeatures(3).transform(X))
    assert p.shape == (3, 3)
    assert p[2].tolist() == [3.0, 9.0, 27.0]
