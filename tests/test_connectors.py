"""Connector contracts: partitioned consumer (Kafka pattern), continuous
file source, bucketing file sink (ref SURVEY §2.8 + the reference's
Kafka/BucketingSink exactly-once tests)."""

import os

import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors import (
    PROCESS_CONTINUOUSLY,
    PROCESS_ONCE,
    BucketingFileSink,
    ContinuousFileSource,
    InMemoryPartitionedSource,
)
from flink_tpu.core.time import TimeCharacteristic
from flink_tpu.runtime.sinks import CollectSink


def test_partitioned_consumer_reads_all_partitions():
    src = InMemoryPartitionedSource({
        0: [("k0", 1.0)] * 3,
        1: [("k1", 1.0)] * 5,
        2: [("k2", 1.0)] * 2,
    })
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    sink = CollectSink()
    env.add_source(src).add_sink(sink)
    env.execute("partitions")
    assert len(sink.results) == 10
    assert {k for k, _ in sink.results} == {"k0", "k1", "k2"}


def test_offsets_committed_only_on_checkpoint_complete(tmp_path):
    """The FlinkKafkaConsumerBase rule: external commits trail checkpoints
    (notifyCheckpointComplete), never the live read position."""
    commits = []

    class Recording(InMemoryPartitionedSource):
        def commit_offsets(self, offsets, cid):
            super().commit_offsets(offsets, cid)
            commits.append((cid, dict(offsets)))

    src = Recording({0: [("k", i, 1.0) for i in range(40)]})
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 8
    env.enable_checkpointing(2, str(tmp_path / "ckpt"))
    sink = CollectSink()
    (
        env.add_source(src)
        .assign_timestamps_and_watermarks(lambda e: e[1])
        .key_by(lambda e: e[0])
        .time_window(10)
        .sum(lambda e: e[2])
        .add_sink(sink)
    )
    env.execute("kafka-commit")
    assert commits, "no offsets were committed"
    cids = [c for c, _ in commits]
    assert cids == sorted(cids)
    # each commit's offsets match a consistent snapshot (multiple of batch)
    for _, offs in commits:
        assert offs[0] <= 40
    assert src.committed == commits[-1][1]


def test_partitioned_exactly_once_under_restart(tmp_path):
    """Failure mid-stream + fixed-delay restart: replay from snapshot
    offsets converges to the no-failure aggregate (ref
    StateCheckpointedITCase pattern)."""
    from flink_tpu.core.config import Configuration

    n = 60
    src = InMemoryPartitionedSource({
        0: [(f"k{i % 5}", i, 1.0) for i in range(0, n, 2)],
        1: [(f"k{i % 5}", i, 1.0) for i in range(1, n, 2)],
    })
    cfg = Configuration()
    cfg.set("restart-strategy", "fixed-delay")
    cfg.set("restart-strategy.fixed-delay.attempts", 3)
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 8
    env.enable_checkpointing(2, str(tmp_path / "ck"))
    sink = CollectSink()

    state = {"count": 0, "failed": False}

    def poison(e):
        state["count"] += 1
        if state["count"] == 30 and not state["failed"]:
            state["failed"] = True
            raise RuntimeError("injected failure")
        return e

    (
        env.add_source(src)
        .map(poison)
        .assign_timestamps_and_watermarks(
            lambda e: e[1],
        )
        .key_by(lambda e: e[0])
        .time_window(1000)   # one big window: totals visible at flush
        .sum(lambda e: e[2])
        .add_sink(sink)
    )
    env.execute("exactly-once")
    assert env.last_job.metrics.restarts == 1
    totals = {}
    for r in sink.results:
        totals[r.key] = totals.get(r.key, 0) + r.value
    assert totals == {f"k{i}": 12.0 for i in range(5)}


def test_continuous_file_source_process_once(tmp_path):
    for i in range(3):
        (tmp_path / f"f{i}.txt").write_text(f"line-{i}-a\nline-{i}-b\n")
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    sink = CollectSink()
    env.add_source(
        ContinuousFileSource(str(tmp_path), "*.txt", PROCESS_ONCE)
    ).add_sink(sink)
    env.execute("files")
    assert sorted(sink.results) == sorted(
        f"line-{i}-{s}" for i in range(3) for s in "ab"
    )


def test_continuous_file_source_picks_up_appends(tmp_path):
    p = tmp_path / "grow.txt"
    p.write_text("a\n")
    src = ContinuousFileSource(str(tmp_path), "*.txt", PROCESS_CONTINUOUSLY)
    src.open()
    lines, end = src.poll(10)
    assert lines == ["a"] and not end
    with open(p, "a") as f:
        f.write("b\npartial")          # unterminated line must wait
    lines, end = src.poll(10)
    assert lines == ["b"] and not end
    with open(p, "a") as f:
        f.write("-done\n")
    lines, _ = src.poll(10)
    assert lines == ["partial-done"]
    # replay: restoring positions re-reads nothing
    snap = src.snapshot_offsets()
    src2 = ContinuousFileSource(str(tmp_path), "*.txt", PROCESS_CONTINUOUSLY)
    src2.open()
    src2.restore_offsets(snap)
    lines, _ = src2.poll(10)
    assert lines == []


def test_bucketing_sink_truncates_on_restore(tmp_path):
    base = str(tmp_path / "out")
    sink = BucketingFileSink(base, bucketer=lambda e: e[0])
    sink.open()
    sink.invoke_batch([("b1", "x"), ("b1", "y")])
    snap = sink.snapshot_state()
    sink.invoke_batch([("b1", "lost-after-failure")])
    # crash: a new sink instance restores the snapshot
    sink2 = BucketingFileSink(base, bucketer=lambda e: e[0])
    sink2.restore_state(snap)
    sink2.open()
    sink2.invoke_batch([("b1", "z")])
    sink2.close()
    final = os.path.join(base, "b1", "part-0")
    assert os.path.exists(final)
    with open(final) as f:
        lines = f.read().splitlines()
    assert lines == ["('b1', 'x')", "('b1', 'y')", "('b1', 'z')"]


def test_checkpointing_with_merged_source(tmp_path):
    """Union/join sources must survive the checkpoint notify fan-out."""
    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.batch_size = 4
    env.enable_checkpointing(2, str(tmp_path / "ck"))
    sink = CollectSink()
    a = env.from_collection([(t * 10, "x", 1.0) for t in range(20)]) \
        .assign_timestamps_and_watermarks(lambda e: e[0])
    b = env.from_collection([(t * 10 + 5, "x", 1.0) for t in range(20)]) \
        .assign_timestamps_and_watermarks(lambda e: e[0])
    (
        a.co_group(b)
        .where(lambda e: e[1]).equal_to(lambda e: e[1])
        .time_window(100)
        .apply(lambda ls, rs: [len(ls) + len(rs)])
        .add_sink(sink)
    )
    env.execute("ckpt-merged")
    assert sum(sink.results) == 40


def test_process_once_unterminated_tail(tmp_path):
    (tmp_path / "f.txt").write_text("a\nb")       # no trailing newline
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 4
    sink = CollectSink()
    env.add_source(
        ContinuousFileSource(str(tmp_path), "*.txt", PROCESS_ONCE)
    ).add_sink(sink)
    env.execute("tail")                           # must terminate
    assert sorted(sink.results) == ["a", "b"]


def test_process_once_ignores_files_created_after_start(tmp_path):
    (tmp_path / "f0.txt").write_text("x\n")
    src = ContinuousFileSource(str(tmp_path), "*.txt", PROCESS_ONCE)
    src.open()
    (tmp_path / "f1.txt").write_text("late\n")
    lines, end = src.poll(10)
    assert lines == ["x"] and end


def test_bucketing_close_finalizes_restored_untouched_buckets(tmp_path):
    base = str(tmp_path / "out")
    sink = BucketingFileSink(base, bucketer=lambda e: e[0],
                             formatter=lambda e: e[1])
    sink.open()
    sink.invoke_batch([("b1", "x"), ("b2", "y")])
    snap = sink.snapshot_state()
    # crash; recovery replays only into b1
    sink2 = BucketingFileSink(base, bucketer=lambda e: e[0],
                              formatter=lambda e: e[1])
    sink2.restore_state(snap)
    sink2.open()
    sink2.invoke_batch([("b1", "z")])
    sink2.close()
    with open(os.path.join(base, "b2", "part-0")) as f:
        assert f.read().splitlines() == ["y"]


def test_bucketing_nested_bucketer_restore_and_finalize(tmp_path):
    """Date-path bucketers (nested dirs) must be truncated on restore and
    finalized on close like flat buckets."""
    base = str(tmp_path / "out")
    sink = BucketingFileSink(base, bucketer=lambda e: f"{e[0]}/{e[1]}",
                             formatter=lambda e: e[2])
    sink.open()
    sink.invoke_batch([("2026-07-29", "12", "x")])
    snap = sink.snapshot_state()
    sink.invoke_batch([("2026-07-29", "12", "lost")])
    sink2 = BucketingFileSink(base, bucketer=lambda e: f"{e[0]}/{e[1]}",
                              formatter=lambda e: e[2])
    sink2.restore_state(snap)
    sink2.open()
    sink2.close()
    final = os.path.join(base, "2026-07-29", "12", "part-0")
    assert os.path.exists(final)
    with open(final) as f:
        assert f.read().splitlines() == ["x"]


def test_savepoint_on_dead_job_fails_fast():
    import time as _time

    from flink_tpu.runtime.cluster import MiniCluster

    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    env.from_collection([1]).add_sink(CollectSink())
    cluster = MiniCluster()
    jid = cluster.submit(env, "short")
    cluster.wait(jid, 30)
    t0 = _time.monotonic()
    with pytest.raises(RuntimeError):
        cluster.trigger_savepoint(jid, "/tmp/never")
    assert _time.monotonic() - t0 < 5


def test_bucketing_sink_end_to_end(tmp_path):
    env = StreamExecutionEnvironment.get_execution_environment()
    env.batch_size = 8
    base = str(tmp_path / "sink")
    (
        env.from_collection(list(range(10)))
        .map(lambda x: ("even" if x % 2 == 0 else "odd", x))
        .add_sink(BucketingFileSink(
            base, bucketer=lambda e: e[0], formatter=lambda e: str(e[1])
        ))
    )
    env.execute("bucketing")
    with open(os.path.join(base, "even", "part-0")) as f:
        assert f.read().splitlines() == ["0", "2", "4", "6", "8"]
    with open(os.path.join(base, "odd", "part-0")) as f:
        assert f.read().splitlines() == ["1", "3", "5", "7", "9"]
