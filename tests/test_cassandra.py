"""Cassandra CQL v3 native-protocol connector vs the in-repo spec server
(the MiniKafkaBroker pattern): real binary frames over real TCP —
STARTUP/READY, PREPARE/EXECUTE with bound values, QUERY, ERROR frames —
plus upsert-by-primary-key idempotent replay.

Ref: flink-streaming-connectors/flink-connector-cassandra/
CassandraSink.java + CassandraSinkBase (prepared-statement send,
flush-before-snapshot)."""

import struct

import numpy as np
import pytest

from flink_tpu import StreamExecutionEnvironment
from flink_tpu.connectors.cassandra import (
    CassandraSink, CqlConnection, CqlError, MiniCassandra,
)


@pytest.fixture
def cass():
    server = MiniCassandra()
    server.start()
    yield server
    server.stop()


def test_handshake_and_raw_query(cass):
    conn = CqlConnection("127.0.0.1", cass.port)   # STARTUP/READY inside
    conn.query("CREATE TABLE kv (k text, v bigint, PRIMARY KEY (k))")
    conn.query("INSERT INTO kv (k, v) VALUES ('a', 7)")
    rows = conn.query("SELECT k, v FROM kv")
    assert len(rows) == 1
    k, v = rows[0]
    assert k == b"a" and struct.unpack(">q", v)[0] == 7
    conn.close()


def test_prepare_execute_bound_values(cass):
    conn = CqlConnection("127.0.0.1", cass.port)
    conn.query("CREATE TABLE m (k text, x double, PRIMARY KEY (k))")
    stmt = conn.prepare("INSERT INTO m (k, x) VALUES (?, ?)")
    for i in range(5):
        conn.execute(stmt, [f"key{i}", float(i) / 2])
    rows = conn.query("SELECT x FROM m WHERE k = 'key3'")
    assert struct.unpack(">d", rows[0][0])[0] == 1.5
    assert cass.row_count("m") == 5
    conn.close()


def test_error_frames_surface(cass):
    conn = CqlConnection("127.0.0.1", cass.port)
    with pytest.raises(CqlError, match="unconfigured table"):
        conn.query("SELECT * FROM missing")
    with pytest.raises(CqlError, match="unsupported CQL"):
        conn.query("DROP KEYSPACE everything")
    conn.close()


def test_sink_upsert_idempotent_replay(cass):
    """INSERT on the same primary key overwrites — deterministic keys
    make checkpoint replay idempotent (the reference's recipe)."""
    sink = CassandraSink(
        "127.0.0.1", cass.port,
        insert_cql="INSERT INTO acc (k, total) VALUES (?, ?)",
        extractor=lambda e: (e[0], e[1]),
        setup_cql=["CREATE TABLE IF NOT EXISTS acc "
                   "(k text, total bigint, PRIMARY KEY (k))"],
    )
    sink.open()
    sink.invoke_batch([("a", 1), ("b", 2)])
    sink.invoke_batch([("a", 10), ("b", 2)])    # replay + update
    assert cass.row_count("acc") == 2
    conn = CqlConnection("127.0.0.1", cass.port)
    rows = conn.query("SELECT total FROM acc WHERE k = 'a'")
    assert struct.unpack(">q", rows[0][0])[0] == 10
    conn.close()
    sink.close()


def test_pipeline_end_to_end(cass):
    """Streaming job -> windowed sums -> Cassandra over real CQL frames,
    queried back."""
    from flink_tpu.core.time import TimeCharacteristic
    from flink_tpu.runtime.sources import GeneratorSource

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    env.set_parallelism(2).set_max_parallelism(32)
    env.set_state_capacity(256)
    env.batch_size = 64

    def gen(off, n):
        idx = np.arange(off, off + n)
        return ({"key": idx % 4, "value": np.ones(n, np.float32)},
                (idx * 10).astype(np.int64))

    sink = CassandraSink(
        "127.0.0.1", cass.port,
        insert_cql="INSERT INTO windows (wk, total) VALUES (?, ?)",
        # deterministic primary key = (key, window): replay upserts
        extractor=lambda r: (f"{r.key}@{r.window_end_ms}",
                             int(r.value)),
        setup_cql=["CREATE TABLE IF NOT EXISTS windows "
                   "(wk text, total bigint, PRIMARY KEY (wk))"],
    )
    (
        env.add_source(GeneratorSource(gen, total=800))
        .key_by(lambda c: c["key"])
        .time_window(1000)
        .sum(lambda c: c["value"])
        .add_sink(sink)
    )
    env.execute("to-cassandra")
    # 800 records, ts = idx*10 -> 8 windows x 4 keys
    assert cass.row_count("windows") == 32
    conn = CqlConnection("127.0.0.1", cass.port)
    rows = conn.query("SELECT total FROM windows WHERE wk = '1@1000'")
    assert struct.unpack(">q", rows[0][0])[0] == 25
    conn.close()


def test_null_bind_value_round_trips(cass):
    """None binds as a CQL null (wire length -1), not the string 'None'."""
    conn = CqlConnection("127.0.0.1", cass.port)
    conn.query("CREATE TABLE n (k text, v bigint, PRIMARY KEY (k))")
    stmt = conn.prepare("INSERT INTO n (k, v) VALUES (?, ?)")
    conn.execute(stmt, ["a", None])
    rows = conn.query("SELECT v FROM n WHERE k = 'a'")
    assert rows[0][0] is None          # null cell, not b"None"
    conn.close()


def test_numpy_scalars_bind_as_proper_wire_types(cass):
    """np.int64/np.float64 (the pipeline's natural outputs) serialize as
    bigint/double wire bytes, not str(); unknown types are rejected."""
    from flink_tpu.connectors.cassandra import encode_value

    assert encode_value(np.int64(42)) == struct.pack(">q", 42)
    assert encode_value(np.float64(1.5)) == struct.pack(">d", 1.5)
    assert encode_value(np.float32(2.0)) == struct.pack(">d", 2.0)
    assert encode_value(np.bool_(True)) == b"\x01"
    assert encode_value(None) is None
    with pytest.raises(TypeError, match="cannot bind"):
        encode_value({"not": "a scalar"})

    conn = CqlConnection("127.0.0.1", cass.port)
    conn.query("CREATE TABLE np (k text, v bigint, PRIMARY KEY (k))")
    stmt = conn.prepare("INSERT INTO np (k, v) VALUES (?, ?)")
    conn.execute(stmt, ["a", np.int64(7)])
    rows = conn.query("SELECT v FROM np WHERE k = 'a'")
    assert struct.unpack(">q", rows[0][0])[0] == 7
    conn.close()


def test_bool_arrays_rejected(cass):
    from flink_tpu.connectors.cassandra import encode_value

    with pytest.raises(TypeError, match="cannot bind"):
        encode_value(np.array([True, False]))
    with pytest.raises(TypeError, match="cannot bind"):
        encode_value(np.array([True]))
