"""Builder functions for the cross-host data-plane tests (imported by the
dcn worker subprocesses via --builder tests/dcn_jobs.py:NAME)."""

import numpy as np

from flink_tpu.runtime.dcn import DCNJobSpec, GeneratorPartitionSource

N_KEYS = 977           # prime: keys spread over all key groups
TOTAL_PER_HOST = 40_000
WIN_MS = 1_000
TS_DIV = 16            # ts advances 1ms per TS_DIV records


def _source(pid, nproc):
    # host p ingests ONLY keys congruent to p mod nproc — a genuinely
    # DISJOINT key slice per host (key % nproc identifies the ingesting
    # host), so any key firing on the other host provably crossed the
    # process boundary through the all_to_all
    per_host = N_KEYS // nproc

    def gen(offset, n):
        idx = np.arange(offset, offset + n, dtype=np.int64)
        keys = pid + nproc * (idx % per_host)
        ts = idx // TS_DIV
        return keys, ts, np.ones(n, np.float32)

    return GeneratorPartitionSource(gen, TOTAL_PER_HOST)


def two_host_window():
    return DCNJobSpec(
        source_factory=_source,
        size_ms=WIN_MS,
        capacity_per_shard=2048,
        max_parallelism=64,
        batch_per_host=2048,
        fires_per_step=4,
    )


def expected(nproc):
    """Per-(key, window_end) expected sums across all hosts."""
    per_host = N_KEYS // nproc
    exp = {}
    for pid in range(nproc):
        for i in range(TOTAL_PER_HOST):
            k = pid + nproc * (i % per_host)
            w = ((i // TS_DIV) // WIN_MS + 1) * WIN_MS
            exp[(k, w)] = exp.get((k, w), 0) + 1.0
    return exp
